#!/usr/bin/env python
"""Headline benchmark: wall-clock per training iteration, 100-peer MNIST
softmax with Krum verification and DP noising — the reference's flagship
configuration (BASELINE.md row 1: 38.2–42.0 s/iteration on 100 Azure
VMs-worth of CPU processes; north star ≲4 s/iteration).

One full iteration here = every contributor's local SGD step + DP noise +
Krum filtering over the round's updates + aggregation + stake update +
convergence metric, all in one jitted XLA program on the TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = reference_seconds / our_seconds (higher is better; ≥10 is the
north-star).
"""

import json
import sys
import time

BASELINE_S_PER_ITER = 38.2  # BASELINE.md: Biscotti wall-clock/iteration, low end


def main():
    import jax

    from biscotti_tpu.config import BiscottiConfig, Defense
    from biscotti_tpu.parallel.sim import Simulator

    cfg = BiscottiConfig(
        dataset="mnist",
        num_nodes=100,
        batch_size=10,  # ref batch size (client_obj __main__, honest.go)
        epsilon=1.0,
        noising=True,
        verification=True,
        defense=Defense.KRUM,
        sample_percent=0.70,
        num_verifiers=3,
        num_miners=3,
        seed=0,
    )
    sim = Simulator(cfg)
    w, stake = sim.init_state()

    # warm-up: compile + first dispatch
    for it in range(3):
        w, stake, mask, err = sim.round_step(w, stake, it)
    jax.block_until_ready(w)

    iters = 30
    t0 = time.perf_counter()
    for it in range(3, 3 + iters):
        w, stake, mask, err = sim.round_step(w, stake, it)
    jax.block_until_ready(w)
    dt = (time.perf_counter() - t0) / iters

    out = {
        "metric": "wall-clock/iteration, 100-peer MNIST softmax + Krum + DP (ref: 38.2s)",
        "value": round(dt, 6),
        "unit": "s/iter",
        "vs_baseline": round(BASELINE_S_PER_ITER / dt, 2),
        "final_error": round(float(err), 4),
        "accepted_per_round": int(mask.sum()),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
