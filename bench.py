#!/usr/bin/env python
"""Headline benchmark — crypto-inclusive wall-clock per training iteration
across the five BASELINE.json configs.

One Biscotti iteration's critical path (deployment model: one peer per
TPU host, as the reference runs one peer per process across VMs) is:

    device round   all peers' SGD + DP noise + Krum + aggregation as ONE
                   vmapped XLA program on the chip (parallel/sim.py)
  + worker crypto  ONE peer's quantize → Pedersen-VSS chunk commitments →
                   blinding rows → int64 Shamir shares (host C++/CPU;
                   peers run this in parallel in deployment, so one
                   peer's cost is the critical-path term)
  + miner crypto   the busiest miner's intake under the PIPELINED engine:
                   share slices fold into the round's VSS accumulator as
                   they arrive (miner_fold_s, overlapped with the intake
                   network window) and mint time pays only the RLC settle
                   (miner_crypto_s). The pre-pipeline whole-intake lump is
                   kept as miner_crypto_oneshot_s for the r02–r05
                   trajectory (× NUM_SAMPLES/2, the mint trigger,
                   ref: main.go:345-363)
  + recovery       leader's Vandermonde least-squares recovery of the
                   aggregate (CPU-pinned int64/f64 path, see
                   ops/secretshare.py docstring: TPUs have no exact s64
                   matmul — a deliberate, validated host fallback)

Round 1's bench measured only the device round and reported 32,965× —
real, but it omitted exactly the costs that dominated the reference's
38.2 s/iter (the O(d) EC work per update, SURVEY §7.3). This bench times
every component and also validates the int64 share pipeline end-to-end
(shares → aggregate → recover == Σ quantized) on this host.

Disclosure: datasets are synthetic Gaussian shards (zero-egress build
environment) with reference dimensions — error columns are NOT comparable
to the reference's real-MNIST curves; timing is, since shapes match.
vs_baseline compares against the reference's published fleet numbers
(BASELINE.md: 38.2 s/iter, 100 nodes over ~20 multi-VM CPU cores);
configs the reference never published numbers for carry vs_baseline null.

Prints ONE compact JSON line on stdout: {"metric", "value", "unit",
"vs_baseline"}. Per-config detail rows go to eval/results/bench_detail.json
and stderr.
"""

import json
import os
import sys
import time

BASELINE_MNIST_S_PER_ITER = 38.2  # BASELINE.md row 1, low end

# TPU v5e (the bench chip) peak: 197 TFLOPS bf16. The MFU column divides
# by this number, so it is the BF16-peak utilization; the sim computes in
# f32, whose MXU peak is lower, making the printed MFU conservative
# either way. Expectation check (VERDICT r3 #8): Biscotti's models are
# 8k-164k params — thousands of times below the size where one chip
# saturates — so the device round is dispatch/latency-bound and MFU is
# honestly tiny; the number exists to say so with data, not to impress.
PEAK_FLOPS_BF16 = 1.97e14


def _timeit(fn, warm=1, iters=3):
    for _ in range(warm):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def _progress(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _round_frame_bytes(cfg, w64, accepted, codec="raw64"):
    """Per-frame byte sizes (verify, submit, block) for one round,
    measured by encoding the ACTUAL frames (runtime/wire.py packers +
    messages.py codec path) with `w64` as the representative
    delta/model vector — the shared kernel of wire_round_bytes and
    cross_host_round_bytes."""
    import numpy as np

    from biscotti_tpu.ledger.block import Block, BlockData, Update
    from biscotti_tpu.ops import secretshare as ss
    from biscotti_tpu.runtime import codecs as wcodecs
    from biscotti_tpu.runtime import messages as msgs
    from biscotti_tpu.runtime import wire as rwire

    wc = wcodecs.get(codec)
    kw = dict(codec=None if wc.name == wcodecs.RAW else wc.name)
    d = len(w64)
    delta, _ = wc.transform(np.asarray(w64, np.float64),
                            topk_k=max(1, int(round(cfg.wire_topk * d))))
    gw = wc.transform_dense(np.asarray(w64, np.float64))
    it = 1

    # worker -> verifier: redacted update, noised copy only (f32 on the
    # wire since PR before this one; the codec can still zlib it)
    redacted = Update(source_id=1, iteration=it,
                      delta=np.zeros(0, np.float64), commitment=b"\0" * 32,
                      noised_delta=np.asarray(delta, np.float32))
    vmeta, varrays = rwire.pack_update(redacted)
    verify = len(msgs.encode("VerifyUpdateKRUM", vmeta, varrays, **kw))

    if cfg.secure_agg:
        c = ss.num_chunks(d, cfg.poly_size)
        submit = len(msgs.encode("RegisterSecret", {
            "iteration": it, "source_id": 1, "miner_index": 0,
            "commitment": "00" * 32,
        }, {
            "share_rows": np.ones((cfg.shares_per_miner, c), np.int64),
            "blind_rows": np.ones((cfg.shares_per_miner, c, 32), np.uint8),
            "comms": np.ones((c, cfg.poly_size, 64), np.uint8),
        }, **kw))
        blk_updates = [Update(source_id=1, iteration=it,
                              delta=np.zeros(0, np.float64),
                              commitment=b"\0" * 32, accepted=True)]
    else:
        u = Update(source_id=1, iteration=it, delta=delta,
                   commitment=b"\0" * 32)
        umeta, uarrays = rwire.pack_update(u)
        submit = len(msgs.encode("RegisterUpdate", umeta, uarrays, **kw))
        blk_updates = [Update(source_id=1, iteration=it, delta=delta,
                              commitment=b"\0" * 32, accepted=True)]

    blk = Block(data=BlockData(iteration=it, global_w=gw,
                               deltas=blk_updates * max(1, accepted)),
                prev_hash=b"\0" * 32,
                stake_map={i: 10 for i in range(cfg.num_nodes)}).seal()
    bmeta, barrays = rwire.pack_block(blk)
    block = len(msgs.encode("RegisterBlock", bmeta, barrays, **kw))
    return verify, submit, block


def wire_round_bytes(cfg, w64, accepted, codec="raw64"):
    """Cluster-wide protocol bytes for ONE round:

        num_samples × (num_verifiers × verify + num_miners × submit)
      + (num_nodes − 1) × block broadcast

    Lossy codecs are applied the way the live runtime applies them —
    transform BEFORE packing (lossy-before-commit), so the frame sizes
    here are exactly what the wire plane produces. Crypto tensors
    (shares, blinds, VSS commitments) are sized from the config and
    always travel lossless, which is why secure-agg rows compress less
    than their plain-mode cousins: the crypto dominates and is
    incompressible by design."""
    verify, submit, block = _round_frame_bytes(cfg, w64, accepted,
                                               codec=codec)
    n_s = cfg.num_samples
    return int(n_s * (cfg.num_verifiers * verify + cfg.num_miners * submit)
               + (cfg.num_nodes - 1) * block)


def cross_host_round_bytes(cfg, w64, accepted, codec="raw64", hosts=2,
                           overlay=False):
    """CROSS-HOST bytes for one round on an `hosts`-host hive fleet
    (peers split evenly, the pod_launch layout): only frames whose two
    ends sit on different hosts count — intra-host traffic rides the
    hive loopback. Frame sizes come from the same real encoders as
    wire_round_bytes; host-crossing fractions are the even-spread
    estimate ((hosts−1)/hosts of a uniform fan-out crosses).

    overlay=True prices the aggregation tree (docs/OVERLAY.md): verify
    traffic is unchanged (point-to-point by design); secure-agg share
    fan-out collapses to one aggregate per (subtree, miner); plain-mode
    update fan-out crosses once per remote miner-holding subtree and
    the block broadcast once per remote subtree instead of once per
    remote peer."""
    verify, submit, block = _round_frame_bytes(cfg, w64, accepted,
                                               codec=codec)
    n = cfg.num_nodes
    n_s = cfg.num_samples
    m = cfg.num_miners
    v = cfg.num_verifiers
    h = max(1, int(hosts))
    remote_frac = (h - 1) / h
    if not overlay:
        return int(remote_frac * (n_s * (v * verify + m * submit)
                                  + (n - 1) * block))
    cross = remote_frac * n_s * v * verify  # verdict traffic: unchanged
    if cfg.secure_agg:
        # offers ride loopback; one aggregate (≈ one submit frame — the
        # summed tensors have identical shapes) per (subtree, miner)
        cross += remote_frac * h * m * submit
    else:
        # one relayed copy per remote host holding >= 1 miner
        cross += n_s * min(m, h - 1) * submit
    cross += (h - 1) * block  # one block crossing per remote subtree
    return int(cross)


def bench_config(name, cfg, device_iters=10, metrics=None):
    import jax
    import numpy as np

    from biscotti_tpu.crypto import commitments as cm
    from biscotti_tpu.ops import secretshare as ss
    from biscotti_tpu.parallel.sim import Simulator

    _progress(f"{name}: building simulator")
    # NB: bench drives round_step() directly, so the registry feeds the
    # bench-level biscotti_bench_* families below, not Simulator.run()'s
    # per-round instrumentation (that is the sim CLI's --metrics-out)
    sim = Simulator(cfg)
    w, stake = sim.init_state()
    _progress(f"{name}: compiling device round")

    # --- device round: all peers' SGD + noise + defense + aggregation
    for it in range(2):
        w, stake, mask, err = sim.round_step(w, stake, it)
    jax.block_until_ready(w)
    t0 = time.perf_counter()
    for it in range(2, 2 + device_iters):
        w, stake, mask, err = sim.round_step(w, stake, it)
    jax.block_until_ready(w)
    device_s = (time.perf_counter() - t0) / device_iters
    _progress(f"{name}: device round {device_s:.4f}s; measuring host crypto")
    accepted = int(np.asarray(mask).sum())

    d = sim.num_params
    k = cfg.poly_size
    total_shares = cfg.total_shares
    per_miner = cfg.shares_per_miner
    # device-round FLOP estimate for the MFU column: per-contributor SGD
    # fwd+bwd ≈ 6·batch·params (dense-layer lower bound — conv layers
    # reuse weights, so CNN rows undercount), Krum's pairwise-distance
    # matmul 2·n²·d, aggregation n·d
    n_s = cfg.num_samples
    flops = (6.0 * cfg.batch_size * d * n_s
             + (2.0 * n_s * n_s * d if cfg.defense.value == "KRUM" else 0)
             + n_s * d)
    row = {
        "dataset": cfg.dataset, "nodes": cfg.num_nodes, "params": d,
        "defense": cfg.defense.value, "secure_agg": cfg.secure_agg,
        "noising": cfg.noising, "poison": cfg.poison_fraction,
        "device_round_s": round(device_s, 6),
        "device_gflops_est": round(flops / 1e9, 3),
        # fraction of one v5e's bf16 peak the device round achieves —
        # see PEAK_FLOPS_BF16 note for why this is honestly tiny
        "mfu": round(flops / max(device_s, 1e-9) / PEAK_FLOPS_BF16, 8),
        "accepted_per_round": accepted,
        "final_error": round(float(err), 4),
    }

    # --- host crypto, measured per-op then composed into the critical path
    delta = np.asarray(w, np.float64)  # representative d-vector
    scale = 10.0 ** cfg.precision
    q = np.trunc(delta * scale).astype(np.int64)
    # CNN-sized models: one timed repetition is enough (each crypto pass is
    # seconds long and variance is low) — keeps the whole 5-config bench
    # inside a driver-friendly wall-clock budget
    reps = 1 if d > 20_000 else 2
    if cfg.secure_agg:
        c = ss.num_chunks(d, k)
        padded = np.zeros(c * k, np.int64)
        padded[:d] = q
        chunks = padded.reshape(c, k)
        xs_all = [i - ss.SHARE_OFFSET for i in range(total_shares)]

        comms = br = sh = None

        def worker():
            nonlocal comms, br, sh
            comms, blinds = cm.vss_commit_chunks(chunks, b"bench-seed" * 3,
                                                 b"ctx")
            br = cm.vss_blind_rows(blinds, xs_all)
            sh = np.asarray(ss.make_shares(q, k, total_shares))

        worker_s = _timeit(worker, warm=1, iters=reps)
        sl = slice(0, per_miner)
        intake = max(1, cfg.num_samples // 2)

        # miner cost, PIPELINED engine (cfg.pipeline + cfg.batch_intake,
        # the runtime's shipping configuration for this bench): arriving
        # share slices fold into the round's VSS accumulator as they
        # land (`fold` — amortized against the intake network window,
        # off the mint path), and mint time pays ONLY the RLC settle —
        # one C·k-point MSM + the lhs comb (VssIntakeBatch.verify).
        c_chunks = ss.num_chunks(d, k)

        def fold_intake():
            acc = cm.VssIntakeBatch(per_miner, c_chunks, k)
            for sidx in range(intake):
                acc.add(sidx, comms, sh[sl], br[sl])
            acc.fold()
            return acc

        t0 = time.perf_counter()
        accs = [fold_intake() for _ in range(reps)]
        fold_s = (time.perf_counter() - t0) / reps
        assert accs[0].verify(xs_all[sl]), "intake settle failed"  # + warm
        miner_s = _timeit(lambda: accs[0].verify(xs_all[sl]),
                          warm=0, iters=reps)
        # the pre-pipeline lump (one-shot vss_verify_multi over the whole
        # intake at mint) — kept for trajectory continuity with
        # BENCH_r02–r05, whose miner_crypto_s was exactly this
        instances = [(comms, xs_all[sl], sh[sl], br[sl])] * intake
        oneshot_s = _timeit(lambda: cm.vss_verify_multi(instances),
                            warm=0, iters=reps)

        # recovery (+ correctness: the int64 pipeline round-trips exactly)
        agg = np.asarray(ss.aggregate_shares(sh[None].repeat(3, axis=0)))
        xs_arr = np.asarray(ss.share_xs(total_shares))

        def recover():
            return np.asarray(ss.recover_update(agg, xs_arr, d, k,
                                                cfg.precision))

        recover_s = _timeit(recover, warm=1, iters=reps)
        rec = recover()
        roundtrip_ok = bool(np.allclose(rec, 3 * q / scale, atol=1e-9))

        # --- accelerator-resident crypto (ISSUE 13, --device-crypto):
        # the SAME mint-time settle with the kernel plane armed
        # (miner_crypto_device_s), plus the device MSM throughput at
        # this config's grid width (msm_points_per_s). Gated by
        # availability and dimensionality: on this bench box the XLA
        # *CPU* backend emulates the limb kernels, so CNN-sized grids
        # are priced out by default — raise BISCOTTI_BENCH_DEVICE_MAX_D
        # on a real accelerator, where the kernels are the point.
        from biscotti_tpu.crypto import kernels as dk

        device_cap = int(os.environ.get("BISCOTTI_BENCH_DEVICE_MAX_D",
                                        "2048"))
        if dk.available() and c_chunks * k <= device_cap:
            dk.set_enabled(True)
            try:
                acc_dev = fold_intake()
                assert acc_dev.verify(xs_all[sl]), "device settle failed"
                if acc_dev._acc_dev is None:
                    # a device fault failed the batch over to CPU
                    # (VssIntakeBatch._device_failover): recording the
                    # CPU settle as a device number would be a lie, and
                    # dk.msm(·, None) would sink the bench — skip the
                    # device keys for this config, loudly
                    _progress(f"{name}: device settle failed over to "
                              f"CPU — device keys skipped")
                else:
                    dev_s = _timeit(lambda: acc_dev.verify(xs_all[sl]),
                                    warm=0, iters=reps)
                    row["miner_crypto_device_s"] = round(dev_s, 4)
                    n_pts = c_chunks * k
                    # RLC-shaped odd ~128-bit scalars (the ladder's cost
                    # is scalar-width independent; match the lhs shape)
                    gammas = [((i + 3)
                               * 0x9E3779B97F4A7C15F39CC0605CEDC835) | 1
                              for i in range(n_pts)]
                    msm_t = _timeit(
                        lambda: dk.msm(gammas, acc_dev._acc_dev),
                        warm=1, iters=reps)
                    row["msm_points_per_s"] = round(
                        n_pts / max(msm_t, 1e-9))
            finally:
                dk.set_enabled(False)
        row.update({
            "worker_crypto_s": round(worker_s, 4),
            "miner_intake": intake,
            # mint-critical-path miner crypto under the pipelined engine
            # (intake folded on arrival; this is the settle)
            "miner_crypto_s": round(miner_s, 4),
            # amortized intake-fold budget for the WHOLE intake (runs on
            # the miner host during the round's network window)
            "miner_fold_s": round(fold_s, 4),
            # the pre-pipeline whole-intake lump (r02–r05 comparison row)
            "miner_crypto_oneshot_s": round(oneshot_s, 4),
            "recovery_s": round(recover_s, 4),
            "share_pipeline_roundtrip_ok": roundtrip_ok,
        })
        # serial composition, definitionally unchanged from r02–r05
        # (device + worker + one-shot miner lump + recovery)
        total = device_s + worker_s + oneshot_s + recover_s
        # pipelined composition (one peer per host, depth-1 overlap):
        # device SGD, worker crypto, and the miner's intake folding run
        # CONCURRENTLY on different hosts during the round window; the
        # serialized tail between intake-complete and block broadcast is
        # the settle + recovery. Steady-state s/iter = slowest
        # overlapped stage + the serialized mint tail.
        total_pipe = (max(device_s, worker_s, fold_s)
                      + miner_s + recover_s)
        row["round_total_pipelined_s"] = round(total_pipe, 4)
    else:
        # plain mode: hash commitment + miner recompute — negligible but
        # measured anyway
        import hashlib

        commit_s = _timeit(lambda: hashlib.sha256(q.tobytes()).digest(),
                           warm=1, iters=5)
        row.update({"worker_crypto_s": round(commit_s, 6),
                    "miner_crypto_s": round(commit_s * cfg.num_samples, 6)})
        total = device_s + commit_s * (1 + cfg.num_samples)
        row["round_total_pipelined_s"] = round(
            max(device_s, commit_s) + commit_s * cfg.num_samples, 4)

    row["round_total_s"] = round(total, 4)
    # --- wire data plane: cluster gossip bytes for one round, from the
    # REAL frame encoders (see wire_round_bytes) — raw64 vs the f32+zlib
    # operating point, so BENCH_*.json tracks communication, not just
    # compute (ISSUE 4; NET-SA's bottleneck axis)
    wire_raw = wire_round_bytes(cfg, delta, accepted, codec="raw64")
    wire_f32z = wire_round_bytes(cfg, delta, accepted, codec="f32+zlib")
    # overlay headline row (docs/OVERLAY.md): TCP-crossing bytes/round on
    # a 2-host hive fleet, flat fan-out vs the aggregation tree — the
    # claim is read straight off the artifact instead of hand-derived
    xh_flat = cross_host_round_bytes(cfg, delta, accepted, hosts=2,
                                     overlay=False)
    xh_overlay = cross_host_round_bytes(cfg, delta, accepted, hosts=2,
                                        overlay=True)
    row.update({
        "wire_bytes_per_round": wire_raw,
        "wire_bytes_per_round_f32_zlib": wire_f32z,
        "wire_compression_x": round(wire_raw / max(1, wire_f32z), 2),
        "cross_host_bytes_per_round": xh_flat,
        "cross_host_bytes_per_round_overlay": xh_overlay,
        "overlay_cross_host_saving_x": round(
            xh_flat / max(1, xh_overlay), 2),
    })
    if metrics is not None:
        g = metrics.gauge("biscotti_bench_wire_bytes_per_round",
                          "bench cluster gossip bytes per round")
        g.set(wire_raw, config=name, codec="raw64")
        g.set(wire_f32z, config=name, codec="f32+zlib")
        gx = metrics.gauge(
            "biscotti_bench_cross_host_bytes_per_round",
            "bench TCP-crossing bytes per round on a 2-host hive fleet")
        gx.set(xh_flat, config=name, overlay="off")
        gx.set(xh_overlay, config=name, overlay="on")
    if metrics is not None:
        # every component lands on the telemetry plane too, as one
        # histogram family labeled (config, phase) — rendered to
        # eval/results/bench_metrics.prom at the end of the run
        hist = metrics.histogram("biscotti_bench_phase_seconds",
                                 "bench critical-path component times")
        for phase_key, src in (("device_round", "device_round_s"),
                               ("worker_crypto", "worker_crypto_s"),
                               ("miner_crypto", "miner_crypto_s"),
                               ("miner_fold", "miner_fold_s"),
                               ("recovery", "recovery_s")):
            if src in row:
                hist.observe(row[src], config=name, phase=phase_key)
        metrics.gauge("biscotti_bench_round_total_seconds",
                      "bench crypto-inclusive s/iter").set(total, config=name)
        metrics.gauge(
            "biscotti_bench_round_pipelined_seconds",
            "bench crypto-inclusive s/iter, pipelined composition").set(
            row["round_total_pipelined_s"], config=name)
    _progress(f"{name}: serial {total:.3f}s/iter, "
              f"pipelined {row['round_total_pipelined_s']:.3f}s/iter")
    return name, row, total


def bench_peer_density(sizes=(100, 400, 1000), iterations=2,
                       budget_s=900.0):
    """Scale-frontier entry (ISSUE 9): LIVE hive-hosted clusters at
    N ∈ {100, 400, 1000} — real protocol rounds over the loopback
    transport with the batched device plane (runtime/hive.py), not a
    simulator row. Reports s/iter, peak RSS per co-hosted peer, and the
    chain-equality verdict, so BENCH_r*.json tracks the density frontier
    alongside the flagship round time. Each size runs as a subprocess
    (its RSS peak must be its own, not the bench driver's); a failed or
    timed-out size yields an error row, never a sunk bench.

    Set BISCOTTI_BENCH_DENSITY=0 to skip (e.g. memory-constrained CI)."""
    import subprocess

    if os.environ.get("BISCOTTI_BENCH_DENSITY", "1") == "0":
        return {"skipped": "BISCOTTI_BENCH_DENSITY=0"}
    out = {}
    deadline = time.time() + budget_s
    for n in sizes:
        name = f"n{n}"
        budget = deadline - time.time()
        if budget < 30.0:
            out[name] = {"error": "density budget exhausted"}
            continue
        _progress(f"peer_density: N={n} live hive "
                  f"({iterations} iterations)")
        cmd = [sys.executable, "-m", "biscotti_tpu.runtime.hive",
               "-t", str(n), "-d", "mnist",
               "--iterations", str(iterations),
               "-sa", "0", "-np", "0", "-vp", "1", "--seed", "3"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            proc = subprocess.run(
                cmd, cwd=os.path.dirname(os.path.abspath(__file__)),
                env=env, capture_output=True, text=True, timeout=budget)
            # one parser for the hive summary format (pod_launch is the
            # other consumer — shared so the two can't drift)
            from biscotti_tpu.tools.pod_launch import hive_summary

            s = hive_summary(proc.stdout)
            if s is None:
                # died before printing its summary (OOM-kill is the
                # expected failure mode at N=1000): record the exit code
                # and the stderr tail, or the density row is undebuggable
                out[name] = {"error": f"no summary (rc={proc.returncode})",
                             "stderr_tail": proc.stderr[-800:]}
                _progress(f"peer_density: N={n} failed rc="
                          f"{proc.returncode}")
                continue
            out[name] = {
                "peers": s["peers"],
                "blocks": s["blocks"],
                "chains_equal": s["chains_equal_local"],
                "s_per_iter": s["s_per_iter"],
                "rss_peak_mb": round(s["rss_peak_bytes"] / 2**20, 1),
                "rss_per_peer_mb": round(
                    s["rss_per_peer_bytes"] / 2**20, 2),
                "loop_lag_s": s["loop_lag_s"],
            }
            _progress(f"peer_density: N={n} {s['s_per_iter']}s/iter, "
                      f"{out[name]['rss_per_peer_mb']}MB/peer, "
                      f"chains_equal={s['chains_equal_local']}")
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            _progress(f"peer_density: N={n} failed: {out[name]['error']}")
    return out


def bench_crypto_kernel(widths=(8, 35, 100)):
    """Device-crypto microbench (ISSUE 13): CPU vs device MSM across
    intake widths — the RLC lhs Σγᵢ·Cᵢ shape whose width is the number
    of commitments a miner batched. Reports per-width seconds and
    points/s for both paths (device timings are steady-state: one warm
    call absorbs the per-shape XLA compile), so the BENCH artifact shows
    device MSM throughput scaling with intake width. The per-config
    `miner_crypto_device_s` / `msm_points_per_s` keys in the main table
    carry the same story at each config's full grid dimensionality.

    Set BISCOTTI_BENCH_CRYPTO_KERNEL=0 to skip."""
    if os.environ.get("BISCOTTI_BENCH_CRYPTO_KERNEL", "1") == "0":
        return {"skipped": "BISCOTTI_BENCH_CRYPTO_KERNEL=0"}
    from biscotti_tpu.crypto import commitments as cm
    from biscotti_tpu.crypto import ed25519 as ed
    from biscotti_tpu.crypto import kernels as dk

    if not dk.available():
        return {"skipped": f"device kernels unavailable "
                           f"({dk.availability_reason()})"}
    _progress(f"crypto_kernel: CPU vs device MSM at widths {widths}")
    key = cm.CommitKey.generate(max(widths), label=b"bench-msm")
    out = {}
    for w in widths:
        pts = key.points[:w]
        scalars = [((i + 3) * 0x9E3779B97F4A7C15F39CC0605CEDC835) | 1
                   for i in range(w)]
        # the parity check reuses the timed runs' last results — no
        # extra MSM just to compare
        res = {}
        cpu_s = _timeit(lambda: res.__setitem__("cpu",
                                                cm.msm(scalars, pts)),
                        warm=1, iters=3)
        dev_s = _timeit(lambda: res.__setitem__("dev",
                                                dk.msm(scalars, pts)),
                        warm=1, iters=3)
        ok = ed.point_equal(res["cpu"], res["dev"])
        out[f"w{w}"] = {
            "cpu_msm_s": round(cpu_s, 5),
            "device_msm_s": round(dev_s, 5),
            "cpu_msm_points_per_s": round(w / max(cpu_s, 1e-9)),
            "device_msm_points_per_s": round(w / max(dev_s, 1e-9)),
            "results_equal": bool(ok),
        }
        _progress(f"crypto_kernel: w={w} cpu {cpu_s:.4f}s "
                  f"device {dev_s:.4f}s equal={bool(ok)}")
    return out


def bench_straggler_degradation(n=10, rounds=3, budget_s=600.0):
    """Straggler-degradation entry (ISSUE 10): LIVE mnist clusters with
    0% / 10% / 20% of peers on a seeded 4x compute-slowdown profile
    (runtime/faults.FaultPlan slow kind), fixed vs adaptive deadlines —
    the mean-round-time degradation curve the straggler-tolerance plane
    exists to flatten, tracked across PRs in the BENCH artifact. Runs
    in-process (the chaos harness pattern): secure-agg + verification on
    so the slowed paths (SGD + worker/miner crypto) actually carry the
    round, rounds measured off the anchor's per-iteration log stamps.

    Set BISCOTTI_BENCH_STRAGGLER=0 to skip."""
    import asyncio

    if os.environ.get("BISCOTTI_BENCH_STRAGGLER", "1") == "0":
        return {"skipped": "BISCOTTI_BENCH_STRAGGLER=0"}

    from biscotti_tpu.config import BiscottiConfig, Timeouts
    from biscotti_tpu.runtime.faults import FaultPlan
    from biscotti_tpu.runtime.peer import PeerAgent
    from biscotti_tpu.tools.chaos import chain_oracle

    fast = Timeouts(update_s=12.0, block_s=30.0, krum_s=5.0, share_s=12.0,
                    rpc_s=8.0)

    def plan_for(frac):
        """Seeded plan drawing EXACTLY round(frac*n) slow peers: the
        per-node draw is probabilistic, so scan seeds for the one whose
        table hits the target count — deterministic once found, and the
        chosen seed rides into the artifact for replay."""
        want = int(round(frac * n))
        if want == 0:
            return FaultPlan(), 0
        for seed in range(500):
            p = FaultPlan(seed=seed, slow=frac, slow_factor=4.0)
            if len(p.slow_table(n)) == want:
                return p, seed
        # no seed hit the exact count (tiny n edge): pin node 1
        return FaultPlan(slow_node=1, slow_factor=4.0), -1

    def run_case(plan, adaptive, port):
        def cfg(i):
            return BiscottiConfig(
                node_id=i, num_nodes=n, dataset="mnist", base_port=port,
                num_verifiers=1, num_miners=1, num_noisers=1,
                secure_agg=True, noising=False, verification=True,
                max_iterations=rounds, convergence_error=0.0,
                sample_percent=1.0, batch_size=10, timeouts=fast, seed=3,
                fault_plan=plan, adaptive_deadlines=adaptive)

        async def go():
            agents = [PeerAgent(cfg(i)) for i in range(n)]
            return await asyncio.gather(*(a.run() for a in agents))

        results = asyncio.run(go())
        eq, _, real = chain_oracle(results)
        stamps = [float(x.split(",")[2]) for x in results[0]["logs"]]
        mean_round = ((stamps[-1] - stamps[0]) / (len(stamps) - 1)
                      if len(stamps) >= 2 else None)
        excluded = sum(
            sum((r["telemetry"]["stragglers"]["excluded"] or {}).values())
            for r in results)
        return {"mean_round_s": (round(mean_round, 4)
                                 if mean_round is not None else None),
                "chains_equal": eq, "real_blocks": real,
                "straggler_excluded": excluded}

    out = {}
    deadline = time.time() + budget_s
    # listen ports BELOW the box's ephemeral range (16000+ here): an
    # earlier case's lingering outbound socket can otherwise squat the
    # next case's listen port (the documented cross-cluster bind flake)
    port = 14310
    # throwaway warm-up: the FIRST live cluster in the process pays the
    # mnist shard load + XLA compile inside its first round — without
    # this the slow0_fixed baseline absorbs ~20 s of one-time cost and
    # the whole degradation curve reads as an improvement
    _progress("straggler_degradation: warm-up cluster (discarded)")
    try:
        run_case(FaultPlan(), False, port)
        port += n + 3
    except Exception as e:
        _progress(f"straggler_degradation: warm-up failed: {e}")
    for frac in (0.0, 0.10, 0.20):
        plan, seed = plan_for(frac)
        slowed = len(plan.slow_table(n))
        for adaptive in (False, True):
            name = f"slow{int(frac * 100)}_" \
                   f"{'adaptive' if adaptive else 'fixed'}"
            if time.time() > deadline - 30:
                out[name] = {"error": "straggler budget exhausted"}
                continue
            _progress(f"straggler_degradation: {name} "
                      f"({slowed}/{n} peers at 4x)")
            try:
                row = run_case(plan, adaptive, port)
                row.update(slowed_peers=slowed, slow_seed=seed,
                           slow_factor=4.0)
                out[name] = row
                _progress(f"straggler_degradation: {name} "
                          f"{row['mean_round_s']}s/round, "
                          f"chains_equal={row['chains_equal']}")
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
                _progress(f"straggler_degradation: {name} failed: "
                          f"{out[name]['error']}")
            port += n + 3
    # the headline ratio: how much a 20% slow fleet degrades the round
    # under fixed vs adaptive deadlines (None until both rows exist)
    base = (out.get("slow0_fixed") or {}).get("mean_round_s")
    for k in ("slow20_fixed", "slow20_adaptive"):
        row = out.get(k) or {}
        if base and row.get("mean_round_s"):
            row["vs_homogeneous"] = round(row["mean_round_s"] / base, 2)
    return out


def bench_attack_matrix(budget_s: float = 600.0):
    """Attack-matrix guard cells (ISSUE 14): the static-vs-adaptive
    poisoner pair under the accept-mask defenses, live at the matrix's
    operating point (eval/eval_attack_matrix.py --quick). The full
    matrix is the eval artifact (eval/results/attack_matrix.json);
    these rows ride the BENCH artifact so `tools/bench_diff` fails
    loudly when a future PR flips a survived cell (`failed` 0 -> 1) or
    lets more poisoned sources through (`accepted_poisoned_n`).

    Set BISCOTTI_BENCH_ATTACK=0 to skip."""
    if os.environ.get("BISCOTTI_BENCH_ATTACK", "1") == "0":
        return {"skipped": "BISCOTTI_BENCH_ATTACK=0"}

    import importlib.util
    from types import SimpleNamespace

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "eval", "eval_attack_matrix.py")
    spec = importlib.util.spec_from_file_location("eval_attack_matrix",
                                                  path)
    am = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(am)

    from biscotti_tpu.config import Defense

    # the matrix driver's default operating point (mnist@dir0.3, 10
    # nodes, 3 verifiers, one seed) — per-cell calls so the budget is
    # enforced BETWEEN cells like every sibling bench entry
    ns = SimpleNamespace(nodes=10, verifiers=3, rounds=8, seed=11,
                         poison=0.3, flood=30, dataset="mnist@dir0.3")
    # hug x ENSEMBLE is THE tentpole guard (ISSUE 16): the adaptive
    # defense plane's claim is exactly this cell flipping to survived —
    # a future PR that un-survives the hugger fails the bench_diff gate
    cells = [("static", Defense.KRUM), ("hug", Defense.KRUM),
             ("static", Defense.FOOLSGOLD), ("hug", Defense.FOOLSGOLD),
             ("hug", Defense.ENSEMBLE)]
    out = {"complete": True}
    deadline = time.time() + budget_s
    port = 14190
    for camp, d in cells:
        name = f"{camp}_{d.value.lower()}"
        if time.time() > deadline - 30:
            out[name] = {"error": "attack-matrix budget exhausted"}
            out["complete"] = False
            continue
        _progress(f"attack_matrix: {name} (live cell)")
        try:
            row = am.run_cell(camp, d, True, port, ns)
            # the survival bits (failed / accepted_poisoned_n) are the
            # regression-gated keys; the live-cluster error is noisy
            # run-to-run (round intake varies with box load), so it
            # rides as `anchor_error` — informational, outside the
            # bench_diff final_error regress pattern
            out[name] = {k: row[k] for k in
                         ("chains_equal", "survived",
                          "failed", "accepted_poisoned_n")}
            out[name]["anchor_error"] = row["final_error"]
            _progress(f"attack_matrix: {name} survived="
                      f"{row['survived']} err={row['final_error']}")
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            out["complete"] = False
            _progress(f"attack_matrix: {name} failed: "
                      f"{out[name]['error']}")
        port += ns.nodes + 2
    return out


def bench_migration(n=100, iterations=2, budget_s=600.0):
    """Migration-cost entry (ISSUE 19): a LIVE two-hive cluster at N=100
    under the placement controller with a rigged hot-host signal so
    every decision point actually moves peers — reporting per-move
    downtime and ticket size (`migration_downtime_s` /
    `migration_bytes`, the two lower-is-better keys tools/bench_diff
    gates). The rig goes through the controller's signals_fn seam: on
    one box the real hive gauges are process-wide, so both hives read
    equally hot and nothing would move — the injection makes the COST
    measurable without faking the decision function itself
    (docs/PLACEMENT.md).

    Set BISCOTTI_BENCH_MIGRATION=0 to skip."""
    if os.environ.get("BISCOTTI_BENCH_MIGRATION", "1") == "0":
        return {"skipped": "BISCOTTI_BENCH_MIGRATION=0"}
    import asyncio

    from biscotti_tpu.config import BiscottiConfig
    from biscotti_tpu.runtime import placement
    from biscotti_tpu.runtime.hive import LoopbackHub
    from biscotti_tpu.runtime.membership import surviving_prefix_oracle
    from biscotti_tpu.runtime.peer import PeerAgent

    _progress(f"migration: N={n} two-hive cluster, rigged hot host")
    plan = placement.PlacementPlan(enabled=True, seed=0, interval=1,
                                   max_moves=2, lag_hot_s=0.05)
    layout = placement.hive_layout(n, 2)
    hive_ids = [f"host{i}" for i in range(len(layout))]
    assignment = {}
    for hid, (start, count) in zip(hive_ids, layout):
        for node in range(start, start + count):
            assignment[node] = hid
    cfg = BiscottiConfig(
        num_nodes=n, dataset="creditcard", base_port=15700,
        num_verifiers=1, num_miners=1, num_noisers=1,
        secure_agg=False, noising=False, verification=False,
        max_iterations=iterations, convergence_error=0.0,
        sample_percent=1.0, batch_size=8, seed=3,
        placement_plan=plan)
    cfg = cfg.replace(timeouts=cfg.timeouts.scaled(
        n, cfg.num_verifiers, cfg.num_miners))
    hubs = {hid: LoopbackHub() for hid in hive_ids}

    def make_agent(node, hive_id, ticket):
        return PeerAgent(cfg.replace(node_id=node), hive=hubs[hive_id],
                         ticket=ticket)

    def rigged_signals(assignment, agents):
        by = {}
        for node, hid in sorted(assignment.items()):
            by.setdefault(hid, []).append(node)
        return [placement.HostSignals(
            hive_id=hid, peers=tuple(nodes),
            loop_lag_s=1.0 if hid == hive_ids[0] else 0.0)
            for hid, nodes in sorted(by.items())]

    ctl = placement.PlacementController(make_agent, assignment, plan,
                                        signals_fn=rigged_signals)
    try:
        results = asyncio.run(asyncio.wait_for(ctl.run(), budget_s))
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    equal, settled, real = surviving_prefix_oracle(results)
    moves = len(ctl.moves_applied)
    out = {
        "peers": n, "iterations": iterations, "moves": moves,
        "chains_equal": equal, "settled_height": settled,
        "real_blocks": real,
    }
    if moves:
        out["migration_downtime_s"] = round(
            sum(ctl.downtimes_s) / moves, 4)
        out["downtime_max_s"] = round(max(ctl.downtimes_s), 4)
        out["migration_bytes"] = int(sum(ctl.ticket_bytes) / moves)
        out["ticket_bytes_max"] = max(ctl.ticket_bytes)
    _progress(f"migration: {moves} moves, "
              f"{out.get('migration_downtime_s', '-')}s/move, "
              f"{out.get('migration_bytes', '-')}B/ticket, "
              f"chains_equal={equal}")
    return out


def main():
    import jax

    from biscotti_tpu.config import BiscottiConfig, Defense

    jax.config.update("jax_enable_x64", True)

    base = dict(batch_size=10, epsilon=1.0, sample_percent=0.70,
                num_verifiers=3, num_miners=3, num_noisers=2, seed=0)
    configs = [
        # BASELINE.json "configs" rows, in order
        ("creditcard_10", BiscottiConfig(
            dataset="creditcard", num_nodes=10, secure_agg=True,
            noising=True, verification=True, defense=Defense.KRUM, **base)),
        ("mnist_100_clean", BiscottiConfig(
            dataset="mnist", num_nodes=100, secure_agg=True, noising=False,
            verification=True, defense=Defense.KRUM, **base)),
        ("mnist_100_poison30_krum", BiscottiConfig(
            dataset="mnist", num_nodes=100, secure_agg=True, noising=True,
            verification=True, defense=Defense.KRUM, poison_fraction=0.30,
            **base)),
        ("mnist_100_dp_eps1", BiscottiConfig(
            dataset="mnist", num_nodes=100, secure_agg=True, noising=True,
            verification=True, defense=Defense.KRUM, **base)),
        ("cifar_lenet_100_krum_secagg", BiscottiConfig(
            dataset="cifar", model_name="cifar_cnn", num_nodes=100,
            secure_agg=True, noising=False, verification=True,
            defense=Defense.KRUM, **base)),
        # remaining reference model families (ML/Pytorch/mnist_cnn_model.py,
        # lfw_cnn_model.py, svm_model.py) — no published fleet numbers, so
        # vs_baseline stays null; rows prove every family runs the full
        # crypto-inclusive round at reference dimensions
        ("mnist_cnn_100_krum_secagg", BiscottiConfig(
            dataset="mnist", model_name="mnist_cnn", num_nodes=100,
            secure_agg=True, noising=False, verification=True,
            defense=Defense.KRUM, **base)),
        ("lfw_cnn_100_krum_secagg", BiscottiConfig(
            dataset="lfw", model_name="lfw_cnn", num_nodes=100,
            secure_agg=True, noising=False, verification=True,
            defense=Defense.KRUM, **base)),
        ("svm_mnist_100_krum_secagg", BiscottiConfig(
            dataset="mnist", model_name="svm", num_nodes=100,
            secure_agg=True, noising=False, verification=True,
            defense=Defense.KRUM, **base)),
    ]

    from biscotti_tpu.telemetry import MetricsRegistry

    registry = MetricsRegistry(max_label_sets=256)  # 8 configs × phases
    rows = {}
    headline_total = None
    for name, cfg in configs:
        iters = 4 if cfg.model_name else 10  # CNN/svm rows: fewer reps
        try:
            name, row, total = bench_config(name, cfg, device_iters=iters,
                                            metrics=registry)
        except Exception as e:  # a config must never sink the whole bench
            rows[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        # only the mnist SOFTMAX rows compare against the reference's 38.2
        # s/iter fleet number (same model family); cnn/svm/lfw rows have no
        # published counterpart
        if name.startswith("mnist_100"):
            row["vs_baseline"] = round(BASELINE_MNIST_S_PER_ITER / total, 2)
        else:
            row["vs_baseline"] = None  # reference published no number
        rows[name] = row
        if name == "mnist_100_dp_eps1":
            # headline = the PIPELINED engine's steady-state s/iter (the
            # runtime this PR ships); the serial composition stays in the
            # row as round_total_s for the r02–r05 trajectory
            headline_total = row["round_total_pipelined_s"]

    # scale frontier: live hive-hosted peer density (one box, real
    # rounds) — the number the hive runtime exists to move
    density = bench_peer_density()

    # straggler-degradation curve (ISSUE 10): live mnist round time at
    # 0/10/20% slowed peers, fixed vs adaptive deadlines
    straggler = bench_straggler_degradation()

    # attack-matrix guard cells (ISSUE 14): static vs adaptive poisoner
    # under the accept-mask defenses — bench_diff fails loudly when a
    # survived cell flips
    attack_matrix = bench_attack_matrix()

    # migration-cost entry (ISSUE 19): per-move downtime + ticket bytes
    # through the live placement controller at N=100 — the two
    # lower-is-better keys bench_diff gates for the elastic fleet plane
    migration = bench_migration()

    # device-crypto microbench (ISSUE 13): CPU vs device MSM across
    # intake widths {8, 35, 100} — the scaling evidence for the
    # accelerator-resident crypto plane
    crypto_kernel = bench_crypto_kernel()
    if registry is not None and isinstance(crypto_kernel, dict):
        msm_gauge = registry.gauge(
            "biscotti_bench_msm_points_per_s",
            "bench MSM throughput by path across intake widths")
        for wname, r in crypto_kernel.items():
            if isinstance(r, dict) and "cpu_msm_points_per_s" in r:
                msm_gauge.set(r["cpu_msm_points_per_s"], width=wname,
                              path="cpu")
                msm_gauge.set(r["device_msm_points_per_s"], width=wname,
                              path="device")

    detail = {
        "device": str(jax.devices()[0]),
        "data_note": ("synthetic Gaussian shards at reference dimensions "
                      "(zero-egress env): timings comparable, error columns "
                      "not"),
        "configs": rows,
        "peer_density": density,
        "straggler_degradation": straggler,
        "attack_matrix": attack_matrix,
        "migration": migration,
        "crypto_kernel": crypto_kernel,
    }
    # Full per-config detail goes to a file + stderr; stdout carries exactly
    # ONE compact JSON line so the driver's parser always succeeds
    # (BENCH_r02 "parsed": null was the oversized inline line).
    detail_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "eval", "results", "bench_detail.json")
    try:
        os.makedirs(os.path.dirname(detail_path), exist_ok=True)
        with open(detail_path, "w") as f:
            json.dump(detail, f, indent=1)
        _progress(f"per-config detail written to {detail_path}")
        # the same numbers in Prometheus text form, for dashboard ingest
        prom_path = os.path.join(os.path.dirname(detail_path),
                                 "bench_metrics.prom")
        with open(prom_path, "w") as f:
            f.write(registry.render())
        _progress(f"telemetry page written to {prom_path}")
    except OSError as e:
        _progress(f"could not write detail file: {e}")
    print(json.dumps(detail), file=sys.stderr, flush=True)
    serial_total = rows.get("mnist_100_dp_eps1", {}).get("round_total_s")
    out = {
        "metric": ("crypto-inclusive s/iter, 100-peer MNIST softmax + Krum "
                   "+ DP eps=1.0 + secure-agg, pipelined round engine "
                   "(ref fleet: 38.2 s/iter)"),
        "value": round(headline_total, 4) if headline_total else None,
        "unit": "s/iter",
        # the pipelined value composes MEASURED components under the
        # depth-1 one-peer-per-host overlap model (see bench_config);
        # the serial sum of the same components rides along so the
        # modeled number never stands alone
        "serial_s_per_iter": serial_total,
        "vs_baseline": (round(BASELINE_MNIST_S_PER_ITER / headline_total, 2)
                        if headline_total else None),
        # live peer-density frontier (hive runtime, runtime/hive.py):
        # s/iter + per-peer RSS at N ∈ {100,400,1000} co-hosted on this
        # box, chains verified equal — tracks the scale wall, not just
        # the flagship round
        "peer_density": density,
        # straggler-degradation curve (runtime/stragglers.py): live
        # mnist mean round time at 0/10/20% peers on the 4x slow
        # profile, fixed vs adaptive deadlines — the robustness number
        # the straggler-tolerance plane exists to move
        "straggler_degradation": straggler,
        # attack-matrix guard cells (runtime/adversary.py): survival +
        # accepted-poison bits for the static/hug x KRUM/FOOLSGOLD
        # cells — a flipped survived cell is a bench_diff regression
        # (docs/ADVERSARY.md; full matrix in eval/results/)
        "attack_matrix": attack_matrix,
        # migration cost (runtime/placement.py): mean per-move downtime
        # + ticket bytes through the live controller at N=100 — a PR
        # that makes moves slower or tickets fatter is a bench_diff
        # regression (docs/PLACEMENT.md)
        "migration": migration,
        # device-crypto microbench (crypto/kernels): CPU vs device MSM
        # across intake widths — the scaling evidence behind
        # --device-crypto (docs/CRYPTO_KERNELS.md)
        "crypto_kernel": crypto_kernel,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
