"""biscotti_tpu — a TPU-native decentralized secure federated-learning framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of Biscotti
(arXiv:1811.09904; reference implementation in Go + embedded CPython):
peer-to-peer multi-party ML where N peers each hold a private shard, take
local SGD steps, and commit one global model per blockchain block, with

  * stake-weighted VRF role election (verifier / miner / noiser committees),
  * differential-privacy noising (pre-sampled Gaussian, committee-averaged),
  * Krum / RONI Byzantine-update filtering,
  * polynomial-commitment + Shamir-secret-sharing secure aggregation.

Design stance (see SURVEY.md §7): all round math — local SGD, DP noise,
Krum's O(n²) distance scan, quantization, share generation / homomorphic
aggregation / recovery — is jitted XLA over device buffers; peers map to a
vmapped batch on one chip (simulation) or to hosts over a gRPC-style mesh
(deployment); the ledger, VRF, and elliptic-curve crypto live in the host
control plane (C++ native library + Python orchestration).
"""

__version__ = "0.1.0"

from biscotti_tpu.config import BiscottiConfig, Defense  # noqa: F401
