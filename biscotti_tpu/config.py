"""Typed configuration covering the reference's full flag + constant surface.

Mirrors the CLI flags of the reference protocol binary
(ref: DistSys/main.go:613-649) and its compile-time constants
(ref: DistSys/main.go:28-60), plus TPU topology fields that have no
reference analogue. Derived quantities (NUM_SAMPLES, KRUM_UPDATETHRESH,
TOTAL_SHARES, collusion threshold; ref: DistSys/main.go:670-687,825-831)
are computed properties so they can never drift from the primary fields.
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import math
from dataclasses import dataclass, field

# stdlib-only modules (hash-derived decisions, breaker state machine,
# token buckets): safe to import here without dragging the asyncio
# runtime into config users
from biscotti_tpu.ops.trust import TrustPlan
from biscotti_tpu.runtime.admission import AdmissionPlan
from biscotti_tpu.runtime.adversary import CAMPAIGNS, CampaignPlan
from biscotti_tpu.runtime.faults import SLOW_PRESETS, FaultPlan
from biscotti_tpu.runtime.placement import PlacementPlan


class Defense(str, enum.Enum):
    """Poisoning-defense selection (ref: DistSys/main.go:57 POISON_DEFENSE).

    MULTIKRUM / TRIMMED_MEAN have no reference analogue — they are the
    non-IID-robust options (ops/robust_agg.py) covering the regime where
    vanilla Krum's closest-neighbour score fails (Dirichlet-skewed shards;
    see poison_mnist_dir0.3_100.json heterogeneity_note).

    Trade-off to understand before picking TRIMMED_MEAN on the live
    protocol: it is an aggregation rule with NO per-update reject, so the
    block-level stake penalty never fires — poisoners keep earning stake
    (and committee lottery weight) even while their coordinate values are
    trimmed out of every aggregate. Where the proof-of-stake deterrent
    matters, prefer MULTIKRUM (a verifier accept mask like KRUM: rejected
    updates are stake-debited) or run TRIMMED_MEAN only in simulator/
    FedSys-style settings where stake does not gate committee election."""

    NONE = "NONE"
    KRUM = "KRUM"
    RONI = "RONI"
    MULTIKRUM = "MULTIKRUM"
    TRIMMED_MEAN = "TRIMMED_MEAN"
    # FoolsGold-style mutual-similarity outlier rejection (robust_agg.py):
    # an accept-mask defense like KRUM, so it composes with secure-agg and
    # the stake penalty; targets the sybil-shaped attack the reference
    # ships (near-duplicate poisoned shards) where it separates under
    # Dirichlet skew that defeats vanilla Krum. Scoring is single-round on
    # the copies the verifier sees: with committee noising at ε=1.0 and
    # mnist dims the DP noise masks update geometry and EVERY geometry
    # defense (this one and Krum alike) degrades toward accept-everyone —
    # its demonstrated win is the noising-off defense-geometry operating
    # point (see ops/robust_agg.py OPERATING POINT note)
    FOOLSGOLD = "FOOLSGOLD"
    # Adaptive defense plane (ops/trust.py, docs/DEFENSES.md): the
    # cross-round TrustLedger composes Krum geometry, keep-set-calibrated
    # pairwise similarity, a magnitude band, a temporal-drift scorer fed
    # by the committed chain's accept/reject walk, and a stake-weighted
    # slow-trust ramp into ONE accept mask with hysteresis. Still a
    # verifier accept-mask defense — rejection mechanics are exact parity
    # with KRUM/MULTIKRUM (worker declines, no record lands), so it
    # composes with secure aggregation; the evidence trail is the verdict
    # stream + trust snapshot. Built to close PR 14's measured hugger gap
    # (the threshold-walking poisoner that defeats memoryless Krum).
    ENSEMBLE = "ENSEMBLE"


@dataclass
class Timeouts:
    """Deadline-timer constants, in seconds (ref: DistSys/main.go:28-36).

    The reference scales these by node count and committee sizes at startup
    (ref: DistSys/main.go:786-825); `scaled()` reproduces that behavior.
    """

    update_s: float = 90.0
    block_s: float = 300.0
    krum_s: float = 60.0
    share_s: float = 90.0
    rpc_s: float = 120.0

    def scaled(self, num_nodes: int, num_verifiers: int, num_miners: int,
               random_sampling: bool = False,
               defense_is_krum: bool = True) -> "Timeouts":
        """The reference's startup scaling, rule for rule
        (ref: DistSys/main.go:786-825): the base constants are sized for
        100 nodes; random sampling doubles RPC+update deadlines; committees
        >10 at N=100 double the affected deadlines; N/100 (integer, so a
        no-op below 200 nodes) multiplies everything."""
        update_s, krum_s, rpc_s = self.update_s, self.krum_s, self.rpc_s
        block_s, share_s = self.block_s, self.share_s
        if defense_is_krum and random_sampling:
            rpc_s *= 2  # ref: main.go:788-791
            update_s *= 2
        if num_miners > 10 and num_nodes == 100:
            update_s *= 2  # ref: main.go:796-800
        if num_verifiers > 10 and num_nodes == 100:
            krum_s *= 2  # ref: main.go:802-807
            update_s *= 2
        mult = num_nodes // 100  # ref: main.go:810-825 (integer division)
        if mult >= 1:
            update_s *= mult
            krum_s *= mult
            block_s *= mult
            rpc_s *= mult
            share_s *= mult
        return Timeouts(update_s=update_s, block_s=block_s, krum_s=krum_s,
                        share_s=share_s, rpc_s=rpc_s)


@dataclass
class BiscottiConfig:
    # --- identity / topology (ref flags -i -t -p -pa -a, main.go:613-649) ---
    node_id: int = 0
    num_nodes: int = 10
    dataset: str = "creditcard"
    # model-zoo override: "" picks the dataset's default entry (softmax for
    # image sets, logreg for creditcard — the reference's client_obj.init
    # default); set e.g. "cifar_cnn" / "mnist_cnn" / "svm" for the CNN/SVM
    # stacks (ref: ML/Pytorch model files)
    model_name: str = ""
    peers_file: str = ""
    my_ip: str = "127.0.0.1"
    public_ip: str = ""
    base_port: int = 8000

    # --- committees (ref flags -na -nv -nn, main.go:629-633) ---
    num_miners: int = 3  # "aggregators" in the reference
    num_verifiers: int = 3
    num_noisers: int = 2

    # --- toggles (ref flags -sa -np -vp, main.go:635-641) ---
    secure_agg: bool = True
    noising: bool = True
    verification: bool = True
    # FedSys baseline mode: fixed leader (node 0) collects and AVERAGES
    # updates, no chain crypto/VRF/committees — the reference's separate
    # FedSys binary as a feature flag (ref: FedSys/main.go, SURVEY §2.5)
    fedsys: bool = False

    # --- privacy / attack (ref flags -ep -po -c, main.go:625,643-647) ---
    epsilon: float = 1.0
    delta: float = 1e-5
    poison_fraction: float = 0.0
    colluders: int = 0
    dp_in_model: bool = False  # DP_IN_MODEL mode (ref: main.go:155,860-864)
    # DP mechanism: "gaussian" = Abadi-16 presampled Gaussian (the
    # reference's default path, client_obj.py:59-67); "mcmc13" = the
    # Song&Sarwate'13 MCMC draw from exp(−ε/2·‖x‖) (the reference's
    # diffPriv13 branch, client_obj.py:44-57 — emcee there, a vectorized
    # Metropolis ensemble under lax.scan here, ops/dp_noise.py)
    dp_mechanism: str = "gaussian"

    # --- sampling (ref flags -ns -rs, main.go:645,649) ---
    sample_percent: float = 0.70  # NUM_SAMPLES = 70% of contributors
    random_sampling: bool = False
    krum_sample_size: int = 0  # 0 = use all collected updates

    # --- protocol constants (ref: DistSys/main.go:28-60) ---
    default_stake: int = 10  # DEFAULT_STAKE (main.go:39)
    stake_unit: int = 5  # STAKE_UNIT (honest.go:46)
    precision: int = 4  # decimal digits kept by quantization (main.go:45)
    poly_size: int = 10  # Shamir chunk degree (main.go:46)
    # Share-row redundancy factor r: TOTAL_SHARES = ceil(r·k/M)·M. The
    # reference hardwires r=2 (main.go:825) — generous fault tolerance, but
    # it lets any ⌈M/2⌉ miners reconstruct an aggregate, so two DISJOINT
    # miner subsets can serve two different aggregation sets and a
    # malicious leader can difference them to unmask an individual update.
    # Any r < 2 makes recovering subsets need > M/2 miners, so every pair
    # of them overlaps in a miner whose one-set-per-round guard then fires
    # (see _h_get_miner_part). r=1.5 still tolerates ⌊M/3⌋ dead miners.
    #
    # DEFAULT r=1.5 (hardened): the configuration people actually run gets
    # the anti-differencing guarantee, not just the documented option.
    # Layouts where ceil-rounding breaks the promise (e.g. M=10, k=10)
    # fail loudly from total_shares — set r=2.0 explicitly for
    # reference-parity fault tolerance there.
    share_redundancy: float = 1.5
    max_iterations: int = 100  # MAX_ITERATIONS (main.go:48)
    fail_prob: float = 0.0  # random per-iteration self-crash (main.go:54-55)
    defense: Defense = Defense.KRUM  # POISON_DEFENSE (main.go:57)
    roni_threshold: float = 0.02  # RONI reject score (main.go:203-231)
    # trimmed-mean trim fraction per tail (no reference analogue): must
    # exceed the worst-case Byzantine fraction (Yin'18); 0.35 clears the
    # reference's 30% operating point with margin
    trim_fraction: float = 0.35
    convergence_error: float = 0.05  # train-error exit threshold
    timeouts: Timeouts = field(default_factory=Timeouts)

    # --- robustness plane (no reference analogue; runtime/faults.py) ---
    # unicast RPC retry budget: attempts = rpc_retries + 1, sleeps follow
    # exponential backoff with decorrelated jitter in [base, cap]
    rpc_retries: int = 2
    rpc_backoff_base_s: float = 0.05
    rpc_backoff_cap_s: float = 2.0
    # per-peer circuit breaker: `threshold` consecutive transport failures
    # open it; after `cooldown_s` one half-open probe may re-close it
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 5.0
    # seeded deterministic fault injection over the live RPC transport
    # (drop/delay/duplicate/reset/flood per frame); default = disabled.
    # The simulator mirrors the `drop` knob at round granularity
    # (parallel/sim.py) so degraded-round semantics agree between sim
    # and live.
    fault_plan: FaultPlan = field(default_factory=FaultPlan)
    # overload governance (runtime/admission.py, docs/ADMISSION.md):
    # per-message-class token-bucket rates, per-peer/global inflight
    # caps, bounded parked-waiter budget, slow-loris read deadline.
    # Over-budget inbound work is shed with a retryable BusyError that
    # never advances the circuit breaker. Default = disabled (seed
    # behavior: admit everything, park without bound).
    admission_plan: AdmissionPlan = field(default_factory=AdmissionPlan)
    # adaptive-adversary campaign plane (runtime/adversary.py,
    # docs/ADVERSARY.md): seeded, state-observing attack strategies —
    # role-aware coordinated flood, churn-riding identity recycling,
    # threshold-hugging adaptive poison. Armed only on the peers the
    # plan draws as attackers; every decision is a pure function of
    # (campaign seed, observed protocol state) and is traced + counted
    # (biscotti_campaign_actions_total). Default = disabled: the seed
    # schedule, bit-identical (guarded by tests/test_adversary.py).
    campaign_plan: CampaignPlan = field(default_factory=CampaignPlan)
    # adaptive defense plane (ops/trust.py, docs/DEFENSES.md): armed only
    # when defense == ENSEMBLE — the plan knobs calibrate the ensemble
    # vetoes, the drift scorer, hysteresis and the slow-trust ramp. With
    # any other defense no TrustLedger is constructed and verdicts are
    # bit-identical to the seed (guarded by tests/test_trust.py).
    trust_plan: TrustPlan = field(default_factory=TrustPlan)
    # elastic fleet plane (runtime/placement.py, docs/PLACEMENT.md):
    # load-aware placement of co-hosted peers — a seeded controller
    # reads signals the planes already export (hive RSS/loop-lag
    # gauges, admission shed rates, straggler profiles) and live-
    # migrates peers between hives with chain, stake, breaker history
    # and round position intact. Default = disabled: no controller is
    # constructed, no biscotti_migration_* metric exists, and the seed
    # schedule is bit-identical (guarded by tests/test_placement.py).
    placement_plan: PlacementPlan = field(default_factory=PlacementPlan)
    # FoolsGold minimum mutually-similar cluster size for a rejection
    # (ops/robust_agg.py small-N fix): 3 stops N=10 honest pools from
    # mass-flagging accidental honest pairs; 1 restores pre-PR-16
    # pair-level rejection
    fg_min_cluster: int = 3

    # --- straggler-tolerance plane (runtime/stragglers.py,
    # docs/STRAGGLERS.md) ---
    # adaptive_deadlines=True arms the per-peer deadline controller AND
    # partial-quorum graceful degradation: each deadline-bearing phase
    # (block wait, miner intake, krum timer, worker collection fan-outs)
    # sets its next budget to clamp(max(EWMA, p95) x margin,
    # [deadline_floor_s, legacy constant]) from its own observed
    # durations, and worker fan-outs proceed once a sufficient quorum is
    # reached after that soft deadline instead of waiting all-or-timeout
    # (excluded honest stragglers are counted, never breaker-fed or
    # stake-debited). Default off = the reference's fixed Timeouts
    # constants and all-or-timeout collection, bit-identical.
    adaptive_deadlines: bool = False
    deadline_margin: float = 1.5
    deadline_floor_s: float = 1.0

    # --- membership plane (runtime/membership.py, docs/MEMBERSHIP.md) ---
    # snapshot_bootstrap=True: a (re)joining peer catches up from a chain
    # SNAPSHOT pulled over the chunked GetSnapshot RPC — genesis hash
    # pinned, the sealed suffix's quorums verified — instead of replaying
    # every block since genesis through the RegisterPeer reply. Default
    # off = the seed join path.
    snapshot_bootstrap: bool = False
    # how many sealed blocks of suffix a GetSnapshot reply carries (plus
    # the trust-anchor base block and genesis); chains at or below this
    # height serve their full chain and the joiner adopts it normally
    snapshot_tail: int = 8
    # reshare=True arms the distributed resharing round: when the leader
    # loses a miner mid-round (a membership epoch bump), surviving share
    # holders re-deal their slices via GetReshareDeal — Shamir proactive
    # resharing with homomorphically-updated Pedersen commitments — and
    # the round's secure-agg recovery proceeds from the re-dealt shares
    # where the seed protocol could only mint an empty block
    reshare: bool = True

    # --- pipelined round engine (docs/RUNTIME.md §Pipelined rounds) ---
    # pipeline=True overlaps work across round boundaries: near-future
    # intake (iteration ≤ current + pipeline_depth) runs its
    # committee-independent crypto checks BEFORE parking for the round
    # (so commitment verification of round r+1 submissions runs while
    # round r mines), and the miner folds secure-agg intake into the
    # round's VSS accumulator as waves arrive instead of in one lump at
    # mint. speculation=True additionally lets a worker start its next
    # local SGD step + VSS commitment off the just-accepted head while
    # the round machinery finishes; a fork discards the speculative
    # products (traced `speculation_discard`). batch_intake=True turns
    # the miner's per-update plain-mode verification loop into one
    # batched RLC check per micro-batch (bisection identifies offenders
    # exactly as the sequential path would). All three default OFF: the
    # disabled configuration reproduces the pre-pipeline round schedule
    # bit-for-bit (guarded by tests/test_pipeline.py).
    pipeline: bool = False
    pipeline_depth: int = 1
    speculation: bool = False
    batch_intake: bool = False

    # --- hierarchical aggregation overlay (runtime/overlay.py,
    # docs/OVERLAY.md) ---
    # overlay=True arms the committee-rooted aggregation tree on the wire
    # plane: peers group into contiguous id blocks of `overlay_group`
    # (the pod_launch --peers-per-host layout, so the leaf->interior hop
    # is loopback on a hive deployment), a seed-derived per-round relay
    # per group pre-aggregates secure-agg share fan-out (summed share
    # rows + homomorphically summed Pedersen commitment grids, one
    # RegisterAggregate per miner per subtree) and deduplicates
    # plain-mode update fan-out and block broadcast (RelayFrames, one
    # frame per remote subtree). Per-update verification traffic stays
    # point-to-point; a missing relay degrades to direct delivery within
    # the round. Default OFF = the seed's flat fan-out, bit-identical
    # traffic schedule (guarded by tests/test_overlay.py).
    overlay: bool = False
    # peers per overlay group (the first interior tree level); the hive
    # launcher defaults it to its own co-hosted span, pod_launch to
    # --peers-per-host. Required >= 2 when overlay is on.
    overlay_group: int = 0

    # --- accelerator-resident crypto plane (crypto/kernels,
    # docs/CRYPTO_KERNELS.md) ---
    # device_crypto=True arms the limb-decomposed Ed25519/Pedersen
    # kernels: the batched miner-crypto seams (RLC commitment batches,
    # VSS intake wave folds + settle, Schnorr quorum batches, Shamir
    # recovery) compute their verdicts on the accelerator instead of as
    # CPU bigint work. The CPU path remains the exact-verdict oracle:
    # every rejection (bisection, per-worker fallback) and therefore
    # every stake debit still comes from the CPU recompute, and the
    # plane degrades loudly-but-gracefully to CPU when jax/x64 is
    # unavailable. Default OFF = today's CPU path bit-identical
    # (guarded by tests/test_crypto_kernels.py).
    device_crypto: bool = False

    # --- wire data plane (runtime/codecs.py, docs/WIRE_PLANE.md) ---
    # negotiated payload codec for protocol traffic: "raw64" (legacy
    # float64 frames, the default), "f32"/"bf16" (downcast — applied to
    # the delta BEFORE commitment/noising/sharing so Pedersen
    # verification and Shamir recovery stay exact), "zlib" (lossless
    # deflate), "topk" (sparsification with error-feedback residuals);
    # stages compose with "+", e.g. "f32+zlib". Crypto-bearing arrays
    # (int64 shares, commitment tensors) always travel lossless. Peers
    # advertise capabilities in the RegisterPeer hello and senders fall
    # back to raw64 for peers that never advertised.
    wire_codec: str = "raw64"
    # payloads above this stream as continuation chunks (reassembled in
    # rpc.FrameStream, MAX_FRAME enforced on the reassembled size);
    # 0 disables chunking. Only used toward chunk-capable peers.
    wire_chunk_bytes: int = 4 * 1024 * 1024
    # fraction of update coordinates the topk stage keeps per round
    wire_topk: float = 0.05

    # --- telemetry plane (biscotti_tpu/telemetry, docs/OBSERVABILITY.md) ---
    # telemetry=False swaps in no-op registry/recorder singletons: spans
    # still feed the legacy PhaseClock totals (pre-telemetry cost), all
    # NEW instrumentation compiles down to nothing
    telemetry: bool = True
    # >0: each peer also serves Prometheus text over HTTP on
    # metrics_port + node_id (same +id layout as base_port); 0 = RPC-only
    # exposition (the `Metrics` method is always available)
    metrics_port: int = 0
    # flight-recorder ring capacity (events) and spill batch size (events
    # buffered per JSONL write; flush happens at round end and shutdown)
    recorder_ring: int = 4096
    recorder_batch: int = 256
    # distributed tracing (docs/OBSERVABILITY.md §Distributed tracing):
    # trace=True threads a compact trace context (trace_id, parent span,
    # round) through every RPC frame toward trace-capable peers
    # (negotiated via the RegisterPeer capability set like wire codecs),
    # opens a child span per dispatched RPC on both transport seams, and
    # stamps span/parent ids on recorder spans/events — the raw material
    # tools/trace_round stitches into one cross-peer round timeline.
    # Default OFF = every frame and recorder event bit-identical to the
    # pre-tracing format (guarded by tests/test_tracing.py).
    trace: bool = False

    # --- versioned protocol plane (runtime/protocol.py, docs/PROTOCOL.md) ---
    # -1 = speak the current protocol version. 0..CURRENT pins the
    # advertised feature set to a historical version row ("old build"
    # emulation for the mixed-version matrix and rolling upgrades):
    # the hello advertises only that row's features AND feature-gated
    # messages introduced later (snapshot pulls, overlay relay frames)
    # are refused exactly like the old build would — unknown method.
    protocol_version: int = -1

    # --- ML hyperparameters (ref: ML/Pytorch/client.py:30,56; ML/code/logistic_model.py:8-13) ---
    learning_rate: float = 1e-3  # torch-path SGD lr (used by optimizer-step modes)
    logreg_alpha: float = 1e-2  # numpy-logreg step size α (ref: logistic_model.py:12)
    # NOTE deliberately absent: momentum / weight_decay. The reference
    # configures SGD(momentum=.75, weight_decay=1e-3) (client.py:30) but its
    # protocol path never calls optimizer.step() — privateFun returns the
    # clipped −grad only (client.py:38-65) — so the knobs do nothing there;
    # carrying dead fields here would imply behavior we (and it) don't have.
    grad_clip: float = 100.0
    batch_size: int = 10
    noise_presample_iters: int = 100  # DP noise tensor depth (client_obj.py:59-67)

    # --- TPU topology (no reference analogue) ---
    mesh_shape: tuple = (1,)
    mesh_axes: tuple = ("peers",)
    param_dtype: str = "float32"
    seed: int = 0

    def __post_init__(self) -> None:
        # trimmed mean reads per-update coordinate values at the
        # aggregation point; additive secret shares only support
        # Σ-aggregates, so the combination cannot be made to work —
        # fail at construction, not silently mid-protocol
        if self.defense == Defense.TRIMMED_MEAN and self.secure_agg:
            raise ValueError(
                "defense=TRIMMED_MEAN is incompatible with secure_agg: "
                "coordinate-wise order statistics cannot be computed over "
                "additive secret shares (ops/robust_agg.py). Run with "
                "secure_agg=0, or choose KRUM/MULTIKRUM, which are "
                "verifier-side accept masks and compose with secure-agg.")
        if not (0.0 <= self.trim_fraction < 0.5) \
                and self.defense == Defense.TRIMMED_MEAN:
            raise ValueError(
                f"trim_fraction={self.trim_fraction} must be in [0, 0.5)")
        # wire-plane validation: a typo'd codec must fail at construction,
        # not mid-round on the event loop (lazy import keeps this module's
        # import footprint numpy-free)
        from biscotti_tpu.runtime.codecs import WireCodecError, parse_codec

        try:
            parse_codec(self.wire_codec)
        except WireCodecError as e:
            raise ValueError(f"wire_codec: {e}") from None
        if not (0.0 < self.wire_topk <= 1.0):
            raise ValueError(
                f"wire_topk={self.wire_topk} must be in (0, 1]")
        if self.wire_chunk_bytes < 0:
            raise ValueError("wire_chunk_bytes must be >= 0")
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        # speculation rides the pipeline plane's block-accept hook; on
        # its own the knob would silently do nothing — refuse the dead
        # configuration instead of benchmarking the serial engine under
        # a flag that claims otherwise (batch_intake IS independent: the
        # micro-batch and the accumulator settle work without pipeline,
        # only the per-arrival fold kicks need it)
        if self.speculation and not self.pipeline:
            raise ValueError(
                "speculation=True requires pipeline=True (speculative "
                "steps are scheduled by the pipelined block-accept hook; "
                "docs/RUNTIME.md §Pipelined rounds)")
        # an enabled admission plan with nonsensical caps must fail at
        # construction, not mid-round when the first frame is budgeted
        self.admission_plan.validate()
        # campaign plane: a typo'd campaign name or nonsensical knob
        # must fail at construction too; fedsys has no election to
        # observe, no stake and no committees — an "adaptive" adversary
        # there would silently be the static one, so refuse the
        # combination instead of mislabeling a run
        self.campaign_plan.validate()
        if self.campaign_plan.enabled \
                and self.campaign_plan.attacker_node >= self.num_nodes:
            raise ValueError(
                f"campaign_plan.attacker_node="
                f"{self.campaign_plan.attacker_node} outside the id "
                f"space 1..{self.num_nodes - 1}: attacker_ids would "
                "silently drop the pin and the run would be an honest "
                "cluster labeled as an attack scenario")
        if self.campaign_plan.enabled and self.fedsys:
            raise ValueError(
                "campaign_plan is incompatible with fedsys mode: the "
                "campaigns adapt to the VRF election and chain state, "
                "which the FedSys baseline does not have "
                "(docs/ADVERSARY.md)")
        # placement plane: an enabled plan with nonsensical cadence or
        # thresholds must fail at construction, not at the controller's
        # first decision point
        self.placement_plan.validate()
        # adaptive defense plane: a nonsensical knob must fail at
        # construction, not on the first verifier decision; the ledger's
        # drift scorer and slow-trust ramp read the committed chain, so
        # fedsys (no chain, no election) cannot host it
        self.trust_plan.validate()
        if self.defense == Defense.ENSEMBLE and self.fedsys:
            raise ValueError(
                "defense=ENSEMBLE is incompatible with fedsys mode: the "
                "TrustLedger's drift scorer and slow-trust ramp are "
                "derived from the committed chain's accept/reject walk, "
                "which the FedSys baseline does not have "
                "(docs/DEFENSES.md)")
        if self.fg_min_cluster < 1:
            raise ValueError(
                f"fg_min_cluster={self.fg_min_cluster} must be >= 1 "
                "(1 = pre-fix pair-level FoolsGold rejection)")
        if not (0.0 <= self.fault_plan.churn < 1.0):
            raise ValueError(
                f"fault_plan.churn={self.fault_plan.churn} must be in "
                "[0, 1): it is the membership fraction churned per window")
        # straggler plane: a typo'd preset must fail at construction, not
        # when the first profile is drawn mid-round; knob sanity likewise
        if self.fault_plan.slow_preset \
                and self.fault_plan.slow_preset not in SLOW_PRESETS:
            raise ValueError(
                f"fault_plan.slow_preset={self.fault_plan.slow_preset!r} "
                f"unknown: pick from {SLOW_PRESETS}")
        if not (0.0 <= self.fault_plan.slow <= 1.0):
            raise ValueError(
                f"fault_plan.slow={self.fault_plan.slow} must be in "
                "[0, 1]: it is the membership fraction assigned a slow "
                "profile")
        if self.fault_plan.slow_factor < 1.0:
            raise ValueError("fault_plan.slow_factor must be >= 1 (it "
                             "multiplies compute wall-clock)")
        if self.deadline_margin < 1.0:
            raise ValueError("deadline_margin must be >= 1: the adaptive "
                             "deadline is estimate x margin and a margin "
                             "below 1 guarantees spurious expiry")
        if self.deadline_floor_s <= 0.0:
            raise ValueError("deadline_floor_s must be > 0")
        if self.snapshot_tail < 1:
            raise ValueError("snapshot_tail must be >= 1")
        # tracing rides the flight recorder and the span plane; with
        # telemetry off it would silently record nothing — refuse the
        # dead configuration (same policy as speculation-sans-pipeline)
        if self.trace and not self.telemetry:
            raise ValueError(
                "trace=True requires telemetry=True (trace context and "
                "span ids ride the flight recorder; "
                "docs/OBSERVABILITY.md §Distributed tracing)")
        # protocol plane: a pin outside the version table is a typo, not
        # an old build — fail at construction (lazy import: the protocol
        # registry pulls the codec table, which imports numpy)
        from biscotti_tpu.runtime.protocol import CURRENT_VERSION
        if not (-1 <= self.protocol_version <= CURRENT_VERSION):
            raise ValueError(
                f"protocol_version={self.protocol_version} must be -1 "
                f"(current) or a historical row in [0, {CURRENT_VERSION}] "
                "(runtime/protocol.py version table; docs/PROTOCOL.md)")
        # the overlay needs a real subtree to aggregate over — an armed
        # flag without a group would silently run the flat fan-out
        # labeled as an overlay run; refuse the dead configuration
        # (hive/pod_launch auto-fill the group from their host layout)
        if self.overlay and self.overlay_group < 2:
            raise ValueError(
                "overlay=True requires overlay_group >= 2 (peers per "
                "aggregation subtree; the hive launcher defaults it to "
                "its co-hosted span — docs/OVERLAY.md)")
        if self.overlay_group < 0:
            raise ValueError("overlay_group must be >= 0")

    # ------------------------------------------------------------------ derived

    @property
    def num_samples(self) -> int:
        """Per-round sampled contributor count: floor(N·perc), clamped to the
        worker population N − verifiers − miners (ref: main.go:672-679)."""
        n = int(self.num_nodes * self.sample_percent)
        return max(1, min(n, self.num_nodes - self.num_verifiers - self.num_miners))

    @property
    def krum_update_thresh(self) -> int:
        """Updates a verifier collects before running Krum: the full worker
        population under random sampling, NUM_SAMPLES otherwise
        (ref: main.go:680-684)."""
        if self.random_sampling:
            return max(1, self.num_nodes - self.num_verifiers - self.num_miners)
        return self.num_samples

    @property
    def total_shares(self) -> int:
        """TOTAL_SHARES = ceil(r·POLY_SIZE/NUM_MINERS)·NUM_MINERS
        (ref: main.go:825 with r fixed at 2; see share_redundancy).

        Exact rational arithmetic — float ceil would let representation
        error round rows-per-miner up and silently reopen the differencing
        channel the knob exists to close. When r < 2 is configured, the
        property it promises (no ⌊M/2⌋-miner subset can reconstruct) is
        CHECKED against the rounded layout and misconfigurations fail
        loudly instead of silently not delivering the guarantee."""
        from fractions import Fraction

        if self.share_redundancy < 1.0:
            raise ValueError("share_redundancy < 1 leaves fewer rows than "
                             "polynomial coefficients: recovery impossible")
        r = Fraction(self.share_redundancy).limit_denominator(1_000_000)
        per = -((-r * self.poly_size) // self.num_miners)  # exact ceil
        per = max(int(per), 1)
        t = per * self.num_miners
        if self.share_redundancy < 2.0:
            half = self.num_miners // 2
            if per * half >= self.poly_size:
                raise ValueError(
                    f"share_redundancy={self.share_redundancy} with "
                    f"poly_size={self.poly_size}, num_miners="
                    f"{self.num_miners} rounds to {per} rows/miner, so "
                    f"{half} miners still hold ≥ poly_size rows and the "
                    "r<2 anti-differencing guarantee does NOT hold — "
                    "lower r, raise poly_size, or use fewer miners")
        return t

    @property
    def shares_per_miner(self) -> int:
        return self.total_shares // self.num_miners

    @property
    def collusion_probability(self) -> float:
        """PRIV_PROB: `colluders` is a percentage (ref: main.go:829)."""
        return self.colluders / 100.0

    @property
    def collusion_threshold(self) -> int:
        """collusionThresh = ceil(N · (1 − colluders/100)) (ref: main.go:830-831)."""
        return int(math.ceil(self.num_nodes * (1.0 - self.collusion_probability)))

    @property
    def quant_scale(self) -> float:
        return float(10 ** self.precision)

    def port_of(self, node_id: int) -> int:
        return self.base_port + node_id

    # ------------------------------------------------------------------ CLI

    @staticmethod
    def add_args(p: argparse.ArgumentParser) -> None:
        """Register the reference-compatible flag surface (ref: main.go:613-649)."""
        p.add_argument("-i", "--node-id", type=int, default=0)
        p.add_argument("-t", "--num-nodes", type=int, default=10)
        p.add_argument("-d", "--dataset", type=str, default="creditcard")
        p.add_argument("--model", dest="model_name", type=str, default="")
        p.add_argument("-f", "--peers-file", type=str, default="")
        p.add_argument("-a", "--my-ip", type=str, default="127.0.0.1")
        p.add_argument("-pa", "--public-ip", type=str, default="")
        p.add_argument("-p", "--base-port", type=int, default=8000)
        p.add_argument("-c", "--colluders", type=int, default=0)
        p.add_argument("-na", "--num-miners", type=int, default=3)
        p.add_argument("-nv", "--num-verifiers", type=int, default=3)
        p.add_argument("-nn", "--num-noisers", type=int, default=2)
        p.add_argument("-sa", "--secure-agg", type=int, default=1)
        p.add_argument("-np", "--noising", type=int, default=1)
        p.add_argument("-vp", "--verification", type=int, default=1)
        p.add_argument("-ep", "--epsilon", type=float, default=1.0)
        p.add_argument("--dp-mechanism", type=str, default="gaussian",
                       choices=["gaussian", "mcmc13"],
                       help="gaussian = Abadi-16 presample (ref default); "
                            "mcmc13 = Song&Sarwate'13 MCMC "
                            "(ref diffPriv13 branch)")
        p.add_argument("-po", "--poison-fraction", type=float, default=0.0)
        p.add_argument("-ns", "--sample-percent", type=float, default=70.0)
        p.add_argument("-rs", "--random-sampling", type=int, default=0)
        p.add_argument("--defense", type=str, default="KRUM", choices=[d.value for d in Defense])
        p.add_argument("--trim-fraction", type=float, default=0.35,
                       help="per-tail trim for defense=TRIMMED_MEAN "
                            "(must exceed the Byzantine fraction)")
        p.add_argument("--max-iterations", type=int, default=100)
        p.add_argument("--convergence-error", type=float, default=0.05,
                       help="train-error exit threshold (ref main.go:1067-"
                            "1094); 0 disables early exit so fault "
                            "harnesses control run length exactly")
        p.add_argument("--fail-prob", type=float, default=0.0)
        p.add_argument("--seed", type=int, default=0)
        p.add_argument("--fedsys", type=int, default=0,
                       help="FedSys leader-aggregation baseline mode")
        # defaults reference the dataclass/FaultPlan field defaults — the
        # single source — so CLI and programmatic construction can't drift
        p.add_argument("--rpc-retries", type=int, default=BiscottiConfig.rpc_retries,
                       help="extra attempts per unicast RPC on transport "
                            "failure (exponential backoff + jitter)")
        p.add_argument("--breaker-threshold", type=int,
                       default=BiscottiConfig.breaker_threshold,
                       help="consecutive failures that quarantine a peer")
        p.add_argument("--breaker-cooldown-s", type=float,
                       default=BiscottiConfig.breaker_cooldown_s,
                       help="seconds quarantined before a half-open probe")
        p.add_argument("--fault-seed", type=int, default=FaultPlan.seed,
                       help="fault plane seed: same seed = same schedule")
        p.add_argument("--fault-drop", type=float, default=FaultPlan.drop,
                       help="P(outbound frame silently lost)")
        p.add_argument("--fault-delay", type=float, default=FaultPlan.delay,
                       help="P(outbound frame delayed)")
        p.add_argument("--fault-delay-s", type=float,
                       default=FaultPlan.delay_s,
                       help="max injected per-frame delay, seconds")
        p.add_argument("--fault-dup", type=float,
                       default=FaultPlan.duplicate,
                       help="P(outbound frame written twice)")
        p.add_argument("--fault-reset", type=float, default=FaultPlan.reset,
                       help="P(connection torn down instead of writing)")
        p.add_argument("--fault-flood", type=int, default=FaultPlan.flood,
                       help="frame-storm replay factor: every outbound "
                            "frame is written 1+N times (deterministic "
                            "flooding peer for admission tests)")
        p.add_argument("--fault-churn", type=float, default=FaultPlan.churn,
                       help="fraction of the membership killed+restarted "
                            "per churn window, seeded schedule (0.2 = "
                            "the ISSUE's 20%% turnover); window-0 "
                            "victims become late JOINERS")
        p.add_argument("--fault-churn-period", type=int,
                       default=FaultPlan.churn_period,
                       help="rounds per churn window")
        p.add_argument("--fault-churn-down", type=int,
                       default=FaultPlan.churn_down,
                       help="rounds a churned peer stays down before its "
                            "scheduled restart")
        p.add_argument("--fault-slow", type=float, default=FaultPlan.slow,
                       help="fraction of the membership assigned a slow "
                            "speed profile, seeded draw (the straggler "
                            "fault kind, docs/STRAGGLERS.md)")
        p.add_argument("--fault-slow-factor", type=float,
                       default=FaultPlan.slow_factor,
                       help="compute-slowdown multiple for drawn slow "
                            "peers (presets override)")
        p.add_argument("--fault-slow-service-s", type=float,
                       default=FaultPlan.slow_service_s,
                       help="extra per-RPC service delay a slow peer "
                            "charges every inbound request")
        p.add_argument("--fault-slow-preset", type=str,
                       default=FaultPlan.slow_preset,
                       choices=["", "tee", "bimodal", "longtail"],
                       help="named speed-profile preset for the drawn "
                            "subset: tee = the arXiv:2501.11771-"
                            "calibrated confidential-compute overhead, "
                            "bimodal = 2x/8x split, longtail = heavy-"
                            "tail severities")
        p.add_argument("--fault-slow-node", type=int,
                       default=FaultPlan.slow_node,
                       help="pin this node slow regardless of the "
                            "fraction draw (-1: none)")
        p.add_argument("--adaptive-deadlines", type=int,
                       default=int(BiscottiConfig.adaptive_deadlines),
                       help="1 arms the straggler-tolerance plane: "
                            "per-phase adaptive round deadlines "
                            "(EWMA+p95, clamped to the legacy "
                            "constants) and partial-quorum graceful "
                            "degradation (docs/STRAGGLERS.md)")
        p.add_argument("--deadline-margin", type=float,
                       default=BiscottiConfig.deadline_margin,
                       help="adaptive deadline = duration estimate x "
                            "this margin")
        p.add_argument("--deadline-floor-s", type=float,
                       default=BiscottiConfig.deadline_floor_s,
                       help="adaptive deadlines never drop below this "
                            "floor")
        p.add_argument("--snapshot-bootstrap", type=int,
                       default=int(BiscottiConfig.snapshot_bootstrap),
                       help="1: (re)joining peers catch up from a chain "
                            "snapshot (GetSnapshot RPC) instead of "
                            "replaying genesis (docs/MEMBERSHIP.md)")
        p.add_argument("--snapshot-tail", type=int,
                       default=BiscottiConfig.snapshot_tail,
                       help="sealed suffix blocks a GetSnapshot reply "
                            "carries")
        p.add_argument("--reshare", type=int,
                       default=int(BiscottiConfig.reshare),
                       help="1: distributed Shamir resharing round when "
                            "a miner is lost mid-round (0 = seed "
                            "behavior, the round goes empty)")
        p.add_argument("--campaign", type=str,
                       default=CampaignPlan.campaign,
                       choices=[""] + list(CAMPAIGNS),
                       help="arm an adaptive-adversary campaign on the "
                            "peers the plan draws as attackers: "
                            "roleflood = flood the per-round elected "
                            "miner/noisers, sybil = churn-riding "
                            "identity recycling, hug = threshold-"
                            "hugging adaptive poisoner "
                            "(docs/ADVERSARY.md; '' = seed behavior)")
        p.add_argument("--campaign-seed", type=int,
                       default=CampaignPlan.seed,
                       help="campaign decision seed (-1: the protocol "
                            "--seed) — same seed + same chain = the "
                            "identical action schedule")
        p.add_argument("--campaign-attackers", type=float,
                       default=CampaignPlan.attackers,
                       help="membership fraction drawn as colluding "
                            "attackers (top ids — the poisoned-id "
                            "formula, so matching --poison-fraction "
                            "makes the sets identical)")
        p.add_argument("--campaign-node", type=int,
                       default=CampaignPlan.attacker_node,
                       help="pin this id into the attacker set (-1: "
                            "none; node 0 refused — oracle anchor)")
        p.add_argument("--campaign-flood", type=int,
                       default=CampaignPlan.flood,
                       help="targeted frame-replay factor for the "
                            "roleflood campaign (frames toward a "
                            "target are written 1+N times)")
        p.add_argument("--campaign-recycle-period", type=int,
                       default=CampaignPlan.recycle_period,
                       help="sybil: rounds between identity recycles")
        p.add_argument("--campaign-recycle-down", type=int,
                       default=CampaignPlan.recycle_down,
                       help="sybil: rounds down before the fresh "
                            "incarnation rejoins")
        p.add_argument("--campaign-hug-start", type=float,
                       default=CampaignPlan.hug_start,
                       help="hug: initial poison blend scale")
        p.add_argument("--campaign-hug-jitter", type=float,
                       default=CampaignPlan.hug_jitter,
                       help="hug: per-attacker decorrelation jitter as "
                            "a fraction of the observed honest step "
                            "norm")
        p.add_argument("--fg-min-cluster", type=int,
                       default=BiscottiConfig.fg_min_cluster,
                       help="FoolsGold: minimum mutually-similar cluster "
                            "size for a rejection (small-N fix; 1 = "
                            "pre-fix pair-level behavior)")
        p.add_argument("--trust-geo-ratio", type=float,
                       default=TrustPlan.geo_ratio,
                       help="ENSEMBLE: geometry veto fires when a Krum "
                            "score exceeds ratio x the worst KEPT score")
        p.add_argument("--trust-sim-margin", type=float,
                       default=TrustPlan.sim_margin,
                       help="ENSEMBLE: similarity veto bar = kept-pair "
                            "cosine median + max(margin, mad_mult x MAD)")
        p.add_argument("--trust-mag-band", type=float,
                       default=TrustPlan.mag_band,
                       help="ENSEMBLE: magnitude veto fires outside "
                            "[median/band, median x band] of kept norms")
        p.add_argument("--trust-drift-hi", type=float,
                       default=TrustPlan.drift_hi,
                       help="ENSEMBLE: drift score that sets the flag "
                            "(Schmitt trigger upper threshold)")
        p.add_argument("--trust-drift-lo", type=float,
                       default=TrustPlan.drift_lo,
                       help="ENSEMBLE: drift score that clears the flag "
                            "(Schmitt trigger lower threshold)")
        p.add_argument("--trust-hold", type=int,
                       default=TrustPlan.hold_rounds,
                       help="ENSEMBLE: rounds a veto keeps rejecting a "
                            "peer after the last scorer vote (hysteresis)")
        p.add_argument("--trust-ramp-rounds", type=int,
                       default=TrustPlan.ramp_rounds,
                       help="ENSEMBLE: accepted on-chain blocks a fresh/"
                            "recycled identity needs to reach full "
                            "slow-trust weight (0 disables the ramp)")
        p.add_argument("--trust-ramp-floor", type=float,
                       default=TrustPlan.ramp_floor,
                       help="ENSEMBLE: slow-trust weight of a zero-"
                            "history identity (duty-cycle admission)")
        p.add_argument("--trust-absence-reset", type=int,
                       default=TrustPlan.absence_reset,
                       help="ENSEMBLE: consecutive eligible-absent real "
                            "blocks that restart an identity's ramp "
                            "(catches churn-recycled sybils)")
        p.add_argument("--admission", type=int,
                       default=int(AdmissionPlan.enabled),
                       help="1 arms the overload-governance plane: "
                            "over-budget inbound work is shed with a "
                            "retryable busy status (docs/ADMISSION.md)")
        p.add_argument("--admit-update-rate", type=float,
                       default=AdmissionPlan.update_rate,
                       help="token-bucket rate (frames/s per peer) for "
                            "update-class messages")
        p.add_argument("--admit-bulk-rate", type=float,
                       default=AdmissionPlan.bulk_rate,
                       help="token-bucket rate for bulk-class messages "
                            "(block push/pull, chain adoption)")
        p.add_argument("--admit-control-rate", type=float,
                       default=AdmissionPlan.control_rate,
                       help="token-bucket rate for control-class messages")
        p.add_argument("--admit-burst-factor", type=float,
                       default=AdmissionPlan.burst_factor,
                       help="bucket capacity = rate x this factor")
        p.add_argument("--admit-peer-inflight", type=int,
                       default=AdmissionPlan.peer_inflight,
                       help="max concurrent inbound handlers per peer")
        p.add_argument("--admit-global-inflight", type=int,
                       default=AdmissionPlan.global_inflight,
                       help="max concurrent inbound handlers, all peers")
        p.add_argument("--admit-parked", type=int,
                       default=AdmissionPlan.max_parked,
                       help="max handlers parked for a future round "
                            "(the oldest waiter is shed at the cap)")
        p.add_argument("--admit-read-deadline-s", type=float,
                       default=AdmissionPlan.read_deadline_s,
                       help="seconds one inbound frame may stay "
                            "partially received before the connection "
                            "drops (slow-loris bound)")
        p.add_argument("--pipeline", type=int,
                       default=int(BiscottiConfig.pipeline),
                       help="1 overlaps phases across rounds: near-future "
                            "intake pre-verifies its crypto while the "
                            "current round mines, miner VSS intake folds "
                            "incrementally (docs/RUNTIME.md)")
        p.add_argument("--pipeline-depth", type=int,
                       default=BiscottiConfig.pipeline_depth,
                       help="how many rounds ahead intake is accepted for "
                            "early verification")
        p.add_argument("--speculation", type=int,
                       default=int(BiscottiConfig.speculation),
                       help="1 starts the next local SGD step + "
                            "commitment speculatively off the freshly "
                            "accepted head (discarded on fork)")
        p.add_argument("--batch-intake", type=int,
                       default=int(BiscottiConfig.batch_intake),
                       help="1 verifies plain-mode miner intake as one "
                            "batched RLC commitment check per "
                            "micro-batch, bisection on failure")
        p.add_argument("--overlay", type=int,
                       default=int(BiscottiConfig.overlay),
                       help="1 arms the hierarchical aggregation overlay "
                            "(committee-rooted per-round tree: share "
                            "fan-out pre-aggregated per subtree, update/"
                            "block fan-out relayed once per remote "
                            "subtree; docs/OVERLAY.md). 0 = the seed's "
                            "flat fan-out, bit-identical")
        p.add_argument("--overlay-group", type=int,
                       default=BiscottiConfig.overlay_group,
                       help="peers per overlay subtree (contiguous ids; "
                            "match --peers-per-host on a hive fleet)")
        p.add_argument("--device-crypto", type=int,
                       default=int(BiscottiConfig.device_crypto),
                       help="1 arms the accelerator-resident crypto "
                            "plane: batched miner crypto (RLC commitment "
                            "batches, VSS intake folds, Schnorr quorums, "
                            "Shamir recovery) runs as limb-decomposed "
                            "device kernels; 0 = the CPU path, "
                            "bit-identical (docs/CRYPTO_KERNELS.md)")
        p.add_argument("--wire-codec", type=str,
                       default=BiscottiConfig.wire_codec,
                       help="payload codec for protocol traffic "
                            "(raw64 | f32 | bf16 | zlib | topk, composed "
                            "with '+', e.g. f32+zlib); negotiated per "
                            "peer, raw64 fallback")
        p.add_argument("--wire-chunk-bytes", type=int,
                       default=BiscottiConfig.wire_chunk_bytes,
                       help="stream payloads above this as continuation "
                            "chunks (0 disables)")
        p.add_argument("--wire-topk", type=float,
                       default=BiscottiConfig.wire_topk,
                       help="fraction of update coordinates the topk "
                            "codec stage keeps per round")
        p.add_argument("--telemetry", type=int,
                       default=int(BiscottiConfig.telemetry),
                       help="0 disables the metrics registry + flight "
                            "recorder (instrumentation becomes no-ops)")
        p.add_argument("--metrics-port", type=int,
                       default=BiscottiConfig.metrics_port,
                       help="serve Prometheus text over HTTP on "
                            "metrics_port + node_id (0 = RPC-only)")
        p.add_argument("--recorder-ring", type=int,
                       default=BiscottiConfig.recorder_ring,
                       help="flight-recorder ring capacity, events")
        p.add_argument("--recorder-batch", type=int,
                       default=BiscottiConfig.recorder_batch,
                       help="events buffered per batched JSONL write")
        p.add_argument("--trace", type=int,
                       default=int(BiscottiConfig.trace),
                       help="1 arms distributed tracing: trace context "
                            "on every RPC frame toward trace-capable "
                            "peers, a child span per dispatched RPC, "
                            "span/parent ids on recorder events "
                            "(tools/trace_round stitches the cross-peer "
                            "round timeline; 0 = frames bit-identical "
                            "to the untraced format)")
        p.add_argument("--protocol-version", type=int,
                       default=BiscottiConfig.protocol_version,
                       help="pin the advertised protocol feature set to "
                            "a historical version row (old-build "
                            "emulation for mixed-version clusters and "
                            "rolling upgrades; -1 = current — "
                            "docs/PROTOCOL.md)")
        p.add_argument("--placement", type=int,
                       default=int(PlacementPlan.enabled),
                       help="1 arms the elastic fleet plane: a seeded "
                            "placement controller live-migrates peers "
                            "off hot hives (docs/PLACEMENT.md); 0 = "
                            "static placement, bit-identical")
        p.add_argument("--placement-seed", type=int,
                       default=PlacementPlan.seed,
                       help="placement decision seed: same seed + same "
                            "signals = the identical move schedule")
        p.add_argument("--placement-interval", type=int,
                       default=PlacementPlan.interval,
                       help="anchor rounds between placement decisions")
        p.add_argument("--placement-max-moves", type=int,
                       default=PlacementPlan.max_moves,
                       help="migrations applied per decision point")
        p.add_argument("--placement-rss-hot", type=int,
                       default=PlacementPlan.rss_hot_bytes,
                       help="hive RSS bytes above which a host is hot "
                            "(0 disarms the signal)")
        p.add_argument("--placement-rss-drift-hot", type=int,
                       default=PlacementPlan.rss_drift_hot_bytes,
                       help="windowed hive RSS drift bytes above which "
                            "a host is hot (leak shape; 0 disarms)")
        p.add_argument("--placement-lag-hot-s", type=float,
                       default=PlacementPlan.lag_hot_s,
                       help="hive event-loop lag seconds above which a "
                            "host is hot (0 disarms)")
        p.add_argument("--placement-shed-hot", type=float,
                       default=PlacementPlan.shed_hot,
                       help="admission shed fraction above which a host "
                            "is hot (0 disarms)")
        p.add_argument("--placement-slow-hot", type=float,
                       default=PlacementPlan.slow_hot,
                       help="straggler compute-factor above which a "
                            "host is hot (0 disarms)")
        p.add_argument("--placement-min-hive-peers", type=int,
                       default=PlacementPlan.min_hive_peers,
                       help="never drain a hive below this many peers")

    @classmethod
    def from_args(cls, ns: argparse.Namespace) -> "BiscottiConfig":
        # -ns is a percentage on the reference CLI (e.g. 70 ⇒ 70%); always
        # divide so "-ns 1" means 1%, not 100%
        sample = ns.sample_percent / 100.0
        return cls(
            node_id=ns.node_id,
            num_nodes=ns.num_nodes,
            dataset=ns.dataset,
            model_name=getattr(ns, "model_name", ""),
            peers_file=ns.peers_file,
            my_ip=ns.my_ip,
            public_ip=ns.public_ip,
            base_port=ns.base_port,
            colluders=ns.colluders,
            num_miners=ns.num_miners,
            num_verifiers=ns.num_verifiers,
            num_noisers=ns.num_noisers,
            secure_agg=bool(ns.secure_agg),
            noising=bool(ns.noising),
            verification=bool(ns.verification),
            epsilon=ns.epsilon,
            dp_mechanism=getattr(ns, "dp_mechanism", "gaussian"),
            poison_fraction=ns.poison_fraction,
            sample_percent=sample,
            random_sampling=bool(ns.random_sampling),
            defense=Defense(ns.defense),
            trim_fraction=getattr(ns, "trim_fraction", 0.35),
            max_iterations=ns.max_iterations,
            convergence_error=getattr(ns, "convergence_error", 0.05),
            fail_prob=ns.fail_prob,
            seed=ns.seed,
            fedsys=bool(getattr(ns, "fedsys", 0)),
            # fallbacks (for hand-built namespaces that skipped add_args)
            # reference the same field defaults the parser advertises
            rpc_retries=getattr(ns, "rpc_retries", cls.rpc_retries),
            breaker_threshold=getattr(ns, "breaker_threshold",
                                      cls.breaker_threshold),
            breaker_cooldown_s=getattr(ns, "breaker_cooldown_s",
                                       cls.breaker_cooldown_s),
            pipeline=bool(getattr(ns, "pipeline", cls.pipeline)),
            pipeline_depth=getattr(ns, "pipeline_depth", cls.pipeline_depth),
            speculation=bool(getattr(ns, "speculation", cls.speculation)),
            batch_intake=bool(getattr(ns, "batch_intake", cls.batch_intake)),
            device_crypto=bool(getattr(ns, "device_crypto",
                                       cls.device_crypto)),
            overlay=bool(getattr(ns, "overlay", cls.overlay)),
            overlay_group=getattr(ns, "overlay_group", cls.overlay_group),
            wire_codec=getattr(ns, "wire_codec", cls.wire_codec),
            wire_chunk_bytes=getattr(ns, "wire_chunk_bytes",
                                     cls.wire_chunk_bytes),
            wire_topk=getattr(ns, "wire_topk", cls.wire_topk),
            adaptive_deadlines=bool(getattr(ns, "adaptive_deadlines",
                                            cls.adaptive_deadlines)),
            deadline_margin=getattr(ns, "deadline_margin",
                                    cls.deadline_margin),
            deadline_floor_s=getattr(ns, "deadline_floor_s",
                                     cls.deadline_floor_s),
            snapshot_bootstrap=bool(getattr(ns, "snapshot_bootstrap",
                                            cls.snapshot_bootstrap)),
            snapshot_tail=getattr(ns, "snapshot_tail", cls.snapshot_tail),
            reshare=bool(getattr(ns, "reshare", cls.reshare)),
            telemetry=bool(getattr(ns, "telemetry", cls.telemetry)),
            metrics_port=getattr(ns, "metrics_port", cls.metrics_port),
            recorder_ring=getattr(ns, "recorder_ring", cls.recorder_ring),
            recorder_batch=getattr(ns, "recorder_batch", cls.recorder_batch),
            trace=bool(getattr(ns, "trace", cls.trace)),
            protocol_version=getattr(ns, "protocol_version",
                                     cls.protocol_version),
            placement_plan=PlacementPlan(
                enabled=bool(getattr(ns, "placement",
                                     PlacementPlan.enabled)),
                seed=getattr(ns, "placement_seed", PlacementPlan.seed),
                interval=getattr(ns, "placement_interval",
                                 PlacementPlan.interval),
                max_moves=getattr(ns, "placement_max_moves",
                                  PlacementPlan.max_moves),
                rss_hot_bytes=getattr(ns, "placement_rss_hot",
                                      PlacementPlan.rss_hot_bytes),
                rss_drift_hot_bytes=getattr(
                    ns, "placement_rss_drift_hot",
                    PlacementPlan.rss_drift_hot_bytes),
                lag_hot_s=getattr(ns, "placement_lag_hot_s",
                                  PlacementPlan.lag_hot_s),
                shed_hot=getattr(ns, "placement_shed_hot",
                                 PlacementPlan.shed_hot),
                slow_hot=getattr(ns, "placement_slow_hot",
                                 PlacementPlan.slow_hot),
                min_hive_peers=getattr(ns, "placement_min_hive_peers",
                                       PlacementPlan.min_hive_peers),
            ),
            fault_plan=FaultPlan(
                seed=getattr(ns, "fault_seed", FaultPlan.seed),
                drop=getattr(ns, "fault_drop", FaultPlan.drop),
                delay=getattr(ns, "fault_delay", FaultPlan.delay),
                delay_s=getattr(ns, "fault_delay_s", FaultPlan.delay_s),
                duplicate=getattr(ns, "fault_dup", FaultPlan.duplicate),
                reset=getattr(ns, "fault_reset", FaultPlan.reset),
                flood=getattr(ns, "fault_flood", FaultPlan.flood),
                churn=getattr(ns, "fault_churn", FaultPlan.churn),
                churn_period=getattr(ns, "fault_churn_period",
                                     FaultPlan.churn_period),
                churn_down=getattr(ns, "fault_churn_down",
                                   FaultPlan.churn_down),
                slow=getattr(ns, "fault_slow", FaultPlan.slow),
                slow_factor=getattr(ns, "fault_slow_factor",
                                    FaultPlan.slow_factor),
                slow_service_s=getattr(ns, "fault_slow_service_s",
                                       FaultPlan.slow_service_s),
                slow_preset=getattr(ns, "fault_slow_preset",
                                    FaultPlan.slow_preset),
                slow_node=getattr(ns, "fault_slow_node",
                                  FaultPlan.slow_node),
            ),
            campaign_plan=CampaignPlan(
                campaign=getattr(ns, "campaign", CampaignPlan.campaign),
                seed=getattr(ns, "campaign_seed", CampaignPlan.seed),
                attackers=getattr(ns, "campaign_attackers",
                                  CampaignPlan.attackers),
                attacker_node=getattr(ns, "campaign_node",
                                      CampaignPlan.attacker_node),
                flood=getattr(ns, "campaign_flood", CampaignPlan.flood),
                recycle_period=getattr(ns, "campaign_recycle_period",
                                       CampaignPlan.recycle_period),
                recycle_down=getattr(ns, "campaign_recycle_down",
                                     CampaignPlan.recycle_down),
                hug_start=getattr(ns, "campaign_hug_start",
                                  CampaignPlan.hug_start),
                hug_jitter=getattr(ns, "campaign_hug_jitter",
                                   CampaignPlan.hug_jitter),
            ),
            trust_plan=TrustPlan(
                geo_ratio=getattr(ns, "trust_geo_ratio",
                                  TrustPlan.geo_ratio),
                sim_margin=getattr(ns, "trust_sim_margin",
                                   TrustPlan.sim_margin),
                mag_band=getattr(ns, "trust_mag_band", TrustPlan.mag_band),
                drift_hi=getattr(ns, "trust_drift_hi", TrustPlan.drift_hi),
                drift_lo=getattr(ns, "trust_drift_lo", TrustPlan.drift_lo),
                hold_rounds=getattr(ns, "trust_hold",
                                    TrustPlan.hold_rounds),
                ramp_rounds=getattr(ns, "trust_ramp_rounds",
                                    TrustPlan.ramp_rounds),
                ramp_floor=getattr(ns, "trust_ramp_floor",
                                   TrustPlan.ramp_floor),
                absence_reset=getattr(ns, "trust_absence_reset",
                                      TrustPlan.absence_reset),
            ),
            fg_min_cluster=getattr(ns, "fg_min_cluster",
                                   cls.fg_min_cluster),
            admission_plan=AdmissionPlan(
                enabled=bool(getattr(ns, "admission",
                                     AdmissionPlan.enabled)),
                update_rate=getattr(ns, "admit_update_rate",
                                    AdmissionPlan.update_rate),
                bulk_rate=getattr(ns, "admit_bulk_rate",
                                  AdmissionPlan.bulk_rate),
                control_rate=getattr(ns, "admit_control_rate",
                                     AdmissionPlan.control_rate),
                burst_factor=getattr(ns, "admit_burst_factor",
                                     AdmissionPlan.burst_factor),
                peer_inflight=getattr(ns, "admit_peer_inflight",
                                      AdmissionPlan.peer_inflight),
                global_inflight=getattr(ns, "admit_global_inflight",
                                        AdmissionPlan.global_inflight),
                max_parked=getattr(ns, "admit_parked",
                                   AdmissionPlan.max_parked),
                read_deadline_s=getattr(ns, "admit_read_deadline_s",
                                        AdmissionPlan.read_deadline_s),
            ),
        )

    def replace(self, **kw) -> "BiscottiConfig":
        return dataclasses.replace(self, **kw)
