"""Host-side crypto plane.

Everything vector-shaped (quantization, share polynomial math, recovery)
lives in XLA under `biscotti_tpu.ops`; this package is the *control-plane*
crypto that stays on the host CPU (SURVEY.md §2.2, §2.7):

  * `ed25519`  — pure-Python Edwards25519 group (RFC 8032 arithmetic)
  * `vrf`      — ECVRF prove/verify (RFC 9381 TAI shape) for role lotteries
  * `commitments` — Pedersen vector commitments + Feldman-style verifiable
                 Shamir shares + Schnorr signatures (C++ fast path via
                 ctypes, pure-Python fallback)
"""
