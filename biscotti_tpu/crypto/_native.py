"""ctypes bridge to the C++ crypto library (native/libbiscotti_native.so).

Loaded lazily; `available()` is False (and the pure-Python paths run) until
`make -C native` has produced the shared object. Negative scalars are
handled here by negating the point — the C side sees small non-negative
scalars, which keeps Pippenger window counts minimal for quantized updates.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

from biscotti_tpu.crypto import ed25519 as ed

_LIB_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "native",
                 "libbiscotti_native.so"),
]

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
# why the native plane is unavailable ("" while loaded / not yet probed):
# surfaced ONCE on stderr at load time — the pure-Python fallback keeps
# every caller correct (parity-tested), but silently eating a ~30x miner
# crypto slowdown deep inside a round was the old failure mode
_load_error = ""


def load_error() -> str:
    """Human-readable reason the native library is unavailable, or ""
    when it loaded (or was never needed). Probes the loader."""
    _load()
    return _load_error


def _degrade(reason: str) -> None:
    """Record and announce the pure-Python degradation, once."""
    global _load_error
    _load_error = reason
    import sys

    print(f"[crypto/_native] native EC backend unavailable: {reason} — "
          f"falling back to the pure-Python path (correct, parity-tested, "
          f"~30x slower miner crypto). Build the `libbiscotti_native.so` "
          f"target with `make -C native` to restore it.", file=sys.stderr)


def _build() -> None:
    """Build the shared object from source if absent (the .so is not
    committed: its provenance could not be audited against the source).
    Disable with BISCOTTI_NO_NATIVE_BUILD=1."""
    if os.environ.get("BISCOTTI_NO_NATIVE_BUILD"):
        return
    import subprocess

    native_dir = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..", "native"))
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=True, timeout=120)
    except Exception:
        pass  # pure-Python fallback covers everything


def _selfcheck(lib: ctypes.CDLL) -> bool:
    """Cross-check the loaded binary against the pure-Python backend on a
    small random instance; a stale or tampered .so is refused, silently
    falling back to Python."""
    import secrets

    scalars = [int.from_bytes(secrets.token_bytes(16), "little") + 1
               for _ in range(4)]
    points = [ed.scalar_mult(i + 2, ed.BASE) for i in range(4)]
    expect = ed.IDENTITY
    for s, p in zip(scalars, points):
        expect = ed.point_add(expect, ed.scalar_mult(s % ed.Q, p))
    sbuf = b"".join((s % ed.Q).to_bytes(32, "little") for s in scalars)
    pbuf = b"".join(_point_bytes(p) for p in points)
    out = ctypes.create_string_buffer(64)
    if lib.ed25519_msm(sbuf, pbuf, 4, out) != 0:
        return False
    return ed.point_equal(point_from_xy64(out.raw), expect)


def _try_load(full: str) -> Tuple[Optional[ctypes.CDLL], str]:
    """(loaded library, "") or (None, reason). AttributeError means the
    binary's exported symbols predate the sources — an ABI-stale .so —
    which gets its own actionable message."""
    try:
        lib = ctypes.CDLL(full)
        lib.ed25519_msm.restype = ctypes.c_int
        lib.ed25519_msm.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p,
        ]
        lib.ed25519_batch_commit.restype = ctypes.c_int
        lib.ed25519_batch_commit.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ed25519_batch_commit_signed.restype = ctypes.c_int
        lib.ed25519_batch_commit_signed.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p,
        ]
        lib.ed25519_load_xy_batch.restype = ctypes.c_int
        lib.ed25519_load_xy_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ed25519_msm_signed.restype = ctypes.c_int
        lib.ed25519_msm_signed.argtypes = [
            # points arg is c_void_p: accepts bytes AND mutable buffers
            # (the VSS intake accumulator passes its bytearray zero-copy)
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ed25519_vss_rlc_scalars.restype = ctypes.c_int
        lib.ed25519_vss_rlc_scalars.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.ed25519_vss_st_accum.restype = ctypes.c_int
        lib.ed25519_vss_st_accum.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.ed25519_vss_blind_rows.restype = ctypes.c_int
        lib.ed25519_vss_blind_rows.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ed25519_decompress_batch.restype = ctypes.c_int
        lib.ed25519_decompress_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ed25519_load_xy_sum.restype = ctypes.c_int
        lib.ed25519_load_xy_sum.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_char_p,
        ]
        lib.ed25519_load_xy_sum_ptrs.restype = ctypes.c_int
        lib.ed25519_load_xy_sum_ptrs.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ed25519_xy_accum.restype = ctypes.c_int
        lib.ed25519_xy_accum.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.ed25519_ext_accum.restype = ctypes.c_int
        lib.ed25519_ext_accum.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        if not _selfcheck(lib):
            return None, (f"{full} failed the cross-backend self-check "
                          "(stale or tampered binary)")
        return lib, ""
    except AttributeError as e:
        return None, (f"{full} is ABI-stale — exported symbols predate "
                      f"the sources ({e})")
    except OSError as e:
        return None, f"{full} failed to load ({e})"


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    # always let make run: it is a no-op when the .so is current, and it
    # refreshes a stale binary whose exported symbols predate the sources
    # (which would otherwise silently drop all native acceleration)
    _build()
    reason = ""
    found = False
    for path in _LIB_PATHS:
        full = os.path.abspath(path)
        if not os.path.exists(full):
            continue
        found = True
        lib, reason = _try_load(full)
        if lib is None:
            _build()  # one retry in case the first build raced/failed
            lib, reason = _try_load(full)
        if lib is not None:
            _lib = lib
            break
    if _lib is None:
        _degrade(reason if found else
                 "native/libbiscotti_native.so not found (never built, "
                 "or BISCOTTI_NO_NATIVE_BUILD=1 suppressed the build)")
    return _lib


def available() -> bool:
    return _load() is not None


def _fe_bytes(v: int) -> bytes:
    return (v % ed.P).to_bytes(32, "little")


def _buf_addr(obj) -> Tuple[int, int, object]:
    """(address, byte length, keepalive) for a bytes-like object or a
    C-contiguous numpy array — zero-copy either way. The keepalive must
    stay referenced for the duration of the native call: the address
    points into the object's own storage."""
    if isinstance(obj, bytes):
        addr = ctypes.cast(ctypes.c_char_p(obj), ctypes.c_void_p).value
        return addr or 0, len(obj), obj
    if isinstance(obj, bytearray):
        raw = (ctypes.c_char * len(obj)).from_buffer(obj)
        return ctypes.addressof(raw), len(obj), (obj, raw)
    # numpy (or anything with the array interface); a non-contiguous view
    # degrades to one copy rather than corrupt reads
    import numpy as _np

    arr = _np.ascontiguousarray(obj)
    return int(arr.ctypes.data), arr.nbytes, arr


def point_from_xy64(buf: bytes) -> ed.Point:
    """Unpack one 64-byte little-endian affine (x, y) pair — the native
    library's output wire shape — into an extended-coordinate point."""
    x = int.from_bytes(buf[:32], "little")
    y = int.from_bytes(buf[32:64], "little")
    return (x, y, 1, (x * y) % ed.P)


def _point_bytes(p: ed.Point) -> bytes:
    x, y, z, t = p
    return _fe_bytes(x) + _fe_bytes(y) + _fe_bytes(z) + _fe_bytes(t)


def msm(scalars: Sequence[int], points: Sequence[ed.Point]) -> ed.Point:
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(scalars) != len(points):
        raise ValueError("scalar/point length mismatch")
    sbuf = bytearray()
    pbuf = bytearray()
    n = 0
    for s, p in zip(scalars, points):
        s = s % ed.Q
        if s == 0:
            continue
        # keep scalars short: a value in the top half of Z_q is a small
        # negative — use |s| with the negated point instead
        if s > ed.Q // 2:
            s = ed.Q - s
            p = ed.point_neg(p)
        sbuf += s.to_bytes(32, "little")
        pbuf += _point_bytes(p)
        n += 1
    if n == 0:
        return ed.IDENTITY
    out = ctypes.create_string_buffer(64)
    rc = lib.ed25519_msm(bytes(sbuf), bytes(pbuf), n, out)
    if rc != 0:
        raise RuntimeError(f"native msm failed: {rc}")
    return point_from_xy64(out.raw)


def load_xy_batch(xy: bytes, n: int) -> Optional[bytes]:
    """n×64B affine (x,y) pairs → n×128B extended buffer, with canonicity
    and on-curve validation (NOT subgroup — fold cofactor 8 into scalars).
    None if any point is invalid."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(xy) != 64 * n:
        raise ValueError("xy buffer length mismatch")
    out = ctypes.create_string_buffer(128 * n)
    rc = lib.ed25519_load_xy_batch(xy, n, out)
    if rc != 0:
        return None
    return out.raw


def vss_rlc_scalars(xs: Sequence[int], gammas_buf: bytes, c_chunks: int,
                    k: int) -> Tuple[bytes, bytes]:
    """Fused RLC → MSM-ready buffers: returns (scalars 32B·C·k magnitudes
    with cofactor 8 folded in, signs C·k bytes) consumable directly by
    msm_signed_raw. gammas_buf: S·C packed (lo u64, hi u64) pairs."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    s = len(xs)
    if len(gammas_buf) != 16 * s * c_chunks:
        raise ValueError("gamma buffer length mismatch")
    import struct

    xbuf = struct.pack(f"<{s}q", *[int(x) for x in xs])
    out_s = ctypes.create_string_buffer(32 * c_chunks * k)
    out_sign = ctypes.create_string_buffer(c_chunks * k)
    rc = lib.ed25519_vss_rlc_scalars(xbuf, gammas_buf, s, c_chunks, k,
                                     out_s, out_sign)
    if rc != 0:
        raise RuntimeError(f"native vss_rlc_scalars failed: {rc}")
    return out_s.raw, out_sign.raw


def decompress_batch(compressed: bytes, n: int) -> Optional[List[ed.Point]]:
    """RFC 8032 decompression of n packed 32-byte points in one native
    call; None if any fails (caller falls back / rejects)."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(compressed) != 32 * n:
        raise ValueError("compressed buffer length mismatch")
    out = ctypes.create_string_buffer(128 * n)
    rc = lib.ed25519_decompress_batch(compressed, n, out)
    if rc != 0:
        return None
    raw = out.raw
    pts: List[ed.Point] = []
    for i in range(n):
        o = raw[128 * i: 128 * (i + 1)]
        x = int.from_bytes(o[:32], "little")
        y = int.from_bytes(o[32:64], "little")
        t = int.from_bytes(o[96:128], "little")
        pts.append((x, y, 1, t))
    return pts


def vss_blind_rows_raw(blinds_buf: bytes, xs: Sequence[int], c_chunks: int,
                       k: int) -> Optional[bytes]:
    """Evaluate all blinding polynomials at all share points mod q.
    blinds_buf: C·k 32-byte little-endian canonical (< q) coefficients;
    returns S·C·32 bytes row-major, or None on invalid share points."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(blinds_buf) != 32 * c_chunks * k:
        raise ValueError("blind buffer length mismatch")
    import struct

    s = len(xs)
    xbuf = struct.pack(f"<{s}q", *[int(x) for x in xs])
    out = ctypes.create_string_buffer(32 * s * c_chunks)
    rc = lib.ed25519_vss_blind_rows(blinds_buf, xbuf, s, c_chunks, k, out)
    if rc != 0:
        return None
    return out.raw


def vss_st_accum(gammas_buf: bytes, rows_buf, blinds_buf,
                 s: int, c_chunks: int) -> Optional[Tuple[int, int]]:
    """(Σγ·row, Σγ·t_val) over all S·C cells — the lhs accumulators of the
    VSS check. rows_buf/blinds_buf may be bytes or C-contiguous numpy
    arrays (int64 rows, uint8 blinds) — passed zero-copy. Returns None if
    any blind value is non-canonical (≥ q)."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    cells = s * c_chunks
    rows_addr, rows_len, keep_r = _buf_addr(rows_buf)
    blinds_addr, blinds_len, keep_b = _buf_addr(blinds_buf)
    if (len(gammas_buf) != 16 * cells or rows_len != 8 * cells
            or blinds_len != 32 * cells):
        raise ValueError("buffer length mismatch")
    out_s = ctypes.create_string_buffer(40)
    out_t = ctypes.create_string_buffer(56)
    rc = lib.ed25519_vss_st_accum(gammas_buf, ctypes.c_void_p(rows_addr),
                                  ctypes.c_void_p(blinds_addr),
                                  s, c_chunks, out_s, out_t)
    del keep_r, keep_b
    if rc != 0:
        return None
    return (int.from_bytes(out_s.raw, "little", signed=True),
            int.from_bytes(out_t.raw, "little"))


def load_xy_sum(xy: bytes, n_batches: int, n: int) -> Optional[bytes]:
    """Fused validate + pointwise sum: n_batches back-to-back batches of
    n×64B affine pairs → the summed n×128B extended batch (msm-ready).
    None if any point is non-canonical or off-curve."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(xy) != 64 * n_batches * n:
        raise ValueError("xy buffer length mismatch")
    out = ctypes.create_string_buffer(128 * n)
    rc = lib.ed25519_load_xy_sum(xy, n_batches, n, out)
    if rc != 0:
        return None
    return out.raw


def load_xy_sum_ptrs(batches: Sequence, n: int) -> Optional[bytes]:
    """load_xy_sum over SEPARATE per-batch buffers (bytes or C-contiguous
    numpy arrays of n×64 bytes each) — no concatenation copy. The miner's
    round intake hands each worker's commitment grid straight from its
    numpy storage; at CNN dims the contiguous form's join alone copies
    hundreds of MB. None if any point is non-canonical or off-curve."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    n_batches = len(batches)
    if n_batches == 0 or n == 0:
        # mirror the native core's rc=1 on degenerate input (and the old
        # contiguous path, which returned None here): callers treat None
        # as "reject", never as an exception
        return None
    ptrs = (ctypes.c_void_p * n_batches)()
    keep = []
    for i, b in enumerate(batches):
        addr, nbytes, ka = _buf_addr(b)
        if nbytes != 64 * n:
            raise ValueError("batch buffer length mismatch")
        ptrs[i] = addr
        keep.append(ka)
    out = ctypes.create_string_buffer(128 * n)
    rc = lib.ed25519_load_xy_sum_ptrs(ptrs, n_batches, n, out)
    del keep
    if rc != 0:
        return None
    return out.raw


def xy_accum(acc: bytearray, xy, n: int) -> Optional[int]:
    """acc[i] += xy[i] over one n×64B affine grid, acc the mutable
    n×128B extended accumulator (initialize with load_xy_batch). Returns
    None on success or the index of the first invalid point, in which
    case acc is UNTOUCHED (validation is a separate first pass) — the
    incremental half of load_xy_sum_ptrs, letting a miner fold each
    worker's commitment grid into the round sum as it arrives."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(acc) != 128 * n:
        raise ValueError("accumulator length mismatch")
    xy_addr, xy_len, keep = _buf_addr(xy)
    if xy_len != 64 * n:
        raise ValueError("xy buffer length mismatch")
    raw = (ctypes.c_char * len(acc)).from_buffer(acc)
    rc = lib.ed25519_xy_accum(ctypes.addressof(raw),
                              ctypes.c_void_p(xy_addr), n)
    del keep, raw
    if rc != 0:
        return rc - 1
    return None


def ext_accum(acc: bytearray, ext: bytes, n: int) -> None:
    """acc[i] += ext[i] pointwise over two n×128B extended buffers — the
    per-wave fold of the incremental intake accumulator."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(acc) != 128 * n or len(ext) != 128 * n:
        raise ValueError("extended buffer length mismatch")
    raw = (ctypes.c_char * len(acc)).from_buffer(acc)
    rc = lib.ed25519_ext_accum(ctypes.addressof(raw),
                               ctypes.c_char_p(ext), n)
    del raw
    if rc != 0:
        raise RuntimeError(f"native ext_accum failed: {rc}")


def scalarmult_noreduce(k: int, p: ed.Point) -> ed.Point:
    """k·P WITHOUT the mod-q reduction the msm wrapper applies — the
    subgroup-membership check ℓ·P == identity needs the full group-order
    scalar to survive (reduced it is 0). k must fit 32 bytes."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    out = ctypes.create_string_buffer(64)
    rc = lib.ed25519_msm(int(k).to_bytes(32, "little"), _point_bytes(p),
                         1, out)
    if rc != 0:
        raise RuntimeError(f"native scalarmult failed: {rc}")
    return point_from_xy64(out.raw)


def msm_signed_raw(scalars_buf: bytes, signs_buf: bytes,
                   points_buf, n: int) -> ed.Point:
    """MSM over pre-packed (magnitude, sign, point) buffers — zero python
    marshalling on the hot path. points_buf may be bytes OR a mutable
    buffer (bytearray/numpy) passed zero-copy — the VSS intake
    accumulator hands its running extended buffer straight in."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    p_addr, p_len, keep = _buf_addr(points_buf)
    if (p_len != 128 * n or len(scalars_buf) != 32 * n
            or len(signs_buf) != n):
        raise ValueError("buffer length mismatch")
    out = ctypes.create_string_buffer(64)
    rc = lib.ed25519_msm_signed(scalars_buf, signs_buf,
                                ctypes.c_void_p(p_addr), n, out)
    del keep
    if rc != 0:
        raise RuntimeError(f"native msm failed: {rc}")
    return point_from_xy64(out.raw)


def msm_raw(scalars: Sequence[int], points_buf: bytes, n: int) -> ed.Point:
    """MSM over an already-validated 128B/point buffer (from
    load_xy_batch) — skips the per-point python int marshalling.

    Scalars may be SIGNED and UNREDUCED (|s| < 2²⁵⁶): short magnitudes keep
    Pippenger's window count down (a mod-q-reduced scalar is dense 252-bit
    even when the underlying combination is ~180-bit), and signs ride a
    separate byte map with on-the-fly point negation in C++."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(points_buf) != 128 * n or len(scalars) != n:
        raise ValueError("buffer length mismatch")
    sbuf = bytearray()
    signs = bytearray(n)
    for i, s in enumerate(scalars):
        s = int(s)
        if s < 0:
            signs[i] = 1
            s = -s
        if s >> 256:
            s %= ed.Q
        sbuf += s.to_bytes(32, "little")
    out = ctypes.create_string_buffer(64)
    rc = lib.ed25519_msm_signed(bytes(sbuf), bytes(signs), points_buf, n, out)
    if rc != 0:
        raise RuntimeError(f"native msm failed: {rc}")
    return point_from_xy64(out.raw)


def batch_commit_signed_raw(mags_buf: bytes, signs_buf: bytes,
                            b_buf: bytes, n: int) -> bytes:
    """Pedersen batch commit over pre-packed buffers: mags n×32B LE
    magnitudes (< q), signs n bytes, b n×32B LE canonical blinds. The
    zero-python-marshalling twin of batch_commit_xy."""
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if (len(mags_buf) != 32 * n or len(signs_buf) != n
            or len(b_buf) != 32 * n):
        raise ValueError("buffer length mismatch")
    from biscotti_tpu.crypto.commitments import H_POINT

    out = ctypes.create_string_buffer(64 * n)
    rc = lib.ed25519_batch_commit_signed(mags_buf, signs_buf, b_buf,
                                         _point_bytes(ed.BASE),
                                         _point_bytes(H_POINT), n, out)
    if rc != 0:
        raise RuntimeError(f"native batch_commit failed: {rc}")
    return out.raw


def batch_commit_xy(a: Sequence[int], b: Sequence[int]) -> bytes:
    """[aᵢ·G + bᵢ·H] as a packed n×64B affine (x,y) buffer — worker-side
    VSS coefficient commitments (fixed-base comb path in C++). The affine
    wire format skips both compression here and the sqrt-heavy
    decompression at every verifier. Data scalars travel as
    signed magnitudes so negative quantized coefficients stay a few bytes
    wide instead of dense q−|a| values."""
    if len(a) != len(b):
        raise ValueError("scalar length mismatch")
    n = len(a)
    if n == 0:
        return b""
    mags = bytearray()
    signs = bytearray(n)
    for i, s in enumerate(a):
        v = int(s)
        if not -ed.Q < v < ed.Q:
            v %= ed.Q
        if v < 0:
            signs[i] = 1
            v = -v
        mags += v.to_bytes(32, "little")
    bbuf = b"".join((int(s) % ed.Q).to_bytes(32, "little") for s in b)
    return batch_commit_signed_raw(bytes(mags), bytes(signs), bbuf, n)


