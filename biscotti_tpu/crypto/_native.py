"""ctypes bridge to the C++ crypto library (native/libbiscotti_native.so).

Loaded lazily; `available()` is False (and the pure-Python paths run) until
`make -C native` has produced the shared object. Negative scalars are
handled here by negating the point — the C side sees small non-negative
scalars, which keeps Pippenger window counts minimal for quantized updates.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence

from biscotti_tpu.crypto import ed25519 as ed

_LIB_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "native",
                 "libbiscotti_native.so"),
]

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    for path in _LIB_PATHS:
        full = os.path.abspath(path)
        if os.path.exists(full):
            try:
                lib = ctypes.CDLL(full)
                lib.ed25519_msm.restype = ctypes.c_int
                lib.ed25519_msm.argtypes = [
                    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
                    ctypes.c_char_p,
                ]
                _lib = lib
                break
            except OSError:
                continue
    return _lib


def available() -> bool:
    return _load() is not None


def _fe_bytes(v: int) -> bytes:
    return (v % ed.P).to_bytes(32, "little")


def _point_bytes(p: ed.Point) -> bytes:
    x, y, z, t = p
    return _fe_bytes(x) + _fe_bytes(y) + _fe_bytes(z) + _fe_bytes(t)


def msm(scalars: Sequence[int], points: Sequence[ed.Point]) -> ed.Point:
    lib = _load()
    assert lib is not None, "native library not built (make -C native)"
    if len(scalars) != len(points):
        raise ValueError("scalar/point length mismatch")
    sbuf = bytearray()
    pbuf = bytearray()
    n = 0
    for s, p in zip(scalars, points):
        s = s % ed.Q
        if s == 0:
            continue
        # keep scalars short: a value in the top half of Z_q is a small
        # negative — use |s| with the negated point instead
        if s > ed.Q // 2:
            s = ed.Q - s
            p = ed.point_neg(p)
        sbuf += s.to_bytes(32, "little")
        pbuf += _point_bytes(p)
        n += 1
    if n == 0:
        return ed.IDENTITY
    out = ctypes.create_string_buffer(64)
    rc = lib.ed25519_msm(bytes(sbuf), bytes(pbuf), n, out)
    if rc != 0:
        raise RuntimeError(f"native msm failed: {rc}")
    x = int.from_bytes(out.raw[:32], "little")
    y = int.from_bytes(out.raw[32:], "little")
    return (x, y, 1, (x * y) % ed.P)
