"""Pedersen commitments, Schnorr signatures, and pairing-free verifiable
secret sharing over Edwards25519.

Reference capabilities being reproduced (SURVEY.md §2.2):
  * polynomial/vector commitment to the quantized update:
    C = Σ qᵢ·PKᵢ over bn256 G1 (ref: DistSys/kyber.go:533-562
    createCommitment, verified by recompute kyber.go:564-577)
  * Schnorr signatures over commitments (ref: kyber.go:873-925)
  * per-share witnesses a miner can check against the sender's commitment
    (ref: kyber.go:611-673 — KZG-style, verified with a bn256 *pairing*)

Design departure, documented on purpose: the reference's share-witness check
needs a pairing-friendly curve. This build replaces it with **Pedersen VSS**
(coefficient commitments Cⱼ = aⱼ·G + bⱼ·H plus a parallel blinding-polynomial
share; check: s·G + t·H == Σ xʲ·Cⱼ), which delivers the same capability —
shares verifiable against a binding, hiding commitment to the polynomial —
on a single fast curve with no pairings. Plain Feldman (aⱼ·G) would leak
low-entropy quantized coefficients to a baby-step/giant-step search; the
blinding term closes that.

The group is the same Edwards25519 used by the VRF; scalars live in Z_q.
Pure-Python backend here (control-plane correctness); `native/` provides a
C++ fast path for the O(d) MSM hot spot, loaded lazily via ctypes.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import ClassVar, Dict, List, Optional, Sequence, Tuple

import numpy as np

from biscotti_tpu.crypto import ed25519 as ed

_Q = ed.Q


def _hash_to_point(label: bytes) -> ed.Point:
    """Nothing-up-my-sleeve generator derivation via the shared
    try-and-increment hash-to-curve in ed25519.py. Injects the native
    decompression when loadable (identical semantics); falls back cleanly
    during module import, when decompress_point below is not yet defined
    (the import-time H_POINT derivation takes the pure path)."""
    try:
        dec = decompress_point
    except NameError:  # import-time H_POINT derivation
        dec = None
    return ed.hash_to_point(b"biscotti-gen" + label, decompress=dec)


# Secondary generator for Pedersen blinding; independent of B by construction.
H_POINT = _hash_to_point(b"pedersen-H")


def _scalar(v: int) -> int:
    return v % _Q


def msm(scalars: Sequence[int], points: Sequence[ed.Point]) -> ed.Point:
    """Multi-scalar multiplication Σ sᵢ·Pᵢ (Pippenger bucket method).

    This is the reference's per-update hot spot — an O(d) MSM per round per
    peer (ref: kyber.go:533-562 at d=7,850 dominated its CPU budget,
    SURVEY.md §7.3). The C++ backend in native/ replaces this when built.
    """
    native = _native_mod()
    if native is not None:
        return native.msm(scalars, points)
    return _msm_python(scalars, points)


def _device_mod():
    """The accelerator-resident kernel plane (crypto/kernels,
    docs/CRYPTO_KERNELS.md) when ARMED (--device-crypto) and runnable —
    None otherwise. Consulted only at the batched seams below: device
    verdicts are computed from the identical group equations, and every
    REJECTION still routes through the CPU recompute/bisection paths, so
    rejection evidence and stake debits stay byte-identical to the CPU
    configuration."""
    try:
        from biscotti_tpu.crypto import kernels

        return kernels.active_module()
    except ImportError:
        return None


def _msm_python(scalars: Sequence[int], points: Sequence[ed.Point]) -> ed.Point:
    if len(scalars) != len(points):
        raise ValueError("scalar/point length mismatch")
    # mirror the native wrapper's top-half-negation EXACTLY: s·P and
    # (q−s)·(−P) differ by q·P, which is NOT the identity for points
    # carrying a small-order (torsion) component — decompression does no
    # subgroup check, so an adversarial torsioned point would otherwise
    # make the two backends disagree on the same inputs (consensus split)
    pairs = []
    for s, p in zip(scalars, points):
        s = _scalar(s)
        if s > _Q // 2:
            s = _Q - s
            p = ed.point_neg(p)
        pairs.append((s, p))
    pairs = [(s, p) for s, p in pairs if s]
    if not pairs:
        return ed.IDENTITY
    c = 8 if len(pairs) >= 32 else 4  # window bits
    maxbits = max(s.bit_length() for s, _ in pairs)
    acc = ed.IDENTITY
    for w in range((maxbits + c - 1) // c - 1, -1, -1):
        if not ed.is_identity(acc):
            for _ in range(c):
                acc = ed.point_double(acc)
        buckets: List[ed.Point] = [ed.IDENTITY] * (1 << c)
        for s, p in pairs:
            idx = (s >> (w * c)) & ((1 << c) - 1)
            if idx:
                buckets[idx] = ed.point_add(buckets[idx], p)
        running = ed.IDENTITY
        window_sum = ed.IDENTITY
        for b in range((1 << c) - 1, 0, -1):
            running = ed.point_add(running, buckets[b])
            window_sum = ed.point_add(window_sum, running)
        acc = ed.point_add(acc, window_sum)
    return acc


# ------------------------------------------------------------- commit key


@dataclass
class CommitKey:
    """d independent generators, one per model parameter — the trusted
    dealer's `commitKey.json` equivalent (ref:
    keyGeneration/generateBootstrapFile.go:26-120, honest.go:760-871).

    Derived transparently from a seed label instead of a dealer's secret
    MSM ladder (ref: publicKey.go:26-61): no trapdoor exists at all, which
    strictly improves on the reference's trusted-dealer assumption."""

    points: List[ed.Point]
    # lazily-built native MSM buffer (128 B/point extended form): built
    # ONCE per key, so per-update commitment recomputes skip the
    # python-point → bytes marshalling that otherwise dominates (measured
    # ~2.4 s/update at d=7,850 — 30× the MSM itself; a keyed miner
    # recomputing its whole intake rode the 90 s round deadline on it)
    _native_buf: Optional[bytes] = None
    # lazily-built device limb buffer ([d, 4, 16] int64 extended limbs)
    # for the --device-crypto MSM path — same build-once rationale
    _device_buf: Optional[object] = None

    # derivation/deserialization memo: the generator ladder is a pure
    # function of (dims, label) and the `_hash_to_point` try-and-increment
    # per generator is the expensive part (a sqrt per candidate). Every
    # in-process peer of an N-node test cluster loads the SAME dealer key,
    # and harnesses regenerate the same transparent key per agent — cache
    # the finished point lists instead of re-deriving N times. Few keys
    # ever exist per process; the cap guards pathological harnesses.
    _CACHE_MAX = 8
    _gen_cache: ClassVar["OrderedDict[Tuple[int, bytes], List[ed.Point]]"] \
        = OrderedDict()
    _deser_cache: ClassVar["OrderedDict[bytes, List[ed.Point]]"] \
        = OrderedDict()

    @classmethod
    def _cache_put(cls, cache: OrderedDict, key, pts) -> None:
        while len(cache) >= cls._CACHE_MAX:
            cache.popitem(last=False)
        cache[key] = pts

    @classmethod
    def generate(cls, dims: int, label: bytes = b"commit-key") -> "CommitKey":
        key = (dims, bytes(label))
        pts = cls._gen_cache.get(key)
        if pts is None:
            pts = [_hash_to_point(label + i.to_bytes(4, "little"))
                   for i in range(dims)]
            cls._cache_put(cls._gen_cache, key, pts)
        else:
            cls._gen_cache.move_to_end(key)
        # the points list is treated as immutable by every consumer;
        # sharing it across CommitKey instances is safe and lets the
        # lazily-built native buffer be the only per-instance state
        return cls(list(pts))

    def serialize(self) -> List[str]:
        return [ed.point_compress(p).hex() for p in self.points]

    @classmethod
    def deserialize(cls, items: Sequence[str]) -> "CommitKey":
        blob = b"".join(bytes.fromhex(s) for s in items)
        ck = hashlib.sha256(blob).digest()
        cached = cls._deser_cache.get(ck)
        if cached is not None:
            cls._deser_cache.move_to_end(ck)
            return cls(list(cached))
        native = _native_mod()
        if native is not None:
            # one native call for the whole key (~10 µs/point vs ~160 µs
            # python): at d=7,850 this is the difference between 0.1 s and
            # ~1.3 s of startup per process
            pts = native.decompress_batch(blob, len(items))
            if pts is None:
                raise ValueError("invalid commit-key point")
            cls._cache_put(cls._deser_cache, ck, pts)
            return cls(list(pts))
        pts = []
        for s in items:
            p = ed.point_decompress(bytes.fromhex(s))
            if p is None:
                raise ValueError("invalid commit-key point")
            pts.append(p)
        cls._cache_put(cls._deser_cache, ck, pts)
        return cls(list(pts))

    def native_buf(self, n: int) -> bytes:
        """First n points as the native 128 B/point MSM buffer."""
        if self._native_buf is None or len(self._native_buf) < 128 * n:
            object.__setattr__(self, "_native_buf", b"".join(
                (x % ed.P).to_bytes(32, "little")
                + (y % ed.P).to_bytes(32, "little")
                + (z % ed.P).to_bytes(32, "little")
                + (t % ed.P).to_bytes(32, "little")
                for x, y, z, t in self.points))
        return self._native_buf[: 128 * n]

    def device_buf(self, n: int):
        """First n points as the device kernel plane's [n, 4, 16] limb
        batch (crypto/kernels); built once per key like native_buf."""
        if self._device_buf is None or len(self._device_buf) < n:
            from biscotti_tpu.crypto.kernels import group as _gp

            object.__setattr__(
                self, "_device_buf",
                _gp.points_to_limbs(self.points).astype("int64"))
        return self._device_buf[:n]


def commit_update(q: np.ndarray, key: CommitKey) -> bytes:
    """C = Σ qᵢ·Gᵢ (ref: kyber.go:533-562). `q` is the int64 quantized
    update; negative entries map to Z_q."""
    if len(q) > len(key.points):
        raise ValueError(f"update dim {len(q)} exceeds commit key {len(key.points)}")
    native = _native_mod()
    if native is not None:
        # zero-marshalling hot path: int64 magnitudes/signs pack in numpy,
        # the key rides its cached native buffer
        flat = np.ascontiguousarray(q, dtype=np.int64)
        n = len(flat)
        mags = np.zeros((n, 32), dtype=np.uint8)
        mags[:, :8] = np.abs(flat).astype("<u8").view(np.uint8).reshape(n, 8)
        signs = (flat < 0).astype(np.uint8)
        pt = native.msm_signed_raw(mags.tobytes(), signs.tobytes(),
                                   key.native_buf(n), n)
        return ed.point_compress(pt)
    return ed.point_compress(msm([int(v) for v in q], key.points[: len(q)]))


def verify_commitment(commitment: bytes, q: np.ndarray, key: CommitKey) -> bool:
    """Recompute-and-compare (ref: kyber.go:564-577)."""
    try:
        return commit_update(q, key) == commitment
    except ValueError:
        return False


def _rlc_gammas(n: int, entropy: Optional[bytes]) -> Optional[List[int]]:
    """n random odd 128-bit RLC weights — from the caller's entropy
    windows (16 B each, determinism for tests) or os.urandom."""
    import os as _os

    if entropy is not None:
        if len(entropy) < 16 * n:
            return None
        raw = entropy[: 16 * n]
    else:
        raw = _os.urandom(16 * n)
    return [int.from_bytes(raw[16 * i: 16 * (i + 1)], "little") | 1
            for i in range(n)]


def _in_subgroup(p: ed.Point) -> bool:
    """ℓ·P == identity — prime-order subgroup membership. Native when
    built (window scalar-mult, the msm wrapper would reduce ℓ to 0)."""
    native = _native_mod()
    if native is not None:
        return ed.is_identity(native.scalarmult_noreduce(_Q, p))
    return ed.is_identity(ed.scalar_mult(_Q, p))


def batch_verify_commitments(items: Sequence[Tuple[bytes, np.ndarray]],
                             key: CommitKey,
                             entropy: Optional[bytes] = None) -> bool:
    """One RLC check for a whole miner intake of plain Pedersen
    commitments: True iff EVERY (commitment, q) pair satisfies
    C = Σ qⱼ·Gⱼ — Σᵢ γᵢ·Cᵢ == Σⱼ (Σᵢ γᵢ·qᵢⱼ)·Gⱼ, ONE d-point MSM with
    ~172-bit combined scalars instead of W d-point MSMs (~10× at the
    35-update mint-trigger intake; the per-update loop this replaces is
    the reference's kyber.go:564-577 recompute run W times).

    Verdict parity with the sequential recompute path is EXACT (failure
    probability 2⁻¹²⁸): every Cᵢ is required to decompress AND to lie in
    the prime-order subgroup (ℓ·C == 0, one cheap scalar-mult each —
    without it two colluders adding the same order-2 torsion point would
    slip past any linear combination whose weight-sum is even, accepted
    here yet rejected by recompute), and valid RFC 8032 encodings are
    bijective to points, so point equality ⟺ bytes equality. On False
    the caller bisects (find_bad_commitments) — rejection evidence is
    always the exact single recompute, never the batch."""
    if not items:
        return True
    n = len(items)
    d = len(items[0][1])
    if d > len(key.points) or any(len(q) != d for _, q in items):
        return False
    # malformed-length commitments return False (the sequential path's
    # byte-compare verdict) instead of tripping the batch decompressor's
    # length check mid-drain
    if any(len(c) != 32 for c, _ in items):
        return False
    gam = _rlc_gammas(n, entropy)
    if gam is None:
        return False
    native = _native_mod()
    c_pts: List[ed.Point] = []
    if native is not None:
        pts = native.decompress_batch(b"".join(c for c, _ in items), n)
        if pts is None:
            return False
        c_pts = pts
    else:
        for c_bytes, _ in items:
            p = ed.point_decompress(c_bytes)
            if p is None:
                return False
            c_pts.append(p)
    if not all(_in_subgroup(p) for p in c_pts):
        return False
    # combined scalars Sⱼ = Σᵢ γᵢ·qᵢⱼ via 8-bit limb decomposition of γ:
    # 16 int64 matmuls keep every partial inside int64 (2⁸·|q|·n — safe
    # for |q| < 2⁵⁵/n, far above any clipped quantized update), with an
    # object-dtype fallback for adversarially huge q values
    qmat = np.stack([np.asarray(q, np.int64) for _, q in items])  # [n, d]
    qmax = int(np.abs(qmat).max()) if qmat.size else 0
    if qmax and qmax * n < (1 << 55):
        limbs = np.zeros((n, 16), np.int64)
        for i, g in enumerate(gam):
            for l in range(16):
                limbs[i, l] = (g >> (8 * l)) & 0xFF
        acc = limbs.T @ qmat  # [16, d] int64, exact
        scalars = [sum(int(acc[l, j]) << (8 * l) for l in range(16))
                   for j in range(d)]
    else:
        accobj = np.zeros(d, dtype=object)
        for g, row in zip(gam, qmat):
            accobj += g * row.astype(object)
        scalars = [int(v) for v in accobj]
    dev = _device_mod()
    if dev is not None:
        # device verdict: same two group equations on the accelerator
        # (RLC lhs over the intake's commitments, combined-scalar rhs
        # over the commit key's limb buffer). Integer limb arithmetic is
        # exact, so the computed group elements — and the verdict — are
        # identical to the CPU backends'; a failed batch still bisects
        # through the CPU recompute (find_bad_commitments), so rejection
        # evidence never comes from this path. Any device fault falls
        # back to the CPU verdict below.
        try:
            lhs = dev.msm(gam, c_pts)
            rhs = dev.msm(scalars, key.device_buf(d))
            return ed.point_equal(lhs, rhs)
        except Exception:
            pass
    lhs = msm(gam, c_pts)
    if native is not None:
        rhs = native.msm_raw(scalars, key.native_buf(d), d)
    else:
        rhs = msm(scalars, key.points[:d])
    return ed.point_equal(lhs, rhs)


def find_bad_commitments(items: Sequence[Tuple[bytes, np.ndarray]],
                         key: CommitKey) -> List[int]:
    """Bisection over a failed batch: indices of every (commitment, q)
    pair the sequential recompute rejects. Each leaf verdict IS the
    sequential `verify_commitment`, so acceptance/rejection evidence is
    bit-identical to the per-update path; clean halves are retired with
    one batched check each, costing O(bad·log W) batch calls instead of
    W recomputes."""
    out: List[int] = []

    def walk(lo: int, hi: int, known_bad: bool) -> None:
        if lo >= hi:
            return
        if hi - lo == 1:
            if not verify_commitment(items[lo][0], items[lo][1], key):
                out.append(lo)
            return
        if not known_bad and batch_verify_commitments(items[lo:hi], key):
            return
        mid = (lo + hi) // 2
        walk(lo, mid, False)
        walk(mid, hi, False)

    # the caller reaches here off a failed whole-intake batch — skip
    # re-proving what is already known and split immediately
    walk(0, len(items), True)
    return out


# ------------------------------------------------------------- Schnorr


def _native_mod():
    try:
        from biscotti_tpu.crypto import _native

        return _native if _native.available() else None
    except ImportError:
        return None


def base_mult_fast(k: int) -> ed.Point:
    """k·B through the native fixed-base comb tables when built (~50× the
    python double-and-add; the comb for B is shared with the Pedersen
    commitment path since G = B there)."""
    native = _native_mod()
    if native is not None:
        return native.point_from_xy64(
            native.batch_commit_xy([int(k) % _Q], [0]))
    return ed.base_mult(k)


# (secret seed) → (x, prefix, compressed pk): signer identities are
# long-lived, so the per-sign base_mult for the public key amortizes away.
# An LRU bounded at 128, not unbounded: every retained entry pins an
# expanded secret scalar in memory (visible to anything that can read
# process memory or a core dump), so ephemeral harness identities fall
# out instead of accumulating forever. The bound stays ABOVE the largest
# in-process cluster the harnesses run (100 peers signing round-robin in
# one process — eval/scale_test.py — is the LRU worst case; a small
# bound would thrash it into a 100% miss rate). Re-expanding on a miss
# costs one sha512 + fixed-base mult (~0.03 ms native).
_sign_key_cache: "OrderedDict[bytes, tuple]" = OrderedDict()
_SIGN_KEY_CACHE_MAX = 128


def schnorr_sign(seed: bytes, message: bytes) -> bytes:
    """Deterministic Schnorr over Ed25519 (ref: kyber.go:873-896 signs with
    bn256; the curve is an implementation detail of the capability)."""
    cached = _sign_key_cache.get(seed)
    if cached is None:
        x, prefix = ed.secret_expand(seed)
        pk = ed.point_compress(base_mult_fast(x))
        while len(_sign_key_cache) >= _SIGN_KEY_CACHE_MAX:
            _sign_key_cache.popitem(last=False)
        _sign_key_cache[seed] = cached = (x, prefix, pk)
    else:
        _sign_key_cache.move_to_end(seed)
    x, prefix, pk = cached
    k = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % _Q
    r_pt = base_mult_fast(k)
    r = ed.point_compress(r_pt)
    c = int.from_bytes(
        hashlib.sha512(r + pk + message).digest(), "little"
    ) % _Q
    s = (k + c * x) % _Q
    return r + s.to_bytes(32, "little")


def batch_schnorr_verify(items: Sequence[Tuple[bytes, bytes, bytes]]) -> bool:
    """Verify MANY (public, message, signature) triples in one shot via a
    random linear combination: Σγᵢ·sᵢ·B == Σγᵢ·Rᵢ + Σγᵢ·cᵢ·Yᵢ, one MSM
    total. With 128-bit random γ a single bad signature survives with
    probability 2⁻¹²⁸; on failure, fall back per-item to identify it.
    This is what makes verifier-quorum checks on whole BLOCKS (and on
    candidate chains during adoption) affordable — one group equation per
    block instead of one per signature."""
    import os as _os

    if not items:
        return True
    for pub, msg, sig in items:
        if len(sig) != 64:
            return False
    # every signature's R nonce is unique (uncacheable) — decompress them
    # all in one native call when the library is built
    native = _native_mod()
    r_pts: Optional[List[ed.Point]] = None
    if native is not None:
        r_pts = native.decompress_batch(
            b"".join(sig[:32] for _, _, sig in items), len(items))
        if r_pts is None:
            return False
    scalars: List[int] = []
    points: List[ed.Point] = []
    s_tot = 0
    for i, (pub, msg, sig) in enumerate(items):
        r_pt = r_pts[i] if r_pts is not None else ed.point_decompress(sig[:32])
        y_pt = _pub_point(pub)  # cofactor-cleared 8Y (see _clear8)
        if r_pt is None or y_pt is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _Q:
            return False
        c = int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % _Q
        g = int.from_bytes(_os.urandom(16), "little") | 1
        # cofactored form: Σγ·8s·B == Σγ·(8R) + Σγc·(8Y) — every point in
        # the MSM is torsion-cleared, matching schnorr_verify exactly
        s_tot += g * 8 * s
        scalars.append(g)
        points.append(_clear8(r_pt))
        scalars.append((g * c) % _Q)
        points.append(y_pt)
    dev = _device_mod()
    if dev is not None:
        # device verdict over the identical cofactored equation; every
        # point in the MSM is already torsion-cleared (8R / 8Y), so the
        # device and CPU backends compute the same group elements. A
        # False verdict still falls back per-item in the caller — the
        # rejection evidence path is untouched.
        try:
            lhs = dev.fixed_base_mult([s_tot % _Q])[0]
            rhs = dev.msm(scalars, points)
            return ed.point_equal(lhs, rhs)
        except Exception:
            pass
    lhs = base_mult_fast(s_tot % _Q)
    rhs = msm(scalars, points)
    return ed.point_equal(lhs, rhs)


# public-key decompression cache: node identities are long-lived and every
# block verification touches the same few committee keys
_pub_cache: dict = {}


def decompress_point(buf: bytes) -> Optional[ed.Point]:
    """RFC 8032 point decompression, native when built — the shared
    dispatch for every caller that decodes a single wire point (VRF
    proofs, public keys). Uncached; long-lived keys go via _pub_point."""
    native = _native_mod()
    if native is not None and len(buf) == 32:
        pts = native.decompress_batch(buf, 1)
        return pts[0] if pts else None
    return ed.point_decompress(buf)


def _clear8(p: ed.Point) -> ed.Point:
    """8·P via three doublings — kills any small-order (torsion) component,
    leaving the prime-order part. Schnorr verification here is COFACTORED
    over cleared points: decompression does no subgroup check, and on a
    torsioned point the exact values of c·Y vs (q−c)·(−Y) differ by a
    torsion element, so cofactorless verification would give different
    verdicts between the single/batch paths (and potentially backends).
    Clearing the points makes every path compute in the prime-order
    subgroup, where all of them agree bit-for-bit."""
    return ed.point_double(ed.point_double(ed.point_double(p)))


def _pub_point(pub: bytes) -> Optional[ed.Point]:
    """Cofactor-CLEARED public point (8·Y) for Schnorr verification —
    see _clear8. Cached: node identities are long-lived."""
    if pub not in _pub_cache:
        p = decompress_point(pub)
        _pub_cache[pub] = _clear8(p) if p is not None else None
    return _pub_cache[pub]


def schnorr_verify(public: bytes, message: bytes, signature: bytes) -> bool:
    """(ref: kyber.go:898-925)."""
    if len(signature) != 64:
        return False
    r_pt = decompress_point(signature[:32])
    y_pt = _pub_point(public)
    if r_pt is None or y_pt is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= _Q:
        return False
    c = int.from_bytes(
        hashlib.sha512(signature[:32] + public + message).digest(), "little"
    ) % _Q
    # cofactored: 8s·B − c·(8Y) == 8R over torsion-cleared points (y_pt
    # from _pub_point is already 8Y) — identical verdicts to the batch
    # path and across backends on ALL inputs, torsioned included
    lhs = msm([(8 * s) % _Q, _Q - c if c else 0], [ed.BASE, y_pt])
    return ed.point_equal(lhs, _clear8(r_pt))


# ------------------------------------------------------- Pedersen VSS


@dataclass
class ChunkVSS:
    """Verifiable sharing of ONE polynomial chunk: coefficient commitments
    plus the blinding polynomial the prover evaluates alongside the real one.
    Plays the role of the reference's per-chunk commitment + KZG witnesses
    (ref: kyber.go:579-673) without pairings."""

    commitments: List[bytes]  # Cⱼ = aⱼ·G + bⱼ·H, j = 0..k−1

    def verify_share(self, x: int, share: int, blind_share: int) -> bool:
        """Check share·G + blind·H == Σ xʲ·Cⱼ — accepts iff (share, blind)
        is a true evaluation of the committed polynomial pair at x."""
        lhs = ed.point_add(
            ed.base_mult(_scalar(share)),
            ed.scalar_mult(_scalar(blind_share), H_POINT),
        )
        rhs = ed.IDENTITY
        xj = 1
        for c_bytes in self.commitments:
            c_pt = ed.point_decompress(c_bytes)
            if c_pt is None:
                return False
            rhs = ed.point_add(rhs, ed.scalar_mult(_scalar(xj), c_pt))
            xj = (xj * x) % _Q
        return ed.point_equal(lhs, rhs)


def vss_commit_chunk(coeffs: Sequence[int], seed: bytes, chunk_index: int,
                     context: bytes = b"") -> Tuple[ChunkVSS, List[int]]:
    """Commit one chunk's coefficients; returns (commitments, blinding
    coefficients). Blinding coefficients are derived deterministically from
    the peer's secret seed AND `context` (pass the round's block hash or
    iteration stamp): reusing blinds across rounds would let an observer
    difference two rounds' commitments, cancel the H term, and brute-force
    the low-entropy quantized coefficient deltas."""
    blinds = [
        int.from_bytes(
            hashlib.sha512(
                seed + b"vss-blind" + context
                + chunk_index.to_bytes(4, "little")
                + j.to_bytes(4, "little")
            ).digest(),
            "little",
        ) % _Q
        for j in range(len(coeffs))
    ]
    comms = [
        ed.point_compress(
            ed.point_add(
                ed.base_mult(_scalar(int(a))),
                ed.scalar_mult(b, H_POINT),
            )
        )
        for a, b in zip(coeffs, blinds)
    ]
    return ChunkVSS(comms), blinds


def eval_poly(coeffs: Sequence[int], x: int) -> int:
    """Exact integer Horner evaluation (shares themselves stay plain ints so
    the XLA aggregation/recovery path is unchanged)."""
    acc = 0
    for a in reversed(list(coeffs)):
        acc = acc * x + int(a)
    return acc


# ------------------------------------------- whole-update VSS (wire format)
#
# The protocol-facing layer: one VSS instance per polynomial chunk of the
# quantized update, flattened to fixed-shape byte tensors so the runtime
# codec can ship them (messages.py allows uint8 arrays). Commitment points
# travel as AFFINE (x, y) pairs (64B), not compressed: loading one costs an
# on-curve check (~7 field mults) instead of a sqrt mod p (~255 squarings),
# and the verifier is the hot side. Subgroup membership is not checked —
# every verification scalar is multiplied by the cofactor 8, which kills
# any small-order component a malicious committer could smuggle in.
#
# A miner verifies ALL (worker, row, chunk) triples of its round intake in
# ONE batched check — a random linear combination collapsing to a single
# MSM (ref: the reference instead runs a bn256 pairing per share,
# kyber.go:650-673). On failure, per-worker fallback identifies the cheat.


# Pedersen blind width in bits. BINDING (what VSS soundness rests on) is
# independent of this; it sets the HIDING level of each coefficient
# commitment. 128-bit blinds give ≥2⁶⁴-operation generic hiding (interval
# kangaroo over [0, 2¹²⁸)) at HALF the comb windows and XOF bytes of full-
# width blinds — and remain categorically stronger than the reference,
# whose commitments carry no blinding at all (C = Σ qᵢ·PKᵢ,
# kyber.go:533-562). Set BISCOTTI_HIDING_BITS=252 for full-width
# (statistically perfect) hiding.
def _hiding_bits_from_env() -> int:
    import os

    raw = os.environ.get("BISCOTTI_HIDING_BITS", "128")
    try:
        v = int(raw)
    except ValueError:
        import sys

        print(f"[commitments] ignoring non-integer BISCOTTI_HIDING_BITS="
              f"{raw!r}; using 128", file=sys.stderr)
        v = 128
    return max(8, min(252, v))


HIDING_BITS = _hiding_bits_from_env()


def vss_blind_bytes(n: int, seed: bytes, context: bytes) -> bytes:
    """n blinding coefficients as packed 32-byte little-endian canonical
    Z_q values, from ONE SHAKE-256 XOF call. At HIDING_BITS=252 each
    value is uniform in [0, 2²⁵²) — statistical distance < 2⁻¹²⁸ from
    uniform mod q (q = 2²⁵² + δ, δ ≈ 2¹²⁴); narrower widths trade
    statistical hiding for computational hiding (see HIDING_BITS) and
    draw proportionally fewer XOF bytes. Zero python bigint traffic."""
    nbytes = (HIDING_BITS + 7) // 8
    raw = bytearray(32 * n)
    xof = hashlib.shake_256(seed + b"vss-blind-xof" + context).digest(
        nbytes * n)
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(n, 32)
    arr[:, :nbytes] = np.frombuffer(xof, dtype=np.uint8).reshape(n, nbytes)
    # mask the top partial byte (252 → 0x0F etc.); value < 2^HIDING_BITS
    # ≤ 2²⁵² < q, so every emitted field is canonical
    arr[:, nbytes - 1] &= (0xFF >> (-HIDING_BITS % 8))
    return bytes(raw)


def vss_commit_chunks_bytes(chunks: np.ndarray, seed: bytes,
                            context: bytes) -> Tuple[np.ndarray, bytes]:
    """Commit every chunk's coefficients — the bytes-native worker path.

    chunks: [C, k] int64 (ss.to_chunks output). Returns (commitments uint8
    [C, k, 64] affine (x,y) LE pairs, blind coefficients as packed C·k
    32-byte LE values). The hot spot is 2·C·k fixed-base mults; the native
    comb path in `native/` takes it when built, fed by numpy-packed
    buffers (no per-value python ints anywhere on this path)."""
    c_chunks, k = chunks.shape
    n = c_chunks * k
    blind_bytes = vss_blind_bytes(n, seed, context)
    flat = np.ascontiguousarray(chunks, dtype=np.int64).reshape(n)
    native = _native_mod()
    if native is not None:
        mags = np.zeros((n, 32), dtype=np.uint8)
        mags[:, :8] = np.abs(flat).astype("<u8").view(np.uint8).reshape(n, 8)
        signs = (flat < 0).astype(np.uint8)
        raw = native.batch_commit_signed_raw(
            mags.tobytes(), signs.tobytes(), blind_bytes, n)
    else:
        flat_b = [int.from_bytes(blind_bytes[32 * i: 32 * (i + 1)], "little")
                  for i in range(n)]
        raw = batch_pedersen_commit_xy([int(v) for v in flat], flat_b)
    out = np.frombuffer(raw, dtype=np.uint8)
    return out.reshape(c_chunks, k, 64).copy(), blind_bytes


def _unpack_blinds(blind_bytes: bytes, c_chunks: int,
                   k: int) -> List[List[int]]:
    """Packed C·k 32-byte LE blinds → [C][k] python ints."""
    return [[int.from_bytes(blind_bytes[32 * (ci * k + j):
                                        32 * (ci * k + j + 1)], "little")
             for j in range(k)] for ci in range(c_chunks)]


def vss_commit_chunks(chunks: np.ndarray, seed: bytes,
                      context: bytes) -> Tuple[np.ndarray, List[List[int]]]:
    """Compatibility wrapper over vss_commit_chunks_bytes returning blind
    coefficients as [C][k] python ints."""
    c_chunks, k = chunks.shape
    comms, blind_bytes = vss_commit_chunks_bytes(chunks, seed, context)
    return comms, _unpack_blinds(blind_bytes, c_chunks, k)


def batch_pedersen_commit_xy(a: Sequence[int], b: Sequence[int]) -> bytes:
    """[aᵢ·G + bᵢ·H] as packed 64B affine pairs, native fast path when
    available."""
    native = _native_mod()
    if native is not None:
        return native.batch_commit_xy(a, b)
    out = bytearray()
    for ai, bi in zip(a, b):
        p = ed.point_add(ed.base_mult(_scalar(int(ai))),
                         ed.scalar_mult(_scalar(int(bi)), H_POINT))
        x, y = ed.to_affine(p)
        out += x.to_bytes(32, "little") + y.to_bytes(32, "little")
    return bytes(out)


def _rlc_coeffs(xs: Sequence[int], gam_bytes: bytes, c_chunks: int,
                k: int) -> List[int]:
    """The python RLC verification-coefficient chain shared by every
    batched-VSS settle path (one-shot fallback, accumulator python
    settle, accumulator device settle): coeff[ci·k + j] = Σ over cells
    (r, ci) of γ_cell·x_rʲ, accumulated over plain signed ints with one
    caller-side mod-q reduction (|x| ≤ S keeps γ·xʲ short). ONE copy —
    the device/CPU verdict-parity contract depends on these chains never
    drifting apart."""
    coeff = [0] * (c_chunks * k)
    cell = 0
    for r, x in enumerate(xs):
        xi = int(x)
        for ci in range(c_chunks):
            xj = int.from_bytes(gam_bytes[16 * cell: 16 * (cell + 1)],
                                "little")
            cell += 1
            base = ci * k
            for j in range(k):
                coeff[base + j] += xj
                xj *= xi
    return coeff


def _xy_to_point(buf: bytes) -> Optional[ed.Point]:
    """Parse + validate one 64B affine pair (python fallback for the native
    batch loader): canonical coords and on-curve, subgroup NOT checked."""
    x = int.from_bytes(buf[:32], "little")
    y = int.from_bytes(buf[32:64], "little")
    if x >= ed.P or y >= ed.P:
        return None
    if (y * y - x * x - 1 - ed.D * x * x * y * y) % ed.P != 0:
        return None
    return (x, y, 1, (x * y) % ed.P)


def vss_digest(comms: np.ndarray) -> bytes:
    """Binding digest over all chunk commitments — used as the update's
    `commitment` field in secure-agg mode, so the verifiers' Schnorr
    signatures cover exactly the object miners verify shares against."""
    return hashlib.sha256(b"vss" + np.ascontiguousarray(comms).tobytes()).digest()


def _blind_rows_python(blinds: List[List[int]],
                       xs: Sequence[int]) -> np.ndarray:
    """Pure-python Horner evaluation of the blind-row tensor (the shared
    fallback body of both vss_blind_rows entry points)."""
    s, c = len(xs), len(blinds)
    out = np.zeros((s, c, 32), dtype=np.uint8)
    for si, x in enumerate(xs):
        xi = int(x)
        for ci, coeffs in enumerate(blinds):
            acc = 0
            for bj in reversed(coeffs):
                acc = acc * xi + bj
            out[si, ci] = np.frombuffer((acc % _Q).to_bytes(32, "little"),
                                        np.uint8)
    return out


def vss_blind_rows_bytes(blind_bytes: bytes, c_chunks: int, k: int,
                         xs: Sequence[int]) -> np.ndarray:
    """vss_blind_rows over the packed 32-byte blind buffer from
    vss_commit_chunks_bytes — native end-to-end, no python ints."""
    native = _native_mod()
    if native is not None and c_chunks and k:
        raw = native.vss_blind_rows_raw(blind_bytes, [int(x) for x in xs],
                                        c_chunks, k)
        if raw is not None:
            return (np.frombuffer(raw, dtype=np.uint8)
                    .reshape(len(xs), c_chunks, 32).copy())
    # straight to python on native failure — re-dispatching through
    # vss_blind_rows would retry the identical native call
    return _blind_rows_python(_unpack_blinds(blind_bytes, c_chunks, k), xs)


def vss_blind_rows(blinds: List[List[int]], xs: Sequence[int]) -> np.ndarray:
    """Evaluate every chunk's blinding polynomial at every share point:
    uint8 [S, C, 32] (little-endian Z_q values), the companion tensor to the
    int64 share matrix.

    The native library evaluates the whole tensor in C (partially-reduced
    256-bit Horner, ~20× the python loop); the python fallback runs Horner
    over the SIGNED small x with one reduction at the end: the share
    points satisfy |x| ≤ S, so the unreduced accumulator stays under
    q·(k·S^k) ≈ 2³⁰⁰ — cheap python-int small-multiplies instead of k
    full-width modmuls per cell."""
    s, c = len(xs), len(blinds)
    k = len(blinds[0]) if blinds else 0
    native = _native_mod()
    if native is not None and c and k and all(len(r) == k for r in blinds):
        # canonicalize mod q before packing: the C kernel requires < q
        # inputs, while this public API (like its python fallback below)
        # accepts arbitrary ints
        buf = b"".join((int(bj) % _Q).to_bytes(32, "little")
                       for row in blinds for bj in row)
        raw = native.vss_blind_rows_raw(buf, [int(x) for x in xs], c, k)
        if raw is not None:
            return (np.frombuffer(raw, dtype=np.uint8)
                    .reshape(s, c, 32).copy())
    return _blind_rows_python(blinds, xs)


def vss_verify_multi(instances: Sequence[Tuple[np.ndarray, Sequence[int],
                                               np.ndarray, np.ndarray]],
                     entropy: Optional[bytes] = None) -> bool:
    """Batched share verification over MANY updates at once, AGGREGATED.

    instances: [(comms [C,k,64], xs, share_rows [S,C], blind_rows
    [S,C,32]), ...]. Instances that share the same evaluation points and
    chunk grid — a miner's whole round intake, since every worker shards
    over the same miner set — are verified as ONE aggregate: Pedersen
    commitments are additively homomorphic, so the per-cell equations
        s^w·G + t^w·H == Σⱼ x_r^j·C^w_cj        (one per worker w)
    sum to
        (Σ_w s^w)·G + (Σ_w t^w)·H == Σⱼ x_r^j·(Σ_w C^w_cj),
    and the verify MSM runs over C·k summed points instead of W·C·k —
    (W−1)·C·k plain point additions replace (W−1)·C·k Pippenger points
    (~8× wall-clock at cifar dims; the reference instead pays a bn256
    pairing per share, kyber.go:650-673).

    Soundness (full argument in docs/NATIVE_CRYPTO.md §aggregated-vss):
    one random odd 128-bit γ per (row, chunk) cell, SHARED by all workers
    in the group, with the cofactor 8 folded into every scalar. Any share
    inconsistent with its own commitments makes the aggregate equation
    fail with probability 1−2⁻¹²⁸ — detection of a lone cheater is NOT
    weakened — unless a coalition corrupts the SAME cell with errors that
    cancel in the group sum. That residual acceptance is harmless ONLY
    for an aggregate covering the whole group (the recovered sum still
    equals the sum of the committed values); an aggregate over a PARTIAL
    group would break the cancellation, so the runtime re-runs this check
    over exactly the aggregation set whenever it does not cover whole
    verified batches (peer.partial_batch_members /
    PeerAgent._ensure_subset_consistent). Callers outside the peer
    runtime must maintain the same invariant: True from this function
    certifies Σ-consistency of THESE instances as one group, not of
    arbitrary sub-multisets. Per-worker identification — call with a
    single instance, which is exact — runs only on failure, costing O(W)
    single checks in the Byzantine case the cheater is evicted and
    debited for."""
    import os as _os

    total_cells = 0
    for comms, xs, rows, blind_rows in instances:
        if comms.ndim != 3 or comms.shape[2] != 64:
            return False
        c_chunks = comms.shape[0]
        if (np.asarray(rows).shape != (len(xs), c_chunks)
                or blind_rows.shape != (len(xs), c_chunks, 32)):
            return False
        total_cells += len(xs) * c_chunks
    if total_cells == 0:
        return True
    # caller-provided entropy keeps the documented per-instance windows
    # (tests drive determinism through it); the default draws one window
    # per GROUP instead — groups only ever consume their first member's
    # window, so the per-instance allocation was W× oversized (46 MB of
    # urandom per mnist_cnn intake, all but 1.3 MB discarded)
    entropy_provided = entropy is not None
    if entropy_provided and len(entropy) < 16 * total_cells:
        return False

    native = _native_mod()

    # Group by (evaluation points, chunk grid); every group member shares
    # one γ vector and one RLC scalar set, and contributes its points to a
    # single summed batch. Entropy windows stay per-instance (16·S·C bytes
    # each, same contract as the ungrouped design); a group consumes its
    # FIRST member's window.
    groups: dict = {}
    off = 0
    for inst in instances:
        comms, xs, _, _ = inst
        key = (tuple(int(x) for x in xs), comms.shape[0], comms.shape[1])
        groups.setdefault(key, []).append((inst, off))
        off += len(xs) * comms.shape[0]

    s_tot = 0
    t_tot = 0
    all_scalars: List[int] = []  # python fallback path
    native_bufs: List[Tuple[bytes, bytes]] = []  # (magnitudes, signs)
    all_pts: List[ed.Point] = []
    sum_bufs: List[bytes] = []  # native: per-group summed point batches
    for (xs_key, c_chunks, k), members in groups.items():
        xs = list(xs_key)
        cells = len(xs) * c_chunks
        # gamma_i = entropy 16-byte window with the low bit forced — as an
        # int for the python s/t accumulation, and verbatim as the packed
        # (lo u64, hi u64) little-endian pair the native RLC consumes
        if entropy_provided:
            g0 = members[0][1]
            gam_bytes = bytearray(entropy[16 * g0: 16 * (g0 + cells)])
        else:
            gam_bytes = bytearray(_os.urandom(16 * cells))
        for i in range(0, len(gam_bytes), 16):
            gam_bytes[i] |= 1
        gam_bytes = bytes(gam_bytes)

        loaded: List = []
        for (comms, _xs, rows, blind_rows), _o in members:
            if native is not None:
                # fused native path, ZERO-COPY: commitment grids, share
                # rows and blind rows pass as numpy storage pointers (at
                # CNN dims the former tobytes()/join staging copied
                # ~0.7 GB per intake). lhs accumulators run per member
                # with the SHARED γ (linearity makes Σ_w γ·s^w ≡
                # γ·Σ_w s^w); zero python bignum traffic either
                loaded.append(np.ascontiguousarray(comms))
                st_acc = native.vss_st_accum(
                    gam_bytes,
                    np.ascontiguousarray(rows, dtype=np.int64),
                    np.ascontiguousarray(blind_rows),
                    len(xs), c_chunks)
                if st_acc is None:
                    return False  # non-canonical blind value
                s_tot += st_acc[0]
                t_tot += st_acc[1]
            else:
                comm_bytes = np.ascontiguousarray(comms).tobytes()
                rows = np.asarray(rows)
                blind_bytes = np.ascontiguousarray(blind_rows).tobytes()
                pts: List[ed.Point] = []
                for i in range(c_chunks * k):
                    p = _xy_to_point(comm_bytes[64 * i: 64 * i + 64])
                    if p is None:
                        return False
                    pts.append(p)
                loaded.append(pts)
                cell = 0
                for r, x in enumerate(xs):
                    for ci in range(c_chunks):
                        g = int.from_bytes(
                            gam_bytes[16 * cell: 16 * (cell + 1)], "little")
                        cell += 1
                        s_tot += g * int(rows[r, ci])
                        boff = 32 * (r * c_chunks + ci)
                        t_val = int.from_bytes(blind_bytes[boff: boff + 32],
                                               "little")
                        if t_val >= _Q:
                            return False
                        t_tot += g * t_val

        # RLC accumulation over plain (signed) integers with one mod-q
        # reduction per accumulator at the end: x is small (|x| ≤ S), so
        # γ·xʲ stays ≲ 2¹⁷² and full-width modmuls are avoided entirely.
        # The cofactor 8 is folded in at reduction time. ONE scalar set
        # per group — the per-cell k-power chain runs once, not per worker.
        if native is not None:
            sb, sgn = native.vss_rlc_scalars(xs, gam_bytes, c_chunks, k)
            native_bufs.append((sb, sgn))
            # ONE fused validate+sum pass over the whole group's affine
            # commitments, handed over as per-member buffer pointers —
            # no intermediate 128B extended batches, no concatenation
            buf = native.load_xy_sum_ptrs(loaded, c_chunks * k)
            if buf is None:
                return False
            sum_bufs.append(buf)
        else:
            coeff = _rlc_coeffs(xs, gam_bytes, c_chunks, k)
            all_scalars.extend((8 * v) % _Q for v in coeff)
            summed = loaded[0]
            for pts in loaded[1:]:
                summed = [ed.point_add(a, b)
                          for a, b in zip(summed, pts)]
            all_pts.extend(summed)

    if native is not None:
        # s·G + t·H in one native fixed-base comb evaluation
        lhs: ed.Point = native.point_from_xy64(
            native.batch_commit_xy([(8 * s_tot) % _Q], [(8 * t_tot) % _Q]))
        sbuf = b"".join(sb for sb, _ in native_bufs)
        signs = b"".join(sgn for _, sgn in native_bufs)
        rhs = native.msm_signed_raw(sbuf, signs, b"".join(sum_bufs),
                                    len(signs))
    else:
        lhs = ed.point_add(ed.base_mult((8 * s_tot) % _Q),
                           ed.scalar_mult((8 * t_tot) % _Q, H_POINT))
        rhs = msm(all_scalars, all_pts)
    return ed.point_equal(lhs, rhs)


# ------------------------------------------------- proactive resharing
#
# Commitment algebra for the distributed resharing round
# (ops/secretshare.reshare_*, docs/MEMBERSHIP.md). Pedersen commitments
# are additively homomorphic in BOTH directions this plane needs:
#
#   * across workers — the commitment grid of an AGGREGATED row slice is
#     the cell-wise point sum of the contributors' grids
#     (sum_commitment_grids), with the aggregated blind the scalar sum
#     of their blind rows (sum_blind_rows);
#   * across coefficients — the commitment to a polynomial's value at x
#     is Σⱼ xʲ·Cⱼ (commitment_eval_xy), with no new commitment needed.
#
# A holder re-dealing its row therefore commits its sub-share polynomial
# with the CONSTANT blinding coefficient pinned to its own blind value
# (reshare_commit_row), and every recipient checks, exactly:
#
#   sub_comms[c][0]  ==  Σⱼ x_oldʲ · orig_comms[c][j]
#
# — the sub-deal's claimed constant IS the original committed row value,
# updated homomorphically, so verification across a resharing epoch
# stays as exact as intake verification was (reshare_verify_deal).


def sum_commitment_grids(grids: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Cell-wise point sum of [C, k, 64] affine commitment grids — the
    commitment grid of the SUM of the committed polynomials. Returns
    None if any cell fails to load (off-curve / non-canonical)."""
    if not grids:
        return None
    c_chunks, k = grids[0].shape[0], grids[0].shape[1]
    out = np.zeros((c_chunks, k, 64), np.uint8)
    for ci in range(c_chunks):
        for j in range(k):
            acc = ed.IDENTITY
            for g in grids:
                p = _xy_to_point(bytes(np.ascontiguousarray(g[ci, j])))
                if p is None:
                    return None
                acc = ed.point_add(acc, p)
            x, y = ed.to_affine(acc)
            out[ci, j, :32] = np.frombuffer(x.to_bytes(32, "little"),
                                            np.uint8)
            out[ci, j, 32:] = np.frombuffer(y.to_bytes(32, "little"),
                                            np.uint8)
    return out


def sum_blind_rows(blind_rows: Sequence[np.ndarray]) -> List[List[int]]:
    """Scalar sum (mod q) of [S, C, 32] blind-row tensors → [S][C] python
    ints: the blinding values of an aggregated share slice, the companion
    of sum_commitment_grids on the opening side."""
    s, c = blind_rows[0].shape[0], blind_rows[0].shape[1]
    out = [[0] * c for _ in range(s)]
    for arr in blind_rows:
        buf = np.ascontiguousarray(arr, np.uint8).tobytes()
        for si in range(s):
            for ci in range(c):
                off = 32 * (si * c + ci)
                out[si][ci] = (out[si][ci] + int.from_bytes(
                    buf[off: off + 32], "little")) % _Q
    return out


def sum_blind_row_tensors(blind_rows: Sequence[np.ndarray]) -> np.ndarray:
    """sum_blind_rows, repacked to the wire-tensor form: scalar sum
    (mod q) of [S, C, 32] blind-row tensors returned as the same uint8
    [S, C, 32] layout — the blinding tensor of an aggregated share
    slice, ready to travel in an overlay aggregate frame or feed
    vss_verify_multi directly."""
    sums = sum_blind_rows(blind_rows)
    s = len(sums)
    c = len(sums[0]) if sums else 0
    out = np.zeros((s, c, 32), np.uint8)
    for si in range(s):
        for ci in range(c):
            out[si, ci] = np.frombuffer(
                int(sums[si][ci]).to_bytes(32, "little"), np.uint8)
    return out


def commitment_eval_xy(comms: np.ndarray, x: int) -> Optional[List[ed.Point]]:
    """Homomorphic evaluation of every chunk's committed polynomial at
    share point `x`: [C, k, 64] grid → one point per chunk,
    Σⱼ xʲ·C_cj = commit(f_c(x), b_c(x)). Returns None when a cell fails
    to load."""
    c_chunks, k = comms.shape[0], comms.shape[1]
    buf = np.ascontiguousarray(comms).tobytes()
    scalars = []
    xj = 1
    for _ in range(k):
        scalars.append(xj % _Q)
        xj *= int(x)
    out: List[ed.Point] = []
    for ci in range(c_chunks):
        pts = []
        for j in range(k):
            off = 64 * (ci * k + j)
            p = _xy_to_point(buf[off: off + 64])
            if p is None:
                return None
            pts.append(p)
        out.append(msm(scalars, pts))
    return out


def reshare_commit_row(coeffs_row: np.ndarray, blind0: Sequence[int],
                       seed: bytes,
                       context: bytes) -> Tuple[np.ndarray, List[List[int]]]:
    """Commit one re-dealt row's sub-share polynomials: [C, k] int64
    coefficients (column 0 = the held row values,
    ops/secretshare.reshare_coeffs) with the CONSTANT blinding
    coefficient pinned to the holder's own blind values `blind0` ([C]
    ints) — that pin is what makes the sub-deal homomorphically
    verifiable against the original commitments. Higher blinding
    coefficients come fresh from the XOF exactly like an intake commit.
    Returns (comms uint8 [C, k, 64], blinds [C][k] ints)."""
    coeffs_row = np.asarray(coeffs_row, np.int64)
    c_chunks, k = coeffs_row.shape
    raw = vss_blind_bytes(c_chunks * k, seed, context + b"|reshare")
    blinds = _unpack_blinds(raw, c_chunks, k)
    for ci in range(c_chunks):
        blinds[ci][0] = int(blind0[ci]) % _Q
    flat_a = [int(v) % _Q for v in coeffs_row.reshape(-1)]
    flat_b = [blinds[ci][j] for ci in range(c_chunks) for j in range(k)]
    rawc = batch_pedersen_commit_xy(flat_a, flat_b)
    comms = np.frombuffer(rawc, dtype=np.uint8).reshape(
        c_chunks, k, 64).copy()
    return comms, blinds


def reshare_verify_deal(orig_comms: np.ndarray, x_old: int,
                        sub_comms: np.ndarray, xs_new: Sequence[int],
                        sub_rows: np.ndarray,
                        sub_blind_rows: np.ndarray) -> bool:
    """Verify one holder's re-deal of the row it held at `x_old`:

    1. BINDING — the sub-deal's constant commitments equal the
       homomorphic evaluation of the ORIGINAL grid at x_old (per chunk):
       the re-dealt secret is provably the row the holder was given, not
       a substitute.
    2. CONSISTENCY — every (sub-share, sub-blind) evaluation verifies
       against the sub-deal grid (the standard batched VSS check).

    `orig_comms` is the [C, k, 64] grid of the shared polynomial — for an
    aggregated slice, sum_commitment_grids of the contributors' grids."""
    ev = commitment_eval_xy(orig_comms, x_old)
    if ev is None or sub_comms.shape != orig_comms.shape:
        return False
    buf = np.ascontiguousarray(sub_comms).tobytes()
    k = sub_comms.shape[1]
    for ci, expect in enumerate(ev):
        p = _xy_to_point(buf[64 * ci * k: 64 * ci * k + 64])
        if p is None or not ed.point_equal(p, expect):
            return False
    return vss_verify_multi([(sub_comms, list(xs_new),
                              np.asarray(sub_rows, np.int64),
                              np.asarray(sub_blind_rows, np.uint8))])


class VssIntakeBatch:
    """Incremental round-intake VSS verification — the pipelined miner's
    half of `vss_verify_multi`.

    The one-shot batched check pays its dominant cost (validate + sum W
    commitment grids, O(W·C·k) point work) in one lump at mint time.
    This object spreads that lump over the round: arriving workers'
    grids are folded into a running point accumulator in WAVES as they
    arrive (`add` books the cheap scalar accumulation, `fold` sums the
    pending wave through the vectorized load_xy_sum path and folds the
    wave sum in with one extended-add pass — amortized against the
    network wait for the other contributors), and `verify` at
    mint/serve time settles the WHOLE accumulated set with just the RLC
    scalar chain + ONE C·k-point MSM + the lhs comb — the only crypto
    left on the mint critical path (measured 3.4× below the one-shot
    check at mnist_cnn dims, W=35).

    Soundness is identical to `vss_verify_multi`'s aggregated group
    check: one random odd 128-bit γ per (row, chunk) cell, drawn ONCE at
    construction, shared by every member (Pedersen homomorphism — the
    per-cell equations sum), cofactor 8 folded into the verification
    scalars. γ never leaves the process and every grid a prover could
    choose is fixed before it learns anything about the check, so the
    early draw gives provers no adaptivity. Same residual as the group
    check: a coalition corrupting the SAME cell with cancelling errors
    passes (harmless for whole-group aggregates; partial sets are
    re-proved at the aggregation boundary exactly as before — members()
    hands back the retained instances for those re-checks and for the
    per-worker fallback identification when verify() fails).
    """

    def __init__(self, num_rows: int, c_chunks: int, k: int,
                 entropy: Optional[bytes] = None):
        import os as _os

        self.rows = int(num_rows)
        self.c = int(c_chunks)
        self.k = int(k)
        cells = self.rows * self.c
        raw = bytearray(entropy[: 16 * cells] if entropy is not None
                        else _os.urandom(16 * cells))
        if len(raw) != 16 * cells:
            raise ValueError("entropy shorter than one gamma window")
        arr = np.frombuffer(raw, dtype=np.uint8)
        arr[::16] |= 1  # odd gammas, vectorized (the cell count is S·C)
        self._gam = bytes(raw)
        self._s_tot = 0
        self._t_tot = 0
        self._members: Dict[int, tuple] = {}  # sid -> retained instance
        self._member_st: Dict[int, Tuple[int, int]] = {}  # for un-booking
        self._pending: List[int] = []  # sids booked but not yet folded
        self._acc: Optional[bytearray] = None  # native 128B/pt extended
        self._acc_py: Optional[List[ed.Point]] = None  # python fallback
        # device limb accumulator ([n, 4, 16] int64) — the --device-crypto
        # wave-fold path. The arming switch is sampled per fold, so one
        # accumulator object must live entirely on one side; the runtime
        # arms the plane at construction and never flips it mid-round.
        # A device FAULT (not a False verdict) sets _dev_failed and
        # rebuilds the CPU accumulator from the retained member grids —
        # the batch finishes on the CPU path instead of failing the round.
        self._acc_dev = None
        self._dev_failed = False

    def __len__(self) -> int:
        return len(self._members)

    def members(self) -> Dict[int, tuple]:
        """sid → (comms, rows, blind_rows) retained references — for the
        aggregation-boundary re-checks and the per-worker fallback."""
        return dict(self._members)

    def add(self, sid: int, comms: np.ndarray, share_rows: np.ndarray,
            blind_rows: np.ndarray) -> bool:
        """Book one worker's grid into the pending wave: shape checks +
        the cheap scalar (Σγ·s, Σγ·t) accumulation. False rejects THIS
        worker only (bad shapes, non-canonical blinds) with the
        accumulator untouched. The point work happens in fold()."""
        comms = np.asarray(comms)
        share_rows = np.asarray(share_rows, dtype=np.int64)
        blind_rows = np.asarray(blind_rows)
        if (sid in self._members
                or comms.shape != (self.c, self.k, 64)
                or share_rows.shape != (self.rows, self.c)
                or blind_rows.shape != (self.rows, self.c, 32)):
            return False
        comms = np.ascontiguousarray(comms)
        share_rows = np.ascontiguousarray(share_rows)
        blind_rows = np.ascontiguousarray(blind_rows)
        native = _native_mod()
        if native is not None:
            st = native.vss_st_accum(self._gam, share_rows, blind_rows,
                                     self.rows, self.c)
            if st is None:
                return False
            s_add, t_add = st
        else:
            blind_bytes = blind_rows.tobytes()
            s_add = t_add = 0
            cell = 0
            for r in range(self.rows):
                for ci in range(self.c):
                    g = int.from_bytes(self._gam[16 * cell: 16 * (cell + 1)],
                                       "little")
                    cell += 1
                    s_add += g * int(share_rows[r, ci])
                    boff = 32 * (r * self.c + ci)
                    t_val = int.from_bytes(blind_bytes[boff: boff + 32],
                                           "little")
                    if t_val >= _Q:
                        return False
                    t_add += g * t_val
        self._s_tot += s_add
        self._t_tot += t_add
        self._member_st[sid] = (s_add, t_add)
        self._members[sid] = (comms, share_rows, blind_rows)
        self._pending.append(sid)
        return True

    def _evict(self, sid: int) -> None:
        s_add, t_add = self._member_st.pop(sid)
        self._s_tot -= s_add
        self._t_tot -= t_add
        self._members.pop(sid, None)

    def _device_failover(self) -> List[int]:
        """A device kernel FAULTED mid-batch (backend OOM, compile
        failure — never a verdict): retire the device accumulator for
        this batch's lifetime and rebuild the CPU accumulator by
        re-folding every retained member grid (earlier waves live only
        in the device accumulator, and the grids are all retained in
        self._members). Returns the sids that need re-folding."""
        self._dev_failed = True
        self._acc_dev = None
        self._acc = None
        self._acc_py = None
        return [sid for sid in self._members if sid not in self._pending]

    def fold(self) -> List[int]:
        """Fold the pending wave of grids into the point accumulator:
        one vectorized validate+sum over the wave (load_xy_sum_ptrs,
        the batch-innermost kernel) plus one extended-add pass into the
        running sum. Returns the sids whose grids failed point
        validation (non-canonical / off-curve) — they are evicted here,
        at intake time, instead of poisoning the round batch at mint."""
        if not self._pending:
            return []
        wave, self._pending = self._pending, []
        rejected: List[int] = []
        native = _native_mod()
        n = self.c * self.k
        dev = None if self._dev_failed else _device_mod()
        if dev is not None:
            # device wave fold: one all-or-nothing canonicity + on-curve
            # validation over the whole wave (grid_validate_sum, the
            # ed25519_xy_accum equivalent) with a per-grid verdict mask —
            # the same cells the CPU loaders reject, so the evicted sid
            # set is identical — then one pointwise tree sum folded into
            # the limb accumulator. A device FAULT rebuilds the CPU
            # accumulator from every retained grid and this batch
            # continues on the CPU path (verdicts unchanged either way).
            try:
                grids = [self._members[sid][0] for sid in wave]
                mask, summed = dev.grid_validate_sum(grids)
                for sid, ok in zip(wave, mask):
                    if not ok:
                        self._evict(sid)
                        rejected.append(sid)
                if summed is not None:
                    self._acc_dev = (summed if self._acc_dev is None
                                     else dev.ext_add(self._acc_dev,
                                                      summed))
                return rejected
            except Exception:
                wave = self._device_failover()
                rejected = []
        if native is not None:
            grids = [self._members[sid][0] for sid in wave]
            if len(wave) == 1 and self._acc is not None:
                # single-grid wave: validate+fold in one in-place pass
                if native.xy_accum(self._acc, grids[0], n) is not None:
                    self._evict(wave[0])
                    return wave
                return []
            summed = native.load_xy_sum_ptrs(grids, n)
            if summed is None:
                # some grid is bad: identify per grid, re-sum the clean
                good = []
                for sid, g in zip(wave, grids):
                    if native.load_xy_batch(g.tobytes(), n) is None:
                        self._evict(sid)
                        rejected.append(sid)
                    else:
                        good.append(g)
                if not good:
                    return rejected
                summed = native.load_xy_sum_ptrs(good, n)
                if summed is None:  # unreachable: every grid validated
                    for sid in wave:
                        if sid not in rejected:
                            self._evict(sid)
                            rejected.append(sid)
                    return rejected
            if self._acc is None:
                self._acc = bytearray(summed)
            else:
                native.ext_accum(self._acc, summed, n)
            return rejected
        for sid in wave:
            comm_bytes = self._members[sid][0].tobytes()
            pts: List[ed.Point] = []
            for i in range(n):
                p = _xy_to_point(comm_bytes[64 * i: 64 * i + 64])
                if p is None:
                    pts = []
                    break
                pts.append(p)
            if not pts:
                self._evict(sid)
                rejected.append(sid)
                continue
            if self._acc_py is None:
                self._acc_py = pts
            else:
                self._acc_py = [ed.point_add(a, b)
                                for a, b in zip(self._acc_py, pts)]
        return rejected

    def verify(self, xs: Sequence[int]) -> bool:
        """Settle the accumulated set against the share points `xs` (the
        miner's row slice, len == num_rows): rlc scalars + one MSM + the
        lhs comb. Folds any still-pending wave first (its rejects count
        as not-members, surfaced by a later members() diff). True
        certifies Σ-consistency of the WHOLE member set as one group
        (the `vss_verify_multi` group contract); on False the caller
        identifies offenders per member. Empty set is True."""
        self.fold()
        if not self._members:
            return True
        if len(xs) != self.rows:
            return False
        native = _native_mod()
        dev = _device_mod()
        if dev is not None and self._acc_dev is not None:
            # device settle: the RLC scalar chain stays host-side (the
            # shared _rlc_coeffs helper), the C·k-point MSM and the
            # s·G + t·H comb run on the accelerator over the wave-folded
            # limb accumulator. Identical group equation ⇒ identical
            # verdict; a False here still falls back to the exact
            # per-member CPU checks in the caller, and a device FAULT
            # rebuilds the CPU accumulator from the retained grids and
            # settles there.
            try:
                coeff = _rlc_coeffs(xs, self._gam, self.c, self.k)
                rhs = dev.msm([(8 * v) % _Q for v in coeff], self._acc_dev)
                lhs = dev.pedersen_commit_point((8 * self._s_tot) % _Q,
                                                (8 * self._t_tot) % _Q)
                return ed.point_equal(lhs, rhs)
            except Exception:
                # re-fold every retained grid through the CPU path, then
                # settle below exactly as an all-CPU batch would
                self._pending = self._device_failover()
                self.fold()
        if native is not None and self._acc is not None:
            sb, sgn = native.vss_rlc_scalars(
                [int(x) for x in xs], self._gam, self.c, self.k)
            rhs = native.msm_signed_raw(sb, sgn, self._acc, len(sgn))
            lhs: ed.Point = native.point_from_xy64(native.batch_commit_xy(
                [(8 * self._s_tot) % _Q], [(8 * self._t_tot) % _Q]))
        else:
            coeff = _rlc_coeffs(xs, self._gam, self.c, self.k)
            assert self._acc_py is not None
            rhs = msm([(8 * v) % _Q for v in coeff], self._acc_py)
            lhs = ed.point_add(ed.base_mult((8 * self._s_tot) % _Q),
                               ed.scalar_mult((8 * self._t_tot) % _Q,
                                              H_POINT))
        return ed.point_equal(lhs, rhs)


