"""Dealerless genesis: Pedersen-verifiable distributed key generation.

The last trusted role in the bootstrap story was the keygen dealer —
every other trust assumption (commitment key, VRF transcripts, share
verification) was already transparent or verifiable, but node genesis
still meant one process that saw everything. This module closes that
gap with a Joint-Feldman-style ceremony built from the resharing
kernels that already ship (`ops/secretshare.reshare_*`,
`crypto/commitments.reshare_commit_row` / `vss_verify_multi`): every
party is simultaneously a dealer (it Shamir-shares its own random
contribution under a Pedersen commitment grid) and a recipient (it
verifies every other dealer's deal against that dealer's grid before
accepting). The joint secret is the sum of the accepted contributions'
constant terms; nobody — including every dealer — ever holds it,
because the Pedersen homomorphism lets the joint commitment grid and
the joint shares be summed without reconstruction.

What each primitive contributes:

* `ss.reshare_coeffs`   — the dealer's sharing polynomial per chunk
  (constant term = the contribution, masks deterministic from the
  dealer seed, so a test ceremony is replayable end to end);
* `cm.reshare_commit_row` — the public Pedersen grid over those
  coefficients (constant blinding pinned to the dealer's own blind0);
* `ss.reshare_subshares` — the per-recipient share rows;
* `cm.vss_verify_multi` — recipient-side deal verification: a share
  row inconsistent with the dealer's own grid is refused loudly
  (`verify_deal`), which is the corrupted-deal rejection the
  acceptance gate demands;
* `cm.sum_commitment_grids` / `sum_blind_row_tensors` + a plain int64
  sum — aggregation into the joint grid / joint shares;
* `ss.reshare_recover_rows` — threshold recovery of the joint secret
  with the exact-integrality corruption detector (any ≥ `threshold`
  verified holders can pool rows; a perturbed row raises ValueError).

The ceremony transcript (sorted dealer digests) seeds the commitment-
key label, so no single party picks the generator ladder either:
`commit_key_label(deals)` is a pure function of every accepted deal.

In-process ceremonies (`run_ceremony`) simulate the N parties inside
one process for keygen and tests; the per-party API (`contribute` /
`verify_deal` / `aggregate` / `recover_secret`) is message-separable so
the same math can ride the `DkgDeal` RPC between live peers (protocol
v8, docs/PLACEMENT.md §Genesis DKG).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from biscotti_tpu.crypto import commitments as cm
from biscotti_tpu.crypto import ed25519 as ed
from biscotti_tpu.ops import secretshare as ss

# Genesis contributions are small by construction: the joint secret is
# ceremony entropy (it seeds labels and genesis randomness), not model
# data, so a handful of chunks suffices and the exactness budget
# (|value| + n·k·RESHARE_COEF_BOUND·S^(k-1) « 2^53) stays comfortable
# for every plausible ceremony size.
DKG_CHUNKS = 8
SECRET_BOUND = 1 << 20

_CONTEXT = b"biscotti-dkg-v1"

# Metric family for live-ceremony deal intake (emitted by the DkgDeal
# RPC handler in runtime/peer.py; row in docs/OBSERVABILITY.md).
DEALS_METRIC = "biscotti_dkg_deals_total"
DEALS_HELP = ("genesis DKG deals received over the DkgDeal RPC, by "
              "verification verdict")


def share_points(n_parties: int) -> List[int]:
    """The ceremony's share points: party i holds x = i + 1 (zero is the
    secret's point and must never be dealt)."""
    return [i + 1 for i in range(int(n_parties))]


@dataclass
class DkgDeal:
    """One dealer's complete deal: the public commitment grid plus the
    per-recipient share/blind rows. In a live ceremony only
    (`comms`, `for_recipient(j)`) travel to recipient j; the in-process
    simulation keeps the whole tensor for convenience."""

    dealer_id: int
    comms: np.ndarray       # uint8 [C, k, 64] Pedersen grid
    xs: List[int]           # the share points this deal was evaluated at
    rows: np.ndarray        # int64 [S, C] share rows (row j -> party j)
    blind_rows: np.ndarray  # uint8 [S, C, 32] blinding rows

    def digest(self) -> bytes:
        """Binding digest of the public grid — what the transcript and
        any dealer-equivocation check are computed over."""
        return cm.vss_digest(self.comms)

    def for_recipient(self, idx: int) -> Tuple[np.ndarray, np.ndarray]:
        """(share row [C], blind row [C, 32]) destined for party `idx`
        (position in `xs`, not the x value)."""
        return self.rows[idx], self.blind_rows[idx]


@dataclass
class DkgShare:
    """One party's aggregated ceremony output: its joint share of the
    genesis secret plus the joint public grid every holder agrees on."""

    party_id: int
    x: int
    row: np.ndarray         # int64 [C] joint share values
    blind_row: np.ndarray   # uint8 [C, 32] joint blinding values
    joint_comms: np.ndarray  # uint8 [C, k, 64] summed grid
    dealers: List[int]      # accepted dealer ids, sorted

    def verify(self) -> bool:
        """Check this party's joint share against the joint grid — the
        holder-side invariant any later resharing/migration re-proves."""
        return cm.vss_verify_multi([
            (self.joint_comms, [self.x],
             self.row.reshape(1, -1).astype(np.int64),
             self.blind_row.reshape(1, -1, 32))])


def _xof(seed: bytes, tag: bytes, nbytes: int) -> bytes:
    return hashlib.shake_256(seed + _CONTEXT + tag).digest(nbytes)


def contribute(dealer_id: int, xs: Sequence[int], threshold: int,
               seed: bytes, chunks: int = DKG_CHUNKS) -> DkgDeal:
    """Build dealer `dealer_id`'s deal: a random bounded secret row, a
    degree-(threshold-1) sharing polynomial per chunk, the Pedersen grid
    over the coefficients, and the evaluation at every party's point.
    Deterministic in `seed` — same seed, same deal — so ceremonies are
    replayable like every other plane."""
    xs = [int(x) for x in xs]
    k = int(threshold)
    if k < 2:
        raise ValueError("DKG threshold must be >= 2 (a 1-threshold "
                         "ceremony hands every dealer the joint secret)")
    if len(xs) < k:
        raise ValueError(
            f"{len(xs)} parties cannot hold a threshold-{k} secret")
    if len(set(xs)) != len(xs) or 0 in xs:
        raise ValueError(f"share points must be distinct and nonzero: {xs}")
    # the contribution: one bounded-uniform int64 row [1, C]
    raw = _xof(seed, b"|secret", 8 * chunks)
    vals = np.frombuffer(raw, dtype="<u8").astype(np.int64)
    secret_row = (np.abs(vals) % (2 * SECRET_BOUND + 1)) - SECRET_BOUND
    secret_row = secret_row.reshape(1, chunks)
    # constant blinding values, one per chunk, full-width in Z_q
    braw = _xof(seed, b"|blind0", 32 * chunks)
    blind0 = [int.from_bytes(braw[32 * i: 32 * i + 32], "little") % ed.Q
              for i in range(chunks)]
    coeffs = ss.reshare_coeffs(secret_row, k, seed,
                               _CONTEXT + b"|deal%d" % int(dealer_id))
    comms, blinds = cm.reshare_commit_row(
        coeffs[0], blind0, seed, _CONTEXT + b"|deal%d" % int(dealer_id))
    rows = ss.reshare_subshares(coeffs, xs)[:, 0, :]  # [S, C]
    blind_rows = cm.vss_blind_rows(blinds, xs)        # [S, C, 32]
    return DkgDeal(dealer_id=int(dealer_id), comms=comms, xs=xs,
                   rows=rows, blind_rows=blind_rows)


def verify_deal(deal: DkgDeal) -> bool:
    """Recipient-side acceptance check: every share row must open the
    dealer's own grid (batched Pedersen VSS). There is no binding check
    against an 'original' grid — at genesis the dealer's grid IS the
    original; what binds the dealer is that its constant-term commitment
    is published before any share is accepted, so it cannot deal
    different secrets to different recipients without the grids (and
    hence the transcript) diverging."""
    comms = np.asarray(deal.comms)
    if comms.ndim != 3 or comms.shape[2] != 64:
        return False
    rows = np.asarray(deal.rows, np.int64)
    if rows.shape != (len(deal.xs), comms.shape[0]):
        return False
    return cm.vss_verify_multi([
        (comms, list(deal.xs), rows,
         np.asarray(deal.blind_rows, np.uint8))])


def transcript_hash(deals: Sequence[DkgDeal]) -> bytes:
    """Ceremony transcript: SHA-256 over the sorted (dealer, grid-digest)
    pairs of the ACCEPTED deals. Every honest party computes the same
    value, and no single party controls it — one honest dealer's
    unpredictable grid randomizes the whole hash."""
    h = hashlib.sha256(_CONTEXT + b"|transcript")
    for deal in sorted(deals, key=lambda d: d.dealer_id):
        h.update(int(deal.dealer_id).to_bytes(4, "little"))
        h.update(deal.digest())
    return h.digest()


def commit_key_label(deals: Sequence[DkgDeal]) -> str:
    """The commitment-key label a DKG-booted cluster derives its
    generator ladder from: transcript-bound, so the ladder is fixed by
    the ceremony rather than picked by any party (the dealer path's
    static label is the legacy alternative)."""
    return f"biscotti-dkg-v1:{transcript_hash(deals).hex()}"


def aggregate(deals: Sequence[DkgDeal],
              reject: Optional[List[int]] = None) -> List[DkgShare]:
    """Verify every deal, sum the accepted ones, and hand each party its
    joint share. Deals that fail verification are EXCLUDED (their dealer
    ids land in `reject` when provided) — exclusion is loud, never a
    silent fallback, because a party that accepts an unverified deal
    holds a share that opens nothing."""
    accepted = []
    for deal in deals:
        if verify_deal(deal):
            accepted.append(deal)
        elif reject is not None:
            reject.append(int(deal.dealer_id))
    if not accepted:
        raise ValueError("DKG ceremony has no verifiable deals")
    xs = accepted[0].xs
    if any(d.xs != xs for d in accepted):
        raise ValueError("accepted deals disagree on the share points")
    joint_comms = cm.sum_commitment_grids([d.comms for d in accepted])
    if joint_comms is None:
        raise ValueError("accepted deal grid failed to load during "
                         "aggregation (off-curve cell)")
    joint_rows = np.sum(np.stack([d.rows for d in accepted]), axis=0)
    joint_blinds = cm.sum_blind_row_tensors(
        [d.blind_rows for d in accepted])
    dealers = sorted(int(d.dealer_id) for d in accepted)
    return [DkgShare(party_id=j, x=int(x), row=joint_rows[j].copy(),
                     blind_row=joint_blinds[j].copy(),
                     joint_comms=joint_comms, dealers=dealers)
            for j, x in enumerate(xs)]


def recover_secret(shares: Sequence[DkgShare], threshold: int) -> np.ndarray:
    """Threshold recovery of the joint genesis secret from any
    >= `threshold` holders' joint shares: exact rational interpolation
    with the integrality corruption detector (a perturbed row makes some
    recovered coefficient non-integer and raises ValueError — recovery
    never silently absorbs a corrupt holder)."""
    if len(shares) < int(threshold):
        raise ValueError(
            f"{len(shares)} shares below the ceremony threshold "
            f"{threshold}")
    xs = [s.x for s in shares]
    sub = np.stack([np.asarray(s.row, np.int64) for s in shares])
    return ss.reshare_recover_rows(sub[:, None, :], xs,
                                   poly_size=int(threshold))[0]


def secret_digest(secret_row: np.ndarray) -> bytes:
    """Digest of the recovered joint secret — the ceremony's genesis
    entropy (seeds, labels), never the secret itself, is what artifacts
    carry."""
    return hashlib.sha256(
        _CONTEXT + b"|secret"
        + np.ascontiguousarray(secret_row, np.int64).tobytes()).digest()


@dataclass
class CeremonyResult:
    """Everything keygen needs from a finished in-process ceremony."""

    shares: List[DkgShare]
    deals: List[DkgDeal]
    rejected: List[int]
    threshold: int

    @property
    def transcript(self) -> bytes:
        accepted = [d for d in self.deals
                    if int(d.dealer_id) not in set(self.rejected)]
        return transcript_hash(accepted)

    @property
    def label(self) -> str:
        accepted = [d for d in self.deals
                    if int(d.dealer_id) not in set(self.rejected)]
        return commit_key_label(accepted)


def run_ceremony(n_parties: int, threshold: int,
                 rng_seed: Optional[int] = None,
                 chunks: int = DKG_CHUNKS) -> CeremonyResult:
    """Simulate the N-party ceremony in one process (keygen, tests).

    Each simulated party draws its dealer seed independently (from OS
    randomness, or deterministically from `rng_seed` for replayable test
    ceremonies), deals, verifies every other deal, and aggregates. The
    simulation preserves the trust structure — every deal passes through
    `verify_deal` before any share sums it, exactly as live peers would
    over the `DkgDeal` RPC — it only collapses the transport."""
    import secrets as _secrets

    xs = share_points(n_parties)
    deals = []
    for i in range(int(n_parties)):
        if rng_seed is None:
            seed = _secrets.token_bytes(32)
        else:
            seed = hashlib.sha256(
                _CONTEXT + b"|party%d|%d" % (i, int(rng_seed))).digest()
        deals.append(contribute(i, xs, threshold, seed, chunks=chunks))
    rejected: List[int] = []
    shares = aggregate(deals, reject=rejected)
    return CeremonyResult(shares=shares, deals=deals, rejected=rejected,
                          threshold=int(threshold))
