"""Pure-Python Edwards25519 group arithmetic (RFC 8032 curve).

Control-plane only: the VRF role lottery runs a handful of group operations
per round per peer, far off the hot path (the reference likewise runs its
ed25519 VRF on the host CPU; ref: DistSys/vrf.go:5, vendored coniks-go at
vrf-reference/crypto/vrf/). Extended homogeneous coordinates keep scalar
multiplication inversion-free; a single field inversion happens at encode.

No external dependencies — `hashlib` only.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

P = 2**255 - 19
Q = 2**252 + 27742317777372353535851937790883648493  # group order ℓ
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
COFACTOR = 8

# Base point: y = 4/5, x the even root.
B_Y = (4 * pow(5, P - 2, P)) % P
B_X = 15112221349535400772501151409588531511454012693041857206046113283949847762202

# Extended coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.
Point = Tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)
BASE: Point = (B_X, B_Y, 1, (B_X * B_Y) % P)


def point_add(p: Point, q: Point) -> Point:
    """Complete addition for a = −1 twisted Edwards (RFC 8032 §5.1.4)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (2 * t1 * D % P) * t2 % P
    dd = (2 * z1 * z2) % P
    e = (b - a) % P
    f = (dd - c) % P
    g = (dd + c) % P
    h = (b + a) % P
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def point_double(p: Point) -> Point:
    x1, y1, z1, _ = p
    a = (x1 * x1) % P
    b = (y1 * y1) % P
    c = (2 * z1 * z1) % P
    h = (a + b) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def point_neg(p: Point) -> Point:
    x, y, z, t = p
    return ((-x) % P, y, z, (-t) % P)


def scalar_mult(k: int, p: Point) -> Point:
    """Double-and-add; not constant-time (lottery inputs are public)."""
    acc = IDENTITY
    addend = p
    while k > 0:
        if k & 1:
            acc = point_add(acc, addend)
        addend = point_double(addend)
        k >>= 1
    return acc


def base_mult(k: int) -> Point:
    return scalar_mult(k % Q, BASE)


def point_equal(p: Point, q: Point) -> bool:
    # X1/Z1 == X2/Z2  <=>  X1·Z2 == X2·Z1 (same for Y)
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def is_identity(p: Point) -> bool:
    return point_equal(p, IDENTITY)


def to_affine(p: Point) -> tuple:
    """(x, y) affine coordinates."""
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    return (x * zinv) % P, (y * zinv) % P


def point_compress(p: Point) -> bytes:
    xa, ya = to_affine(p)
    return ((ya | ((xa & 1) << 255)).to_bytes(32, "little"))


def point_decompress(s: bytes) -> Optional[Point]:
    if len(s) != 32:
        return None
    val = int.from_bytes(s, "little")
    sign = val >> 255
    y = val & ((1 << 255) - 1)
    if y >= P:
        return None
    # x² = (y² − 1) / (d·y² + 1)
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root: (u/v)^((p+3)/8) = u·v³·(u·v⁷)^((p−5)/8)
    x = (u * pow(v, 3, P) % P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    if (v * x * x) % P == u:
        pass
    elif (v * x * x) % P == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if (x & 1) != sign:
        x = P - x
    return (x, y, 1, (x * y) % P)


def clamp_scalar(h32: bytes) -> int:
    a = bytearray(h32[:32])
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


def secret_expand(seed: bytes) -> Tuple[int, bytes]:
    """RFC 8032 key expansion: seed → (clamped scalar, 32-byte prefix)."""
    h = hashlib.sha512(seed).digest()
    return clamp_scalar(h[:32]), h[32:]


def public_key(seed: bytes) -> bytes:
    x, _ = secret_expand(seed)
    return point_compress(base_mult(x))


def hash_to_point(prefix: bytes, suffix: bytes = b"",
                  decompress=None) -> Point:
    """Try-and-increment hash-to-curve, cofactor-cleared (the RFC 9381
    §5.4.1.1 TAI construction). Candidate = first 32 bytes of
    SHA-512(prefix ‖ ctr ‖ suffix) for ctr = 0..255. Shared by the VRF's
    encode-to-curve and the commitment-scheme generator derivation —
    security-critical, keep the single copy. `decompress` lets callers
    inject an accelerated (but semantically identical) decompression —
    this module itself stays dependency-free pure python."""
    decompress = decompress or point_decompress
    for ctr in range(256):
        h = hashlib.sha512(prefix + bytes([ctr]) + suffix).digest()[:32]
        pt = decompress(h)
        if pt is None:
            continue
        pt8 = scalar_mult(COFACTOR, pt)
        if not is_identity(pt8):
            return pt8
    raise ValueError("hash_to_point failed for all 256 counters")
