"""Accelerator-resident crypto plane (ISSUE 13, ROADMAP open item #2).

After PR 6's batching, miner crypto is one big multi-scalar
multiplication per intake — CPU bigint work while the device idles. This
package moves the four hot kernels onto the accelerator as limb-
decomposed vmapped jnp programs (`field.py` → `group.py` → `msm.py`),
behind one process-wide arming switch:

    from biscotti_tpu.crypto import kernels
    kernels.set_enabled(True)          # what --device-crypto does
    kernels.active()                   # armed AND runnable here

**Default OFF.** Disarmed (or unavailable: no jax, x64 mode off), every
caller takes today's CPU path bit-identically. Armed, the seams PR 6
created — `cm.batch_verify_commitments`, `VssIntakeBatch` wave folds,
`cm.batch_schnorr_verify`, `ss.recover_coeffs` — compute their batch
verdicts on device; the CPU path stays the exact-verdict oracle, and
REJECTION evidence (bisection, per-worker fallback, stake debits) always
comes from the CPU recompute, so debits stay byte-identical
(docs/CRYPTO_KERNELS.md spells out the contract; the property suite in
tests/test_crypto_kernels.py pins every kernel against the python-int
oracles).

Importing this package is cheap (numpy only): jax loads lazily on first
`available()` / kernel call, so the disarmed runtime never pays for it.
"""

from __future__ import annotations

import sys
from typing import Optional

from biscotti_tpu.crypto.kernels.instrument import (  # noqa: F401
    device_calls, device_seconds, release_hooks, reset_counters,
    set_metrics_registry, set_span_hook)
from biscotti_tpu.crypto.kernels.primitives import (  # noqa: F401
    ext_add, fixed_base_mult, grid_validate_sum, msm, pedersen_commit_point,
    point_neg_limbs, prewarm, shamir_recover)

_enabled = False
_avail: Optional[bool] = None
_avail_reason = ""
_warned = False


def set_enabled(on: bool) -> None:
    """Arm/disarm the device-crypto plane process-wide (the
    --device-crypto switch). Arming while unavailable degrades loudly —
    one stderr note naming why — but gracefully: every seam keeps its
    CPU path."""
    global _enabled, _warned
    _enabled = bool(on)
    if _enabled and not available() and not _warned:
        _warned = True
        print(f"[crypto/kernels] --device-crypto requested but the device "
              f"plane is unavailable ({_avail_reason}); all crypto stays "
              f"on the CPU path", file=sys.stderr)


def enabled() -> bool:
    return _enabled


def available() -> bool:
    """True when the kernel plane can run here: jax imports and x64 mode
    is on (the limb accumulators are int64; enable via JAX_ENABLE_X64=1
    or jax.config.update('jax_enable_x64', True) before first use)."""
    global _avail, _avail_reason
    if _avail is None:
        try:
            import jax

            if not jax.config.jax_enable_x64:
                _avail = False
                _avail_reason = ("jax x64 mode disabled — int64 limb "
                                 "accumulators need JAX_ENABLE_X64=1")
            else:
                jax.devices()
                _avail = True
        except Exception as e:  # pragma: no cover - env-dependent
            _avail = False
            _avail_reason = f"jax unavailable: {type(e).__name__}: {e}"
    return bool(_avail)


def availability_reason() -> str:
    available()
    return _avail_reason


def active() -> bool:
    """Armed AND runnable — the one predicate every CPU/device dispatch
    seam consults."""
    return _enabled and available()


def active_module():
    """This package when `active()`, else None — the shared body of the
    per-seam `_device_mod()` probes (commitments.py, secretshare.py), so
    the dispatch predicate lives in exactly one place."""
    import biscotti_tpu.crypto.kernels as _k

    return _k if active() else None


def _reset_probe_for_tests() -> None:
    """Forget the cached availability probe (tests flip x64/jax state)."""
    global _avail, _avail_reason, _warned
    _avail = None
    _avail_reason = ""
    _warned = False
