"""Limb-decomposed Ed25519 base-field arithmetic as pure jnp ops.

The accelerator has no bigint datapath, so field elements of
GF(p), p = 2²⁵⁵ − 19 are carried as **16 radix-2¹⁶ limbs** (stored int32
on the wire buffers, widened to int64 inside the kernels) with *lazy*
carries: ops keep limbs inside a loose `< 2¹⁷` invariant instead of
canonicalizing after every step, so a field multiply is one outer-product
+ one constant [256, 31] convolution matmul (MXU-shaped) + two short
carry chains. The loose invariant is what makes the bounds work:

    inputs  < 2¹⁷ per limb
    products < 2³⁴, convolution sum of ≤ 16 terms < 2³⁸
    2²⁵⁶ ≡ 38 fold:  lo + 38·hi < 2³⁸·39 < 2⁴⁴  — comfortably int64
    two carry passes → every limb back under 2¹⁷

Hot-path carries are PARALLEL carry-save passes (4 vector ops, no
16-step chain — see `carry`); only the canonical representative pays
for exact sequential propagation (`carry_seq`). Subtraction adds a
limb-wise 8p constant (representable in 16 *non-normalized* limbs, each
≥ 2¹⁸ > any loose limb) so intermediate limbs never need
signed-magnitude handling beyond the carry passes' arithmetic shifts.
Exact canonical form (for equality / on-curve verdicts) is four
sequential carry passes + two conditional subtractions of p — value
< 2²⁵⁶ < 2p + 38 makes two enough.

Everything here is shape-polymorphic over leading batch dimensions
([..., 16] limb tensors), so the group layer vmaps for free. Host-side
packing helpers (python ints / RFC-8032 byte strings ↔ limb arrays) are
numpy, zero python-bigint work per element beyond `int.to_bytes`.

Oracle: `crypto/ed25519.py` python ints — every op here is property-
tested bit-equal against it (tests/test_crypto_kernels.py, including the
carry-overflow edges 0, 1, p−1, q−1, all-limbs-0xFFFF).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from biscotti_tpu.crypto import ed25519 as ed

LIMBS = 16
LIMB_BITS = 16
MASK = (1 << LIMB_BITS) - 1

P = ed.P
Q = ed.Q

# 2²⁵⁶ mod p = 38 — the high-half fold constant
FOLD = 38


def int_to_limbs(v: int) -> np.ndarray:
    """One canonical field element → (16,) int32 limb vector."""
    b = (int(v) % P).to_bytes(32, "little")
    return np.frombuffer(b, dtype="<u2").astype(np.int32)


def ints_to_limbs(vals: Sequence[int]) -> np.ndarray:
    """[n] canonical field elements → [n, 16] int32 limbs (one bytes
    join, no per-limb python arithmetic)."""
    blob = b"".join((int(v) % P).to_bytes(32, "little") for v in vals)
    return (np.frombuffer(blob, dtype="<u2")
            .reshape(len(vals), LIMBS).astype(np.int32))


def limbs_to_int(arr) -> int:
    """(…,16) limb vector (any non-negative magnitudes) → python int.
    NOT reduced mod p — callers reduce when they need the field value."""
    a = np.asarray(arr, dtype=object).reshape(-1)
    return sum(int(a[i]) << (LIMB_BITS * i) for i in range(len(a)))


def bytes_to_limbs(buf: bytes, n: int) -> np.ndarray:
    """n packed 32-byte little-endian values → [n, 16] int32 limbs.
    No canonicity check — feed the result to `lt_p` for that."""
    if len(buf) != 32 * n:
        raise ValueError("buffer length mismatch")
    return (np.frombuffer(buf, dtype="<u2")
            .reshape(n, LIMBS).astype(np.int32))


# constant limb tables (numpy; jnp closes over them as constants).
# P itself must bypass int_to_limbs — that helper canonicalizes mod p,
# which would turn the modulus into the zero vector.
P_LIMBS = np.frombuffer(P.to_bytes(32, "little"),
                        dtype="<u2").astype(np.int64)
# 8p as 16 NON-NORMALIZED limbs: 4 × (2²⁵⁶ − 38) limb-wise. Every limb is
# ≥ 2¹⁸ − 152 > 2¹⁷, so `a + EIGHT_P - b` never goes negative under the
# loose < 2¹⁷ limb invariant.
EIGHT_P = (np.array([0xFFFF - 37] + [0xFFFF] * 15, dtype=np.int64) * 4)
D_LIMBS = int_to_limbs(ed.D).astype(np.int64)
D2_LIMBS = int_to_limbs(2 * ed.D % P).astype(np.int64)
ONE_LIMBS = int_to_limbs(1).astype(np.int64)
ZERO_LIMBS = np.zeros(LIMBS, dtype=np.int64)


def _conv_matrix() -> np.ndarray:
    """[256, 31] 0/1 matrix routing the 16×16 outer products to their
    convolution diagonals — the field multiply becomes one matmul."""
    m = np.zeros((LIMBS * LIMBS, 2 * LIMBS - 1), dtype=np.int64)
    for i in range(LIMBS):
        for j in range(LIMBS):
            m[i * LIMBS + j, i + j] = 1
    return m


CONV = _conv_matrix()


def _jnp():
    import jax.numpy as jnp

    return jnp


def carry(x, passes: int = 2):
    """PARALLEL (carry-save) lazy-carry passes with the 2²⁵⁶ ≡ 38 top
    fold: every pass is four vector ops (split, mask, rotate-with-fold,
    add) with NO sequential limb chain — the hot-ladder form. A pass
    moves each carry one limb; it does NOT fully propagate, which the
    loose `< 2¹⁷` invariant tolerates:

        post-multiply v < 2⁴⁴  → pass 1 carries < 2²⁸, limbs < 2¹⁶+2²⁸
                               → pass 2 carries < 2¹³, limbs < 2¹⁶+2¹³ ✓
        post-add/sub  v < 2¹⁹  → one pass leaves limbs < 2¹⁶+2⁹ ✓

    Arithmetic shifts + two's-complement masking keep the pass exact for
    the ≥ −2¹⁶ limbs subtraction can transiently produce. Exact
    propagation (canonical form) is `carry_seq`'s job."""
    jnp = _jnp()
    for _ in range(passes):
        c = x >> LIMB_BITS
        rot = jnp.concatenate([FOLD * c[..., LIMBS - 1:],
                               c[..., :LIMBS - 1]], axis=-1)
        x = (x & MASK) + rot
    return x


def carry_seq(x, passes: int = 2):
    """Sequential full-propagation carry chains (the slow exact form the
    canonical representative needs). Arithmetic shifts make the chain
    correct for (slightly) negative limbs too."""
    jnp = _jnp()
    for _ in range(passes):
        out = []
        c = jnp.zeros_like(x[..., 0])
        for i in range(LIMBS):
            v = x[..., i] + c
            c = v >> LIMB_BITS
            out.append(v & MASK)
        x = jnp.stack(out, axis=-1)
        x = x.at[..., 0].add(FOLD * c)
    return x


def fmul(a, b):
    """Field multiply of two loose (< 2¹⁷ limbs) elements; returns a
    loose element. One outer product + the CONV matmul + fold + carries."""
    jnp = _jnp()
    prod = a[..., :, None] * b[..., None, :]  # [..., 16, 16] < 2^34
    conv = prod.reshape(*prod.shape[:-2], LIMBS * LIMBS) @ CONV  # [..., 31]
    lo = conv[..., :LIMBS]
    hi = jnp.concatenate(
        [conv[..., LIMBS:],
         jnp.zeros_like(conv[..., :1])], axis=-1)  # pad position 31
    return carry(lo + FOLD * hi, passes=2)


def fadd(a, b):
    return carry(a + b, passes=1)


def fsub(a, b):
    """a − b mod p via the non-normalized 8p limb constant (keeps every
    intermediate limb non-negative under the loose invariant)."""
    return carry(a + EIGHT_P - b, passes=1)


def _cond_sub_p(x):
    """One conditional canonical-form subtraction: x − p when x ≥ p.
    Requires properly carried limbs (< 2¹⁶)."""
    jnp = _jnp()
    outs = []
    borrow = jnp.zeros_like(x[..., 0])
    for i in range(LIMBS):
        v = x[..., i] - int(P_LIMBS[i]) - borrow
        borrow = (v < 0).astype(v.dtype)
        outs.append(v + (borrow << LIMB_BITS))
    sub = jnp.stack(outs, axis=-1)
    keep = (borrow > 0)[..., None]  # final borrow → x < p → keep x
    return jnp.where(keep, x, sub)


def canonical(x):
    """Exact canonical representative (< p, limbs < 2¹⁶) of a loose
    element — the form equality and on-curve verdicts compare. Four
    sequential passes: three settle the loose magnitudes, the fourth
    retires the ≤ 38 residue the top fold can leave on limb 0, so
    `_cond_sub_p`'s borrow logic always sees properly carried limbs."""
    x = carry_seq(x, passes=4)
    x = _cond_sub_p(x)
    return _cond_sub_p(x)


def is_zero(x):
    """True where the loose element ≡ 0 mod p. Returns a boolean with
    the input's batch shape."""
    jnp = _jnp()
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a, b):
    jnp = _jnp()
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def lt_p(x):
    """Canonicity test for *carried* (< 2¹⁶ limbs) values: strict x < p,
    matching the pure-python loaders' rejection of non-canonical wire
    coordinates."""
    jnp = _jnp()
    lt = jnp.zeros(x.shape[:-1], dtype=bool)
    eq_so_far = jnp.ones(x.shape[:-1], dtype=bool)
    for i in range(LIMBS - 1, -1, -1):
        pi = int(P_LIMBS[i])
        lt = lt | (eq_so_far & (x[..., i] < pi))
        eq_so_far = eq_so_far & (x[..., i] == pi)
    return lt


def scalars_to_bits(scalars: Sequence[int], bits: int = 256,
                    msb_first: bool = True) -> np.ndarray:
    """[n] non-negative ints (< 2^bits) → [n, bits] uint8 bit matrix.
    MSB-first is the double-and-add order; LSB-first feeds the fixed-base
    table walk."""
    n = len(scalars)
    blob = b"".join(int(s).to_bytes(bits // 8, "little") for s in scalars)
    by = np.frombuffer(blob, dtype=np.uint8).reshape(n, bits // 8)
    b = np.unpackbits(by, axis=1, bitorder="little")  # [n, bits] LSB-first
    return b[:, ::-1].copy() if msb_first else b


__all__: List[str] = [
    "LIMBS", "LIMB_BITS", "MASK", "P", "Q", "CONV",
    "int_to_limbs", "ints_to_limbs", "limbs_to_int", "bytes_to_limbs",
    "P_LIMBS", "EIGHT_P", "D_LIMBS", "D2_LIMBS", "ONE_LIMBS", "ZERO_LIMBS",
    "carry", "carry_seq", "fmul", "fadd", "fsub", "canonical", "is_zero", "eq", "lt_p",
    "scalars_to_bits",
]
