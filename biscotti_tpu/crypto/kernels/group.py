"""Extended-coordinate Edwards25519 group ops over limb tensors.

A point batch is one int64 tensor [..., 4, 16] — rows X, Y, Z, T of the
extended homogeneous coordinates (x = X/Z, y = Y/Z, T = XY/Z), each a
16-limb field element from `kernels.field`. The complete a = −1 twisted
Edwards addition (RFC 8032 §5.1.4) is formula-for-formula the
pure-python `crypto/ed25519.py` oracle, so the two backends compute the
*same group element* on every input — verdict parity is algebraic, not
numerical.

All ops are shape-polymorphic over leading batch dims; `select` is the
vmappable conditional the scalar-mult ladders branch with (no data-
dependent control flow on device).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from biscotti_tpu.crypto import ed25519 as ed
from biscotti_tpu.crypto.kernels import field as fe


def _jnp():
    import jax.numpy as jnp

    return jnp


# identity (0, 1, 1, 0) as a [4, 16] limb constant
IDENTITY_LIMBS = np.stack([
    fe.ZERO_LIMBS, fe.ONE_LIMBS.astype(np.int64),
    fe.ONE_LIMBS.astype(np.int64), fe.ZERO_LIMBS,
]).astype(np.int64)


def identity(shape=()) -> np.ndarray:
    """Identity point broadcast to leading batch shape `shape`."""
    out = np.broadcast_to(IDENTITY_LIMBS, tuple(shape) + (4, fe.LIMBS))
    return np.ascontiguousarray(out)


def point_add(p, q):
    """Complete addition — ed25519.point_add, limb-for-limb."""
    jnp = _jnp()
    x1, y1, z1, t1 = (p[..., 0, :], p[..., 1, :], p[..., 2, :],
                      p[..., 3, :])
    x2, y2, z2, t2 = (q[..., 0, :], q[..., 1, :], q[..., 2, :],
                      q[..., 3, :])
    a = fe.fmul(fe.fsub(y1, x1), fe.fsub(y2, x2))
    b = fe.fmul(fe.fadd(y1, x1), fe.fadd(y2, x2))
    c = fe.fmul(fe.fmul(t1, fe.D2_LIMBS), t2)
    zz = fe.fmul(z1, z2)
    dd = fe.fadd(zz, zz)
    e = fe.fsub(b, a)
    f = fe.fsub(dd, c)
    g = fe.fadd(dd, c)
    h = fe.fadd(b, a)
    return jnp.stack([fe.fmul(e, f), fe.fmul(g, h),
                      fe.fmul(f, g), fe.fmul(e, h)], axis=-2)


def point_double(p):
    """ed25519.point_double, limb-for-limb."""
    jnp = _jnp()
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe.fmul(x1, x1)
    b = fe.fmul(y1, y1)
    zz = fe.fmul(z1, z1)
    c = fe.fadd(zz, zz)
    h = fe.fadd(a, b)
    xy = fe.fadd(x1, y1)
    e = fe.fsub(h, fe.fmul(xy, xy))
    g = fe.fsub(a, b)
    f = fe.fadd(c, g)
    return jnp.stack([fe.fmul(e, f), fe.fmul(g, h),
                      fe.fmul(f, g), fe.fmul(e, h)], axis=-2)


def select(mask, p, q):
    """Per-lane conditional: mask True → p, else q. mask has the batch
    shape of p/q minus the trailing (4, 16)."""
    jnp = _jnp()
    return jnp.where(mask[..., None, None], p, q)


def on_curve(x, y):
    """−x² + y² = 1 + d·x²y² over loose limb elements → bool batch."""
    xx = fe.fmul(x, x)
    yy = fe.fmul(y, y)
    lhs = fe.fsub(yy, xx)
    rhs = fe.fadd(fe.ONE_LIMBS.astype(np.int64),
                  fe.fmul(fe.D_LIMBS, fe.fmul(xx, yy)))
    return fe.eq(lhs, rhs)


def tree_sum(pts):
    """Pointwise batch reduction Σᵢ pts[i] along axis 0 (length must be a
    power of two — pad with identity) via log₂ halving rounds of the
    complete addition."""
    n = pts.shape[0]
    assert n and (n & (n - 1)) == 0, "tree_sum wants a power-of-two batch"
    while n > 1:
        half = n // 2
        pts = point_add(pts[:half], pts[half:n])
        n = half
    return pts[0]


# ----------------------------------------------------- host conversions


def points_to_limbs(points: Sequence[ed.Point]) -> np.ndarray:
    """[n] extended-coordinate python-int points → [n, 4, 16] int32
    limbs (one bytes join per coordinate row)."""
    n = len(points)
    blob = b"".join(
        (c % fe.P).to_bytes(32, "little")
        for pt in points for c in pt)
    return (np.frombuffer(blob, dtype="<u2")
            .reshape(n, 4, fe.LIMBS).astype(np.int32))


def ext_bytes_to_limbs(buf: bytes, n: int) -> np.ndarray:
    """n×128-byte extended buffers (the native plane's wire form:
    x‖y‖z‖t, 32B LE each) → [n, 4, 16] int32 limbs."""
    if len(buf) != 128 * n:
        raise ValueError("extended buffer length mismatch")
    return (np.frombuffer(buf, dtype="<u2")
            .reshape(n, 4, fe.LIMBS).astype(np.int32))


def xy_bytes_to_limbs(buf, n: int) -> np.ndarray:
    """n×64-byte affine (x, y) LE pairs (the VSS commitment wire form) →
    [n, 2, 16] int32 limbs, uninterpreted — validation happens on
    device (`msm.grid_validate_sum`)."""
    arr = np.frombuffer(bytes(buf), dtype="<u2")
    if arr.size != 32 * n:
        raise ValueError("xy buffer length mismatch")
    return arr.reshape(n, 2, fe.LIMBS).astype(np.int32)


def limbs_to_point(arr) -> ed.Point:
    """[4, 16] limb tensor (any loose magnitudes) → extended python-int
    point, coordinates reduced mod p."""
    a = np.asarray(arr)
    coords = [fe.limbs_to_int(a[i]) % fe.P for i in range(4)]
    return (coords[0], coords[1], coords[2], coords[3])


__all__: List[str] = [
    "IDENTITY_LIMBS", "identity", "point_add", "point_double", "select",
    "on_curve", "tree_sum", "points_to_limbs", "ext_bytes_to_limbs",
    "xy_bytes_to_limbs", "limbs_to_point",
]
