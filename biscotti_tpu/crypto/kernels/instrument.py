"""Device-crypto instrumentation seam (stdlib-only, import-cheap).

Every public kernel entry wraps itself in `timed(kernel)`, which charges
three sinks at once:

  * a module-level seconds/calls accumulator (`device_seconds()` /
    `device_calls()`) — what bench.py and the chaos report read;
  * the `biscotti_crypto_device_seconds{kernel=}` histogram on whatever
    registry the runtime installed (`set_metrics_registry`, wired by
    PeerAgent when --device-crypto is armed with telemetry on);
  * an optional span hook (`set_span_hook`) the runtime points at
    `Telemetry.span("crypto_device", kernel=...)`, so the flight
    recorder / trace_round / profile_round see device work as its own
    `crypto_device` critical-path segment, tagged at the kernel call
    site.

Hooks are process-global by design (the arming switch is too): one
live cluster per process is the supported deployment, and in-process
test harnesses arm/disarm around each cluster.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_seconds: Dict[str, float] = {}
_calls: Dict[str, int] = {}
_metrics_registry = None
_span_hook: Optional[Callable] = None
# THREAD-local, not a module global: co-hosted peers prewarm
# concurrently from separate to_thread workers, and a global flag's
# unordered enter/restore pairs can race each other into leaving the
# whole process silenced (observed live: a 4-peer cluster reporting
# zero kernel calls). Each worker suppresses only its own calls.
_tls = threading.local()


@contextlib.contextmanager
def suppressed():
    """Silence ALL instrumentation (spans, metrics, accumulators) for
    the CALLING THREAD for the duration — prewarm compiles run under
    this so warm-up wall-clock never pollutes the round-work readouts
    (device_seconds, the histogram, crypto_device spans; profile_round's
    residency split relies on every emitted span being nested round
    work)."""
    prev = getattr(_tls, "suppress", False)
    _tls.suppress = True
    try:
        yield
    finally:
        _tls.suppress = prev


def set_metrics_registry(reg) -> None:
    """Install (or clear, with None) the MetricsRegistry receiving the
    `biscotti_crypto_device_seconds` histogram."""
    global _metrics_registry
    _metrics_registry = reg


def set_span_hook(hook: Optional[Callable]) -> None:
    """Install a callable `hook(kernel_name) -> context manager` opened
    around every kernel call — the runtime passes a `crypto_device`
    telemetry span factory. None disarms."""
    global _span_hook
    _span_hook = hook


def release_hooks(span_hook=None, registry=None) -> None:
    """Identity-guarded teardown: clear each hook only if it is STILL
    the one the caller installed. A shut-down peer must drop its hooks
    (the span closure pins the whole agent object graph, and a dead
    cluster's telemetry must stop receiving kernel events) without
    stripping a later live agent's installation."""
    global _span_hook, _metrics_registry
    if span_hook is not None and _span_hook is span_hook:
        _span_hook = None
    if registry is not None and _metrics_registry is registry:
        _metrics_registry = None


def device_seconds() -> Dict[str, float]:
    """Cumulative wall-clock per kernel since process start (or the last
    reset) — end-to-end: host marshalling + XLA execute."""
    with _lock:
        return dict(_seconds)


def device_calls() -> Dict[str, int]:
    with _lock:
        return dict(_calls)


def reset_counters() -> None:
    with _lock:
        _seconds.clear()
        _calls.clear()


@contextlib.contextmanager
def timed(kernel: str):
    if getattr(_tls, "suppress", False):
        yield
        return
    hook = _span_hook
    cm = hook(kernel) if hook is not None else contextlib.nullcontext()
    t0 = time.perf_counter()
    try:
        with cm:
            yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _seconds[kernel] = _seconds.get(kernel, 0.0) + dt
            _calls[kernel] = _calls.get(kernel, 0) + 1
        reg = _metrics_registry
        if reg is not None:
            reg.histogram(
                "biscotti_crypto_device_seconds",
                "device-crypto kernel wall-clock, end-to-end "
                "(host marshalling + XLA execute)",
            ).observe(dt, kernel=kernel)
