"""Experimental Pallas on-curve validation kernel.

The commitment-grid check is the one device-crypto kernel that is pure
element-wise limb arithmetic (no cross-lane reduction until the final
all()), i.e. the VPU-shaped candidate the ISSUE's "where profitable,
Pallas" clause names. This kernel computes the curve residual
y² − x² − 1 − d·x²y² per cell over a (TILE, 2, 16) limb block and emits
the per-cell zero-residual mask. The limb constants (the convolution
routing matrix, the 8p subtraction bias, the curve d) ride in as kernel
inputs — Pallas kernels cannot close over traced constants — while the
carry chains and canonical-form logic reuse `kernels.field` directly
(those touch python-int scalars only).

Status: **experimental, off by default** (BISCOTTI_PALLAS_CRYPTO=1 opts
in; `primitives.grid_validate_sum` then cross-checks it against the XLA
verdict and fails loudly on disagreement — the two paths must never
split a consensus verdict). Off-TPU it runs in interpret mode, the same
pattern `ops/krum_pallas.py` uses; on TPU hardware the int64 limb
algebra would need the 8-bit-limb re-tiling documented in
docs/CRYPTO_KERNELS.md before Mosaic accepts it, which is why the XLA
conv-matmul path — which already lowers to MXU-shaped ops — remains the
shipping default.
"""

from __future__ import annotations

import numpy as np

from biscotti_tpu.crypto.kernels import field as fe

TILE = 128


def _kernel(xy_ref, conv_ref, eightp_ref, d_ref, out_ref):
    import jax.numpy as jnp

    x = xy_ref[:, 0, :]
    y = xy_ref[:, 1, :]
    conv = conv_ref[...]
    eightp = eightp_ref[...]
    d_limbs = jnp.broadcast_to(d_ref[...][None, :], x.shape)

    def fmul(a, b):
        prod = a[:, :, None] * b[:, None, :]
        c = prod.reshape(a.shape[0], fe.LIMBS * fe.LIMBS) @ conv
        lo = c[:, :fe.LIMBS]
        hi = jnp.concatenate([c[:, fe.LIMBS:], jnp.zeros_like(c[:, :1])],
                             axis=1)
        return fe.carry(lo + 38 * hi, passes=2)

    def fsub(a, b):
        return fe.carry(a + eightp[None, :] - b, passes=1)

    xx = fmul(x, x)
    yy = fmul(y, y)
    lhs = fsub(yy, xx)
    one = jnp.zeros_like(x).at[:, 0].set(1)
    rhs = fe.carry(one + fmul(d_limbs, fmul(xx, yy)), passes=1)
    ok = jnp.all(fe.canonical(lhs) == fe.canonical(rhs), axis=-1)
    out_ref[:] = ok.astype(jnp.int32)[:, None]


def oncurve_mask(xy: np.ndarray) -> np.ndarray:
    """[N, 2, 16] limb cells → [N] bool on-curve mask (mod p — canonicity
    is the caller's separate check)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xy.shape[0]
    n_pad = -(-n // TILE) * TILE
    buf = np.zeros((n_pad, 2, fe.LIMBS), dtype=np.int64)
    buf[:n] = xy
    buf[n:, 1, 0] = 1  # affine identity padding: on-curve
    out = pl.pallas_call(
        _kernel,
        grid=(n_pad // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, 2, fe.LIMBS), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fe.LIMBS * fe.LIMBS, 2 * fe.LIMBS - 1),
                         lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((fe.LIMBS,), lambda i: (0,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fe.LIMBS,), lambda i: (0,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), np.int32),
        interpret=jax.default_backend() != "tpu",
    )(buf, fe.CONV, np.asarray(fe.EIGHT_P), fe.D_LIMBS.astype(np.int64))
    return np.asarray(out[:n, 0]).astype(bool)
