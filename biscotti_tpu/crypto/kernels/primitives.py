"""The four hot device-crypto kernels, vmapped over limb tensors.

  * `msm`              — multi-scalar mult Σ sᵢ·Pᵢ: per-lane MSB-first
                         double-and-add over the 8-bit-limb scalar
                         decomposition PR 6's RLC already produces,
                         then a log₂-depth pointwise tree reduction.
                         Embarrassingly data-parallel: every lane runs
                         the identical 256-step ladder, so the batch
                         vectorizes across the intake width.
  * `fixed_base_mult`  — k·B (and k·H) via a precomputed 2ⁱ·base table:
                         256 conditional adds per lane, no doubles.
  * `grid_validate_sum`— the `ed25519_xy_accum` equivalent: whole-intake
                         all-or-nothing canonicity + on-curve validation
                         of affine commitment grids, plus the pointwise
                         sum of the valid grids (the VSS wave fold).
  * `shamir_recover`   — vectorized Shamir interpolation: the memoized
                         Vandermonde pseudoinverse × aggregated-share
                         matmul on device, rounded back to int64.

Scalars are normalized exactly like `commitments._msm_python` — mod-q
reduction, then top-half scalars become (q−s)·(−P) — so the device MSM
agrees with the CPU backends on EVERY input, torsioned points included
(see _norm_scalar_point). All
jitted programs are cached per power-of-two batch shape — batches pad
with the identity point / zero scalar, which the complete addition
absorbs — so a steady-state round never recompiles.

jax imports are function-local: importing this module (or the package)
from the CPU-only path costs nothing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from biscotti_tpu.crypto import ed25519 as ed
from biscotti_tpu.crypto.kernels import field as fe
from biscotti_tpu.crypto.kernels import group as gp
from biscotti_tpu.crypto.kernels.instrument import timed

_fn_cache: Dict[tuple, object] = {}
_table_cache: Dict[str, np.ndarray] = {}

# 4p as limb-wise quadrupled P limbs (loose, non-normalized): used for
# host-side point negation −x ≡ 4p − x. 4p rather than 2p because the
# VSS settle negates LOOSE accumulator limbs (< 2¹⁷, which can exceed a
# 2p limb): every 4p limb is ≥ 2¹⁸ − 76, so the result stays
# non-negative at < 2¹⁸ per limb — one bit over the documented loose
# bound, which the fmul analysis absorbs (products < 2³⁶, folded
# < 2⁴⁶, still far inside int64).
_FOURP_LIMBS = 4 * fe.P_LIMBS


def _pow2(n: int, floor: int = 1) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


# lane-count floors: batches pad UP to a power-of-two bucket no smaller
# than these, so a steady-state round compiles each ladder once instead
# of once per intake width (identity-point padding lanes are dead cheap
# next to a 30 s XLA CPU compile; on TPU they vanish into the vector
# width). MSM sees the widest spread of widths (RLC lhs = intake W,
# rhs = C·k), hence the bigger floor.
MSM_MIN_LANES = 32
FIXED_MIN_LANES = 4
GRID_MIN_WAVES = 4


def point_neg_limbs(arr: np.ndarray) -> np.ndarray:
    """Limb-domain point negation (−X, Y, Z, −T) of [..., 4, 16] batches
    with canonical OR loose (< 2¹⁷) coordinate limbs — near-loose
    (< 2¹⁸) output, safe for the ladder's field ops (see _FOURP_LIMBS)."""
    out = np.asarray(arr, dtype=np.int64).copy()
    out[..., 0, :] = _FOURP_LIMBS - out[..., 0, :]
    out[..., 3, :] = _FOURP_LIMBS - out[..., 3, :]
    return out


def _fixed_table(which: str) -> np.ndarray:
    """[256, 4, 16] int64 limb table of 2ⁱ·base for base ∈ {B, H} —
    derived once per process with the python-int oracle (exact)."""
    tab = _table_cache.get(which)
    if tab is None:
        if which == "B":
            pt = ed.BASE
        elif which == "H":
            from biscotti_tpu.crypto.commitments import H_POINT

            pt = H_POINT
        else:
            raise ValueError(f"unknown fixed base {which!r}")
        pts = []
        for _ in range(256):
            pts.append(pt)
            pt = ed.point_double(pt)
        tab = gp.points_to_limbs(pts).astype(np.int64)
        _table_cache[which] = tab
    return tab


# ------------------------------------------------------------- compiled


def _get(key, builder):
    fn = _fn_cache.get(key)
    if fn is None:
        fn = _fn_cache[key] = builder()
    return fn


def _build_msm(n: int):
    import jax
    import jax.numpy as jnp

    ident = jnp.asarray(np.broadcast_to(gp.IDENTITY_LIMBS,
                                        (n, 4, fe.LIMBS)).copy())

    def run(bits, pts):
        def body(i, acc):
            acc = gp.point_double(acc)
            return gp.select(bits[:, i] > 0, gp.point_add(acc, pts), acc)

        acc = jax.lax.fori_loop(0, 256, body, ident)
        return gp.tree_sum(acc)

    return jax.jit(run)


def _build_fixed(n: int):
    import jax
    import jax.numpy as jnp

    ident = jnp.asarray(np.broadcast_to(gp.IDENTITY_LIMBS,
                                        (n, 4, fe.LIMBS)).copy())

    def run(bits, table):
        # bits [n, steps] LSB-first against table[i] = 2ⁱ·base (tables
        # may be concatenated: B‖H walks both in one loop)
        steps = bits.shape[1]

        def body(i, acc):
            t = jnp.broadcast_to(table[i], (n, 4, fe.LIMBS))
            return gp.select(bits[:, i] > 0, gp.point_add(acc, t), acc)

        return jax.lax.fori_loop(0, steps, body, ident)

    return jax.jit(run)


def _build_grid(w: int, n: int):
    import jax
    import jax.numpy as jnp

    def run(xy):  # [w, n, 2, 16] int64
        x = xy[..., 0, :]
        y = xy[..., 1, :]
        ok = fe.lt_p(x) & fe.lt_p(y) & gp.on_curve(x, y)  # [w, n]
        grid_ok = jnp.all(ok, axis=1)  # [w]
        one = jnp.broadcast_to(
            jnp.asarray(fe.ONE_LIMBS), (w, n, fe.LIMBS)).astype(x.dtype)
        pts = jnp.stack([x, y, one, fe.fmul(x, y)], axis=-2)
        ident = jnp.broadcast_to(jnp.asarray(gp.IDENTITY_LIMBS),
                                 (w, n, 4, fe.LIMBS)).astype(x.dtype)
        pts = jnp.where(grid_ok[:, None, None, None], pts, ident)
        summed = gp.tree_sum(pts)  # [n, 4, 16]
        return grid_ok, summed

    return jax.jit(run)


def _build_ext_add():
    import jax

    return jax.jit(lambda a, b: gp.point_add(a, b))


def _build_recover():
    import jax
    import jax.numpy as jnp

    def run(pinv, agg):
        sol = pinv @ agg.astype(jnp.float64)  # [k, C]
        return jnp.round(sol).astype(jnp.int64)

    return jax.jit(run)


# ----------------------------------------------------------- public API


def _norm_scalar_point(scalars, pts_limbs) -> Tuple[np.ndarray, np.ndarray]:
    """Signed/unreduced python-int scalars + [n,4,16] limb points →
    (MSB-first bit matrix, possibly-negated limb points), mirroring
    `commitments._msm_python`'s pair normalization EXACTLY: reduce mod
    q (python semantics cover negatives), then replace top-half scalars
    by (q−s)·(−P). The mirror matters beyond bit-shortness: s·P and
    (q−s)·(−P) differ by q·P, which is NOT the identity for points
    carrying a small-order (torsion) component — commitment-grid cells
    are validated on-curve but NOT subgroup-checked, so without the
    identical fold an adversarial torsioned cell would make the device
    and CPU settles disagree on the same input (consensus split — the
    exact hazard _msm_python's own normalization exists to close).
    Zero scalars ride along (their adds never fire)."""
    mags: List[int] = []
    pts = np.asarray(pts_limbs, dtype=np.int64)
    neg_idx = []
    for i, s in enumerate(scalars):
        s = int(s) % fe.Q
        if s > fe.Q // 2:
            s = fe.Q - s
            neg_idx.append(i)
        mags.append(s)
    if neg_idx:
        pts = pts.copy()
        pts[neg_idx] = point_neg_limbs(pts[neg_idx])
    bits = fe.scalars_to_bits(mags, msb_first=True)
    return bits, pts


def msm(scalars: Sequence[int], points) -> ed.Point:
    """Σ sᵢ·Pᵢ on device. `points` is a sequence of extended python-int
    points or an [n, 4, 16] limb array (e.g. `CommitKey.device_buf`).
    Returns an extended python-int point — projectively equal (identical
    group element) to the CPU oracle's result on every input."""
    n = len(scalars)
    if n == 0:
        return ed.IDENTITY
    with timed("msm"):
        if isinstance(points, np.ndarray):
            pts = np.asarray(points[:n], dtype=np.int64)
        else:
            pts = gp.points_to_limbs(points).astype(np.int64)
        bits, pts = _norm_scalar_point(scalars, pts)
        m = _pow2(n, MSM_MIN_LANES)
        if m != n:
            bits = np.concatenate(
                [bits, np.zeros((m - n, 256), bits.dtype)])
            pts = np.concatenate(
                [pts, np.broadcast_to(gp.IDENTITY_LIMBS,
                                      (m - n, 4, fe.LIMBS))])
        fn = _get(("msm", m), lambda: _build_msm(m))
        out = np.asarray(fn(bits.astype(np.int32), pts))
    return gp.limbs_to_point(out)


def fixed_base_mult(scalars: Sequence[int], which: str = "B") -> List[ed.Point]:
    """[kᵢ·base] for base ∈ {B, H}: 256 conditional table adds per lane,
    vmapped across the batch. Scalars reduce mod q (fixed-base callers
    are group-order scalars by construction)."""
    n = len(scalars)
    if n == 0:
        return []
    with timed("fixed_base"):
        red = [int(s) % fe.Q for s in scalars]
        bits = fe.scalars_to_bits(red, msb_first=False)
        m = _pow2(n, FIXED_MIN_LANES)
        if m != n:
            bits = np.concatenate(
                [bits, np.zeros((m - n, 256), bits.dtype)])
        fn = _get(("fixed", m), lambda: _build_fixed(m))
        out = np.asarray(fn(bits.astype(np.int32), _fixed_table(which)))
    return [gp.limbs_to_point(out[i]) for i in range(n)]


def pedersen_commit_point(a: int, b: int) -> ed.Point:
    """a·B + b·H in ONE device ladder (the concatenated-table walk) —
    the lhs comb of the batched VSS / commitment equations."""
    with timed("fixed_base"):
        bits = np.concatenate([
            fe.scalars_to_bits([int(a) % fe.Q], msb_first=False),
            fe.scalars_to_bits([int(b) % fe.Q], msb_first=False),
        ], axis=1)  # [1, 512]
        table = np.concatenate([_fixed_table("B"), _fixed_table("H")])
        fn = _get(("fixed", 1), lambda: _build_fixed(1))
        out = np.asarray(fn(bits.astype(np.int32), table))
    return gp.limbs_to_point(out[0])


def grid_validate_sum(grids: Sequence) -> Tuple[np.ndarray,
                                                Optional[np.ndarray]]:
    """Whole-wave commitment-grid validation + pointwise sum — the
    device `ed25519_xy_accum`. `grids`: W buffers of n packed 64-byte
    affine (x, y) pairs (bytes or uint8 arrays of any shape totalling
    n·64 bytes). Returns (ok mask [W] bool, summed [n, 4, 16] int64 over
    the VALID grids — None when none are valid).

    Verdict parity with the CPU loaders is exact: a grid is ok iff every
    cell has canonical (< p) coordinates AND lies on the curve (subgroup
    NOT checked — callers fold the cofactor 8 into verification scalars,
    exactly like the native plane)."""
    w = len(grids)
    if w == 0:
        return np.zeros(0, dtype=bool), None
    bufs = [bytes(g) if isinstance(g, (bytes, bytearray))
            else np.ascontiguousarray(g).tobytes() for g in grids]
    n = len(bufs[0]) // 64
    with timed("grid_validate"):
        xy = np.stack([gp.xy_bytes_to_limbs(b, n)
                       for b in bufs]).astype(np.int64)  # [w, n, 2, 16]
        wp = _pow2(w, GRID_MIN_WAVES)
        if wp != w:
            pad = np.zeros((wp - w, n, 2, fe.LIMBS), dtype=np.int64)
            pad[..., 1, 0] = 1  # affine identity (0, 1): valid, sums away
            xy = np.concatenate([xy, pad])
        fn = _get(("grid", wp, n), lambda: _build_grid(wp, n))
        grid_ok, summed = fn(xy)
        mask = np.asarray(grid_ok)[:w]
        if _use_pallas():
            # experimental Pallas validation path: the on-curve mask from
            # the Mosaic kernel must agree with the XLA verdict (the sum
            # stays on the XLA path either way); a disagreement is a
            # kernel bug and fails loudly rather than splitting verdicts
            from biscotti_tpu.crypto.kernels import pallas_validate as pv

            pm = pv.oncurve_mask(xy.reshape(wp * n, 2, fe.LIMBS))
            pm = pm.reshape(wp, n)[:w]
            xla_cell = _cell_canonical_mask(xy[:w])
            if not np.array_equal(pm & xla_cell[0], xla_cell[1]):
                raise RuntimeError(
                    "pallas on-curve mask disagrees with the XLA verdict")
        if not mask.any():
            return mask, None
        summed_np = np.asarray(summed)
    return mask, summed_np


def _cell_canonical_mask(xy: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side per-cell (canonicity, canonicity AND on-curve) masks —
    the cross-check oracle for the experimental Pallas path."""
    w, n = xy.shape[0], xy.shape[1]
    canon = np.zeros((w, n), dtype=bool)
    full = np.zeros((w, n), dtype=bool)
    for i in range(w):
        for j in range(n):
            x = fe.limbs_to_int(xy[i, j, 0])
            y = fe.limbs_to_int(xy[i, j, 1])
            c = x < fe.P and y < fe.P
            canon[i, j] = c
            full[i, j] = c and (
                (y * y - x * x - 1 - ed.D * x * x * y * y) % fe.P == 0)
    return canon, full


def ext_add(acc: np.ndarray, other: np.ndarray) -> np.ndarray:
    """Pointwise acc[i] += other[i] over two [n, 4, 16] limb batches —
    the accumulator fold of the incremental VSS intake."""
    with timed("ext_add"):
        fn = _get(("ext_add",), _build_ext_add)
        return np.asarray(fn(np.asarray(acc, np.int64),
                             np.asarray(other, np.int64)))


def shamir_recover(pinv: np.ndarray, agg: np.ndarray) -> np.ndarray:
    """[k, S] Vandermonde pseudoinverse × [S, C] aggregated shares on
    device, rounded → [C, k] int64 chunk coefficients (the
    `ss.recover_coeffs` tail)."""
    with timed("shamir_recover"):
        fn = _get(("recover",), _build_recover)
        sol = np.asarray(fn(np.asarray(pinv, np.float64),
                            np.asarray(agg, np.int64)))
    return np.ascontiguousarray(sol.T)


def prewarm(grid_points: int = 0) -> None:
    """Compile the ladder kernels at the bucket shapes a cluster of this
    dimensionality will hit (`grid_points` = C·k, the commitment-grid
    width), so XLA compile time is paid ONCE at peer startup instead of
    inside a round deadline. No-op when the plane is disarmed; any
    compile failure is swallowed — the seams fall back to CPU exactly as
    they would mid-round."""
    from biscotti_tpu.crypto import kernels
    from biscotti_tpu.crypto.kernels import instrument

    if not kernels.active():
        return
    try:
        # suppressed: warm-up wall-clock must not pollute the round-work
        # instrumentation (seconds accumulators, histogram, spans)
        with instrument.suppressed():
            fixed_base_mult([1])
            pedersen_commit_point(1, 1)
            n = max(1, int(grid_points))
            msm([1] * n, [ed.BASE] * n)
            if grid_points:
                ident = np.zeros((n, 64), np.uint8)
                ident[:, 32] = 1  # affine identity (0, 1): on-curve
                grid_validate_sum([ident])
    except Exception:
        pass


def _use_pallas() -> bool:
    """Pallas grid-validation dispatch: off by default (the XLA path's
    conv-matmul already lowers to MXU-shaped ops); BISCOTTI_PALLAS_CRYPTO=1
    opts in (interpret mode off-TPU — exercised by the kernel tests)."""
    import os

    return os.environ.get("BISCOTTI_PALLAS_CRYPTO", "") == "1"


__all__ = [
    "msm", "fixed_base_mult", "pedersen_commit_point",
    "grid_validate_sum", "ext_add", "shamir_recover", "point_neg_limbs",
]
