"""ECVRF over Edwards25519 — verifiable random function for role lotteries.

Replaces the reference's vendored coniks-go ed25519 VRF
(ref: DistSys/vrf.go:5-52, vrf-reference/crypto/vrf/vrf.go). Construction
follows the RFC 9381 ECVRF-EDWARDS25519-SHA512-TAI shape (hash-to-curve by
try-and-increment, Chaum-Pedersen style DLEQ proof): prove/verify are
self-consistent and the output is uniformly pseudorandom and *unique* per
(key, input) — the properties the lottery needs. Wire formats are ours, not
coniks'; nothing interoperates with the reference network protocol anyway.

API mirrors the reference surface:
  VRFKey.prove(alpha)  -> (beta, pi)   (vrf.go: Prove -> output, proof)
  verify(pk, alpha, pi) -> beta | None (vrf.go: Verify)
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Tuple

from biscotti_tpu.crypto import ed25519 as ed

SUITE = b"\x03"  # edwards25519-SHA512-TAI domain separator
CHALLENGE_LEN = 16
PROOF_LEN = 32 + CHALLENGE_LEN + 32


# role lotteries run one prove plus committee-many verifies per peer per
# round; the shared dispatchers in commitments.py route these through the
# native library when built (the pure-python double-and-add was a measured
# hot spot of whole-cluster runs) and guarantee the two backends compute
# identical group elements on ALL inputs, torsioned included
from biscotti_tpu.crypto.commitments import decompress_point as _decompress
from biscotti_tpu.crypto.commitments import msm as _msm_dispatch


def _msm(scalars, points) -> ed.Point:
    return _msm_dispatch(list(scalars), list(points))


def _encode_to_curve(pk_bytes: bytes, alpha: bytes) -> ed.Point:
    """RFC 9381 §5.4.1.1 TAI preimage layout over the shared hash-to-curve
    (native decompression injected — identical semantics, ~10× faster)."""
    return ed.hash_to_point(SUITE + b"\x01" + pk_bytes + alpha, b"\x00",
                            decompress=_decompress)


def _challenge(*points: ed.Point) -> int:
    buf = SUITE + b"\x02" + b"".join(ed.point_compress(p) for p in points) + b"\x00"
    return int.from_bytes(hashlib.sha512(buf).digest()[:CHALLENGE_LEN], "little")


def _proof_to_hash(gamma: ed.Point) -> bytes:
    g8 = ed.scalar_mult(ed.COFACTOR, gamma)
    return hashlib.sha512(
        SUITE + b"\x03" + ed.point_compress(g8) + b"\x00"
    ).digest()


@dataclass
class VRFKey:
    """One lottery identity. The reference holds two per node — roles and
    noise (ref: DistSys/vrf.go:9-32)."""

    seed: bytes

    def __post_init__(self):
        if len(self.seed) != 32:
            raise ValueError("VRF seed must be 32 bytes")
        self._x, self._prefix = ed.secret_expand(self.seed)
        self._public_pt = ed.base_mult(self._x)
        self.public = ed.point_compress(self._public_pt)

    def prove(self, alpha: bytes) -> Tuple[bytes, bytes]:
        """(beta, pi): 64-byte pseudorandom output + proof anyone can check
        against `self.public`."""
        h_pt = _encode_to_curve(self.public, alpha)
        h_bytes = ed.point_compress(h_pt)
        gamma = _msm([self._x], [h_pt])
        # deterministic nonce, RFC 8032 style: SHA512(prefix ‖ H)
        k = int.from_bytes(
            hashlib.sha512(self._prefix + h_bytes).digest(), "little"
        ) % ed.Q
        u = _msm([k], [ed.BASE])
        v = _msm([k], [h_pt])
        y_pt = self._public_pt
        c = _challenge(y_pt, h_pt, gamma, u, v)
        s = (k + c * self._x) % ed.Q
        pi = (
            ed.point_compress(gamma)
            + c.to_bytes(CHALLENGE_LEN, "little")
            + s.to_bytes(32, "little")
        )
        return _proof_to_hash(gamma), pi


def verify(public: bytes, alpha: bytes, pi: bytes) -> Optional[bytes]:
    """Returns beta iff pi proves that beta = VRF_sk(alpha) for the sk behind
    `public`; None on any failure (never raises on malformed input)."""
    if len(pi) != PROOF_LEN:
        return None
    gamma = _decompress(pi[:32])
    if gamma is None:
        return None
    c = int.from_bytes(pi[32 : 32 + CHALLENGE_LEN], "little")
    s = int.from_bytes(pi[32 + CHALLENGE_LEN :], "little")
    if s >= ed.Q:
        return None
    y_pt = _decompress(public)
    if y_pt is None:
        return None
    try:
        h_pt = _encode_to_curve(public, alpha)
    except ValueError:
        return None
    # U = s·B − c·Y ; V = s·H − c·Γ (each one two-term MSM)
    u = _msm([s, ed.Q - (c % ed.Q)], [ed.BASE, y_pt])
    v = _msm([s, ed.Q - (c % ed.Q)], [h_pt, gamma])
    if _challenge(y_pt, h_pt, gamma, u, v) != c:
        return None
    return _proof_to_hash(gamma)
