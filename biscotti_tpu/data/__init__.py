from biscotti_tpu.data.datasets import (
    DATASETS,
    DatasetSpec,
    load_shard,
    num_classes,
    num_features,
    num_params,
)

__all__ = [
    "DATASETS", "DatasetSpec", "load_shard",
    "num_classes", "num_features", "num_params",
]
