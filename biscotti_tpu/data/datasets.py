"""Dataset registry and deterministic per-peer shards.

Capability parity with the reference's registry (ref: ML/Pytorch/datasets.py:6-52
— mnist 784/10, lfw 8742/12, cifar 3072/10, creditcard 24/2) and its per-peer
`.npy` shard loader with an 80/20 train cut (ref: ML/Pytorch/mnist_dataset.py:16-31).

This environment has zero egress, so the reference-dimension shards (mnist /
cifar / lfw / creditcard) are *synthesized*: each dataset is a fixed mixture of
Gaussian class clusters drawn from a dataset-specific threefry key. Generation
is fully deterministic in (dataset, shard_name), so every peer process
regenerates bit-identical shards — the property the reference gets from
shipping `.npy` files, and the chain-equality oracle implicitly relies on.

Two REAL datasets ship alongside them, loaded from scikit-learn's bundled
(offline) data so accuracy claims are falsifiable on real distributions:

  "digits"  1,797 real 8×8 handwritten digit scans (UCI optical digits,
            the small real sibling of MNIST) — 64 features, 10 classes
  "cancer"  569 real tabular diagnostic records (Wisconsin breast cancer) —
            30 standardized features, 2 classes, the real sibling of the
            reference's creditcard tabular task

Real shards are disjoint slices of a deterministic dataset-keyed shuffle, so
they are bit-identical across peer processes exactly like the synthetic ones.

Poisoned shards follow the reference's generate_poisoned exactly
(ref: ML/Pytorch/data/mnist/parse_mnist.py:295-301): ALL-source-class
data relabeled as the target (1 → 7 for mnist) — every row carries the
attack, which is both its damage and the geometric signal Krum separates
on. The reference calls these `mnist_bad` / `creditbad`, here uniformly
`<dataset>_bad<i>` — use `shard_name()` to construct names. Real-corpus
bad shards draw from the TRAIN slice only (never the held-out rows the
attack-rate metric scores). The attack split (`<dataset>_digit1`) is
all-source-class data for the attack-rate metric. Malformed shard names
raise instead of silently resolving.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, Tuple

import numpy as np


@dataclass(frozen=True)
class DatasetSpec:
    name: str
    d_in: int
    n_classes: int
    shard_size: int  # samples per peer shard
    test_size: int
    attack_source: int = 1  # label-flip source class (1→7 for mnist)
    attack_target: int = 7
    cluster_scale: float = 1.0  # intra-class spread
    real: bool = False  # backed by a bundled real dataset (see module doc)


DATASETS: Dict[str, DatasetSpec] = {
    "mnist": DatasetSpec("mnist", 784, 10, 600, 2000),
    "cifar": DatasetSpec("cifar", 3072, 10, 500, 2000),
    "lfw": DatasetSpec("lfw", 8742, 12, 200, 1000),
    "creditcard": DatasetSpec("creditcard", 24, 2, 400, 1000,
                              attack_source=0, attack_target=1),
    # real data (scikit-learn bundled, offline): shard/test sizes chosen so
    # a 10-peer run consumes the whole corpus with a held-out test pool
    "digits": DatasetSpec("digits", 64, 10, 140, 397, real=True),
    "cancer": DatasetSpec("cancer", 30, 2, 40, 169,
                          attack_source=0, attack_target=1, real=True),
}


def base_name(dataset: str) -> str:
    """Strip the heterogeneity suffix: "mnist@dir0.3" → "mnist"."""
    return dataset.split("@dir", 1)[0]


def dirichlet_alpha(dataset: str) -> "float | None":
    """Per-peer class-skew knob (VERDICT r3 #2). A dataset named
    "<base>@dir<alpha>" draws every SYNTHETIC peer shard's class
    distribution from Dirichlet(alpha·1): small alpha ⇒ each peer holds a
    few dominant classes — the natural heterogeneity real federated
    shards have, and the geometry Krum needs to separate label-flip
    poisoners from honest peers (homogeneous shards make every honest
    update near-identical, so poisoned ones hide inside the cluster; see
    eval/results/poison.json separation_note). Test/attack splits stay
    balanced and IDENTICAL to the base dataset, so error columns remain
    comparable."""
    if "@dir" not in dataset:
        return None
    raw = dataset.split("@dir", 1)[1]
    try:
        alpha = float(raw)
    except ValueError:
        raise ValueError(f"malformed heterogeneity suffix in {dataset!r}; "
                         f"expected <base>@dir<float>")
    if alpha <= 0:
        raise ValueError(f"dirichlet alpha must be positive, got {alpha}")
    return alpha


def _spec(dataset: str) -> DatasetSpec:
    alpha = dirichlet_alpha(dataset)  # validates the suffix shape
    dataset = base_name(dataset)
    if dataset not in DATASETS:
        raise KeyError(f"dataset {dataset!r} not defined; have {sorted(DATASETS)}")
    spec = DATASETS[dataset]
    if alpha is not None and spec.real:
        raise ValueError("@dir heterogeneity applies to synthetic datasets "
                         "only (real corpora carry their own skew)")
    return spec


def num_features(dataset: str) -> int:
    return _spec(dataset).d_in


def num_classes(dataset: str) -> int:
    return _spec(dataset).n_classes


def num_params(dataset: str) -> int:
    """Reference-registry parity value: the *softmax* parameter count
    d_in·k + k (ref: datasets.py:19-20 — mnist 7850, creditcard 50).

    NOTE: the authoritative wire size for any run is
    `model_for_dataset(ds).num_params` — e.g. creditcard's default model is
    the numpy-parity logreg (25 params), while this registry reports the
    softmax value 50, exactly as the reference registry does even though
    its creditcard runs use the d=25 logreg stack. Size buffers from the
    model, not from here."""
    s = _spec(dataset)
    return s.d_in * s.n_classes + s.n_classes


def _rng(dataset: str, tag: str) -> np.random.Generator:
    seed = int.from_bytes(
        hashlib.sha256(f"biscotti_tpu/{dataset}/{tag}".encode()).digest()[:8], "little"
    )
    return np.random.default_rng(seed)


@lru_cache(maxsize=None)
def _class_means(dataset: str) -> np.ndarray:
    """Fixed class-cluster means. Separation 6.0 makes a linear model's
    reachable test error ≈7% from a few hundred samples — the same band as
    the reference's real-MNIST finals (BASELINE.md: 0.065–0.113) — while
    smaller separations drown the signal in 784-dim noise."""
    s = _spec(dataset)
    rng = _rng(dataset, "means")
    means = rng.normal(0.0, 1.0, size=(s.n_classes, s.d_in))
    return (means / np.linalg.norm(means, axis=1, keepdims=True)).astype(np.float32) * 6.0


@lru_cache(maxsize=None)
def _real_corpus(dataset: str) -> Tuple[np.ndarray, np.ndarray]:
    """Full real corpus, standardized, in a deterministic dataset-keyed
    shuffle order (identical in every peer process). sklearn's bundled
    datasets load from files inside the installed package — no network."""
    from sklearn.datasets import load_breast_cancer, load_digits

    if dataset == "digits":
        raw = load_digits()
        x = (raw.data / 16.0).astype(np.float32)  # pixel range 0..16
    elif dataset == "cancer":
        raw = load_breast_cancer()
        x = raw.data.astype(np.float32)
        x = (x - x.mean(axis=0)) / (x.std(axis=0) + 1e-8)
    else:
        raise KeyError(f"no real corpus for dataset {dataset!r}")
    y = raw.target.astype(np.int32)
    order = _rng(dataset, "corpus-shuffle").permutation(len(x))
    return np.ascontiguousarray(x[order]), np.ascontiguousarray(y[order])


def disjoint_shard_capacity(dataset: str) -> "int | None":
    """How many peers can hold fully DISJOINT shards of a REAL corpus
    (None for synthetic datasets, which generate per-peer data freely).
    Beyond this count `_draw`'s wrap-around reuses overlapping slices —
    callers reporting defense statistics should disclose that (a poisoned
    peer's shard may coincide with an honest peer's). Single source of
    truth for the slicing math in `_draw` below."""
    s = _spec(dataset)
    if not s.real:
        return None
    corpus_n = len(_real_corpus(dataset)[0])
    return max(1, (corpus_n - s.test_size) // s.shard_size)


def _draw(dataset: str, tag: str, n: int) -> Tuple[np.ndarray, np.ndarray]:
    s = _spec(dataset)
    if s.real:
        x, y = _real_corpus(dataset)
        if tag in ("test", "attack"):
            return x[-s.test_size:], y[-s.test_size:]
        assert tag.startswith("shard")
        peer = int(tag[len("shard"):])
        train_n = len(x) - s.test_size
        # disjoint slices while the corpus lasts; peers beyond capacity wrap
        # around (real corpora are small — a 100-peer digits run reuses
        # slices rather than failing, and the wrap is deterministic)
        start = (peer * s.shard_size) % max(1, train_n - s.shard_size + 1)
        return x[start:start + n], y[start:start + n]
    alpha = dirichlet_alpha(dataset)
    if tag in ("test", "attack"):
        # shared splits are balanced and IDENTICAL across @dir variants
        dataset = base_name(dataset)
        alpha = None
    rng = _rng(dataset, tag)
    means = _class_means(base_name(dataset))
    if alpha is not None:
        # per-peer class skew: the shard's own tag-seeded stream draws its
        # Dirichlet class distribution, so every peer's skew is distinct
        # and deterministic
        p = rng.dirichlet(np.full(s.n_classes, alpha))
        y = rng.choice(s.n_classes, size=n, p=p)
    else:
        y = rng.integers(0, s.n_classes, size=n)
    x = means[y] + rng.normal(0.0, s.cluster_scale, size=(n, s.d_in)).astype(np.float32)
    return x.astype(np.float32), y.astype(np.int32)


@lru_cache(maxsize=None)
def load_shard(dataset: str, shard: str) -> Dict[str, np.ndarray]:
    """Load a named shard, mirroring the reference file names:

      "<dataset><i>"      honest shard of peer i  (ref: mnistN.npy)
      "<dataset>_bad<i>"  label-flipped shard     (ref: mnist_bad)
      "<dataset>_test"    shared held-out split
      "<dataset>_digit1"  attack split (all source-class samples)

    Returns {"x_train","y_train","x_test","y_test"} with an 80/20 cut for
    per-peer shards (ref: mnist_dataset.py:16-31).
    """
    s = _spec(dataset)
    if shard == f"{dataset}_test":
        x, y = _draw(dataset, "test", s.test_size)
        return {"x_train": x, "y_train": y, "x_test": x, "y_test": y}
    if shard == f"{dataset}_digit1":
        x, y = _draw(dataset, "attack", s.test_size)
        keep = y == s.attack_source
        return {"x_train": x[keep], "y_train": y[keep],
                "x_test": x[keep], "y_test": y[keep]}

    bad = shard.startswith(f"{dataset}_bad")
    prefix = f"{dataset}_bad" if bad else dataset
    if not shard.startswith(prefix):
        raise ValueError(f"shard {shard!r} does not belong to dataset {dataset!r}")
    idx = shard[len(prefix):]
    if idx and not idx.isdigit():
        raise ValueError(f"malformed shard name {shard!r} for dataset {dataset!r}")
    peer = int(idx) if idx else 0
    x, y = _draw(dataset, f"shard{peer}", s.shard_size)
    if bad:
        # The reference's poisoned shard is ALL-source-class data labeled
        # as the target (parse_mnist.py generate_poisoned: mnist_digit1
        # with y := 7 saved as mnist_bad) — NOT an honest shard with its
        # source rows flipped. Every poisoned minibatch row pushes the
        # 1→7 direction, which is both the attack's damage and the
        # geometric signal Krum separates on. Mirror it: keep the peer's
        # own deterministic stream but condition every row on the source
        # class, then relabel. (Round 1-3 flipped ~10% of an honest
        # shard — a 10× weaker attack than the reference's.)
        if s.real:
            cx, cy = _real_corpus(dataset)
            # TRAIN slice only: the corpus tail is the held-out test/
            # attack split — letting poisoned peers train on the exact
            # rows attack_rate is measured on would inflate the
            # undefended attack into a memorization artifact
            train_n = len(cx) - s.test_size
            keep = cy[:train_n] == s.attack_source
            sx, sy = cx[:train_n][keep], cy[:train_n][keep]
            if len(sx) == 0:
                raise ValueError(
                    f"corpus train slice for {dataset!r} has no "
                    f"attack-source (class {s.attack_source}) rows — "
                    f"cannot build a poisoned shard")
            start = (peer * s.shard_size) % max(1, len(sx))
            idxs = (start + np.arange(s.shard_size)) % len(sx)
            x, y = sx[idxs], sy[idxs].copy()
        else:
            rng = _rng(dataset, f"badshard{peer}")
            means = _class_means(base_name(dataset))
            y = np.full(s.shard_size, s.attack_source, dtype=np.int32)
            x = (means[y] + rng.normal(0.0, s.cluster_scale,
                                       size=(s.shard_size, s.d_in))
                 ).astype(np.float32)
        y = y.copy()
        y[:] = s.attack_target
    cut = int(0.8 * len(x))
    return {"x_train": x[:cut], "y_train": y[:cut],
            "x_test": x[cut:], "y_test": y[cut:]}


def shard_name(dataset: str, peer_id: int, poisoned: bool) -> str:
    """Reference naming: top `poison_fraction` of node ids get bad shards
    (ref: DistSys/main.go:836-845)."""
    return f"{dataset}_bad{peer_id}" if poisoned else f"{dataset}{peer_id}"


def spec(dataset: str) -> DatasetSpec:
    """Public spec accessor — resolves @dir heterogeneity suffixes, so
    callers never index DATASETS directly with a runtime dataset name."""
    return _spec(dataset)
