from biscotti_tpu.ledger.block import Block, BlockData, Update, genesis_block
from biscotti_tpu.ledger.chain import Blockchain

__all__ = ["Block", "BlockData", "Update", "Blockchain", "genesis_block"]
