"""Ledger data model: Update, BlockData, Block.

Capability parity with the reference's in-memory chain records
(ref: DistSys/update.go:13-22, DistSys/blockData.go, DistSys/block.go).
The reference hashes gob-encoded structs (ref: DistSys/block.go:23-28);
gob is Go-specific, so we define our own *canonical byte serialization*
(little-endian lengths + raw float64 buffers) and SHA-256 over that. The
serialization is deterministic across processes, which is what the
chain-equality oracle (ref: DistSys/localTest.sh:40-96) requires.

Weights live here as float64 numpy arrays: the ledger is host-side control
plane; device math gets views of these buffers and never mutates them.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


def _pack_f64(vec: Optional[np.ndarray]) -> bytes:
    if vec is None:
        return struct.pack("<q", -1)
    a = np.ascontiguousarray(np.asarray(vec, dtype=np.float64))
    return struct.pack("<q", a.size) + a.tobytes()


def _pack_bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack("<q", -1)
    return struct.pack("<q", len(b)) + b


@dataclass
class Update:
    """The wire unit of learning (ref: DistSys/update.go:13-22)."""

    source_id: int
    iteration: int
    delta: np.ndarray  # raw local gradient delta, float64[d]
    commitment: bytes = b""  # Pedersen commitment to quantized delta
    noise: Optional[np.ndarray] = None  # committee-averaged DP noise
    noised_delta: Optional[np.ndarray] = None  # delta + noise, sent to verifiers
    accepted: bool = False
    signatures: List[bytes] = field(default_factory=list)  # verifier Schnorr sigs
    # which verifier produced each signature — receivers verify each sig
    # against the claimed signer's public key (the reference ships bare
    # signature lists, update.go:21, and its miner-side check was disabled;
    # here the quorum check is enforced, so the binding must travel)
    signers: List[int] = field(default_factory=list)

    def canonical_bytes(self) -> bytes:
        out = [struct.pack("<qq?", self.source_id, self.iteration, self.accepted)]
        out.append(_pack_f64(self.delta))
        out.append(_pack_bytes(self.commitment))
        out.append(_pack_f64(self.noise))
        out.append(_pack_f64(self.noised_delta))
        out.append(struct.pack("<q", len(self.signatures)))
        out.extend(_pack_bytes(s) for s in self.signatures)
        out.append(struct.pack("<q", len(self.signers)))
        out.extend(struct.pack("<q", s) for s in self.signers)
        return b"".join(out)


@dataclass
class BlockData:
    """Per-iteration payload (ref: DistSys/blockData.go:10-14).

    Carries the *full* global model: the blockchain doubles as the
    checkpoint store (ref: SURVEY.md §5.4).
    """

    iteration: int
    global_w: np.ndarray  # float64[d], the model after this round's aggregation
    deltas: List[Update] = field(default_factory=list)

    def canonical_bytes(self) -> bytes:
        out = [struct.pack("<q", self.iteration), _pack_f64(self.global_w)]
        out.append(struct.pack("<q", len(self.deltas)))
        out.extend(u.canonical_bytes() for u in self.deltas)
        return b"".join(out)


@dataclass
class Block:
    """Hash-chained block (ref: DistSys/block.go:13-28) carrying the stake
    map adopted by all peers on append (ref: DistSys/main.go:1346-1349)."""

    data: BlockData
    prev_hash: bytes
    stake_map: Dict[int, int] = field(default_factory=dict)
    timestamp: int = 0  # fixed at 0 by default: hashes must be equal across peers
    hash: bytes = b""

    def compute_hash(self) -> bytes:
        h = hashlib.sha256()
        h.update(struct.pack("<q", self.timestamp))
        h.update(self.data.canonical_bytes())
        h.update(_pack_bytes(self.prev_hash))
        for k in sorted(self.stake_map):
            h.update(struct.pack("<qq", k, self.stake_map[k]))
        return h.digest()

    def seal(self) -> "Block":
        self.hash = self.compute_hash()
        return self

    @property
    def iteration(self) -> int:
        return self.data.iteration

    def is_empty(self) -> bool:
        """Empty blocks advance the round when a committee times out
        (ref: DistSys/main.go:2099-2143)."""
        return len(self.data.deltas) == 0

    def summary(self) -> str:
        """One-line digest used by the chain-equality oracle."""
        return (
            f"iter={self.iteration} ndeltas={len(self.data.deltas)} "
            f"hash={self.hash.hex()[:16]} prev={self.prev_hash.hex()[:16]} "
            f"|w|={float(np.linalg.norm(self.data.global_w)):.6f}"
        )


def genesis_block(num_params: int, num_nodes: int, default_stake: int) -> Block:
    """Genesis with zero weights (ref: DistSys/block.go:46-52) and the
    initial uniform stake map (ref: DistSys/main.go:39,714)."""
    data = BlockData(iteration=-1, global_w=np.zeros(num_params, dtype=np.float64))
    blk = Block(data=data, prev_hash=b"\x00" * 32,
                stake_map={i: default_stake for i in range(num_nodes)})
    return blk.seal()
