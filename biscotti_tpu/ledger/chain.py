"""In-memory hash chain with the reference's append/replace semantics.

Capability parity with DistSys/blockchain.go:
  * AddBlock / getBlock / getLatestGradient / getLatestBlockHash / PrintChain
    (ref: DistSys/blockchain.go:12-96)
  * structural invariant chain[i].iteration == i-1, enforced fatally
    (ref: DistSys/blockchain.go:77-96)
  * block-quality ordering — matching prev-hash first, then non-empty beats
    empty (ref: DistSys/honest.go:631-647) — and same-height replacement
    (ref: DistSys/honest.go:649-653)
  * longest-chain adoption for late joiners (ref: DistSys/main.go:1001-1013)

`dump()` is the chain-equality oracle: every peer prints its chain at exit
and all dumps must be byte-identical (ref: DistSys/localTest.sh:40-96).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

import numpy as np

from biscotti_tpu.ledger.block import Block, genesis_block


class ChainInvariantError(RuntimeError):
    pass


class Blockchain:
    # Snapshot-bootstrap support (docs/MEMBERSHIP.md): a late joiner
    # adopting a chain SUFFIX holds [genesis] + blocks[pruned_before..head]
    # — the heights in [0, pruned_before) are absent by design (the whole
    # point of the snapshot is not fetching them). Class-level defaults so
    # instances built via __new__ (checkpoint.load, the announce path)
    # stay contiguous full chains with zero behavior change.
    pruned_before: int = 0
    # fork-choice weight CLAIMED for the pruned-away range (advisory, like
    # the join path's have_weight — over/underclaiming only affects which
    # chains this peer bothers adopting; adopted chains are verified)
    pruned_weight: int = 0

    def __init__(self, num_params: int, num_nodes: int, default_stake: int = 10):
        self.blocks: List[Block] = [genesis_block(num_params, num_nodes, default_stake)]

    # ------------------------------------------------------------- accessors

    def __len__(self) -> int:
        return len(self.blocks)

    @property
    def latest(self) -> Block:
        return self.blocks[-1]

    def get_block(self, iteration: int) -> Optional[Block]:
        if self.pruned_before:
            if iteration == -1:
                return self.blocks[0]
            if iteration < self.pruned_before:
                return None  # pruned away: the snapshot's whole purpose
            idx = iteration - self.pruned_before + 1
        else:
            idx = iteration + 1
        if 0 <= idx < len(self.blocks):
            return self.blocks[idx]
        return None

    def latest_gradient(self) -> np.ndarray:
        """Copy of the current global model (ref: blockchain.go:31-37)."""
        return self.latest.data.global_w.copy()

    def latest_hash(self) -> bytes:
        return self.latest.hash

    def latest_stake_map(self) -> Dict[int, int]:
        return dict(self.latest.stake_map)

    @property
    def next_iteration(self) -> int:
        return self.latest.iteration + 1

    # ------------------------------------------------------------- mutation

    def _check_links(self, blk: Block) -> None:
        if blk.iteration != self.latest.iteration + 1:
            raise ChainInvariantError(
                f"append iteration {blk.iteration} onto chain at {self.latest.iteration}"
            )
        if blk.prev_hash != self.latest.hash:
            raise ChainInvariantError("block prev-hash does not link to chain head")

    def add_block(self, blk: Block) -> None:
        """Append, enforcing chain[i].iteration == i-1 (ref: blockchain.go:77-96)."""
        self._check_links(blk)
        if blk.hash != blk.compute_hash():
            raise ChainInvariantError("block hash does not match contents")
        self.blocks.append(blk)

    @staticmethod
    def block_quality(blk: Block, prev_hash: bytes) -> int:
        """Ordering key: prev-hash match dominates, then non-empty beats empty
        (ref: DistSys/honest.go:631-647)."""
        return (2 if blk.prev_hash == prev_hash else 0) + (0 if blk.is_empty() else 1)

    def consider_block(self, blk: Block) -> bool:
        """Add / replace / ignore an incoming block for its height.

        Returns True if the chain changed. Same-height replacement keeps the
        higher-quality block (ref: honest.go:649-653); future blocks are the
        caller's problem (the runtime parks them, ref: main.go:1300-1320).
        """
        if blk.iteration == self.latest.iteration + 1:
            # tampered or unlinked network blocks are ignored, never raised:
            # a Byzantine peer must not be able to crash an honest one
            try:
                self.add_block(blk)
            except ChainInvariantError:
                return False
            return True
        if blk.iteration == self.latest.iteration and len(self.blocks) >= 2:
            if blk.hash != blk.compute_hash():
                return False
            # the head's true parent hash: equals blocks[-2].hash on a
            # contiguous chain (the append invariant), and stays correct
            # when blocks[-2] is genesis across a pruned gap
            prev = self.latest.prev_hash
            if self.block_quality(blk, prev) > self.block_quality(self.latest, prev):
                self.blocks[-1] = blk
                return True
        return False

    def adoption_key(self) -> tuple:
        """The fork-choice comparison key: (weight, length), weight =
        non-empty block count. A chain is adopted over another iff its key
        is strictly greater — the single source of truth shared by
        maybe_adopt and the join path's chain-omission gate. A pruned
        (snapshot-bootstrapped) chain counts its absent range via the
        snapshot's advisory weight claim plus the range's known length."""
        return (sum(1 for b in self.blocks if not b.is_empty())
                + self.pruned_weight,
                len(self.blocks) + self.pruned_before)

    def maybe_adopt(self, other: "Blockchain") -> bool:
        """Fork-choice adoption on (re)join (ref: main.go:1001-1013 adopts
        any longer chain blindly).

        Rule: WEIGHT (count of non-empty blocks) then LENGTH, from the same
        pinned genesis, structurally verified, deep-copied. Weight means a
        fabricated chain of free-to-seal empty filler can never displace
        real history. Weight itself is only unforgeable when the non-empty
        blocks' update records are authenticated — the ledger layer checks
        structure only, so the RUNTIME must (and does) verify each
        candidate block's verifier-signature quorums against the committees
        the candidate chain itself elects before calling this
        (PeerAgent._chain_quorums_ok); callers adopting from untrusted
        suppliers without that check inherit the reference's blind-adopt
        trust model.
        """
        # Fork choice on rejoin: WEIGHT-then-length, where weight = number
        # of non-empty blocks. The reference adopts any longer chain
        # blindly (main.go:1001-1013); pure length would let anyone
        # fabricate a long chain of empty timeout-filler (empty blocks are
        # free to seal) and wipe real history. Weighing non-empty blocks
        # means a partitioned minority that padded its chain with empties —
        # or even minted a minority-side real block — heals onto the
        # majority chain (which accumulated strictly more real rounds),
        # while an attacker must out-mint the honest network's real blocks
        # to rewrite anything. Genesis is pinned: a chain grown from a
        # forged genesis is refused outright.
        if not other.blocks or not self.blocks or \
                other.blocks[0].hash != self.blocks[0].hash:
            return False  # different genesis — refuse before any O(n) work
        if other.adoption_key() <= self.adoption_key():
            return False
        try:
            other.verify()
        except ChainInvariantError:
            return False
        self.blocks = copy.deepcopy(other.blocks)
        self.pruned_before = other.pruned_before
        self.pruned_weight = other.pruned_weight
        return True

    # ------------------------------------------------------------- oracle

    def dump(self) -> str:
        """Deterministic chain dump; byte-equality across peers is the
        top-level integration oracle (ref: DistSys/localTest.sh:40-96). A
        pruned chain interleaves an explicit gap marker so a
        snapshot-bootstrapped peer's dump is honest about what it never
        held (the churn oracle compares per-height `iter=` lines and
        skips the marker; runtime/membership.surviving_prefix_oracle)."""
        lines = [self.blocks[0].summary()]
        if self.pruned_before:
            lines.append(f"pruned heights=0..{self.pruned_before - 1} "
                         f"claimed_weight={self.pruned_weight}")
        lines.extend(b.summary() for b in self.blocks[1:])
        return "\n".join(lines)

    def verify(self) -> None:
        """Full structural re-check: hashes, links, iteration numbering.
        A pruned chain is allowed exactly ONE numbering/link gap — between
        genesis and the snapshot suffix's first block (whose prev_hash
        names a block deliberately not held); everything else is checked
        identically."""
        for i, b in enumerate(self.blocks):
            expect_iter = (i - 1 if not self.pruned_before or i == 0
                           else self.pruned_before + i - 1)
            if b.iteration != expect_iter:
                raise ChainInvariantError(f"block {i} has iteration {b.iteration}")
            if b.hash != b.compute_hash():
                raise ChainInvariantError(f"block {i} hash mismatch")
            if i > 0 and b.prev_hash != self.blocks[i - 1].hash \
                    and not (self.pruned_before and i == 1):
                raise ChainInvariantError(f"block {i} prev-hash mismatch")
