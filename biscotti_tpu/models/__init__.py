from biscotti_tpu.models.base import Model, make_model
from biscotti_tpu.models.zoo import MODELS, model_for_dataset

__all__ = ["Model", "make_model", "MODELS", "model_for_dataset"]
