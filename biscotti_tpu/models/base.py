"""Functional model abstraction.

The reference moves *flat* float vectors across every boundary (Go ⇄ Python,
peer ⇄ peer): models expose `reshape` to unflatten (ref:
ML/Pytorch/softmax_model.py:20-24, mnist_cnn_model.py:43-67). We keep that
contract — the framework's wire unit is a flat vector — but derive
flatten/unflatten automatically from the param pytree with
`jax.flatten_util.ravel_pytree`, so every model gets it for free and layouts
can never drift from the init.

All apply/loss functions are pure and jittable; `vmap` over the params axis
is how N peers train in one XLA program (see parallel/sim.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


@dataclass(frozen=True)
class Model:
    name: str
    d_in: int
    n_classes: int
    init: Callable[[jax.Array], Any]  # key -> params pytree
    apply: Callable[[Any, jax.Array], jax.Array]  # (params, x[B,d_in]) -> logits
    loss: Callable[[Any, jax.Array, jax.Array], jax.Array]  # mean scalar loss
    num_params: int
    unravel: Callable[[jax.Array], Any] = field(repr=False, default=None)

    def flat_init(self, key: jax.Array) -> jax.Array:
        return ravel_pytree(self.init(key))[0].astype(jnp.float32)

    def flatten(self, params: Any) -> jax.Array:
        return ravel_pytree(params)[0].astype(jnp.float32)

    def apply_flat(self, flat_w: jax.Array, x: jax.Array) -> jax.Array:
        return self.apply(self.unravel(flat_w), x)

    def loss_flat(self, flat_w: jax.Array, x: jax.Array, y: jax.Array) -> jax.Array:
        return self.loss(self.unravel(flat_w), x, y)

    def error_flat(self, flat_w: jax.Array, x: jax.Array, y: jax.Array) -> jax.Array:
        """1 − accuracy (ref: ML/Pytorch/client.py:136-160)."""
        pred = jnp.argmax(self.apply_flat(flat_w, x), axis=-1)
        return jnp.mean((pred != y).astype(jnp.float32))


def make_model(name, d_in, n_classes, init, apply, loss) -> Model:
    """Bind flatten/unflatten to a canonical zero-key init layout."""
    example = init(jax.random.PRNGKey(0))
    flat, unravel = ravel_pytree(example)
    return Model(
        name=name, d_in=d_in, n_classes=n_classes, init=init, apply=apply,
        loss=loss, num_params=int(flat.size), unravel=unravel,
    )


def cross_entropy(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Mean CE over the batch (ref: nn.CrossEntropyLoss, client.py:29)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None].astype(jnp.int32), axis=-1))


def multiclass_hinge(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Crammer–Singer hinge for the SVM model (ref: ML/Pytorch/svm_model.py)."""
    yi = jnp.take_along_axis(logits, y[:, None].astype(jnp.int32), axis=-1)
    margins = jnp.maximum(0.0, 1.0 + logits - yi)
    margins = margins.at[jnp.arange(logits.shape[0]), y].set(0.0)
    return jnp.mean(jnp.sum(margins, axis=-1))
