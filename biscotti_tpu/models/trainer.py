"""Per-peer trainer — the framework's internal replacement for the reference's
embedded-Python bridge API (init / privateFun / getNoise / roni / getTestErr /
get17AttackRate; ref: ML/Pytorch/client_obj.py, DistSys/honest.go:204-324).

Two step rules, matching the two reference stacks:

  * torch-parity ("grad"): delta = −clip₁₀₀(∇CE(w; minibatch))
    (ref: client.py:38-65 — backward + clip_grad_norm(100), no optimizer.step,
    privateFun returns −grad, client_obj.py:73-77)
  * logreg-parity ("sgd"): delta = −α·∇f(w; minibatch), α=1e-2, f the
    L2-regularized logistic loss (ref: logistic_model.py:113-140)

Everything below `Trainer.__init__` is jitted XLA; the minibatch draw is a
threefry `random.choice` folded from (seed, iteration) so peers are
deterministic given their id — required by the chain-equality oracle.

`local_step_fn` is exposed standalone (pure) so parallel/sim.py can vmap the
identical computation over a stacked peer axis.
"""

from __future__ import annotations

import zlib
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from biscotti_tpu.data import datasets as ds
from biscotti_tpu.models.base import Model
from biscotti_tpu.models.zoo import model_for_dataset
from biscotti_tpu.ops import dp_noise

GRAD_CLIP = 100.0  # default, ref: client.py:56; overridable via cfg.grad_clip
LOGREG_ALPHA = 1e-2  # default α, ref: logistic_model.py:12; overridable via cfg.logreg_alpha


def clip_by_global_norm(g: jax.Array, max_norm: float) -> jax.Array:
    n = jnp.linalg.norm(g)
    return g * jnp.minimum(1.0, max_norm / jnp.maximum(n, 1e-12))


def local_step_fn(model: Model, mode: str = "grad", clip: float = GRAD_CLIP,
                  alpha: float = LOGREG_ALPHA) -> Callable:
    """Pure per-peer update rule: (flat_w, x_batch, y_batch) -> flat_delta."""
    if mode == "grad":

        def step(flat_w, x, y):
            g = jax.grad(model.loss_flat)(flat_w, x, y)
            return -clip_by_global_norm(g, clip)

    elif mode == "sgd":

        def step(flat_w, x, y):
            # model.loss is already (1/B)Σ data + λ/2‖w‖², whose gradient is
            # the reference's (1/B)·Xᵀres + λw (ref: logistic_model.py:100-106)
            g = jax.grad(model.loss_flat)(flat_w, x, y)
            return -alpha * g

    else:
        raise ValueError(f"unknown step mode {mode!r}")
    return step


def sample_batch(key: jax.Array, n: int, batch_size: int) -> jax.Array:
    """Minibatch without replacement (ref: logistic_model.py:121-125,
    torch DataLoader shuffle)."""
    return jax.random.choice(key, n, (min(batch_size, n),), replace=False)


# Compiled-function cache shared by every Trainer with the same
# (model, step rule) — N peers of one cluster reuse ONE XLA executable per
# function instead of tracing N closures that differ only in their captured
# shard constants. At N=100 the per-peer closures serialized ~100 identical
# mnist compilations behind the GIL and stalled the first round for minutes;
# passing the shard as an argument makes the trace shape-polymorphic-enough
# (same shapes → same executable) and startup O(1) compilations.
_FN_CACHE: dict = {}


def _compiled_fns(model: Model, mode: str, clip: float, alpha: float,
                  cache_key=None):
    if cache_key is not None and cache_key in _FN_CACHE:
        return _FN_CACHE[cache_key]
    step = local_step_fn(model, mode, clip=clip, alpha=alpha)

    from functools import partial

    @partial(jax.jit, static_argnames=("batch_size",))
    def _private(flat_w, it, x_train, y_train, batch_key, batch_size):
        k = jax.random.fold_in(batch_key, it)
        idx = sample_batch(k, x_train.shape[0], batch_size)
        return step(flat_w, x_train[idx], y_train[idx])

    @jax.jit
    def _err(flat_w, x, y):
        return model.error_flat(flat_w, x, y)

    @jax.jit
    def _roni(flat_w, delta, x, y):
        # score = err(w+δ) − err(w) on the local train split
        # (ref: client_obj.py:100-112; rejected if > 0.02, main.go:203-231)
        before = model.error_flat(flat_w, x, y)
        after = model.error_flat(flat_w + delta, x, y)
        return after - before

    fns = (_private, _err, _roni)
    if cache_key is not None:
        _FN_CACHE[cache_key] = fns
    return fns


# Shared eval-split device arrays: the test and attack splits are
# IDENTICAL for every peer of a dataset (datasets.load_shard memoizes the
# numpy, but jnp.asarray re-uploaded a fresh device buffer per Trainer) —
# co-hosted clusters paid N copies of the same 6 MB test split. Keyed on
# the dataset name; jax arrays are immutable, so sharing is safe.
_EVAL_CACHE: dict = {}


def _shared_eval_arrays(dataset: str):
    if dataset not in _EVAL_CACHE:
        test = ds.load_shard(dataset, f"{dataset}_test")
        attack = ds.load_shard(dataset, f"{dataset}_digit1")
        _EVAL_CACHE[dataset] = (
            jnp.asarray(test["x_test"]), jnp.asarray(test["y_test"]),
            jnp.asarray(attack["x_test"]), jnp.asarray(attack["y_test"]))
    return _EVAL_CACHE[dataset]


class Trainer:
    """One peer's ML state: shard on device, shared jitted step/metric
    functions (see _compiled_fns).

    `light=True` (the hive runtime's co-hosted mode, runtime/hive.py)
    skips the per-peer train-shard upload and the DP-noise presample
    bank: a hive-hosted peer's SGD and noise draws are served by the
    shared HiveStepper, so duplicating them per agent would only burn
    the memory budget the hive exists to fit N≥1000 peers into. The
    eval splits (shared device buffers either way) and the compiled
    metric functions stay, so test_error / RONI / attack metrics work;
    private_fun / get_noise / train_error / roni raise loudly."""

    def __init__(self, dataset: str, shard: str, cfg=None, model: Model = None,
                 seed: int = None, light: bool = False):
        from biscotti_tpu.config import BiscottiConfig

        self.cfg = cfg or BiscottiConfig(dataset=dataset)
        self.dataset = dataset
        self.model = model or model_for_dataset(
            dataset, getattr(self.cfg, "model_name", ""))
        self.mode = "sgd" if self.model.name == "logreg" else "grad"
        self.batch_size = self.cfg.batch_size
        # Every stream is keyed on (config seed, shard identity) so peers
        # built with default args still get independent DP noise and batch
        # draws — the shard name is the peer identity.
        if seed is None:
            seed = zlib.crc32(shard.encode())
        self.seed = seed
        # optional telemetry registry (telemetry.MetricsRegistry), armed
        # by the embedding runtime: SGD steps and DP noise draws are
        # counted so cluster scrapes can attribute compute to peers.
        # Thread-safe (registry locks internally) — private_fun runs off
        # the event loop via asyncio.to_thread.
        self.metrics = None

        self.light = bool(light)
        if self.light:
            self.x_train = self.y_train = None
        else:
            shard_data = ds.load_shard(dataset, shard)
            self.x_train = jnp.asarray(shard_data["x_train"])
            self.y_train = jnp.asarray(shard_data["y_train"])
        (self.x_test, self.y_test,
         self.x_attack, self.y_attack) = _shared_eval_arrays(dataset)

        self.num_params = self.model.num_params
        base = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), self.seed)
        noise_key, batch_key = jax.random.split(base)
        eps_live = (self.cfg.epsilon
                    if self.cfg.noising or self.cfg.dp_in_model else 0.0)
        self.noise_accept_rate = None
        if self.light:
            self.noise_samples = None
        elif self.cfg.dp_mechanism == "mcmc13":
            # Song&Sarwate'13 branch (ref: client_obj.py:44-57); served
            # through the same noise_at/get_noise surface as the Gaussian
            self.noise_samples, acc = dp_noise.mcmc_presample(
                noise_key, eps_live, self.cfg.noise_presample_iters,
                self.num_params)
            self.noise_accept_rate = float(acc) if eps_live > 0 else None
        else:
            self.noise_samples = dp_noise.presample(
                noise_key, eps_live, self.cfg.delta, self.batch_size,
                self.cfg.noise_presample_iters, self.num_params,
            )

        alpha = self.cfg.logreg_alpha
        self._batch_key = batch_key
        # share compiled functions across peers of the same (zoo model,
        # step-rule) family; a caller-supplied custom model skips the cache
        cache_key = ((dataset, self.model.name, self.mode,
                      self.cfg.grad_clip, alpha)
                     if model is None else None)
        self._private, self._err_fn, self._roni_fn = _compiled_fns(
            self.model, self.mode, self.cfg.grad_clip, alpha,
            cache_key=cache_key)

    # ---- reference bridge API (honest.go:204-324 surface) ----

    def init_weights(self) -> np.ndarray:
        """Zero init, matching the genesis global model (ref: block.go:46-52)."""
        return np.zeros(self.num_params, dtype=np.float64)

    def _require_full(self, what: str) -> None:
        if self.light:
            raise RuntimeError(
                f"Trainer(light=True) holds no {what}: the hive's shared "
                "stepper serves SGD/noise for co-hosted peers "
                "(runtime/hive.py); construct a full Trainer for "
                "per-agent dispatch")

    def private_fun(self, flat_w: np.ndarray, iteration: int) -> np.ndarray:
        self._require_full("train shard")
        if self.metrics is not None:
            self.metrics.counter("biscotti_trainer_steps_total",
                                 "local SGD steps computed").inc()
        return np.asarray(
            self._private(jnp.asarray(flat_w, jnp.float32), iteration,
                          self.x_train, self.y_train, self._batch_key,
                          batch_size=min(self.batch_size,
                                         int(self.x_train.shape[0]))),
            dtype=np.float64,
        )

    def get_noise(self, iteration: int) -> np.ndarray:
        self._require_full("noise bank")
        if self.metrics is not None:
            self.metrics.counter("biscotti_noise_draws_total",
                                 "DP noise vectors served/consumed").inc()
        alpha = self.cfg.logreg_alpha if self.mode == "sgd" else 1.0
        return np.asarray(
            dp_noise.noise_at(self.noise_samples, iteration, self.batch_size, alpha),
            dtype=np.float64,
        )

    def train_error(self, flat_w: np.ndarray) -> float:
        self._require_full("train shard")
        return float(self._err_fn(jnp.asarray(flat_w, jnp.float32),
                                  self.x_train, self.y_train))

    def test_error(self, flat_w: np.ndarray) -> float:
        return float(self._err_fn(jnp.asarray(flat_w, jnp.float32),
                                  self.x_test, self.y_test))

    def attack_rate(self, flat_w: np.ndarray) -> float:
        """Reference-faithful metric: 1 − accuracy on the attack-source split
        (ref: client.py:163-172 get17AttackRate is literally
        1 − accuracy_score on the digit-1 loader). Counts *any*
        misclassification of source-class samples."""
        return float(self._err_fn(jnp.asarray(flat_w, jnp.float32),
                                  self.x_attack, self.y_attack))

    def attack_success_rate(self, flat_w: np.ndarray) -> float:
        """Stricter 1→7 metric: fraction of attack-source samples predicted
        as exactly the attack target class (not inflated by benign
        confusion the way `attack_rate` can be)."""

        target = ds.spec(self.dataset).attack_target
        logits = self.model.apply_flat(jnp.asarray(flat_w, jnp.float32),
                                       self.x_attack)
        pred = jnp.argmax(logits, axis=-1)
        return float(jnp.mean((pred == target).astype(jnp.float32)))

    def roni(self, flat_w: np.ndarray, delta: np.ndarray) -> float:
        self._require_full("train shard")
        return float(self._roni_fn(jnp.asarray(flat_w, jnp.float32),
                                   jnp.asarray(delta, jnp.float32),
                                   self.x_train, self.y_train))
