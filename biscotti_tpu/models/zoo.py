"""Model zoo — capability parity with ML/Pytorch/*.py plus the numpy logreg.

  softmax    linear d_in→k                (ref: softmax_model.py:7-24; mnist 7,850 params)
  logreg     L2 binary logistic, y∈{−1,1} (ref: ML/code/logistic_model.py:92-106)
  mnist_cnn  conv(1→16,5,pad 4)+relu+fc   (ref: mnist_cnn_model.py:7-41, "ONE LAYER")
  cifar_cnn  LeNet-5 shape                 (ref: cifar_cnn_model.py; BASELINE.md "CIFAR LeNet")
  lfw_cnn    small conv net over 62×47×3   (ref: lfw_cnn_model.py)
  svm        linear + multiclass hinge     (ref: svm_model.py)

Inits are MXU-friendly (fan-in scaled normal) and every model is expressed in
channels-last NHWC, the layout XLA prefers on TPU.
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp

from biscotti_tpu.data.datasets import base_name, spec as dspec
from biscotti_tpu.models.base import Model, cross_entropy, make_model, multiclass_hinge


def _linear_init(key, d_in, d_out):
    kw, _ = jax.random.split(key)
    s = 1.0 / math.sqrt(d_in)
    return {
        "w": jax.random.uniform(kw, (d_in, d_out), jnp.float32, -s, s),
        "b": jnp.zeros((d_out,), jnp.float32),
    }


def softmax_model(d_in: int, n_classes: int) -> Model:
    def init(key):
        return _linear_init(key, d_in, n_classes)

    def apply(p, x):
        return x.reshape(x.shape[0], d_in) @ p["w"] + p["b"]

    def loss(p, x, y):
        return cross_entropy(apply(p, x), y)

    return make_model("softmax", d_in, n_classes, init, apply, loss)


def svm_model(d_in: int, n_classes: int) -> Model:
    def init(key):
        return _linear_init(key, d_in, n_classes)

    def apply(p, x):
        return x.reshape(x.shape[0], d_in) @ p["w"] + p["b"]

    def loss(p, x, y):
        return multiclass_hinge(apply(p, x), y)

    return make_model("svm", d_in, n_classes, init, apply, loss)


def logreg_model(d_in: int, lammy: float = 0.01) -> Model:
    """Binary L2 logistic regression on ±1 labels with a bias feature
    (ref: logistic_model.py:8-13,92-106; bias column added by utils.py)."""
    d = d_in + 1

    def init(key):
        return {"w": jnp.zeros((d,), jnp.float32)}

    def _with_bias(x):
        return jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)

    def apply(p, x):
        # two-column logits so argmax-style error/accuracy code works unchanged
        z = _with_bias(x) @ p["w"]
        return jnp.stack([-z, z], axis=-1)

    def loss(p, x, y):
        # The reference's gradient is (1/B)·Xᵀres + λw (ref:
        # logistic_model.py:100-106 — data term batch-averaged, L2 term
        # NOT), so the loss whose gradient matches is
        # mean(logaddexp(0, −y·Xw)) + λ/2‖w‖², y∈{−1,1}.
        ypm = 2.0 * y.astype(jnp.float32) - 1.0
        z = _with_bias(x) @ p["w"]
        return jnp.mean(jnp.logaddexp(0.0, -ypm * z)) + 0.5 * lammy * jnp.dot(p["w"], p["w"])

    return make_model("logreg", d_in, 2, init, apply, loss)


def _conv_init(key, shape):  # HWIO
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)


def mnist_cnn_model() -> Model:
    """conv(1→16, 5×5, stride 1, pad 4) + relu + fc(16·32·32→10)
    (ref: mnist_cnn_model.py:12-16,31-41 — the active "ONE LAYER" branch;
    MaxPool2d(1) is the identity, so it is omitted)."""

    def init(key):
        k1, k2 = jax.random.split(key)
        return {
            "conv": {"w": _conv_init(k1, (5, 5, 1, 16)), "b": jnp.zeros((16,), jnp.float32)},
            "fc": _linear_init(k2, 16 * 32 * 32, 10),
        }

    def apply(p, x):
        x = x.reshape(x.shape[0], 28, 28, 1)
        h = jax.lax.conv_general_dilated(
            x, p["conv"]["w"], window_strides=(1, 1), padding=[(4, 4), (4, 4)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + p["conv"]["b"]
        h = jax.nn.relu(h)
        h = h.reshape(h.shape[0], -1)
        return h @ p["fc"]["w"] + p["fc"]["b"]

    def loss(p, x, y):
        return cross_entropy(apply(p, x), y)

    return make_model("mnist_cnn", 784, 10, init, apply, loss)


def _lenet_apply(p, x, hw, chans):
    h = x.reshape(x.shape[0], *hw, chans)
    for name in ("c1", "c2"):
        h = jax.lax.conv_general_dilated(
            h, p[name]["w"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + p[name]["b"]
        h = jax.nn.relu(h)
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
    h = h.reshape(h.shape[0], -1)
    for name in ("f1", "f2"):
        h = jax.nn.relu(h @ p[name]["w"] + p[name]["b"])
    return h @ p["f3"]["w"] + p["f3"]["b"]


def cifar_cnn_model() -> Model:
    """LeNet-5: conv(3→6,5) pool conv(6→16,5) pool fc120 fc84 fc10
    (ref: cifar_cnn_model.py; BASELINE.md row "CIFAR LeNet")."""

    def init(key):
        ks = jax.random.split(key, 5)
        return {
            "c1": {"w": _conv_init(ks[0], (5, 5, 3, 6)), "b": jnp.zeros((6,), jnp.float32)},
            "c2": {"w": _conv_init(ks[1], (5, 5, 6, 16)), "b": jnp.zeros((16,), jnp.float32)},
            "f1": _linear_init(ks[2], 16 * 5 * 5, 120),
            "f2": _linear_init(ks[3], 120, 84),
            "f3": _linear_init(ks[4], 84, 10),
        }

    def apply(p, x):
        return _lenet_apply(p, x, (32, 32), 3)

    def loss(p, x, y):
        return cross_entropy(apply(p, x), y)

    return make_model("cifar_cnn", 3072, 10, init, apply, loss)


def lfw_cnn_model() -> Model:
    """Small LeNet-shape net over 62×47×3 gender/face classes (ref: lfw_cnn_model.py)."""

    def init(key):
        ks = jax.random.split(key, 4)
        return {
            "c1": {"w": _conv_init(ks[0], (5, 5, 3, 6)), "b": jnp.zeros((6,), jnp.float32)},
            "c2": {"w": _conv_init(ks[1], (5, 5, 6, 16)), "b": jnp.zeros((16,), jnp.float32)},
            # 62×47 → conv5 VALID 58×43 → pool2 29×21 → conv5 VALID 25×17 → pool2 12×8
            "f1": _linear_init(ks[2], 16 * 12 * 8, 84),
            "f3": _linear_init(ks[3], 84, 12),
        }

    def apply(p, x):
        h = x.reshape(x.shape[0], 62, 47, 3)
        for name in ("c1", "c2"):
            h = jax.lax.conv_general_dilated(
                h, p[name]["w"], window_strides=(1, 1), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + p[name]["b"]
            h = jax.nn.relu(h)
            h = jax.lax.reduce_window(
                h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
            )
        h = h.reshape(h.shape[0], -1)
        h = jax.nn.relu(h @ p["f1"]["w"] + p["f1"]["b"])
        return h @ p["f3"]["w"] + p["f3"]["b"]

    def loss(p, x, y):
        return cross_entropy(apply(p, x), y)

    return make_model("lfw_cnn", 8742, 12, init, apply, loss)


MODELS: Dict[str, callable] = {
    "softmax": lambda ds: softmax_model(dspec(ds).d_in, dspec(ds).n_classes),
    "logreg": lambda ds: logreg_model(dspec(ds).d_in),
    "svm": lambda ds: svm_model(dspec(ds).d_in, dspec(ds).n_classes),
    "mnist_cnn": lambda ds: mnist_cnn_model(),
    "cifar_cnn": lambda ds: cifar_cnn_model(),
    "lfw_cnn": lambda ds: lfw_cnn_model(),
}


def model_for_dataset(dataset: str, model: str = "") -> Model:
    """Default model per dataset, mirroring the reference pairings
    (softmax for mnist/cifar/lfw via client_obj.init; logreg for creditcard
    via ML/code/logistic_model.py)."""
    if model:
        return MODELS[model](dataset)
    if base_name(dataset) == "creditcard":
        return logreg_model(dspec(dataset).d_in)
    return softmax_model(dspec(dataset).d_in, dspec(dataset).n_classes)
