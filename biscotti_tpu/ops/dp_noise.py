"""Differential-privacy noise presampling (Abadi-16 style).

Parity with the reference (ref: ML/Pytorch/client_obj.py:59-67,
ML/code/logistic_model.py:79-87):

    σ = √(2·ln(1.25/δ)) / ε
    samples = Σ_batch σ·N(0,1)[batch, iters, d]      (presampled once)
    noise(i) = (−1/batch)·samples[i mod iters]        (torch path)
    noise(i) = (−α/batch)·samples[i mod iters]        (logreg path, α folded by caller)

Summing `batch` iid Gaussians equals one draw with std σ·√batch, so we sample
the reduced tensor directly — same distribution, 1/batch the HBM traffic.
A threefry key (not global RNG) keeps every peer's stream independent and
reproducible.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def sigma_for(epsilon: float, delta: float = 1e-5) -> float:
    if epsilon <= 0:
        return 0.0
    return math.sqrt(2.0 * math.log(1.25 / delta)) / epsilon


def presample(key: jax.Array, epsilon: float, delta: float, batch_size: int,
              expected_iters: int, d: int) -> jax.Array:
    """Return samples[iters, d] ~ Σ_batch σ·N(0,1) (ref: client_obj.py:63-66)."""
    s = sigma_for(epsilon, delta)
    if s == 0.0:
        # one all-zero row suffices: noise_at indexes `i % iters`, so the
        # bank is all zeros either way — materializing [iters, d] of zeros
        # per peer cost ~3 MB × N agents in co-hosted clusters (the hive's
        # per-peer memory account made it visible)
        return jnp.zeros((1, d), jnp.float32)
    return s * math.sqrt(batch_size) * jax.random.normal(
        key, (expected_iters, d), jnp.float32
    )


def noise_at(samples: jax.Array, iteration, batch_size: int,
             alpha: float = 1.0) -> jax.Array:
    """noise(i) = (−α/batch)·samples[i mod iters] (ref: client_obj.py:97-98,
    logistic_model.py:108-109)."""
    i = jnp.asarray(iteration) % samples.shape[0]
    return (-alpha / batch_size) * samples[i]


def mcmc_presample(key: jax.Array, epsilon: float, expected_iters: int,
                   d: int, n_walkers: int = 0, burn: int = 64,
                   thin: int = 5):
    """Song&Sarwate'13 alternative DP mechanism (ref: ML/Pytorch/
    client_obj.py:44-57): noise rows drawn from the K-norm-style density
    p(x) ∝ exp(−(ε/2)·‖x‖₂) by Markov-chain Monte Carlo.

    The reference runs emcee's affine-invariant ensemble (max(4d, 250)
    walkers, 100 burn-in, 1000 kept steps) on the CPU, per peer, at
    startup. Here the ensemble is W independent random-walk Metropolis
    chains advanced by ONE vectorized `lax.scan` — each scan step
    proposes W×d Gaussian moves and applies W accept masks, which XLA
    fuses into a few device kernels; burn-in and thinning run as nested
    scans that materialize only the kept rows (keeps × W × d), never the
    full chain history. The proposal step 4.76/ε is the Roberts-Rosenthal
    2.38/√d rule against this target's per-coordinate scale 2√d/ε —
    dimension-free, so acceptance stays near-optimal at every model size.

    Correctness at ANY dimension comes from the initialization, not from
    mixing: every walker starts from an EXACT draw of the target (the
    closed radial form `knorm_draw` samples — r ~ Gamma(d, 2/ε) times a
    uniform direction), so the chain is in equilibrium from step 0 and
    every emitted row is exactly target-distributed no matter how slowly
    RWM relaxes at large d (its relaxation time is O(d) steps — a
    cold-started chain at d = 164k would need ~10⁵ burn-in steps; an
    equilibrium-started one needs none). Row INDEPENDENCE holds whenever
    expected_iters ≤ W, since then each kept row comes from a different,
    never-interacting walker; the default W = max(250, min(1024, iters))
    guarantees that for every shipped presample depth (the reference's
    own nwalkers = max(4d, 250) plays the same role for emcee). Beyond
    1024 rows, same-walker rows thin apart and are correlated at large d
    — mirror of the reference's flatchain, whose consecutive ensemble
    sweeps are equally correlated.

    Returns (samples[expected_iters, d] float32, acceptance_rate scalar).
    The samples feed the same `noise_at` the Gaussian path uses (the
    reference serves both mechanisms' presample through one getNoise,
    client_obj.py:97-98)."""
    if epsilon <= 0 or expected_iters <= 0 or d <= 0:
        return (jnp.zeros((max(expected_iters, 0), max(d, 0)), jnp.float32),
                jnp.asarray(0.0, jnp.float32))
    w = int(n_walkers) if n_walkers else max(250, min(1024, expected_iters))
    keeps = -(-expected_iters // w)  # ceil
    step = jnp.float32(2.38 * 2.0 / epsilon)

    k_init, k_burn, k_keep = jax.random.split(key, 3)
    # equilibrium start: exact draws from the target itself (see above)
    x0 = knorm_draw(k_init, epsilon, w, d)
    lp0 = -(epsilon / 2.0) * jnp.linalg.norm(x0, axis=1)

    def mh_step(carry, k):
        x, lp, acc = carry
        k1, k2 = jax.random.split(k)
        prop = x + step * jax.random.normal(k1, x.shape, jnp.float32)
        lp_p = -(epsilon / 2.0) * jnp.linalg.norm(prop, axis=1)
        take = jnp.log(jax.random.uniform(k2, (w,))) < (lp_p - lp)
        x = jnp.where(take[:, None], prop, x)
        lp = jnp.where(take, lp_p, lp)
        return (x, lp, acc + take.mean()), None

    carry = (x0, lp0, jnp.asarray(0.0, jnp.float32))
    carry, _ = jax.lax.scan(mh_step, carry,
                            jax.random.split(k_burn, burn))

    def keep_block(carry, ks):
        carry, _ = jax.lax.scan(mh_step, carry, ks)
        return carry, carry[0]

    carry, kept = jax.lax.scan(
        keep_block, carry,
        jax.random.split(k_keep, keeps * thin).reshape(keeps, thin, 2))
    samples = kept.reshape(keeps * w, d)[:expected_iters]
    accept = carry[2] / (burn + keeps * thin)
    return samples, accept


def knorm_draw(key: jax.Array, epsilon: float, n: int, d: int) -> jax.Array:
    """Exact draw of n vectors from p(x) ∝ exp(−(ε/2)·‖x‖₂) — the
    Song&Sarwate'13 density in closed form: the distribution is
    spherically symmetric with radial law r ~ Gamma(shape=d, scale=2/ε),
    so direction (uniform on S^{d−1}) × radius samples it exactly. This
    is the stationary distribution `mcmc_presample`'s chain converges to;
    the vmapped simulator uses this form (fresh per-round draws, no chain
    state), the per-peer trainer keeps the chain for mechanism parity
    with the reference's emcee path (client_obj.py:44-57)."""
    if epsilon <= 0:
        return jnp.zeros((n, d), jnp.float32)
    kd, kr = jax.random.split(key)
    dirn = jax.random.normal(kd, (n, d), jnp.float32)
    dirn = dirn / jnp.maximum(jnp.linalg.norm(dirn, axis=1, keepdims=True),
                              1e-30)
    r = jax.random.gamma(kr, jnp.float32(d), (n,)) * (2.0 / epsilon)
    return dirn * r[:, None]
