"""Differential-privacy noise presampling (Abadi-16 style).

Parity with the reference (ref: ML/Pytorch/client_obj.py:59-67,
ML/code/logistic_model.py:79-87):

    σ = √(2·ln(1.25/δ)) / ε
    samples = Σ_batch σ·N(0,1)[batch, iters, d]      (presampled once)
    noise(i) = (−1/batch)·samples[i mod iters]        (torch path)
    noise(i) = (−α/batch)·samples[i mod iters]        (logreg path, α folded by caller)

Summing `batch` iid Gaussians equals one draw with std σ·√batch, so we sample
the reduced tensor directly — same distribution, 1/batch the HBM traffic.
A threefry key (not global RNG) keeps every peer's stream independent and
reproducible.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def sigma_for(epsilon: float, delta: float = 1e-5) -> float:
    if epsilon <= 0:
        return 0.0
    return math.sqrt(2.0 * math.log(1.25 / delta)) / epsilon


def presample(key: jax.Array, epsilon: float, delta: float, batch_size: int,
              expected_iters: int, d: int) -> jax.Array:
    """Return samples[iters, d] ~ Σ_batch σ·N(0,1) (ref: client_obj.py:63-66)."""
    s = sigma_for(epsilon, delta)
    if s == 0.0:
        return jnp.zeros((expected_iters, d), jnp.float32)
    return s * math.sqrt(batch_size) * jax.random.normal(
        key, (expected_iters, d), jnp.float32
    )


def noise_at(samples: jax.Array, iteration, batch_size: int,
             alpha: float = 1.0) -> jax.Array:
    """noise(i) = (−α/batch)·samples[i mod iters] (ref: client_obj.py:97-98,
    logistic_model.py:108-109)."""
    i = jnp.asarray(iteration) % samples.shape[0]
    return (-alpha / batch_size) * samples[i]
