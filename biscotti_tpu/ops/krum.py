"""Krum Byzantine-update filtering as a fused XLA kernel.

This is the flagship device kernel of the framework (SURVEY.md §2.3 row 18):
the reference computes the O(n²·d) pairwise-distance matrix in numpy on the
verifier's CPU behind an embedded-Python bridge
(ref: ML/Pytorch/client_obj.py:114-143, duplicate
ML/code/logistic_validator.py:36-65, invoked from DistSys/krum.go:100-166).
Here it is one jitted function: a single [n,d]·[d,n] matmul on the MXU plus a
top-k, fused by XLA — no host round-trip.

Semantics (kept bit-faithful to the reference):
  f          = floor(NumAdversaries · n), NumAdversaries = 0.5 (krum.go:27-28,110)
  groupsize  = n − f
  D_ij       = ‖x_i‖² + ‖x_j‖² − 2·x_i·x_j
  score_i    = Σ of the (groupsize − 2) smallest D_ij, j ≠ i
               (the reference sums sorted(D_i)[1 : groupsize−1], dropping the
               self-distance at index 0)
  accept     = the n − f lowest-scoring updates

Returned as both an index set and a dense mask — the mask form is what the
simulator's fully-jitted round step consumes (no dynamic shapes).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp


def pairwise_sq_dists(x: jax.Array) -> jax.Array:
    """D[i,j] = ‖x_i − x_j‖², computed as one MXU matmul (ref:
    client_obj.py:131-134). float32 accumulation keeps scores stable for
    bfloat16 inputs."""
    x = x.astype(jnp.float32)
    sq = jnp.sum(x * x, axis=-1)
    d = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)
    return jnp.maximum(d, 0.0)  # clamp fp cancellation noise


@partial(jax.jit, static_argnames=("num_adversaries",))
def krum_scores(deltas: jax.Array, num_adversaries: int) -> jax.Array:
    """score_i = Σ of the (n − f − 2) nearest-neighbor distances
    (ref: client_obj.py:127-143)."""
    n = deltas.shape[0]
    groupsize = n - num_adversaries
    k = max(groupsize - 2, 0)
    d = pairwise_sq_dists(deltas)
    # exclude self-distance exactly (the reference's sorted[0] drop)
    d = d + jnp.diag(jnp.full((n,), jnp.inf, jnp.float32))
    if k == 0:
        return jnp.zeros((n,), jnp.float32)
    neg_nearest, _ = jax.lax.top_k(-d, k)
    return -jnp.sum(neg_nearest, axis=-1)


@partial(jax.jit, static_argnames=("num_adversaries",))
def krum_accept_mask(deltas: jax.Array, num_adversaries: int) -> jax.Array:
    """Dense bool mask of the n − f accepted updates (lowest Krum scores;
    ref: client_obj.py:119-124 argpartition). Large committees on TPU
    score through the fused Pallas kernel (ops/krum_pallas)."""
    from biscotti_tpu.ops.krum_pallas import krum_scores_auto

    n = deltas.shape[0]
    keep = n - num_adversaries
    scores = krum_scores_auto(deltas, num_adversaries)
    _, idx = jax.lax.top_k(-scores, keep)
    return jnp.zeros((n,), jnp.bool_).at[idx].set(True)


def krum_select(deltas: jax.Array, num_adversaries: int) -> jax.Array:
    """Reference-shaped API: the accepted index set, ascending by score rank
    then index (ref: krum(deltas, clip) -> good_idx). Host-side helper; the
    jitted mask form is preferred inside compiled round steps."""
    mask = krum_accept_mask(jnp.asarray(deltas), num_adversaries)
    return jnp.nonzero(mask)[0]


def default_num_adversaries(n: int, frac: float = 0.5) -> int:
    """adversaryCount = int(0.5·n) (ref: krum.go:110)."""
    return int(frac * n)


def collusion_accept_override(peer_id: int, num_nodes: int,
                              poison_fraction: float) -> bool:
    """Colluding poisoners rubber-stamp each other's updates when they land
    on the verifier committee (ref: krum.go:47-58): poisoners are the node
    ids above ceil(N·(1−POISONING))."""
    if poison_fraction <= 0:
        return False
    poisoning_index = math.ceil(num_nodes * (1.0 - poison_fraction))
    return peer_id > poisoning_index
