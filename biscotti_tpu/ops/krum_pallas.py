"""Fused Krum scoring as a Pallas TPU kernel for large committees.

The XLA path in ops/krum.py (one [n,d]x[d,n] matmul + lax.top_k with
k ~ n/2) is ideal up to a few hundred peers, but at large n it
materializes the full n x n distance matrix in HBM and pays a per-row
sort for the "sum of the k smallest" reduction (top_k at k ~ n/2 lowers
to a full variadic sort). This kernel fuses the whole score pipeline
(SURVEY.md §2.3 row 18 calls Krum the flagship device kernel; the
reference computes it in numpy on the verifier's CPU,
ML/Pytorch/client_obj.py:114-143):

  grid (row-tile i, feature-tile kd), kd innermost:
    1. accumulate G[i-tile, :] += X[i-tile, kd] . X[:, kd]^T on the MXU
       into a VMEM scratch — the n x n Gram/distance matrix exists only
       as one (TILE_M, n) stripe at a time, never in HBM;
    2. at the last kd step, form D = |xi|^2 + |xj|^2 - 2G, mask the
       diagonal and column padding to +inf, and run an EXACT per-row
       selection of the k-th smallest distance by bisection on the
       float bit pattern (non-negative IEEE floats compare like their
       int bits, so 31 VPU passes pin the exact value — no sort, no
       approximation);
    3. score_i = sum(D < t_i) + (k - count_lt) * t_i  — exactly the
       reference's sum of the (n - f - 2) nearest distances, with ties
       at the threshold handled the way a sorted prefix would.

Scores match ops/krum.krum_scores to float-sum reassociation (tested
bit-tight at 1e-4 rtol, including duplicate-update ties). The dispatcher
krum_scores_auto keeps the XLA path for small n and switches to this
kernel when the committee is large enough for the fusion to pay.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_M = 128
# f32 sign bit is never set for distances (>= 0, +inf mask included),
# so bisection over bits 30..0 pins the exact k-th smallest value
_SELECT_BITS = 31


def _select_kth_and_sum(dist: jax.Array, k: int) -> jax.Array:
    """Per-row sum of the k smallest entries of `dist` (TILE_M, n_pad),
    exact selection via integer bisection on the float bit pattern.
    Returns (TILE_M, 1) float32."""
    bits = jax.lax.bitcast_convert_type(dist, jnp.int32)

    def body(t, ans):
        cand = ans | (1 << (_SELECT_BITS - 1 - t))
        cnt_lt = jnp.sum((bits < cand).astype(jnp.int32), axis=1,
                         keepdims=True)
        # count(x < cand) >= k  =>  k-th smallest < cand: bit stays 0
        return jnp.where(cnt_lt >= k, ans, cand)

    ans = jax.lax.fori_loop(
        0, _SELECT_BITS, body,
        jnp.zeros((dist.shape[0], 1), jnp.int32))
    kth = jax.lax.bitcast_convert_type(ans, jnp.float32)
    below = bits < ans
    cnt_lt = jnp.sum(below.astype(jnp.int32), axis=1, keepdims=True)
    ssum = jnp.sum(jnp.where(below, dist, 0.0), axis=1, keepdims=True)
    # ties at the threshold: a sorted prefix would take (k - cnt_lt)
    # copies of the k-th value
    return ssum + (k - cnt_lt).astype(jnp.float32) * kth


def _krum_kernel(x_row_ref, x_all_ref, sq_row_ref, sq_col_ref, out_ref,
                 gram, *, n: int, k: int, kd_steps: int):
    i = pl.program_id(0)
    kd = pl.program_id(1)

    @pl.when(kd == 0)
    def _():
        gram[:] = jnp.zeros_like(gram)

    gram[:] += jax.lax.dot_general(
        x_row_ref[:], x_all_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kd == kd_steps - 1)
    def _():
        n_pad = gram.shape[1]
        d = sq_row_ref[:] + sq_col_ref[:] - 2.0 * gram[:]
        d = jnp.maximum(d, 0.0)  # clamp fp cancellation noise
        cols = jax.lax.broadcasted_iota(jnp.int32, (TILE_M, n_pad), 1)
        rows = jax.lax.broadcasted_iota(jnp.int32, (TILE_M, n_pad), 0)
        rows = rows + i * TILE_M
        # self-distance (the reference's sorted[0] drop) + column padding
        d = jnp.where((cols == rows) | (cols >= n), jnp.inf, d)
        out_ref[:] = _select_kth_and_sum(d, k)


@functools.partial(jax.jit, static_argnames=("num_adversaries",))
def krum_scores_pallas(deltas: jax.Array, num_adversaries: int) -> jax.Array:
    """Krum scores (ops/krum.krum_scores semantics) via the fused kernel.

    score_i = sum of the (n - f - 2) smallest off-diagonal squared
    distances in row i (ref: client_obj.py:127-143).
    """
    n, d = deltas.shape
    groupsize = n - num_adversaries
    k = max(groupsize - 2, 0)
    if k == 0:
        return jnp.zeros((n,), jnp.float32)

    x = deltas.astype(jnp.float32)
    n_pad = -(-n // TILE_M) * TILE_M
    # feature tile: bounded VMEM for the (n_pad, d_t) operand stripe
    d_t = 256 if n_pad <= 4096 else 128
    d_pad = -(-d // d_t) * d_t
    x = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
    sq = jnp.sum(x * x, axis=-1)  # zero padding leaves norms exact
    kd_steps = d_pad // d_t

    kernel = functools.partial(_krum_kernel, n=n, k=k, kd_steps=kd_steps)
    scores = pl.pallas_call(
        kernel,
        grid=(n_pad // TILE_M, kd_steps),
        in_specs=[
            pl.BlockSpec((TILE_M, d_t), lambda i, kd: (i, kd),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n_pad, d_t), lambda i, kd: (0, kd),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_M, 1), lambda i, kd: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad), lambda i, kd: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_M, 1), lambda i, kd: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((TILE_M, n_pad), jnp.float32)],
        interpret=jax.default_backend() != "tpu",
    )(x, x, sq[:, None], sq[None, :])
    return scores[:n, 0]


# committees below this stay on the XLA matmul+top_k path (faster at
# small n: one fused HLO, no grid/padding overhead). Device-trace
# measurements inside the window at d=7850 on v5e (eval/eval_krum_kernel):
# 1.17x at n=512, 1.48x at 1024, 0.96x at 2048 (break-even: XLA's sort
# happens to tile well there), 1.48x at 4096 — the window is kept
# contiguous rather than carving out the one ~4% break-even size.
PALLAS_MIN_N = 512
# above this the kernel's VMEM working set (double-buffered (n_pad, d_t)
# operand stripe + (TILE_M, n_pad) gram scratch) no longer compiles on
# v5e (verified: n=8192 fails Mosaic VMEM allocation) — fall back to XLA
PALLAS_MAX_N = 4096


def krum_scores_auto(deltas: jax.Array, num_adversaries: int) -> jax.Array:
    """Dispatch Krum scoring: XLA path for small committees (and for
    n beyond the kernel's VMEM ceiling), the fused Pallas kernel for
    large ones on TPU.

    Deployment constraint (ADVICE r3): inside the [PALLAS_MIN_N,
    PALLAS_MAX_N] window the accept set is backend-dependent — Pallas and
    XLA scores agree only to ~1e-4 rtol, so tie-boundary accept sets can
    differ between a TPU verifier and a CPU verifier. All verifiers of one
    cluster must therefore share a backend (see docs/RUNTIME.md,
    "Verifier backend homogeneity"). The live protocol's committees
    (3-70 verifiers) sit below PALLAS_MIN_N, where every backend takes
    the same XLA path, so the constraint binds only for sampled-committee
    sizes >= 512."""
    from biscotti_tpu.ops.krum import krum_scores

    n = deltas.shape[0]
    if PALLAS_MIN_N <= n <= PALLAS_MAX_N and jax.default_backend() == "tpu":
        return krum_scores_pallas(deltas, num_adversaries)
    return krum_scores(deltas, num_adversaries)
