"""LSH-sieve aggregator — sybil/duplicate attenuation (XLA kernel).

The reference's experimental extra defense builds a falconn LSH index over
the centred updates and divides each update's contribution by its
near-neighbor count, so a cluster of (near-)identical sybil updates sums
to ~one update's worth of influence (ref: ML/code/logistic_aggregator.py:7-27).

TPU-native redesign: random-hyperplane LSH. B threefry-drawn hyperplanes
give every update a B-bit sign code (one [n,d]×[d,B] matmul — MXU work);
near-neighbors are pairs whose codes differ in ≤ radius bits, counted with
a single ±1 code Gram matrix (another matmul). No index structure, no
host loops — two matmuls and a compare, batched over all n updates at
once, where falconn's query loop was per-update on the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_planes", "radius"))
def lsh_sieve_weights(deltas: jax.Array, key: jax.Array,
                      num_planes: int = 64, radius: int = 2) -> jax.Array:
    """Per-update attenuation weights 1/|near-neighbors| (self included, so
    weights ∈ (0, 1]). deltas: [n, d] float."""
    n, d = deltas.shape
    centred = deltas - jnp.mean(deltas, axis=0, keepdims=True)
    planes = jax.random.normal(key, (d, num_planes), deltas.dtype)
    codes = jnp.where(centred @ planes >= 0, 1.0, -1.0)  # [n, B]
    # hamming(i,j) = (B − codes_i·codes_j) / 2
    gram = codes @ codes.T  # [n, n]
    hamming = (num_planes - gram) / 2.0
    neighbors = jnp.sum(hamming <= radius, axis=1)  # ≥ 1 (self)
    return 1.0 / neighbors.astype(deltas.dtype)


@partial(jax.jit, static_argnames=("num_planes", "radius"))
def lsh_sieve_aggregate(deltas: jax.Array, key: jax.Array,
                        num_planes: int = 64, radius: int = 2) -> jax.Array:
    """Σᵢ wᵢ·deltaᵢ with LSH attenuation weights — the reference's
    `lsh_sieve` aggregate (ref: logistic_aggregator.py:20-27)."""
    w = lsh_sieve_weights(deltas, key, num_planes, radius)
    return jnp.sum(deltas * w[:, None], axis=0)
