"""Non-IID-robust aggregation kernels: Multi-Krum and coordinate-wise
trimmed mean.

Vanilla Krum's closest-neighbour score is captured by a mutually tight
poisoner cluster once honest updates spread wider than it — the documented
non-IID failure mode reproduced in eval/results/poison_mnist_dir0.3_100.json
(defended 0.93 vs undefended 0.935 at 30% poison, Dirichlet α=0.3). The
reference ships only vanilla Krum (ref: ML/Pytorch/client_obj.py:114-143,
DistSys/krum.go:100-166) and inherits the same failure; these kernels are
the beyond-reference fix, selectable as `Defense` enum members.

Multi-Krum (Blanchard et al., NeurIPS'17 §4) keeps the m lowest-scoring
updates instead of n−f — same distance matrix (one MXU matmul), so it
shares vanilla Krum's geometry and is kept mainly as the literature
control: it inherits the tight-cluster capture under non-IID.

Coordinate-wise trimmed mean (Yin et al., ICML'18) sorts each coordinate
across updates, drops the top/bottom `trim_frac` fraction, and averages the
remainder. It never compares whole update vectors, so a directionally
consistent poisoner cluster lands in the trimmed tails coordinate-by-
coordinate no matter how tightly it clusters — this is the one that
separates on the Dirichlet(0.3) sweep. The sort is a single `jnp.sort`
along the peer axis; XLA lowers it to an on-device bitonic sort, no host
round-trip.

Protocol note: trimmed mean consumes per-update COORDINATE VALUES at the
aggregation point, so it is structurally incompatible with additive secret
sharing (shares only support Σ-aggregates) — config.py rejects
secure_agg + TRIMMED_MEAN at construction. Multi-Krum is a verifier-side
accept mask like vanilla Krum and composes with every transport mode.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def multikrum_m(n: int, num_adversaries: int) -> int:
    """Blanchard et al.'s selection size m = n − f − 2, floored at 1."""
    return max(n - num_adversaries - 2, 1)


@partial(jax.jit, static_argnames=("num_adversaries", "m"))
def multikrum_accept_mask(deltas: jax.Array, num_adversaries: int,
                          m: int = 0) -> jax.Array:
    """Dense bool mask of the m lowest-Krum-scored updates (m = n − f − 2
    by default). Reuses the fused score kernel, so large committees ride
    the Pallas path on TPU."""
    from biscotti_tpu.ops.krum_pallas import krum_scores_auto

    n = deltas.shape[0]
    keep = m if m > 0 else multikrum_m(n, num_adversaries)
    keep = min(keep, n)
    scores = krum_scores_auto(deltas, num_adversaries)
    _, idx = jax.lax.top_k(-scores, keep)
    return jnp.zeros((n,), jnp.bool_).at[idx].set(True)


@partial(jax.jit, static_argnames=("trim_frac",))
def trimmed_mean(updates: jax.Array, trim_frac: float) -> jax.Array:
    """Coordinate-wise β-trimmed mean over the peer axis of [n, d]:
    per coordinate, sort the n values, drop ⌊β·n⌋ from each end, average
    the rest. β must exceed the Byzantine fraction for the robustness
    guarantee (Yin'18 Thm 1); at β ≥ 0.5 the kept band degenerates to the
    median element(s)."""
    n = updates.shape[0]
    t = int(trim_frac * n)
    t = min(t, (n - 1) // 2)  # always keep at least one element
    s = jnp.sort(updates.astype(jnp.float32), axis=0)
    return jnp.mean(s[t:n - t], axis=0)


def trimmed_mean_aggregate(updates: jax.Array, trim_frac: float) -> jax.Array:
    """Sum-scale form: (n − 2t)·trimmed_mean, so the global step magnitude
    matches the reference's Σ-of-accepted aggregation (honest.go:360-375,
    which SUMS the ≈(n−f) accepted deltas) instead of shrinking the
    learning rate by a factor of n."""
    n = updates.shape[0]
    t = min(int(trim_frac * n), (n - 1) // 2)
    return (n - 2 * t) * trimmed_mean(updates, trim_frac)


def median_aggregate(updates: jax.Array) -> jax.Array:
    """Coordinate-wise median, scaled to the sum-aggregation magnitude by
    the equivalent honest-majority count ⌈n/2⌉ — the β→0.5 limit of the
    trimmed mean, exposed for completeness."""
    n = updates.shape[0]
    med = jnp.median(updates.astype(jnp.float32), axis=0)
    return ((n + 1) // 2) * med
