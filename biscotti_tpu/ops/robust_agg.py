"""Non-IID-robust aggregation kernels: Multi-Krum, coordinate-wise
trimmed mean, and FoolsGold similarity down-weighting.

Vanilla Krum's closest-neighbour score is captured by a mutually tight
poisoner cluster once honest updates spread wider than it — the documented
non-IID failure mode reproduced in eval/results/poison_mnist_dir0.3_100.json
(Dirichlet α=0.3). The reference ships only vanilla Krum (ref:
ML/Pytorch/client_obj.py:114-143, DistSys/krum.go:100-166) and inherits
the same failure; these kernels are the beyond-reference options,
selectable as `Defense` enum members. What the round-5 seeded sweeps
taught us about each (poison_mnist_dir0.3_100.json):

Multi-Krum (Blanchard et al., NeurIPS'17 §4) keeps the m lowest-scoring
updates instead of n−f — same distance matrix (one MXU matmul), so it
shares vanilla Krum's geometry and is kept as the literature control: it
inherits the tight-cluster capture under non-IID.

Coordinate-wise trimmed mean (Yin et al., ICML'18) sorts each coordinate
across updates, drops the top/bottom `trim_frac` fraction, and averages
the remainder (one `jnp.sort` along the peer axis). MEASURED LIMITATION:
under heavy label skew the honest population straddles zero on the
attack-relevant coordinates (only the minority of source-class holders
provides counterweight), so the kept middle band filters out the
minority-class signal together with the poison — at dir(0.3)/30% the
trimmed aggregate performs WORSE than undefended (attack 1.0 vs 0.905;
kept in the artifact as an honest negative result). Use it for IID or
moderate skew only; it is also incompatible with additive secret shares
(config rejects secure_agg + TRIMMED_MEAN) and has no per-update reject,
so the stake penalty never fires.

FoolsGold (Fung et al., RAID'20 — the reference group's own successor
work on sybil-robust FL) targets exactly the attack the reference ships:
poisoned shards are near-duplicates of one another (parse_mnist.py
generate_poisoned writes ONE mnist_bad for every poisoner), so poisoner
updates are mutually far more similar than honest non-IID updates.
Per-client statistics from pairwise cosine similarity (one [n,n] matmul
on the MXU); the accept decision is a robust outlier test on the
max-mutual-cosine statistic (see foolsgold_accept_mask), which keeps it
compatible with additive secure aggregation and the block-level stake
penalty — the two protocol properties the paper's soft-weighting form
would break.

OPERATING POINT (measured, eval_poison --noising help): scoring is
SINGLE-ROUND, on whatever copies the verifier sees. Under the full
protocol's committee noising at ε=1.0 and d=7,850 the DP noise norm is
~14× the update norm, so mutual cosines are noise-dominated and this
defense — like every update-geometry defense including the reference's
Krum — degrades toward accept-everyone there (poison.json ε=1.0 rows).
Its demonstrated win is the defense-geometry operating point (noising
off, the reference's own ML-layer poison-eval configuration):
dir(0.3)/30% attack 0.01 vs 0.905 undefended
(poison_mnist_dir0.3_100_nonoise.json). Cross-round history
accumulation (signal grows T, noise √T) would need T ≳ (14)² ≈ 200
rounds to surface the ε=1.0 signal and is future work, not implemented.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def multikrum_m(n: int, num_adversaries: int) -> int:
    """Blanchard et al.'s selection size m = n − f − 2, floored at 1."""
    return max(n - num_adversaries - 2, 1)


@partial(jax.jit, static_argnames=("num_adversaries", "m"))
def multikrum_accept_mask(deltas: jax.Array, num_adversaries: int,
                          m: int = 0) -> jax.Array:
    """Dense bool mask of the m lowest-Krum-scored updates (m = n − f − 2
    by default). Reuses the fused score kernel, so large committees ride
    the Pallas path on TPU."""
    from biscotti_tpu.ops.krum_pallas import krum_scores_auto

    n = deltas.shape[0]
    keep = m if m > 0 else multikrum_m(n, num_adversaries)
    keep = min(keep, n)
    scores = krum_scores_auto(deltas, num_adversaries)
    _, idx = jax.lax.top_k(-scores, keep)
    return jnp.zeros((n,), jnp.bool_).at[idx].set(True)


@partial(jax.jit, static_argnames=("trim_frac",))
def trimmed_mean(updates: jax.Array, trim_frac: float) -> jax.Array:
    """Coordinate-wise β-trimmed mean over the peer axis of [n, d]:
    per coordinate, sort the n values, drop ⌊β·n⌋ from each end, average
    the rest. β must exceed the Byzantine fraction for the robustness
    guarantee (Yin'18 Thm 1); at β ≥ 0.5 the kept band degenerates to the
    median element(s)."""
    n = updates.shape[0]
    t = int(trim_frac * n)
    t = min(t, (n - 1) // 2)  # always keep at least one element
    s = jnp.sort(updates.astype(jnp.float32), axis=0)
    return jnp.mean(s[t:n - t], axis=0)


def trimmed_mean_aggregate(updates: jax.Array, trim_frac: float) -> jax.Array:
    """Sum-scale form: (n − 2t)·trimmed_mean, so the global step magnitude
    matches the reference's Σ-of-accepted aggregation (honest.go:360-375,
    which SUMS the ≈(n−f) accepted deltas) instead of shrinking the
    learning rate by a factor of n."""
    n = updates.shape[0]
    t = min(int(trim_frac * n), (n - 1) // 2)
    return (n - 2 * t) * trimmed_mean(updates, trim_frac)


def median_aggregate(updates: jax.Array) -> jax.Array:
    """Coordinate-wise median, scaled to the sum-aggregation magnitude by
    the equivalent honest-majority count ⌈n/2⌉ — the β→0.5 limit of the
    trimmed mean, exposed for completeness."""
    n = updates.shape[0]
    med = jnp.median(updates.astype(jnp.float32), axis=0)
    return ((n + 1) // 2) * med


# --------------------------------------------------------------- FoolsGold


def _cosine_matrix(updates: jax.Array) -> jax.Array:
    """[n,n] pairwise cosine with the diagonal masked to −inf — the one
    place the normalization/masking numerics live (weights and mask must
    never disagree on the same input)."""
    x = updates.astype(jnp.float32)
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
    cs = xn @ xn.T
    return jnp.where(jnp.eye(cs.shape[0], dtype=jnp.bool_), -jnp.inf, cs)


@jax.jit
def foolsgold_weights(updates: jax.Array) -> jax.Array:
    """Per-client FoolsGold weights in [0, 1] from this round's pairwise
    cosine similarity (Fung et al., RAID'20, Alg. 1): mutually-similar
    (sybil) clients are driven to 0, dissimilar (honest non-IID) clients
    stay near 1. Entire computation is one [n,d]·[d,n] matmul plus O(n²)
    elementwise — MXU-friendly, no host. Note the logit transform
    saturates unless sybils are near-duplicates; the protocol's accept
    decision therefore uses foolsgold_accept_mask, not these weights.
    """
    cs = _cosine_matrix(updates)
    v = jnp.max(cs, axis=1)  # max similarity per client
    # pardoning: honest clients that happen to resemble a sybil are
    # re-scaled by v_i/v_j when the sybil's own max is larger
    ratio = v[:, None] / jnp.where(v[None, :] > 0, v[None, :], 1.0)
    cs = jnp.where((v[None, :] > v[:, None]) & (v[None, :] > 0),
                   cs * ratio, cs)
    alpha = 1.0 - jnp.max(cs, axis=1)
    alpha = jnp.clip(alpha, 0.0, 1.0)
    alpha = alpha / jnp.maximum(jnp.max(alpha), 1e-12)
    # logit sharpening, clipped to [0, 1] (paper's confidence transform)
    a = jnp.clip(alpha, 1e-5, 1.0 - 1e-5)
    alpha = jnp.clip(jnp.log(a / (1.0 - a)) + 0.5, 0.0, 1.0)
    return alpha


@jax.jit
def max_mutual_cosine(updates: jax.Array) -> jax.Array:
    """v_i = max_{j≠i} cos(update_i, update_j) — the sybil statistic:
    members of a coordinated poisoner cluster have a fellow member as
    their nearest direction, honest non-IID clients do not."""
    return jnp.max(_cosine_matrix(updates), axis=1)


@partial(jax.jit, static_argnames=("min_cluster",))
def foolsgold_accept_mask(updates: jax.Array,
                          min_cluster: int = 3) -> jax.Array:
    """Binary accept mask: reject clients whose max mutual cosine is a
    robust (median + 3·MAD) upper outlier of the round's v-distribution
    AND who sit in a mutually-similar cluster of >= `min_cluster`.

    Deviation from the paper, on purpose: FoolsGold's logit-clipped
    weights assume near-duplicate sybils (cos → 1) and saturate to 1 for
    every client when the poisoners' mutual similarity is merely
    *moderately* elevated — which is what the reference's attack actually
    produces here (per-peer bad shards drawn around one source-class
    mean + minibatch sampling ⇒ poison-poison cos ≈ 0.3 vs honest ≈ 0.04
    at Dirichlet(0.3)). A self-calibrating outlier test on v separates
    whenever ANY gap exists, needs no absolute threshold, and — unlike
    the soft weights — yields the accept/reject decision the protocol
    needs for additive secure aggregation and block-level stake debits.
    Honest-majority assumption: median(v) tracks the honest level. At
    least half the clients are always kept (MAD floor), so a degenerate
    uniform round rejects no one.

    Small-N fix (PR 16): with pools of ~6 the outlier test alone
    mass-flags honest peers — an honest pair that happens to share a
    minibatch direction lands above the bar and gets stake-starved round
    after round. A sybil attack is by definition a *coordinated cluster*,
    so the rejection additionally requires the flagged client to have at
    least `min_cluster - 1` partners that are themselves flagged and
    mutually similar at the same threshold. `min_cluster=1` restores the
    pre-fix behaviour; the 100-node eval's 30-strong poison cluster is
    far above any sensible setting. Trade-off, documented in
    docs/ADVERSARY.md: sub-`min_cluster` poison cliques (e.g. a pair)
    now pass this kernel — the ENSEMBLE defense's keep-set-calibrated
    similarity veto covers that case without a cluster floor, because
    its bar is anchored on the Krum-kept set rather than the pool
    median."""
    v = max_mutual_cosine(updates)
    med = jnp.median(v)
    mad = jnp.median(jnp.abs(v - med))
    # reject only ABOVE med + max(3·MAD, 0.05): the relative term adapts
    # to the round's spread, the absolute floor keeps clean-round false
    # rejects near zero — on a tight honest v-distribution (tiny MAD) the
    # upper tail of honest clients would otherwise be flagged round after
    # round and stake-starved for cosine noise far below any real sybil
    # signal (poison-poison cos ≈ 0.3 vs honest ≈ 0.04; ADVICE r5)
    thresh = med + jnp.maximum(3.0 * mad, 0.05)
    flagged = v > thresh
    if min_cluster > 1:
        # cluster size = self + flagged partners whose pairwise cosine is
        # commensurate with the pair's own sybil statistic (>= 80% of the
        # larger v). Gating on `thresh` instead would let an honest
        # bystander that merely clears the outlier test inflate a real
        # pair into a "cluster" — coordination means the partners are
        # each other's similarity signal, not just any two outliers.
        cs = _cosine_matrix(updates)
        vmax = jnp.maximum(v[:, None], v[None, :])
        partners = (cs >= 0.8 * vmax) & flagged[None, :] & flagged[:, None]
        csize = jnp.sum(partners, axis=1) + 1
        flagged = flagged & (csize >= min_cluster)
    return ~flagged
