"""RONI (Reject On Negative Influence) validation as a batched XLA kernel.

The reference scores one update at a time through the Python bridge:
score = err(w + δ) − err(w) on the verifier's local data, rejecting when
score > 0.02 (ref: ML/Pytorch/client_obj.py:100-112, threshold check
DistSys/main.go:203-231). Here the whole round's updates are scored in one
vmapped evaluation — n model evaluations batched into one XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from biscotti_tpu.models.base import Model

RONI_THRESHOLD = 0.02  # ref: DistSys/main.go:203-231


def roni_scores(model: Model, flat_w: jax.Array, deltas: jax.Array,
                x_val: jax.Array, y_val: jax.Array) -> jax.Array:
    """scores[i] = err(w + δ_i) − err(w) on the validation split."""
    base = model.error_flat(flat_w, x_val, y_val)
    per = jax.vmap(lambda d: model.error_flat(flat_w + d, x_val, y_val))(deltas)
    return per - base


def roni_accept_mask(model: Model, flat_w: jax.Array, deltas: jax.Array,
                     x_val: jax.Array, y_val: jax.Array,
                     threshold: float = RONI_THRESHOLD) -> jax.Array:
    """accept iff the update does not worsen validation error by more than
    the threshold (ref: main.go:203-231)."""
    return roni_scores(model, flat_w, deltas, x_val, y_val) <= threshold


def make_roni_kernel(model: Model, threshold: float = RONI_THRESHOLD):
    """Build a jitted (flat_w, deltas[n,d], x_val, y_val) -> mask[n] kernel."""

    @jax.jit
    def kernel(flat_w, deltas, x_val, y_val):
        return roni_accept_mask(model, flat_w, deltas, x_val, y_val, threshold)

    return kernel
