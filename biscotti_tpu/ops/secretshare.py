"""Shamir-style share math for secure aggregation — batched XLA tensor ops.

The reference generates each peer's shares with per-chunk scalar loops on the
CPU (ref: DistSys/kyber.go:456-482 generateMinerSecretShares,
kyber.go:579-646 createShareAndWitness, kyber.go:712-743 makePolynomialMap)
and recovers the aggregate with a gonum QR least-squares solve
(ref: kyber.go:809-867 recoverSecret/Vandermonde). Here the whole pipeline is
three tensor programs:

    shares   = V @ coeffsᵀ        one [S,k]·[k,C] matmul for ALL chunks
    agg      = Σ_peers shares     one sum (psum across miner shards)
    coeffs'  = lstsq(V, agg)      one batched least-squares

Semantics kept from the reference:
  * quantization: int(x · 10^PRECISION), truncated toward zero
    (ref: kyber.go:698-710; PRECISION=4, main.go:45)
  * polynomial chunking: POLY_SIZE=10 coefficients per chunk, last chunk
    zero-padded (ref: kyber.go:712-743; main.go:46)
  * share points: x_i = i − SHARE_OFFSET for share index i
    (ref: kyber.go:589 `minerSecretX := int64(minerPubKey - 10)`)
  * integer polynomial evaluation — *exact* here via int64 Horner/matmul,
    where the reference evaluates each term in float64 and truncates
    (kyber.go:599-602), accumulating avoidable rounding error
  * recovery: float64 Vandermonde least-squares, rounded back to int
    (ref: kyber.go:809-867)
  * per-miner striding: miner m holds share rows [m·S/M, (m+1)·S/M)
    (ref: kyber.go:205-242 extractMinerSecret)

Device placement: the single-host share pipeline runs as **plain numpy on
the host CPU** (exact native int64); the mesh-sharded variant
(`make_sharded_share_fns`) is jitted shard_map XLA and requires x64 mode.
TPUs have no native int64 datapath — XLA's x64 rewriter cannot split an
`s64 dot_general` (observed: a jitted make_shares fails AOT compilation on
v5e with "X64 rewriting not implemented" for the share matmul), and the
values here genuinely need 64 exact integer bits (share values reach ~10¹³
for degree-9 chunks at PRECISION=4). This is a deliberate design decision,
not a fallback-by-accident: share algebra is control-plane crypto that
rides next to the (host-side) EC commitments, its cost is O(S·d) integer
ops — trivial against the O(d) curve MSM on the same path — and keeping it
in numpy avoids both emulated-int64 stalls on the TPU program AND jit
dispatch overhead on the host (a CPU-jitted callback paid ~600× the
matmul's cost in per-call dispatch). The float ML path never touches this
module.
"""

from __future__ import annotations

import hashlib
import math

import jax
import jax.numpy as jnp
import numpy as np

PRECISION = 4  # ref: main.go:45
POLY_SIZE = 10  # ref: main.go:46
SHARE_OFFSET = 10  # ref: kyber.go:589


def _require_x64(what: str) -> None:
    """Fail loudly instead of silently wrapping in int32: without x64 mode
    jnp int64 arrays degrade to int32 and share values (~10¹³) overflow."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"{what} requires JAX x64 mode: call "
            "jax.config.update('jax_enable_x64', True) (or set "
            "JAX_ENABLE_X64=1) before any share math")


def total_shares_for(num_miners: int, poly_size: int = POLY_SIZE) -> int:
    """TOTAL_SHARES = ceil(2·POLY_SIZE/NUM_MINERS)·NUM_MINERS
    (ref: main.go:825)."""
    return math.ceil(2 * poly_size / num_miners) * num_miners


def quantize(delta: jax.Array, precision: int = PRECISION) -> jax.Array:
    """float → int64 at 10^precision, truncated toward zero like Go's
    int64() conversion (ref: kyber.go:698-710)."""
    scaled = delta.astype(jnp.float64) * (10.0 ** precision)
    return jnp.trunc(scaled).astype(jnp.int64)


def dequantize(q: jax.Array, precision: int = PRECISION) -> jax.Array:
    return q.astype(jnp.float64) / (10.0 ** precision)


def num_chunks(num_params: int, poly_size: int = POLY_SIZE) -> int:
    return -(-num_params // poly_size)


def to_chunks(q: jax.Array, poly_size: int = POLY_SIZE,
              chunk_multiple: int = 1) -> jax.Array:
    """[d] int64 → [C, k] coefficient rows, zero-padded last chunk
    (ref: kyber.go:712-743). `chunk_multiple` additionally pads the CHUNK
    axis up to a multiple — the standard static-shape practice for
    sharding C over a mesh axis (make_sharded_share_fns requires mesh-size
    divisibility); zero chunks share/recover as zeros and from_chunks
    drops them."""
    d = q.shape[0]
    c = num_chunks(d, poly_size)
    if chunk_multiple > 1:
        c = -(-c // chunk_multiple) * chunk_multiple
    padded = jnp.zeros((c * poly_size,), q.dtype).at[:d].set(q)
    return padded.reshape(c, poly_size)


def from_chunks(coeffs: jax.Array, num_params: int) -> jax.Array:
    return coeffs.reshape(-1)[:num_params]


def share_xs(total_shares: int, offset: int = SHARE_OFFSET) -> jax.Array:
    """x_i = i − offset; note x = 0 occurs at i = offset, exactly as in the
    reference (kyber.go:589)."""
    return jnp.arange(total_shares, dtype=jnp.int64) - offset


def vandermonde(xs: jax.Array, poly_size: int = POLY_SIZE) -> jax.Array:
    """V[s, j] = xs[s]^j, int64 exact (|x| ≤ S, j < k → well inside int64)."""
    powers = jnp.arange(poly_size, dtype=jnp.int64)
    return xs[:, None] ** powers[None, :]


def _vandermonde_np(xs: np.ndarray, poly_size: int) -> np.ndarray:
    """numpy twin of vandermonde() for the host path — shared by share
    generation and recovery so the two matrices cannot drift apart."""
    xsn = np.asarray(xs, dtype=np.int64)
    return xsn[:, None] ** np.arange(poly_size, dtype=np.int64)[None, :]


# ---- shared kernel bodies for the chunk-sharded shard_map wrappers below;
# the numpy host-path functions implement the identical math (pinned
# against each other by test_sharded_chunk_axis_matches_unsharded)


def _shares_kernel(coeffs: jax.Array, v: jax.Array) -> jax.Array:
    """[C, k] coefficients × [S, k] Vandermonde → [S, C] shares."""
    return v @ coeffs.T


def _agg_kernel(peer_shares: jax.Array) -> jax.Array:
    return jnp.sum(peer_shares, axis=0)


def _recover_kernel(agg: jax.Array, vv: jax.Array) -> jax.Array:
    """float64 least-squares per chunk, rounded back to int64."""
    sol, _, _, _ = jnp.linalg.lstsq(vv, agg.astype(jnp.float64))
    return jnp.round(sol.T).astype(jnp.int64)


def make_shares(q: jax.Array, poly_size: int = POLY_SIZE,
                total_shares: int = 2 * POLY_SIZE) -> np.ndarray:
    """[d] quantized update → [S, C] share matrix: share s of chunk c is the
    exact integer evaluation of chunk-polynomial c at x_s. Runs as plain
    numpy on the host (see module docstring: TPUs have no exact-int64
    matmul, and a jitted CPU callback pays ~600× the matmul's cost in
    per-call dispatch — measured 0.11 s dispatch vs 0.2 ms math at mnist
    shape; the mesh-sharded variant below keeps the XLA path)."""
    q = np.asarray(q)
    if q.dtype != np.int64:
        raise TypeError(f"make_shares wants int64 quantized input, got {q.dtype}")
    # NOTE for callers re-entering jax with this result: share values reach
    # ~10¹³, so without jax_enable_x64 a jnp conversion silently truncates
    # to int32 garbage — keep the result in numpy, or enable x64 first.
    d = q.shape[0]
    c = num_chunks(d, poly_size)
    padded = np.zeros(c * poly_size, np.int64)
    padded[:d] = q
    coeffs = padded.reshape(c, poly_size)  # [C, k]
    xs = np.arange(total_shares, dtype=np.int64) - SHARE_OFFSET
    v = _vandermonde_np(xs, poly_size)  # [S, k]
    return v @ coeffs.T  # [S, C], exact int64


def miner_rows(total_shares: int, miner_idx: int, num_miners: int) -> slice:
    """Miner m's contiguous share-row range (ref: kyber.go:205-242)."""
    per = total_shares // num_miners
    return slice(miner_idx * per, (miner_idx + 1) * per)


def aggregate_shares(peer_shares: jax.Array) -> np.ndarray:
    """Homomorphic aggregation: [P, S, C] → [S, C]. Works identically on a
    miner's slice [P, S/M, C] (ref: kyber.go:244-287 aggregateSecret).
    Plain numpy with the rest of the host int64 share pipeline."""
    return np.sum(np.asarray(peer_shares), axis=0)


# Memoized Lagrange-basis (Vandermonde pseudoinverse) per share-point
# set: the live runtime rebuilds `xs` and re-factorizes the SAME [S, k]
# Vandermonde every round (peer.py recovery + blind-row evaluation use a
# fixed committee-row layout for the whole run), so recovery collapses to
# one cached [k, S] @ [S, C] matmul — interpolation vectorized across
# every chunk of every contributor at once. Tiny (k ≤ ~10, S ≤ ~2k) and
# bounded: distinct layouts per process are the distinct (miner count,
# redundancy) configs, a handful.
_pinv_cache: dict = {}
_PINV_CACHE_MAX = 32


def _vandermonde_pinv(xs_key: tuple, poly_size: int) -> np.ndarray:
    key = (xs_key, poly_size)
    pinv = _pinv_cache.get(key)
    if pinv is None:
        if len(_pinv_cache) >= _PINV_CACHE_MAX:
            _pinv_cache.clear()
        vv = _vandermonde_np(np.asarray(xs_key, np.int64),
                             poly_size).astype(np.float64)
        pinv = np.linalg.pinv(vv)  # [k, S]
        _pinv_cache[key] = pinv
    return pinv


def _device_kernels():
    """The armed accelerator crypto plane (crypto/kernels) or None —
    recovery's device seam (--device-crypto, docs/CRYPTO_KERNELS.md)."""
    try:
        from biscotti_tpu.crypto import kernels

        return kernels.active_module()
    except ImportError:
        return None


def recover_coeffs(agg_shares: jax.Array, xs: jax.Array,
                   poly_size: int = POLY_SIZE) -> np.ndarray:
    """[S, C] aggregated shares (+ their x points) → [C, k] int64 chunk
    coefficients via float64 least-squares, rounded (ref: kyber.go:809-867 —
    the reference also recovers approximately, via mat64 QR). Plain numpy
    with the rest of the host int64 share pipeline; the least-squares
    solve rides the memoized Vandermonde pseudoinverse (same minimum-norm
    solution lstsq produces for this full-column-rank system — distinct
    share points keep the Vandermonde full rank).

    --device-crypto moves the [k, S] @ [S, C] interpolation matmul onto
    the accelerator (kernels.shamir_recover), vectorized across every
    chunk at once; the pseudoinverse itself stays the SAME memoized host
    factorization, so both backends solve the identical system. Honest
    share sums sit ≥ 10¹⁰ ulp from the rounding boundary; for
    adversarially boundary-crafted shares this is the crypto plane's one
    FLOAT seam, covered by the backend-homogeneity deployment constraint
    (all miners of a cluster share a crypto backend — the krum_pallas
    precedent; docs/CRYPTO_KERNELS.md §oracle-parity)."""
    agg = np.asarray(agg_shares)
    xs_key = tuple(int(x) for x in np.asarray(xs).reshape(-1))
    pinv = _vandermonde_pinv(xs_key, poly_size)
    dev = _device_kernels()
    if dev is not None:
        try:
            return dev.shamir_recover(pinv, agg)
        except Exception:
            pass  # exact host matmul below
    sol = pinv @ agg.astype(np.float64)  # [k, C]
    return np.round(sol.T).astype(np.int64)  # [C, k]


def recover_update(agg_shares: jax.Array, xs: jax.Array, num_params: int,
                   poly_size: int = POLY_SIZE,
                   precision: int = PRECISION) -> np.ndarray:
    """Full miner-side recovery: aggregated shares → float aggregate update
    (ref: honest.go:442-502 recoverAggregateUpdates)."""
    coeffs = recover_coeffs(agg_shares, xs, poly_size)
    flat = from_chunks(coeffs, num_params)  # numpy in → numpy out
    return np.asarray(flat).astype(np.float64) / (10.0 ** precision)


# ------------------------------------------------- proactive resharing
#
# Dynamic membership (docs/MEMBERSHIP.md): when committee-relevant
# membership changes mid-epoch, surviving share-holders RE-DEAL their
# slices without any dealer — each holder sub-shares every held row as a
# fresh Shamir instance whose constant term is the row value, and
# recipients interpolate fresh shares of the same secret (two-level /
# share-of-shares resharing). Recovery across the epoch needs only the
# re-dealt material: ≥ poly_size surviving OLD rows, each re-dealt over
# ≥ poly_size NEW points. Pedersen consistency is preserved exactly —
# the sub-deal's constant-coefficient commitment must equal the
# homomorphic evaluation of the ORIGINAL coefficient commitments at the
# holder's old share point (crypto/commitments.commitment_eval_xy), so a
# holder cannot re-deal a lie about its own row.
#
# Exactness bound, same contract as the rest of this module: sub-share
# values are exact int64 and float64-recoverable, which caps the masking
# coefficients at RESHARE_COEF_BOUND (|g(x)| ≤ |row| + k·bound·|x|^(k-1)
# must stay well under 2^53). Hiding of a re-dealt row in transit is
# therefore statistical-bounded, not perfect — categorically the same
# trade the integer share pipeline itself makes (its share at x=0 IS a
# raw coefficient); the BINDING side, which soundness rests on, is the
# full-strength Pedersen check.

RESHARE_COEF_BOUND = 1 << 22


def reshare_coeffs(rows: np.ndarray, poly_size: int, seed: bytes,
                   context: bytes) -> np.ndarray:
    """Sub-share polynomial coefficients for every held row: [R, C] int64
    row values → [R, C, k] int64 where [..., 0] is the row value and
    higher coefficients are deterministic bounded-uniform masks drawn
    from SHAKE-256(seed, context) — same seed + context ⇒ the identical
    deal, so a resharing round is replayable like everything else."""
    rows = np.asarray(rows, np.int64)
    r, c = rows.shape
    k = int(poly_size)
    out = np.zeros((r, c, k), np.int64)
    out[:, :, 0] = rows
    if k > 1:
        n = r * c * (k - 1)
        raw = hashlib.shake_256(
            seed + b"biscotti-reshare" + context).digest(8 * n)
        mask = np.frombuffer(raw, dtype="<u8").astype(np.int64)
        mask = np.abs(mask) % (2 * RESHARE_COEF_BOUND + 1)
        out[:, :, 1:] = (mask - RESHARE_COEF_BOUND).reshape(r, c, k - 1)
    return out


def reshare_subshares(coeffs: np.ndarray, xs_new) -> np.ndarray:
    """Evaluate every sub-share polynomial at the new share points:
    [R, C, k] coefficients × [S'] points → [S', R, C] exact int64
    (sub[s, r, c] = g_{r,c}(x'_s)). One einsum over the Vandermonde —
    the share-generation matmul, batched across held rows."""
    coeffs = np.asarray(coeffs, np.int64)
    k = coeffs.shape[2]
    v = _vandermonde_np(np.asarray(xs_new, np.int64), k)  # [S', k]
    return np.einsum("sk,rck->src", v, coeffs)


# Exact rational Vandermonde inverse, memoized per point set: the
# masking coefficients push sub-share magnitudes past float64's exact-
# integer range (2⁵³), so — unlike first-level recovery, whose values the
# protocol keeps small — interpolation runs in EXACT python-int
# arithmetic: inv(V) scaled to a common denominator D, one object-dtype
# matmul, and a divisibility-checked //D at the end. Recovering the FULL
# coefficient vector (not just the constant term) is what makes the
# integrality check a corruption detector: an honest deal has int64
# coefficients, while any single perturbed evaluation shifts the
# interpolant by a Lagrange basis polynomial whose leading coefficient
# 1/Π(x_j − x_m) cannot be ±1 over ≥ 3 distinct integer points — some
# recovered coefficient goes non-integer and the deal is refused loudly.
_vinv_cache: dict = {}


def _vandermonde_inv_scaled(xs_key: tuple) -> tuple:
    """(integer matrix M [k,k], common denominator D) with
    inv(vandermonde(xs)) = M / D; row 0 of M/D is the Lagrange-at-zero
    weight vector."""
    got = _vinv_cache.get(xs_key)
    if got is None:
        from fractions import Fraction
        from math import lcm

        k = len(xs_key)
        # Gauss-Jordan over exact rationals on [V | I]
        aug = [[Fraction(int(x) ** p) for p in range(k)] +
               [Fraction(int(i == j)) for j in range(k)]
               for i, x in enumerate(xs_key)]
        for col in range(k):
            piv = next(i for i in range(col, k) if aug[i][col])
            aug[col], aug[piv] = aug[piv], aug[col]
            pv = aug[col][col]
            aug[col] = [v / pv for v in aug[col]]
            for i in range(k):
                if i != col and aug[i][col]:
                    f = aug[i][col]
                    aug[i] = [a - f * b for a, b in zip(aug[i], aug[col])]
        # right half now holds inv(V): inv(V)[p][j] = coefficient p of
        # the Lagrange basis polynomial L_j
        inv = [row[k:] for row in aug]
        d = lcm(*(f.denominator for row in inv for f in row))
        m = tuple(tuple(int(f * d) for f in row) for row in inv)
        if len(_vinv_cache) >= 64:
            _vinv_cache.clear()
        _vinv_cache[xs_key] = got = (m, d)
    return got


def reshare_recover_rows(sub: np.ndarray, xs_new,
                         poly_size: int = POLY_SIZE) -> np.ndarray:
    """Interpolate every sub-share polynomial's constant term back out:
    [S', R, C] sub-shares over S' ≥ poly_size distinct points → [R, C]
    original row values, EXACT (rational interpolation over the first
    poly_size points — each point's integrity is separately proven by
    the sub-deal's VSS check, so recovery may use any k of them; the
    full recovered coefficient vector must additionally be integral,
    which refuses any singly-corrupted evaluation set loudly). This
    is what a coordinator — or any ≥ poly_size of the NEW holders
    pooling their rows — computes to reconstruct the re-dealt secret."""
    sub = np.asarray(sub, np.int64)
    s = sub.shape[0]
    if s < poly_size:
        raise ValueError(
            f"{s} sub-share points cannot determine a degree-"
            f"{poly_size - 1} sub-polynomial: resharing recovery needs "
            f">= {poly_size} new holders")
    xs = [int(x) for x in np.asarray(xs_new).reshape(-1)]
    m, den = _vandermonde_inv_scaled(tuple(xs[:poly_size]))
    r, c = sub.shape[1], sub.shape[2]
    flat = sub[:poly_size].reshape(poly_size, r * c).astype(object)
    coef = np.array(m, dtype=object) @ flat  # [k, r*c], scaled by den
    if any(int(v) % den for v in coef.reshape(-1)):
        raise ValueError("sub-shares are not evaluations of one integer "
                         "polynomial (corrupt or mismatched deal)")
    out = np.array([int(v) // den for v in coef[0]], dtype=np.int64)
    return out.reshape(r, c)


# ----------------------------------------------------- chunk-axis sharding
#
# SURVEY §5.7: the reference scales model dim d only through its O(d)
# commitment cost — its honest analogue of sequence sharding is the
# polynomial CHUNK axis of the secret-sharing tensors. The chunk axis is
# embarrassingly parallel (every chunk's polynomial is independent: share
# generation, aggregation, and per-chunk least-squares recovery touch no
# other chunk), so sharding it over a mesh needs NO collectives until the
# final from_chunks reshape — large-d models split their share tensors
# across devices and each device runs the identical small program.


def make_sharded_share_fns(mesh, axis: str = "chunks",
                           poly_size: int = POLY_SIZE,
                           total_shares: int = 2 * POLY_SIZE):
    """shard_map share pipeline over the chunk axis. Returns
    (make_shares_sh, aggregate_sh, recover_coeffs_sh):

        make_shares_sh(coeffs [C,k] int64)        -> [S, C] shares
        aggregate_sh(peer_shares [P,S,C])         -> [S, C]
        recover_coeffs_sh(agg [S,C], xs [S])      -> [C, k]

    C must divide over the mesh axis size. Runs wherever the mesh lives —
    the 8-device virtual CPU mesh in tests; on TPU pods this axis rides
    hosts (int64 — see module docstring on device placement)."""
    from biscotti_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    _require_x64("make_sharded_share_fns")
    v = vandermonde(share_xs(total_shares), poly_size)  # [S, k], replicated

    def _make(coeffs):  # [C_loc, k] -> [S, C_loc]
        return _shares_kernel(coeffs, v)

    def _agg(peer_shares):  # [P, S, C_loc] -> [S, C_loc]
        return _agg_kernel(peer_shares)

    def _recover(agg, xs):  # [S, C_loc] -> [C_loc, k]
        return _recover_kernel(agg, vandermonde(xs, poly_size)
                               .astype(jnp.float64))

    make_sh = jax.jit(shard_map(
        _make, mesh=mesh, in_specs=(P(axis, None),),
        out_specs=P(None, axis), check_vma=False))
    agg_sh = jax.jit(shard_map(
        _agg, mesh=mesh, in_specs=(P(None, None, axis),),
        out_specs=P(None, axis), check_vma=False))
    recover_sh = jax.jit(shard_map(
        _recover, mesh=mesh, in_specs=(P(None, axis), P()),
        out_specs=P(axis, None), check_vma=False))
    return make_sh, agg_sh, recover_sh
