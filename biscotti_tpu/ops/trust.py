"""Adaptive defense plane: cross-round trust ledger + ensemble verdicts.

Every shipped single-round defense (Krum, MultiKrum, FoolsGold, RONI) is
memoryless: it sees one pool of deltas and must decide from geometry
alone. PR 14's attack matrix showed what that costs — the
threshold-hugging poisoner walks its poison scale up to just under the
accept boundary and defeats both KRUM (0.228 → 0.425 final error) and
MULTIKRUM (0.443 → 0.710). But the protocol owns something the attacker
cannot rewrite: the committed chain. Which identities landed accepted
records, in which rounds, at what step magnitude — that history is
signed, replicated, and identical on every honest peer. This module
turns it into a defense.

Three scorers, one ledger:

* **Cross-round consistency (drift)** — per peer, the verifier keeps a
  short series of observed log-residuals (distance from the round's
  Krum-kept centroid) and correlates its increments with the peer's
  chain-derived accept/reject walk. A hugger's scale controller moves
  *with* its verdicts (up on accept, down on reject) — that coupling is
  the signature; honest minibatch noise is uncorrelated with verdicts.
* **Ensemble verdict with hysteresis** — per round, four near-zero
  false-positive vetoes are unioned: Krum-geometry outlier (score far
  above the kept set's worst), FoolsGold pairwise similarity (max mutual
  cosine above a bar calibrated on the kept set's own pairs), magnitude
  band (norm above a multiple of the pool median — one-sided, because an
  update's influence is proportional to its norm: boosting is the
  dangerous direction, while a scaled-down probe carries proportionally
  little poison), and the drift flag. Any veto arms a
  hold-down counter so a flagged peer cannot flap back in the moment one
  scorer loses sight of it (e.g. its only cluster partner is on the
  committee this round). The two one-shot vetoes (geometry, magnitude)
  are additionally gated on chain history: an identity with a recent
  majority-accepted walk is *proven* and exempt — non-IID honest shards
  converge at wildly different rates, so single-round geometry misfires
  on veterans, while attacker identities can never become proven
  (rejection leaves no chain record to graduate on).
* **Stake-weighted slow-trust** — a fresh or recycled identity carries
  reduced weight until it accrues `ramp_rounds` accepted on-chain
  records. Weight gates admission through a duty-cycle credit
  accumulator (an update either aggregates fully or not at all — under
  secure aggregation the miner only ever sees the Shamir *sum*, so a
  fractional multiplier is not implementable verifier-side), and an
  eligible identity that goes absent for `absence_reset` consecutive
  real blocks restarts its ramp — the sybil campaign's churn-recycled
  identities never graduate.

Calibration is self-referential, not absolute: every bar is derived from
the current round's Krum-kept set (minus peers currently flagged or
held), so the same defaults work on near-duplicate creditcard gradients
(honest cos ≈ 0.9) and non-IID Dirichlet MNIST shards (honest cos ≈
0.04) without per-dataset knobs.

Determinism contract: the ledger is a pure function of (plan, the block
sequence fed to ``sync_block``, the decision sequence fed to
``decide``). No wall clock, no RNG, float math in plain python — two
verifiers fed the same chain and the same pools produce bit-identical
snapshots on any transport layout (TCP vs hive-loopback).

Stdlib-only at module level, like ``runtime/adversary.py``: the config
layer imports :class:`TrustPlan` for CLI plumbing, so importing this
module must not drag in jax/numpy.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

TRUST_METRIC = "biscotti_trust_score"
TRUST_HELP = ("per-peer trust score on this verifier's ledger: slow-trust "
              "weight x (1 - drift score), 0 while flagged/held")
#: slow-trust duty-cycle credit ceiling: a long eligible-absent streak
#: banks at most this many future passes, so a throttled identity cannot
#: stockpile unbounded catch-up acceptance (credit is chain-derived —
#: see TrustLedger.sync_block — and this cap keeps it bounded state)
CREDIT_CAP = 2.0

VOTES_METRIC = "biscotti_defense_votes_total"
VOTES_HELP = ("ensemble defense votes by scorer (geometry/similarity/"
              "magnitude/drift/slow_trust/hold reject votes, plus the "
              "composed ensemble verdict per peer-round)")

#: scorer names, in vote order (also the `scorer=` label values)
SCORERS = ("geometry", "similarity", "magnitude", "drift", "slow_trust",
           "hold")


@dataclass(frozen=True)
class TrustPlan:
    """Knobs for the ensemble defense (``--defense ENSEMBLE``).

    Defaults are tuned at the attack-matrix operating point (10 nodes,
    3 verifiers, Dirichlet-0.3 MNIST, 30% poisoners) and validated by
    the clean-run zero-false-reject criterion; see docs/DEFENSES.md for
    the knob table and the threat model each scorer answers.
    """

    # -- ensemble vote calibration (anchored on the Krum-kept set) -----
    geo_ratio: float = 2.5     # Krum score > ratio x worst kept score
    sim_margin: float = 0.15   # cosine bar = kept-pair median + margin
    sim_mad_mult: float = 6.0  # ... or + mult x kept-pair MAD if larger
    sim_min_pairs: int = 3     # anchor pairs needed before the bar is
    #                            trusted (1 pair = an unusable sample)
    mag_band: float = 2.5      # norm > band x pool-median norm. One-
    #                            sided and pool-anchored: an update's
    #                            influence is proportional to its norm,
    #                            so only the boosted direction is
    #                            dangerous, and the pool median survives
    #                            Krum capturing an accidental tiny-norm
    #                            cluster as its kept set (honest non-IID
    #                            shards converge at different rates)
    # -- chain-history gate on the one-shot vetoes ---------------------
    proven_accepts: int = 1    # accepted records in the recent walk that
    #                            exempt a peer from geometry/magnitude
    #                            (0 = never exempt). Non-IID honest norms
    #                            go bimodal as shards converge, so the
    #                            one-shot vetoes are scoped to identities
    #                            with no earned chain history — exactly
    #                            the set every campaign's attackers live
    #                            in, since rejection leaves no record.
    proven_window: int = 8     # walk entries the gate looks back over
    # -- temporal-drift scorer -----------------------------------------
    drift_window: int = 16     # observations kept per peer
    drift_min_obs: int = 4     # pairs needed before the score can form
    drift_hi: float = 0.6      # Schmitt trigger: flag at/above
    drift_lo: float = 0.3      # ... unflag at/below
    drift_slope: float = 0.3   # constant-verdict ramp: |mean dlog| bar
    drift_range: float = 0.35  # log-residual span needed in the window
    # -- hysteresis ----------------------------------------------------
    hold_rounds: int = 3       # rounds a veto keeps rejecting after it
    # -- stake-weighted slow-trust ramp --------------------------------
    ramp_rounds: int = 4       # accepted blocks to graduate (0 = off)
    ramp_floor: float = 0.4    # weight of a zero-history identity
    absence_reset: int = 3     # consecutive eligible-absent rounds that
    #                            restart an identity's ramp
    # -- bounded evidence ----------------------------------------------
    stream_cap: int = 256      # verdict-stream entries kept per verifier

    def validate(self) -> None:
        if self.geo_ratio <= 1.0:
            raise ValueError("trust: geo_ratio must be > 1 (it multiplies "
                             "the worst KEPT Krum score)")
        if not 0.0 < self.sim_margin < 1.0:
            raise ValueError("trust: sim_margin must be in (0, 1)")
        if self.sim_mad_mult < 0.0:
            raise ValueError("trust: sim_mad_mult must be >= 0")
        if self.sim_min_pairs < 1:
            raise ValueError("trust: sim_min_pairs must be >= 1")
        if self.mag_band <= 1.0:
            raise ValueError("trust: mag_band must be > 1 (a multiplicative "
                             "norm band)")
        if self.proven_accepts < 0:
            raise ValueError("trust: proven_accepts must be >= 0")
        if self.proven_window < 1:
            raise ValueError("trust: proven_window must be >= 1")
        if self.drift_window < 2 or self.drift_min_obs < 2:
            raise ValueError("trust: drift_window and drift_min_obs must "
                             "be >= 2 (the scorer works on increments)")
        if not 0.0 <= self.drift_lo < self.drift_hi <= 1.0:
            raise ValueError("trust: need 0 <= drift_lo < drift_hi <= 1 "
                             "(Schmitt trigger would flap or never fire)")
        if self.drift_slope <= 0.0 or self.drift_range <= 0.0:
            raise ValueError("trust: drift_slope and drift_range must be "
                             "positive")
        if self.hold_rounds < 0:
            raise ValueError("trust: hold_rounds must be >= 0")
        if self.ramp_rounds < 0:
            raise ValueError("trust: ramp_rounds must be >= 0")
        if not 0.0 < self.ramp_floor <= 1.0:
            raise ValueError("trust: ramp_floor must be in (0, 1] — 0 "
                             "would permanently mute a fresh identity")
        if self.absence_reset < 1:
            raise ValueError("trust: absence_reset must be >= 1")
        if self.stream_cap < 1:
            raise ValueError("trust: stream_cap must be >= 1")


def pearson(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Plain-python Pearson correlation; 0.0 when either side is
    constant (the callers handle the constant regimes explicitly)."""
    n = len(xs)
    if n < 2 or n != len(ys):
        return 0.0
    mx = sum(xs) / n
    my = sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in xs)
    syy = sum((y - my) ** 2 for y in ys)
    if sxx <= 0.0 or syy <= 0.0:
        return 0.0
    sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    return sxy / math.sqrt(sxx * syy)


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


@dataclass
class _PeerState:
    """Everything the ledger tracks for one identity."""

    #: chain-derived accept walk: iteration -> accepted. An *eligible*
    #: identity absent from a real block records False — a verifier
    #: rejection leaves no record at all (the worker declines), so
    #: absence-while-eligible IS the reject signal, the same inference
    #: the hug campaign itself runs on (`_campaign_observe`).
    walk: Dict[int, bool] = field(default_factory=dict)
    #: (iteration, log residual) series from this verifier's own pools
    obs: List[Tuple[int, float]] = field(default_factory=list)
    absent_run: int = 0
    ramp: Optional[int] = None   # accepted-since-reset; None = graduated
    resets: int = 0
    #: slow-trust duty-cycle accumulator — CHAIN-derived (accrued and
    #: consumed in sync_block, only read at decide time), so verifiers
    #: that folded the same blocks agree on it regardless of which
    #: rounds each happened to decide
    credit: float = 0.0
    flagged: bool = False        # drift Schmitt state
    drift_score: float = 0.0
    hold: int = 0                # hysteresis hold-down counter


class TrustLedger:
    """Per-verifier adaptive-defense state: chain walk + drift series +
    slow-trust ramps + the ensemble decision procedure."""

    def __init__(self, plan: TrustPlan, num_nodes: int):
        plan.validate()
        if num_nodes < 1:
            raise ValueError("trust: num_nodes must be >= 1")
        self.plan = plan
        self.num_nodes = num_nodes
        self.synced_it = -1
        self.decisions = 0
        self._peers: Dict[int, _PeerState] = {}
        self._votes: Dict[str, int] = {}

    def _peer(self, pid: int) -> _PeerState:
        st = self._peers.get(pid)
        if st is None:
            st = self._peers[pid] = _PeerState()
        return st

    # ------------------------------------------------------- chain walk

    def sync_block(self, iteration: int, records: Dict[int, bool],
                   committee: Optional[Set[int]]) -> None:
        """Fold one settled block into the ledger.

        ``records`` maps source_id -> accepted flag for the block's delta
        records; ``committee`` is the round's verifier+miner set (those
        identities do not submit, so their absence carries no signal) or
        None when the electorate cannot be re-derived (pruned prev
        block). Empty/fallback blocks carry no information and are
        skipped entirely. Idempotent per iteration; out-of-order blocks
        are ignored so the walk stays append-only and replayable."""
        if iteration <= self.synced_it:
            return
        self.synced_it = iteration
        if not records:
            return
        ramp_on = self.plan.ramp_rounds > 0
        for pid in range(self.num_nodes):
            if pid in records:
                st = self._peer(pid)
                st.absent_run = 0
                st.walk[iteration] = records[pid]
                if ramp_on and st.ramp is not None:
                    if records[pid]:
                        # slow-trust credit is CHAIN-derived (not
                        # decide()-local): an accepted record is the
                        # chain's own evidence that the duty-cycle gate
                        # passed this round — consume the pass, advance
                        # the ramp, then accrue the new weight. Every
                        # verifier folding the same blocks holds the
                        # same credit, so churned/rotated committees
                        # issue UNANIMOUS slow_trust verdicts.
                        st.credit = max(0.0, st.credit - 1.0)
                        st.ramp += 1
                        if st.ramp >= self.plan.ramp_rounds:
                            st.ramp = None     # graduated: full weight
                            st.credit = 0.0
                        else:
                            st.credit = min(
                                CREDIT_CAP,
                                st.credit + self.weight(pid))
                    else:
                        st.credit = min(CREDIT_CAP,
                                        st.credit + self.weight(pid))
            elif committee is not None and pid not in committee:
                st = self._peer(pid)
                st.walk[iteration] = False
                st.absent_run += 1
                if ramp_on and st.absent_run == self.plan.absence_reset:
                    # ramp restart: credit restarts at the floor too —
                    # starting from zero would need 1/ramp_floor eligible
                    # absences before the FIRST pass, and at the default
                    # plan that streak re-triggers this very reset: a
                    # fresh identity would starve in a reset loop
                    st.ramp = 0
                    st.credit = self.plan.ramp_floor
                    st.resets += 1
                elif ramp_on and st.ramp is not None:
                    # a throttled (or rejected) eligible round still
                    # banks duty-cycle credit toward the next pass
                    st.credit = min(CREDIT_CAP,
                                    st.credit + self.weight(pid))
            # committee members (or unknown electorate): no signal

    # ------------------------------------------------------- slow-trust

    def weight(self, pid: int) -> float:
        """Aggregation weight in (0, 1]: 1.0 for graduated identities,
        a floor-to-1 ramp over accepted blocks for fresh/reset ones."""
        if self.plan.ramp_rounds <= 0:
            return 1.0
        st = self._peers.get(pid)
        if st is None or st.ramp is None:
            return 1.0
        f = self.plan.ramp_floor
        return f + (1.0 - f) * min(1.0, st.ramp / self.plan.ramp_rounds)

    def seed_fresh(self, pids: Sequence[int]) -> None:
        """Mark identities as ramp-fresh (zero verified history). Called
        for join-round admissions; pre-genesis members are grandfathered
        at full weight so arming the plane mid-deployment cannot starve
        the existing fleet."""
        if self.plan.ramp_rounds <= 0:
            return
        for pid in pids:
            st = self._peer(pid)
            if st.ramp is None and not any(st.walk.values()):
                st.ramp = 0
                st.credit = self.plan.ramp_floor

    def proven(self, pid: int) -> bool:
        """Whether a peer's recent chain walk has earned it out of the
        one-shot geometry/magnitude vetoes: at least ``proven_accepts``
        accepted records at a majority accept rate over the last
        ``proven_window`` walk entries. Attackers cannot reach this
        state — a rejected update leaves no chain record, so a
        consistently-vetoed identity's walk never accrues accepts —
        while honest peers graduate within a couple of rounds, before
        shard convergence makes their norms bimodal and single-round
        geometry unreliable."""
        p = self.plan
        if p.proven_accepts <= 0:
            return False
        st = self._peers.get(pid)
        if st is None or not st.walk:
            return False
        recent = [st.walk[t] for t in sorted(st.walk)[-p.proven_window:]]
        acc = sum(1 for ok in recent if ok)
        return acc >= p.proven_accepts and 2 * acc >= len(recent)

    def committee_clean(self, pid: int) -> bool:
        """Whether a peer's empty walk is fully committee-explained: real
        blocks have settled, yet the peer has no walk entries — every
        absence was committee duty (sync_block only skips committee
        members), so there is no negative evidence either. Such a peer
        earns the same benefit of the doubt as a proven one: an unlucky
        early committee draw must not expose an honest peer to the
        one-shot vetoes once honest norms go bimodal. An attacker can
        ride this at most one round — its first rejection (or eligible
        absence) writes the negative walk entry that ends the exemption."""
        if self.synced_it < 0:
            return False
        st = self._peers.get(pid)
        return st is None or (not st.walk and st.absent_run == 0)

    # ------------------------------------------------------ drift score

    def _drift(self, st: _PeerState) -> float:
        """Correlation between the peer's log-residual increments and its
        chain verdict walk across the same gaps. Returns a score in
        [0, 1]; the Schmitt trigger in :meth:`decide` turns it into the
        flag. Honest peers: increments are minibatch noise, uncorrelated
        with verdicts, and the walk is constant-accept (handled by the
        monotone regime, which additionally demands a sustained slope)."""
        p = self.plan
        obs = st.obs[-p.drift_window:]
        if len(obs) < 2:
            return 0.0
        xs: List[float] = []
        ys: List[float] = []
        for (it1, r1), (it2, r2) in zip(obs, obs[1:]):
            verdicts = [1.0 if ok else -1.0
                        for t, ok in st.walk.items() if it1 <= t < it2]
            if not verdicts:
                continue
            xs.append(sum(verdicts))
            ys.append(r2 - r1)
        if len(xs) < p.drift_min_obs:
            return 0.0
        span = max(r for _, r in obs) - min(r for _, r in obs)
        if span < p.drift_range:
            return 0.0
        if min(xs) < max(xs):
            return max(0.0, pearson(xs, ys))
        # constant-verdict regime: always-accepted (honest, or a hugger
        # the defense has not caught) ramping steadily, or an
        # always-rejected hugger backing its scale off — both move the
        # residual monotonically WITH the verdict sign
        sign = 1.0 if xs[0] > 0 else -1.0
        mean_dy = sum(ys) / len(ys)
        return 1.0 if sign * mean_dy >= p.drift_slope else 0.0

    # --------------------------------------------------------- decision

    def decide(self, iteration: int, ids: Sequence[int],
               norms: Sequence[float], residuals: Sequence[float],
               scores: Sequence[float], keep: Sequence[bool],
               cos: Sequence[Sequence[float]],
               ) -> Tuple[List[bool], List[List[str]], Dict[str, float]]:
        """One ensemble verdict over a verifier pool.

        Inputs are per-pool-index, pool sorted by source id: ``norms``
        delta L2 norms, ``residuals`` distances from the Krum-kept
        centroid, ``scores`` Krum scores, ``keep`` the Krum accept mask,
        ``cos`` the pairwise cosine matrix (diagonal ignored). Returns
        (accept flags, per-peer reject votes, calibration detail)."""
        p = self.plan
        n = len(ids)
        # calibration anchor: the Krum-kept set minus anyone this ledger
        # already distrusts — a hugger sits geometrically central, so
        # without the exclusion it would poison its own bar
        kept = [i for i in range(n) if keep[i]]
        clean = [i for i in kept
                 if not (self._peers.get(ids[i]) is not None
                         and (self._peers[ids[i]].flagged
                              or self._peers[ids[i]].hold > 0))]
        anchor = clean if clean else kept
        ref_geo = max((scores[i] for i in anchor), default=0.0)
        # similarity bar: prefer the clean anchor's pairs, but when holds
        # have thinned it below a usable sample fall back to the full
        # kept set — its median survives one attacker pair among >= 3,
        # and a single-pair anchor (tiny pools) disables the veto rather
        # than calibrating a bar from one cosine sample
        pairs = [cos[i][j] for i in anchor for j in anchor if j > i]
        if len(pairs) < p.sim_min_pairs and anchor is not kept:
            pairs = [cos[i][j] for i in kept for j in kept if j > i]
        if len(pairs) >= p.sim_min_pairs:
            ref_sim = _median(pairs)
            mad = _median([abs(c - ref_sim) for c in pairs])
            sim_bar = ref_sim + max(p.sim_margin, p.sim_mad_mult * mad)
        else:
            sim_bar = 2.0  # unusable anchor: similarity veto disabled
        lognorms = [math.log(norms[i]) for i in range(n) if norms[i] > 0.0]
        ref_mag = _median(lognorms) if lognorms else None
        mag_bar = math.log(p.mag_band)

        # record this round's observations before voting so the drift
        # scorer sees the freshest increment
        for i, pid in enumerate(ids):
            if residuals[i] > 0.0:
                st = self._peer(pid)
                st.obs.append((iteration, math.log(residuals[i])))
                if len(st.obs) > 2 * p.drift_window:
                    del st.obs[:-p.drift_window]

        accepts: List[bool] = []
        votes_out: List[List[str]] = []
        for i, pid in enumerate(ids):
            st = self._peer(pid)
            votes: List[str] = []
            # the one-shot vetoes only scrutinise unproven identities:
            # honest non-IID shards converge at different rates, making
            # single-round geometry/norm bands misfire on veterans,
            # while every attacker identity stays unproven (its rejected
            # updates leave no chain record to graduate on)
            unproven = not (self.proven(pid) or self.committee_clean(pid))
            if (unproven and ref_geo > 0.0
                    and scores[i] > p.geo_ratio * ref_geo):
                votes.append("geometry")
            vmax = max((cos[i][j] for j in range(n) if j != i),
                       default=-1.0)
            if vmax >= sim_bar:
                votes.append("similarity")
            if (unproven and ref_mag is not None and norms[i] > 0.0
                    and math.log(norms[i]) - ref_mag > mag_bar):
                votes.append("magnitude")
            st.drift_score = self._drift(st)
            if st.drift_score >= p.drift_hi:
                st.flagged = True
            elif st.drift_score <= p.drift_lo:
                st.flagged = False
            if st.flagged:
                votes.append("drift")
            # slow-trust is READ-ONLY here: the credit accumulator is a
            # pure function of the committed chain (sync_block), so any
            # verifier — including one that just joined a churned
            # committee mid-ramp — reaches the identical verdict. The
            # pass itself is consumed by the accepted record the chain
            # commits, not by this decision.
            if self.weight(pid) < 1.0 and st.credit < 1.0:
                votes.append("slow_trust")
            if votes:
                # slow_trust is a duty-cycle throttle, not an accusation:
                # arming the hold for it would starve a ramping identity
                # forever (throttled -> held -> absent -> reset). Only
                # the suspicion vetoes arm hysteresis.
                if any(v != "slow_trust" for v in votes):
                    st.hold = p.hold_rounds
                reject = True
            elif st.hold > 0:
                st.hold -= 1
                votes = ["hold"]
                reject = True
            else:
                reject = False
            for v in votes:
                self._votes[v] = self._votes.get(v, 0) + 1
            accepts.append(not reject)
            votes_out.append(votes)
        self.decisions += 1
        detail = {"ref_geo": ref_geo, "sim_bar": sim_bar,
                  "ref_mag": ref_mag if ref_mag is not None else 0.0}
        return accepts, votes_out, detail

    # -------------------------------------------------------- reporting

    def trust_scores(self) -> Dict[int, float]:
        """Per-peer score in [0, 1] for the pull-model gauge: slow-trust
        weight x (1 - drift score), zeroed while flagged or held."""
        out: Dict[int, float] = {}
        for pid in range(self.num_nodes):
            st = self._peers.get(pid)
            if st is None:
                out[pid] = 1.0
                continue
            if st.flagged or st.hold > 0:
                out[pid] = 0.0
            else:
                out[pid] = self.weight(pid) * (1.0 - st.drift_score)
        return out

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe ledger state for telemetry_snapshot()/obs merging."""
        ramping = {str(pid): st.ramp for pid, st in sorted(self._peers.items())
                   if st.ramp is not None}
        resets = {str(pid): st.resets
                  for pid, st in sorted(self._peers.items()) if st.resets}
        held = {str(pid): st.hold
                for pid, st in sorted(self._peers.items()) if st.hold > 0}
        drift = {str(pid): round(st.drift_score, 4)
                 for pid, st in sorted(self._peers.items())
                 if st.obs or st.drift_score}
        return {
            "synced_it": self.synced_it,
            "decisions": self.decisions,
            "votes": dict(sorted(self._votes.items())),
            "flagged": sorted(pid for pid, st in self._peers.items()
                              if st.flagged),
            "held": held,
            "ramping": ramping,
            "resets": resets,
            "drift": drift,
            "weights": {str(pid): round(self.weight(pid), 4)
                        for pid in range(self.num_nodes)
                        if self.weight(pid) < 1.0},
        }
