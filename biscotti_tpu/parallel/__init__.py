from biscotti_tpu.parallel.sim import Simulator

__all__ = ["Simulator"]
