"""Stake-weighted role election — verifier/miner/noiser committees per round.

Reference behavior (DistSys/vrf.go:54-182, main.go:497-565):
  * lottery tickets ∝ stake: node i appears stake[i] times in the ticket list
  * winners drawn from 2-byte big-endian windows of an entropy string,
    `idx = (e[i]·256 + e[i+1]) mod len(tickets)`, advancing one byte per
    draw and re-hashing with SHA-256 when the string is exhausted
  * verifier/miner draws consume the *public* latest block hash
    (vrf.go:134-141 draws from `input`, not the VRF output) — every peer
    computes the same committees with no communication; we keep that
    common-coin behavior deliberately
  * noiser draws consume the requester's *private* VRF output over the block
    hash (vrf.go:57-83), excluding the requester; the proof lets a chosen
    noiser check it was really selected
  * roles are encoded per node as a product of primes V=2/M=3/N=5
    (main.go:41-43, 497-527); contributors ("vanilla") are the nodes whose
    role id is 1 or NOISER_PRIME only (main.go:530-565)
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from biscotti_tpu.crypto.vrf import VRFKey, verify as vrf_verify

VERIFIER_PRIME = 2  # ref: main.go:41
MINER_PRIME = 3  # ref: main.go:42
NOISER_PRIME = 5  # ref: main.go:43


def lottery_tickets(stake_map: Dict[int, int], total_nodes: int) -> List[int]:
    """node i gets stake[i] tickets (ref: vrf.go:67-72, 119-124)."""
    tickets: List[int] = []
    for node in range(total_nodes):
        tickets.extend([node] * max(0, stake_map.get(node, 0)))
    if not tickets:
        raise ValueError("empty lottery: no node holds positive stake")
    return tickets


class _EntropyWindows:
    """2-byte sliding windows over an entropy string, SHA-256 re-hash on
    exhaustion (ref: vrf.go:77-83, 134-141)."""

    def __init__(self, entropy: bytes):
        self.entropy = entropy
        self.i = 0

    def next_index(self, modulus: int) -> int:
        if self.i >= len(self.entropy) - 1:
            self.entropy = hashlib.sha256(self.entropy).digest()
            self.i = 0
        idx = (self.entropy[self.i] * 256 + self.entropy[self.i + 1]) % modulus
        self.i += 1
        return idx


def draw_winners(entropy: bytes, tickets: Sequence[int], count: int,
                 exclude: Optional[int] = None) -> List[int]:
    """First `count` distinct ticket holders along the entropy stream."""
    distinct = len(set(tickets) - ({exclude} if exclude is not None else set()))
    if count > distinct:
        raise ValueError(f"cannot draw {count} distinct winners from {distinct}")
    windows = _EntropyWindows(entropy)
    winners: List[int] = []
    seen = set()
    while len(winners) < count:
        w = tickets[windows.next_index(len(tickets))]
        if w not in seen and w != exclude:
            seen.add(w)
            winners.append(w)
    return winners


def elect_committees(stake_map: Dict[int, int], block_hash: bytes,
                     num_verifiers: int, num_miners: int,
                     total_nodes: int) -> Tuple[List[int], List[int]]:
    """Deterministic verifier + miner committees from the public block hash.

    Every peer runs this locally and agrees (the reference's draws read the
    shared block hash, vrf.go:134-141, so its committees are likewise a
    common coin; we drop the vestigial per-node VRF it computes but never
    uses for these draws). Verifiers and miners continue one shared entropy
    stream, so the sets may overlap exactly as in the reference
    (vrf.go:127-179)."""
    tickets = lottery_tickets(stake_map, total_nodes)
    windows = _EntropyWindows(block_hash)

    def take(count: int) -> List[int]:
        got: List[int] = []
        seen = set()
        while len(got) < count:
            w = tickets[windows.next_index(len(tickets))]
            if w not in seen:
                seen.add(w)
                got.append(w)
        return got

    if num_verifiers + num_miners > 0 and num_verifiers > len(set(tickets)):
        raise ValueError("more verifiers requested than staked nodes")
    if num_miners > len(set(tickets)):
        raise ValueError("more miners requested than staked nodes")
    verifiers = take(num_verifiers)
    miners = take(num_miners)
    return verifiers, miners


@dataclass
class NoiserDraw:
    """A requester's private noiser selection plus the proof that binds it
    to (requester key, block hash) — ref: vrf.go:54-99 returns
    (noisers, vrfOutput, vrfProof)."""

    noisers: List[int]
    output: bytes
    proof: bytes


def elect_noisers(noise_key: VRFKey, stake_map: Dict[int, int],
                  block_hash: bytes, source_id: int, num_noisers: int,
                  total_nodes: int) -> NoiserDraw:
    beta, pi = noise_key.prove(block_hash)
    tickets = lottery_tickets(stake_map, total_nodes)
    noisers = draw_winners(beta, tickets, num_noisers, exclude=source_id)
    return NoiserDraw(noisers=noisers, output=beta, proof=pi)


def verify_noiser_draw(public: bytes, stake_map: Dict[int, int],
                       block_hash: bytes, source_id: int, draw: NoiserDraw,
                       total_nodes: int) -> bool:
    """A selected noiser checks the requester's lottery honestly picked it
    (the capability the reference's returned-but-unchecked proof was for)."""
    beta = vrf_verify(public, block_hash, draw.proof)
    if beta is None or beta != draw.output:
        return False
    tickets = lottery_tickets(stake_map, total_nodes)
    try:
        expected = draw_winners(beta, tickets, len(draw.noisers),
                                exclude=source_id)
    except ValueError:
        return False
    return expected == draw.noisers


# --------------------------------------------------------------- role codec


@dataclass
class RoleMap:
    """Prime-product role encoding, one int per node (ref: main.go:497-565)."""

    roles: Dict[int, int] = field(default_factory=dict)

    @classmethod
    def build(cls, total_nodes: int, verifiers: Sequence[int],
              miners: Sequence[int], noisers: Sequence[int] = ()) -> "RoleMap":
        roles = {i: 1 for i in range(total_nodes)}
        for v in verifiers:
            roles[v] *= VERIFIER_PRIME
        for m in miners:
            roles[m] *= MINER_PRIME
        for n in noisers:
            roles[n] *= NOISER_PRIME
        return cls(roles)

    def is_verifier(self, node: int) -> bool:
        return self.roles.get(node, 1) % VERIFIER_PRIME == 0

    def is_miner(self, node: int) -> bool:
        return self.roles.get(node, 1) % MINER_PRIME == 0

    def is_noiser(self, node: int) -> bool:
        return self.roles.get(node, 1) % NOISER_PRIME == 0

    def is_vanilla(self, node: int) -> bool:
        """Plain contributor: role id 1 or noiser-only (ref: main.go:539-541)."""
        return self.roles.get(node, 1) in (1, NOISER_PRIME)

    def committee(self) -> Tuple[List[int], List[int], List[int], int]:
        """(sorted verifiers, miners, noisers, #vanilla) — the reference
        sorts verifiers because Krum's threshold fan-out needs a stable
        order (ref: main.go:560-562)."""
        verifiers = sorted(n for n in self.roles if self.is_verifier(n))
        miners = [n for n in self.roles if self.is_miner(n)]
        noisers = [n for n in self.roles if self.is_noiser(n)]
        vanilla = sum(1 for n in self.roles if self.is_vanilla(n))
        return verifiers, miners, noisers, vanilla
