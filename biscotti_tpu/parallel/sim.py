"""In-process N-peer round simulator — peers mapped to the device.

This is the TPU-idiomatic replacement for the reference's process-per-peer
deployment when you want *round math* rather than *protocol transport*: the
reference can only simulate N peers by booting N OS processes exchanging RPC
(ref: DistSys/localTest.sh) or by a Python for-loop (ref:
ML/Pytorch/ml_main_mnist.py:24-60). Here one jitted XLA program executes the
whole round for all peers at once:

    deltas   = vmap(local_step)     — S contributors' SGD steps, batched matmuls
    noise    = vmap(threefry draw)  — DP noising committee equivalent
    mask     = Krum | RONI kernel   — verifier committee equivalent
    w'       = w + Σ maskᵢ·deltaᵢ   — miner aggregation (sum, ref honest.go:360-375)
    stake'   = ±STAKE_UNIT scatter  — ledger bookkeeping (ref honest.go:414-419)

Peers-as-devices: `make_sharded_round_step` shards the peer axis over a
`jax.sharding.Mesh` with `shard_map`; the only cross-peer communication is an
`all_gather` of the [S,d] noised deltas for Krum and a `psum` of the masked
aggregate — both ride ICI, replacing the reference's TCP fan-out.

Committee *identity* (who is verifier/miner this round) does not change the
round's math, only who executes it; the distributed runtime (runtime/peer.py)
models identities. The simulator reproduces the math at full fidelity,
including contributor sampling and stake evolution.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from biscotti_tpu.config import BiscottiConfig, Defense
from biscotti_tpu.data import datasets as ds
from biscotti_tpu.models.base import Model
from biscotti_tpu.models.trainer import local_step_fn, sample_batch
from biscotti_tpu.models.zoo import model_for_dataset
from biscotti_tpu.ops import dp_noise
from biscotti_tpu.ops.krum import default_num_adversaries, krum_accept_mask
from biscotti_tpu.ops.roni import roni_accept_mask


@dataclass
class RoundLog:
    """One reference-log row: `iteration,error,timestamp`
    (ref: eval parser usenix-eval/generateResults.py:23-52)."""

    iteration: int
    error: float
    timestamp: float
    accepted: int = 0

    def csv(self) -> str:
        return f"{self.iteration},{self.error:.6f},{self.timestamp:.6f}"


def defense_mask(defense: Defense, model: Model, w: jax.Array,
                 noised: jax.Array, x_val: jax.Array, y_val: jax.Array,
                 roni_threshold: float, num_adversaries: int) -> jax.Array:
    """Verifier-committee accept mask over the round's noised updates —
    shared by the single-chip (vmap) and sharded (shard_map) round steps so
    the two paths cannot drift. TRIMMED_MEAN has no per-update reject (it
    is an aggregation rule, not a mask — see masked_aggregate), so it
    accepts all like NONE."""
    n = noised.shape[0]
    if defense == Defense.KRUM:
        return krum_accept_mask(noised, num_adversaries)
    if defense == Defense.MULTIKRUM:
        from biscotti_tpu.ops.robust_agg import multikrum_accept_mask

        return multikrum_accept_mask(noised, num_adversaries)
    if defense == Defense.FOOLSGOLD:
        from biscotti_tpu.ops.robust_agg import foolsgold_accept_mask

        return foolsgold_accept_mask(noised)
    if defense == Defense.RONI:
        return roni_accept_mask(model, w, noised, x_val, y_val, roni_threshold)
    return jnp.ones((n,), jnp.bool_)


def masked_aggregate(mask: jax.Array, deltas: jax.Array, noised: jax.Array,
                     dp_in_model: bool, defense: Defense = Defense.KRUM,
                     trim_fraction: float = 0.35) -> jax.Array:
    """Miner aggregation: sum of accepted RAW deltas (the noised copies exist
    only for verification, ref: SURVEY §2.3 row 21) — except in dp_in_model
    mode where the noise IS part of the update (ref: honest.go:172-179).
    Under TRIMMED_MEAN the sum is replaced by the coordinate-wise trimmed
    aggregate (ops/robust_agg.py); the mask is all-ones there."""
    agg_src = noised if dp_in_model else deltas
    if defense == Defense.TRIMMED_MEAN:
        from biscotti_tpu.ops.robust_agg import trimmed_mean_aggregate

        return trimmed_mean_aggregate(agg_src, trim_fraction)
    return jnp.sum(jnp.where(mask[:, None], agg_src, 0.0), axis=0)


def _poisoned_ids(num_nodes: int, poison_fraction: float) -> set:
    """Top poison_fraction of node ids load bad shards
    (ref: DistSys/main.go:836-845, honest.go:102-118). THE formula lives
    in tools/verdicts.poisoned_ids — one definition shared with the live
    runtime, the campaign plane's attacker draw, and every verdict
    reader; this name stays as the sim-side alias."""
    from biscotti_tpu.tools.verdicts import poisoned_ids

    return poisoned_ids(num_nodes, poison_fraction)


class Simulator:
    """N peers on one chip (vmapped) or across a mesh (shard_map)."""

    def __init__(self, cfg: BiscottiConfig, model: Optional[Model] = None,
                 metrics=None):
        self.cfg = cfg
        # optional telemetry registry (telemetry.MetricsRegistry): run()
        # then feeds a per-round duration histogram and height/error
        # gauges — the simulator's rounds land on the same scrapeable
        # plane as the live runtime's (the CLI's --metrics-out wires this)
        self.metrics = metrics
        self.model = model or model_for_dataset(
            cfg.dataset, getattr(cfg, "model_name", ""))
        self.mode = "sgd" if self.model.name == "logreg" else "grad"
        self.num_params = self.model.num_params
        n = cfg.num_nodes

        poisoned = _poisoned_ids(n, cfg.poison_fraction)
        xs, ys = [], []
        for i in range(n):
            shard = ds.load_shard(cfg.dataset,
                                  ds.shard_name(cfg.dataset, i, i in poisoned))
            xs.append(shard["x_train"])
            ys.append(shard["y_train"])
        rows = min(len(x) for x in xs)
        self.x = jnp.asarray(np.stack([x[:rows] for x in xs]))  # [N, rows, d]
        self.y = jnp.asarray(np.stack([y[:rows] for y in ys]))  # [N, rows]
        self.rows = rows

        test = ds.load_shard(cfg.dataset, f"{cfg.dataset}_test")
        self.x_val = jnp.asarray(test["x_test"])
        self.y_val = jnp.asarray(test["y_test"])
        attack = ds.load_shard(cfg.dataset, f"{cfg.dataset}_digit1")
        self.x_attack = jnp.asarray(attack["x_test"])
        self.y_attack = jnp.asarray(attack["y_test"])

        self.root_key = jax.random.PRNGKey(cfg.seed)
        alpha = cfg.logreg_alpha
        self._step = local_step_fn(self.model, self.mode, clip=cfg.grad_clip,
                                   alpha=alpha)
        self._noise_eps = (cfg.epsilon
                           if cfg.noising or cfg.dp_in_model else 0.0)
        self._noise_scale = dp_noise.sigma_for(self._noise_eps, cfg.delta)
        self._dp_mechanism = cfg.dp_mechanism
        self._noise_alpha = alpha if self.mode == "sgd" else 1.0
        self._round_step_raw = self._build_round_step()
        self._round_step_jit = jax.jit(self._round_step_raw,
                                       donate_argnums=(0, 1))

        def round_step(w, stake, it):
            return self._round_step_jit(w, stake, it,
                                        jnp.asarray(self.cfg.seed, jnp.int32),
                                        self.x, self.y,
                                        self.x_val, self.y_val)

        self.round_step = round_step

    # ------------------------------------------------------------------ build

    def _contributors(self, key: jax.Array) -> jax.Array:
        """Per-round contributor subset of static size NUM_SAMPLES. The
        reference's verifier acts on the first KRUM_UPDATETHRESH arrivals
        (ref: krum.go:296); arrival order is scheduling noise, which a random
        subset models."""
        n, s = self.cfg.num_nodes, self.cfg.num_samples
        if s >= n:
            return jnp.arange(n)
        return jax.random.choice(key, n, (s,), replace=False)

    def _peer_noise(self, key: jax.Array) -> jax.Array:
        """Fresh per-round draw, distribution-identical to the reference's
        presampled bank row (Σ_batch σ·N(0,1) scaled by −α/batch; ref:
        client_obj.py:59-67,97-98). Presampling a [N,iters,d] bank would cost
        GBs of HBM at CNN sizes for zero statistical difference."""
        b = self.cfg.batch_size
        if self._dp_mechanism == "mcmc13":
            # Song&Sarwate'13 mechanism: fresh exact draw from the
            # MCMC path's stationary density (dp_noise.knorm_draw; the
            # per-peer trainer runs the chain itself for emcee parity)
            draw = dp_noise.knorm_draw(key, self._noise_eps, 1,
                                       self.num_params)[0]
        else:
            draw = self._noise_scale * math.sqrt(b) * jax.random.normal(
                key, (self.num_params,), jnp.float32
            )
        return (-self._noise_alpha / b) * draw

    def _build_round_step(self):
        cfg = self.cfg
        model = self.model
        batch = cfg.batch_size
        use_noise = cfg.noising or cfg.dp_in_model
        defense = cfg.defense if cfg.verification else Defense.NONE
        # cheap mirror of the live fault plane (cfg.fault_plan, runtime/
        # faults.py): with drop probability p, each contributor's round
        # frame is lost with p — deterministically in (fault seed, it, i),
        # so same seed ⇒ same degraded rounds here AND in the live runtime
        # sense (fewer contributors, no stake movement for the lost ones).
        # Semantics match the live system's dominant drop outcome: the
        # worker computed and verifiers scored the update (defense_mask
        # still sees it), but the miner-bound frame died, so it joins no
        # aggregate and earns no stake. Per-link structure is not modeled
        # — this is the ROUND-level agreement knob, not a transport sim.
        drop_p = cfg.fault_plan.drop if cfg.fault_plan.enabled else 0.0
        if drop_p > 0.0 and defense == Defense.TRIMMED_MEAN:
            raise ValueError(
                "fault_plan.drop is not supported with defense=TRIMMED_MEAN "
                "in the simulator: the trimmed aggregate has no per-update "
                "mask to carry the drops (run the live runtime for that)")
        fault_base = jax.random.PRNGKey(cfg.fault_plan.seed)

        def one_delta(w, key, xi, yi):
            idx = sample_batch(key, self.rows, batch)
            return self._step(w, xi[idx], yi[idx])

        # data tensors are ARGUMENTS, not closure captures: a captured jnp
        # array is baked into the HLO as a constant, which at CNN sizes
        # makes the program itself hundreds of MB (the [N, rows, d] peer
        # stack) — slow to compile and over upload limits on remote-compile
        # setups. As arguments they stay device-resident buffers. The SEED
        # is an argument for the same reason: a baked-in PRNGKey constant
        # would force a fresh trace+compile per seed, making multi-seed
        # sweeps (eval_poison --seeds) pay the compile N times.
        seed_base = jax.random.PRNGKey(0)  # same constant for every sim

        def round_step(w, stake, it, seed, x, y, x_val, y_val):
            rkey = jax.random.fold_in(jax.random.fold_in(seed_base, seed),
                                      it)
            ckey, bkey, nkey = jax.random.split(rkey, 3)
            cidx = self._contributors(ckey)
            s = cidx.shape[0]

            bkeys = jax.vmap(lambda i: jax.random.fold_in(bkey, i))(cidx)
            deltas = jax.vmap(one_delta, in_axes=(None, 0, 0, 0))(
                w, bkeys, x[cidx], y[cidx]
            )  # [S, d]

            if use_noise:
                nkeys = jax.vmap(lambda i: jax.random.fold_in(nkey, i))(cidx)
                noise = jax.vmap(self._peer_noise)(nkeys)
            else:
                noise = jnp.zeros_like(deltas)
            noised = deltas + noise

            mask = defense_mask(defense, model, w, noised, x_val,
                                y_val, cfg.roni_threshold,
                                default_num_adversaries(s))
            delta_stake = jnp.where(mask, cfg.stake_unit, -cfg.stake_unit)
            if drop_p > 0.0:
                dkey = jax.random.fold_in(fault_base, it)
                keep = jax.random.uniform(dkey, (s,)) >= drop_p
                mask = mask & keep  # lost frames join no aggregate …
                delta_stake = jnp.where(keep, delta_stake, 0)  # … or ledger
            w_next = w + masked_aggregate(mask, deltas, noised,
                                          cfg.dp_in_model, defense,
                                          cfg.trim_fraction)

            stake_next = stake.at[cidx].add(delta_stake)

            err = model.error_flat(w_next, x_val, y_val)
            return w_next, stake_next, mask, err

        return round_step

    # ------------------------------------------------------------------ run

    def init_state(self):
        w = jnp.zeros((self.num_params,), jnp.float32)
        stake = jnp.full((self.cfg.num_nodes,), self.cfg.default_stake, jnp.int32)
        return w, stake

    def run(self, num_rounds: Optional[int] = None, log_every: int = 1,
            stop_at_convergence: bool = True):
        """Python round loop over the jitted step; returns (w, stake, logs).
        Log rows mirror the reference's parsed node-0 output so eval tooling
        is directly comparable (BASELINE.md)."""
        if num_rounds is None:
            num_rounds = self.cfg.max_iterations
        w, stake = self.init_state()
        logs: List[RoundLog] = []
        m = self.metrics
        for it in range(num_rounds):
            t0 = time.perf_counter()
            w, stake, mask, err = self.round_step(w, stake, it)
            if m is not None:
                jax.block_until_ready(w)  # charge the round its device time
                m.histogram("biscotti_sim_round_seconds",
                            "simulator device-round wall clock").observe(
                    time.perf_counter() - t0)
                m.gauge("biscotti_sim_round_height",
                        "simulator rounds completed").set(it + 1)
            if it % log_every == 0 or it == num_rounds - 1:
                e = float(err)
                logs.append(RoundLog(it, e, time.time(), int(mask.sum())))
                if m is not None:
                    m.gauge("biscotti_sim_error",
                            "simulator latest test error").set(e)
                if stop_at_convergence and e < self.cfg.convergence_error:
                    break
        return w, stake, logs

    def run_scan(self, num_rounds: Optional[int] = None,
                 seed: Optional[int] = None):
        """Whole training as ONE compiled XLA program (`lax.scan` over
        rounds) — no host in the loop at all. Upper bound of the TPU design;
        nothing in the reference's architecture can express this. `seed`
        overrides cfg.seed without rebuilding the Simulator (it is a traced
        argument, so multi-seed sweeps reuse one compiled executable)."""
        if num_rounds is None:
            num_rounds = self.cfg.max_iterations
        w, stake = self.init_state()
        step = self._round_step_raw

        # cache the jitted scan per run length: a fresh @jax.jit wrapper
        # each call would empty the in-memory jit cache and re-trace the
        # whole N-round program per seed, defeating the seed-as-argument
        # design
        full = getattr(self, "_scan_cache", {}).get(num_rounds)
        if full is None:

            @jax.jit
            def full(w, stake, seed, x, y, x_val, y_val):
                def body(carry, it):
                    w, stake = carry
                    w, stake, mask, err = step(w, stake, it, seed, x, y,
                                               x_val, y_val)
                    return (w, stake), (err, jnp.sum(mask))

                return jax.lax.scan(body, (w, stake),
                                    jnp.arange(num_rounds))

            self._scan_cache = getattr(self, "_scan_cache", {})
            self._scan_cache[num_rounds] = full

        s = self.cfg.seed if seed is None else seed
        (w, stake), (errs, accepted) = full(
            w, stake, jnp.asarray(s, jnp.int32), self.x, self.y,
            self.x_val, self.y_val)
        return w, stake, np.asarray(errs), np.asarray(accepted)

    # ------------------------------------------------------------------ metrics

    def test_error(self, w) -> float:
        return float(self.model.error_flat(jnp.asarray(w), self.x_val, self.y_val))

    def attack_rate(self, w) -> float:
        return float(self.model.error_flat(jnp.asarray(w), self.x_attack,
                                           self.y_attack))

    def attack_success_rate(self, w) -> float:
        """Stricter source→target metric: fraction of attack-source samples
        predicted as exactly the attack target class (the 1→7 rate;
        trainer.attack_success_rate analogue — not inflated by benign
        confusion the way attack_rate's 1−accuracy is)."""
        target = ds.spec(self.cfg.dataset).attack_target
        logits = self.model.apply_flat(jnp.asarray(w), self.x_attack)
        pred = jnp.argmax(logits, axis=-1)
        return float(jnp.mean((pred == target).astype(jnp.float32)))


# ---------------------------------------------------------------- sharded path


def make_sharded_round_step(sim: Simulator, mesh: jax.sharding.Mesh,
                            axis: str = "peers"):
    """Peers-across-devices round step via shard_map.

    Every peer contributes (S = N — contributor sampling is a single-chip
    refinement); the peer axis of (x, y) is sharded over `axis`, the model is
    replicated. Cross-device traffic is exactly one all_gather of the [N,d]
    noised deltas (Krum needs the full set) and one psum of the masked local
    aggregate — the ICI-collective replacement for the reference's
    TCP update fan-out (ref: SURVEY §5.8).

    Randomness derives from the same seed-as-argument scheme as the
    single-chip round_step — fold_in(fold_in(PRNGKey(0), seed), it) — so
    `run_step(w, it, seed=...)` overrides behave identically on both paths
    (previously this path read sim.root_key and seed overrides silently
    no-opped on sharded runs; ADVICE round 5). The fault plane's drop-mask
    knob (cfg.fault_plan.drop) is mirrored here too — see _build_round_step.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from biscotti_tpu.utils.compat import shard_map

    cfg = sim.cfg
    model = sim.model
    n = cfg.num_nodes
    use_noise = cfg.noising or cfg.dp_in_model
    defense = cfg.defense if cfg.verification else Defense.NONE
    f = default_num_adversaries(n)
    seed_base = jax.random.PRNGKey(0)  # same constant as _build_round_step
    drop_p = cfg.fault_plan.drop if cfg.fault_plan.enabled else 0.0
    fault_base = jax.random.PRNGKey(cfg.fault_plan.seed)

    def local_deltas(w, x_loc, y_loc, it, seed):
        def one(key, xi, yi):
            idx = sample_batch(key, sim.rows, cfg.batch_size)
            return sim._step(w, xi[idx], yi[idx])

        pid = jax.lax.axis_index(axis)
        n_loc = x_loc.shape[0]
        gids = pid * n_loc + jnp.arange(n_loc)
        rkey = jax.random.fold_in(jax.random.fold_in(seed_base, seed), it)
        bkey, nkey = jax.random.split(rkey)
        bkeys = jax.vmap(lambda i: jax.random.fold_in(bkey, i))(gids)
        deltas = jax.vmap(one)(bkeys, x_loc, y_loc)
        if use_noise:
            nkeys = jax.vmap(lambda i: jax.random.fold_in(nkey, i))(gids)
            noise = jax.vmap(sim._peer_noise)(nkeys)
        else:
            noise = jnp.zeros_like(deltas)
        return deltas, deltas + noise

    def sharded_step(w, x_loc, y_loc, it, seed):
        deltas, noised = local_deltas(w, x_loc, y_loc, it, seed)
        all_noised = jax.lax.all_gather(noised, axis, tiled=True)  # [N, d]
        mask = defense_mask(defense, model, w, all_noised, sim.x_val,
                            sim.y_val, cfg.roni_threshold, f)
        if drop_p > 0.0:
            # mirror of the live fault plane's frame drops: the accepted
            # update whose miner-bound frame is lost contributes nothing
            # (see _build_round_step for the exact shared semantics)
            dkey = jax.random.fold_in(fault_base, it)
            mask = mask & (jax.random.uniform(dkey, (n,)) >= drop_p)
        pid = jax.lax.axis_index(axis)
        n_loc = deltas.shape[0]
        if defense == Defense.TRIMMED_MEAN:
            # order statistics need the FULL peer set: one more all_gather
            # (of the raw deltas) and the trimmed aggregate is computed
            # replicated — same collective budget class as Krum's gather
            src = all_noised if cfg.dp_in_model else jax.lax.all_gather(
                deltas, axis, tiled=True)
            agg = masked_aggregate(mask, src, src, cfg.dp_in_model,
                                   defense, cfg.trim_fraction)
        else:
            local_mask = jax.lax.dynamic_slice_in_dim(mask, pid * n_loc,
                                                      n_loc)
            local_agg = masked_aggregate(local_mask, deltas, noised,
                                         cfg.dp_in_model)
            agg = jax.lax.psum(local_agg, axis)
        w_next = w + agg
        err = model.error_flat(w_next, sim.x_val, sim.y_val)
        return w_next, mask, err

    mapped = shard_map(
        sharded_step, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    step = jax.jit(mapped)

    sharding = NamedSharding(mesh, P(axis))
    x_sh = jax.device_put(sim.x, sharding)
    y_sh = jax.device_put(sim.y, sharding)

    def run_step(w, it, seed: Optional[int] = None):
        s = sim.cfg.seed if seed is None else seed
        return step(w, x_sh, y_sh, jnp.asarray(it),
                    jnp.asarray(s, jnp.int32))

    return run_step


# ------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    """Standalone federated simulation CLI — the reference's ml_main_* file
    family (ref: ML/Pytorch/ml_main_mnist.py:24-60, ml_main_diffpriv.py,
    _credit/_cifar/_lfw variants) as one parameterized entry point, with
    the whole round jitted instead of a Python peer loop."""
    import argparse
    import json as _json

    from biscotti_tpu.config import BiscottiConfig

    ap = argparse.ArgumentParser(description="in-process N-peer simulator")
    BiscottiConfig.add_args(ap)
    ap.add_argument("--rounds", type=int, default=0,
                    help="override max-iterations for the run")
    ap.add_argument("--scan", action="store_true",
                    help="compile the WHOLE training run as one XLA program")
    ap.add_argument("--csv", default="",
                    help="write iteration,error,timestamp rows here")
    ap.add_argument("--metrics-out", default="",
                    help="write a Prometheus text page of the run's "
                         "telemetry (round histogram, height/error gauges) "
                         "here; non-scan runs only")
    ns = ap.parse_args(argv)
    if ns.metrics_out and ns.scan:
        ap.error("--metrics-out requires a non-scan run (run_scan compiles "
                 "the whole training into one XLA program; there are no "
                 "per-round host observations to export)")
    cfg = BiscottiConfig.from_args(ns)
    registry = None
    if ns.metrics_out:
        from biscotti_tpu.telemetry import MetricsRegistry

        registry = MetricsRegistry()
    sim = Simulator(cfg, metrics=registry)
    rounds = ns.rounds or cfg.max_iterations
    if ns.scan:
        w, stake, errs, accepted = sim.run_scan(rounds)
        logs = [RoundLog(i, float(e), time.time(), int(a))
                for i, (e, a) in enumerate(zip(errs, accepted))]
    else:
        w, stake, logs = sim.run(rounds)
    if ns.csv:
        with open(ns.csv, "w") as f:
            f.write("\n".join(l.csv() for l in logs) + "\n")
    if registry is not None:
        with open(ns.metrics_out, "w") as f:
            f.write(registry.render())
    summary = {
        "dataset": cfg.dataset, "nodes": cfg.num_nodes,
        "rounds_run": len(logs),
        "final_error": logs[-1].error if logs else float("nan"),
        "test_error": sim.test_error(w),
        "attack_rate": sim.attack_rate(w),
    }
    print(_json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
