"""Distributed runtime — the host control plane.

The reference's native layer is a Go binary per peer speaking net/rpc+gob
over TCP (SURVEY.md §1 comms, §2.1). Here the control plane is an asyncio
peer agent (`peer.py`) over a length-prefixed binary codec (`messages.py`,
`rpc.py`); all round *math* (SGD, noising, Krum, share algebra) stays in
jitted XLA via the Trainer/ops layers. FedSys (the reference's baseline
system, SURVEY.md §2.5) is the same runtime in leader-aggregation mode —
a config flag, not a second codebase.

Wire data plane (`codecs.py`, docs/WIRE_PLANE.md): negotiated per-payload
codecs — f32/bf16 downcast and top-k sparsification applied to the delta
BEFORE commitment/noising/sharing so all crypto stays exact, zlib
lossless framing, raw64 fallback for legacy peers — plus chunked
streaming for oversized frames and per-frame byte accounting
(`biscotti_wire_bytes_total{msg_type,direction,codec}`).

Robustness plane (`faults.py`, docs/FAULT_PLANE.md): a seeded
deterministic fault injector at the transport boundary (per-frame
drop/delay/duplicate/reset — same seed ⇒ same schedule), retry with
decorrelated-jitter backoff in `PeerAgent._call`, and a per-peer
circuit breaker with half-open probing that quarantines dead peers so
gossip and committee RPCs stop burning round budget on them.
"""
