"""Overload-governance plane: admission control for the live runtime.

Biscotti's threat model lets ANY peer send ANYTHING — and before this
module, send it *as fast as it likes*: the RPC server spawned one unbounded
task per inbound frame, handlers parked callers in unbounded wait loops,
and no per-peer budget existed anywhere, so a single flooding or slow-loris
peer could exhaust an honest peer's memory and event loop without ever
failing a signature check. Making overload a survivable, *observable*
condition is the system-support-for-Byzantine-ML line of Garfield
(arXiv:2010.05888) and the volunteer-hostile setting of "Secure Distributed
Training at Scale" (arXiv:2106.11257).

Pieces (docs/ADMISSION.md):

  * `AdmissionPlan` — frozen config surface on `BiscottiConfig` (like
    `fault_plan`): per-message-class token-bucket rates, per-peer and
    global inflight-handler caps, a bounded parked-waiter budget, and the
    mid-frame read deadline `rpc.FrameStream` enforces against slow-loris
    connections. Disabled by default: a bare config behaves like the seed.
  * `TokenBucket` — standard refill-on-read bucket with injectable clock.
  * `ParkingLot` — the counted, capped replacement for the unbounded
    `_wait_for_iteration`/`_wait_round_ready` sleep loops: when the budget
    is exhausted the OLDEST waiter is shed (woken with a retryable busy
    signal) rather than the lot growing without bound.
  * `AdmissionController` — per-agent enforcement state. The RPC server
    consults `try_admit(peer, msg_type)` for every decoded frame; over-
    budget work is SHED with a retryable `rpc.BusyError` wire status
    instead of queued without bound. Every shed increments
    `biscotti_shed_total{reason,msg_type}`; inflight/parked levels ride
    `biscotti_inflight_handlers` / `biscotti_parked_waiters` gauges plus
    high-water marks in the structured snapshot.

Shedding is deliberately NOT a security verdict: a busy honest peer and a
flooding Byzantine one get the same `BusyError`, and the client side
(`PeerAgent._call`) treats it as retry-with-backoff that never feeds the
`HealthLedger` breaker — overload must not quarantine honest peers.

stdlib-only, like `faults.py`: imported by the config layer, so it must
pull in neither numpy nor asyncio machinery.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

# metric names shared by the controller's push-on-change updates and the
# peer's pull-refresh at scrape time — one definition, or the registry
# would fork the series on any drift
SHED_METRIC = "biscotti_shed_total"
SHED_HELP = "inbound work refused by the admission plane"
INFLIGHT_GAUGE = "biscotti_inflight_handlers"
INFLIGHT_HELP = "inbound RPC handler tasks currently running"
PARKED_GAUGE = "biscotti_parked_waiters"
PARKED_HELP = "handlers parked waiting for a future round"

# ------------------------------------------------------- message classes

# Token-bucket rates are per MESSAGE CLASS, not per method: the classes
# group methods by cost profile, so the config surface stays three knobs
# instead of thirteen.
BULK = "bulk"        # multi-MB bodies: block push/pull, chain adoption
UPDATE = "update"    # per-round protocol writes: updates, shares, verify
CONTROL = "control"  # small control/read frames

_MSG_CLASS: Dict[str, str] = {
    "RegisterBlock": BULK,
    "RegisterPeer": BULK,
    "GetBlock": BULK,
    "RegisterUpdate": UPDATE,
    "RegisterSecret": UPDATE,
    "VerifyUpdateKRUM": UPDATE,
    "VerifyUpdateRONI": UPDATE,
    "RequestNoise": UPDATE,
    # membership plane (docs/MEMBERSHIP.md): a snapshot reply is the
    # biggest frame the protocol serves (a whole sealed chain suffix),
    # and a reshare deal carries per-row commitment grids — both budget
    # as bulk so join storms and reshare rounds cannot starve the
    # round-critical update class
    "GetSnapshot": BULK,
    "GetReshareDeal": BULK,
    # hierarchical aggregation overlay (runtime/overlay.py,
    # docs/OVERLAY.md): offers carry a worker's FULL share/blind/
    # commitment tensors, aggregates a whole subtree's sums, and relay
    # frames fan a block/update out — all multi-payload bodies. Classed
    # bulk so a hot interior node SHEDS overlay load (the sender then
    # degrades to the seed's direct delivery) instead of melting.
    "OverlayOffer": BULK,
    "RegisterAggregate": BULK,
    "RelayFrames": BULK,
    "AdvertiseBlock": CONTROL,
    "RegisterDecline": CONTROL,
    "GetUpdateList": CONTROL,
    "GetMinerPart": CONTROL,
    "Metrics": CONTROL,
}


def msg_class(msg_type: str) -> str:
    """Unknown methods are classed BULK — the conservative budget (they
    will be rejected by dispatch anyway, but they must not enjoy the
    generous control-plane rate while doing so)."""
    return _MSG_CLASS.get(msg_type, BULK)


@dataclass(frozen=True)
class AdmissionPlan:
    """Overload-governance knobs (surfaced as cfg.admission_plan).

    Rates are tokens/second PER (peer, class); bucket capacity is
    rate × burst_factor, so short honest bursts (a round boundary's
    gossip fan-in) ride the burst while sustained floods drain the
    bucket and shed. Inflight caps bound concurrently-running handler
    tasks; `max_parked` bounds waiters parked for a future round;
    `read_deadline_s` bounds how long one frame may stay partially
    received before the connection is dropped (slow-loris)."""

    enabled: bool = False
    update_rate: float = 80.0
    bulk_rate: float = 40.0
    control_rate: float = 160.0
    burst_factor: float = 2.0
    peer_inflight: int = 32      # concurrent handlers per peer
    global_inflight: int = 256   # concurrent handlers, all peers
    max_parked: int = 128        # parked round-waiters, all peers
    # sized so one window fits a full wire-chunk (4 MiB default) on a
    # ~1.5 Mbps link: chunk completions count as progress, so a chunked
    # multi-MB transfer only needs one chunk per window — but UNCHUNKED
    # near-MAX_FRAME payloads on slow WAN links need this raised above
    # frame_bytes / link_rate
    read_deadline_s: float = 30.0

    def class_rate(self, cls: str) -> Tuple[float, float]:
        """(tokens/s, bucket capacity) for one message class."""
        rate = {UPDATE: self.update_rate, BULK: self.bulk_rate,
                CONTROL: self.control_rate}.get(cls, self.bulk_rate)
        return rate, rate * self.burst_factor

    def validate(self) -> None:
        if not self.enabled:
            return
        for name, v in (("update_rate", self.update_rate),
                        ("bulk_rate", self.bulk_rate),
                        ("control_rate", self.control_rate),
                        ("burst_factor", self.burst_factor)):
            if v <= 0:
                raise ValueError(f"admission_plan.{name} must be > 0")
        for name, v in (("peer_inflight", self.peer_inflight),
                        ("global_inflight", self.global_inflight),
                        ("max_parked", self.max_parked)):
            if int(v) < 1:
                raise ValueError(f"admission_plan.{name} must be >= 1")


class TokenBucket:
    """Refill-on-read token bucket. `clock` is injectable so rate tests
    run on a fake clock (same pattern as faults.HealthLedger)."""

    __slots__ = ("rate", "burst", "tokens", "_last", "_clock")

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def is_full(self) -> bool:
        """True when the bucket has refilled to its full burst — its
        state is then indistinguishable from a brand-new bucket's (the
        lossless-eviction invariant)."""
        self._refill()
        return self.tokens >= self.burst


class ParkToken:
    """One parked waiter. The parked coroutine polls `shed` each tick of
    its wait loop (the loops already sleep in 20–50 ms ticks, so a shed
    surfaces within one tick) and raises `rpc.BusyError` when set."""

    __slots__ = ("kind", "shed", "seq")

    def __init__(self, kind: str, seq: int):
        self.kind = kind
        self.shed: Optional[str] = None
        self.seq = seq


class ParkingLot:
    """Counted, capped parked-waiter budget. At capacity the OLDEST
    waiter is shed to make room — the newest message is the freshest
    evidence of real traffic, while the oldest waiter has already
    burned the most of its budget and is the most likely to be stale.
    With cap <= 0 the lot only counts (legacy unbounded behavior)."""

    def __init__(self, cap: int = 0):
        self.cap = int(cap)
        self._seq = 0
        self._waiting: Dict[int, ParkToken] = {}  # insertion-ordered
        self.peak = 0
        self.shed_count = 0

    def __len__(self) -> int:
        return len(self._waiting)

    def park(self, kind: str) -> Tuple[ParkToken, Optional[ParkToken]]:
        """Returns (token, shed_victim): the victim is the oldest waiter
        evicted to make room (already marked shed), None otherwise."""
        self._seq += 1
        tok = ParkToken(kind, self._seq)
        shed: Optional[ParkToken] = None
        if self.cap > 0 and len(self._waiting) >= self.cap:
            oldest = next(iter(self._waiting))
            shed = self._waiting.pop(oldest)
            shed.shed = "parked_cap"
            self.shed_count += 1
        self._waiting[tok.seq] = tok
        self.peak = max(self.peak, len(self._waiting))
        return tok, shed

    def unpark(self, tok: ParkToken) -> None:
        self._waiting.pop(tok.seq, None)


class AdmissionController:
    """Per-agent admission state: one consult per decoded inbound frame.

    `try_admit(peer, msg_type)` returns None when the frame may spawn a
    handler (the caller MUST pair it with `release(peer)` when the
    handler finishes) or a shed-reason string when it must be refused
    with `rpc.BusyError`. With the plan disabled every frame is admitted
    and only the (cheap) inflight accounting runs, so the gauges stay
    meaningful in observability-only deployments."""

    # bucket-table cardinality cap: past it, NEW budget keys share one
    # overflow bucket per class. Closes the fresh-bucket bypass — a
    # flooder spinning fabricated source_ids (or redialing for a new
    # ephemeral-port peername) would otherwise mint itself a full burst
    # allowance per spin AND grow this dict without bound; spun keys all
    # landing in one fast-draining bucket makes the spin itself the
    # thing that gets rate-limited. Honest clusters (N well below the
    # cap, 3 classes each) never touch the overflow path.
    BUCKET_CAP = 4096

    def __init__(self, plan: AdmissionPlan, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.plan = plan
        self.metrics = metrics  # telemetry.MetricsRegistry or None
        self._clock = clock
        self._buckets: Dict[Tuple[object, str], TokenBucket] = {}
        # per-peer inflight is self-bounding (entries are removed when
        # they drain, so the dict never exceeds the concurrent-handler
        # count), unlike the bucket table above
        self._inflight: Dict[object, int] = {}
        self.inflight_total = 0
        self.inflight_peak = 0
        self.parking = ParkingLot(plan.max_parked if plan.enabled else 0)
        # shed tallies by reason (msg_type detail rides the metric labels)
        self.shed_counts: Dict[str, int] = {}

    # ------------------------------------------------------------ admit

    def try_admit(self, peer, msg_type: str) -> Optional[str]:
        plan = self.plan
        if plan.enabled:
            if self.inflight_total >= plan.global_inflight:
                return self._shed("global_inflight", msg_type)
            if self._inflight.get(peer, 0) >= plan.peer_inflight:
                return self._shed("peer_inflight", msg_type)
            cls = msg_class(msg_type)
            key = (peer, cls)
            bucket = self._buckets.get(key)
            if bucket is None:
                if len(self._buckets) >= self.BUCKET_CAP:
                    self._evict_full_buckets()
                if len(self._buckets) >= self.BUCKET_CAP:
                    key = ("overflow", cls)
                    bucket = self._buckets.get(key)
                if bucket is None:
                    rate, burst = plan.class_rate(cls)
                    bucket = self._buckets[key] = TokenBucket(
                        rate, burst, clock=self._clock)
            if not bucket.try_take():
                return self._shed("rate", msg_type)
        self._inflight[peer] = self._inflight.get(peer, 0) + 1
        self.inflight_total += 1
        self.inflight_peak = max(self.inflight_peak, self.inflight_total)
        if self.metrics is not None:
            self.metrics.gauge(INFLIGHT_GAUGE, INFLIGHT_HELP).set(
                self.inflight_total)
        return None

    def _evict_full_buckets(self) -> None:
        """Drop every bucket that has refilled to its full burst — a
        LOSSLESS eviction (TokenBucket.is_full). Dead keys (closed
        connections, departed peers) go idle, refill, and get reaped
        here the next time the table hits its cap, so reconnect churn
        cannot saturate the cap permanently; an attacker's
        actively-drained buckets are NOT full and stay pinned, so
        spinning identities still funnels into the shared overflow
        bucket instead of minting fresh burst."""
        dead = [k for k, b in self._buckets.items() if b.is_full()]
        for k in dead:
            del self._buckets[k]

    def release(self, peer) -> None:
        n = self._inflight.get(peer, 0)
        if n <= 1:
            self._inflight.pop(peer, None)
        else:
            self._inflight[peer] = n - 1
        self.inflight_total = max(0, self.inflight_total - 1)
        if self.metrics is not None:
            self.metrics.gauge(INFLIGHT_GAUGE, INFLIGHT_HELP).set(
                self.inflight_total)

    def _shed(self, reason: str, msg_type: str) -> str:
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics.counter(SHED_METRIC, SHED_HELP).inc(
                reason=reason, msg_type=msg_type)
        return reason

    # ------------------------------------------------------------- park

    def park(self, kind: str) -> ParkToken:
        tok, victim = self.parking.park(kind)
        if victim is not None:
            # namespaced label: park kinds must not masquerade as RPC
            # method names in the shed metric's msg_type vocabulary
            self._shed("parked_cap", "park:" + victim.kind)
        if self.metrics is not None:
            self.metrics.gauge(PARKED_GAUGE, PARKED_HELP).set(
                len(self.parking))
        return tok

    def unpark(self, tok: ParkToken) -> None:
        self.parking.unpark(tok)
        if self.metrics is not None:
            self.metrics.gauge(PARKED_GAUGE, PARKED_HELP).set(
                len(self.parking))

    # ---------------------------------------------------------- readout

    def snapshot(self) -> Dict[str, object]:
        """Structured readout for `PeerAgent.telemetry_snapshot()` — the
        chaos report and the acceptance assertions (bounded peaks, shed
        tallies) read THIS, not private state."""
        return {
            "enabled": self.plan.enabled,
            "shed": dict(self.shed_counts),
            "shed_total": sum(self.shed_counts.values()),
            "inflight": self.inflight_total,
            "inflight_peak": self.inflight_peak,
            "parked": len(self.parking),
            "parked_peak": self.parking.peak,
            "caps": {
                "peer_inflight": self.plan.peer_inflight,
                "global_inflight": self.plan.global_inflight,
                "max_parked": self.plan.max_parked,
            },
        }

    # --------------------------------------------------------- migration

    def export_state(self) -> Dict[str, object]:
        """Rate-governance state for a migration ticket
        (runtime/placement.py): shed tallies + peaks (forensics survive
        the move) and every token bucket's current fill, keyed by
        "peer|class" strings so the export is JSON-clean. Inflight and
        parked waiters are NOT exported — they are handler tasks, which
        by definition die with the old incarnation."""
        buckets = {}
        for (peer, cls), b in self._buckets.items():
            b._refill()
            buckets[f"{peer}|{cls}"] = round(float(b.tokens), 6)
        return {
            "shed_counts": dict(self.shed_counts),
            "inflight_peak": self.inflight_peak,
            "parked_peak": self.parking.peak,
            "parked_shed": self.parking.shed_count,
            "buckets": buckets,
        }

    def restore_state(self, state: Dict[str, object]) -> None:
        """Rehydrate an export: a flooder must not get a fresh burst
        allowance just because its victim migrated — drained buckets
        come back drained. Bucket keys whose peer id parses as an int
        are restored under the int key (the runtime's peer key type);
        anything else (overflow, peername tuples) restores under the
        string, which the overflow path still matches."""
        for reason, n in dict(state.get("shed_counts", {})).items():
            self.shed_counts[reason] = (self.shed_counts.get(reason, 0)
                                        + int(n))
        self.inflight_peak = max(self.inflight_peak,
                                 int(state.get("inflight_peak", 0)))
        self.parking.peak = max(self.parking.peak,
                                int(state.get("parked_peak", 0)))
        self.parking.shed_count += int(state.get("parked_shed", 0))
        for key, tokens in dict(state.get("buckets", {})).items():
            peer_s, _, cls = key.rpartition("|")
            try:
                peer: object = int(peer_s)
            except ValueError:
                peer = peer_s
            rate, burst = self.plan.class_rate(cls)
            b = self._buckets.get((peer, cls))
            if b is None:
                if len(self._buckets) >= self.BUCKET_CAP:
                    continue  # the overflow path re-limits organically
                b = self._buckets[(peer, cls)] = TokenBucket(
                    rate, burst, clock=self._clock)
            b.tokens = min(b.burst, float(tokens))
            b._last = self._clock()
