"""Adaptive-adversary campaign plane: seeded, state-observing attack
strategies for the live runtime (docs/ADVERSARY.md).

Every hostile knob the repo already ships is STATIC: the poisoned set is
a pure function of the seed (`poison_fraction` → top ids), `--fault-flood`
replays every outbound frame regardless of who the round elected, and the
churn plane kills on a fixed timetable. Real adversaries adapt — Garfield
(arXiv:2010.05888) and the Byzantine setting of "Secure Distributed
Training at Scale" (arXiv:2106.11257) both treat coordinated, state-aware
attackers as the operating regime, not unit faults. This module is that
adversary, built with the same contract as every other hostile plane here:

  * `CampaignPlan` — frozen config surface on `BiscottiConfig` (like
    `FaultPlan` / `AdmissionPlan`); disabled by default, and a disabled
    plan is bit-identical to the seed schedule (guarded by
    tests/test_adversary.py).
  * Campaign strategies — one object per ATTACKER peer, observing only
    what a real attacker at that peer could see (the public VRF committee
    election, its own noiser draw, block contents, its own submission's
    fate) and deciding actions as a pure function of
    (campaign seed, observed state). Same seed + same chain ⇒ the
    identical action schedule, on any transport layout.
  * Every decision is traced (`campaign_round` / `campaign_poison`
    events) and counted (`biscotti_campaign_actions_total{campaign,
    action}`), so a campaign run's behavior is auditable from a scrape
    and replayable from its flags (`tools/chaos --campaign`).

The three shipped campaigns:

  roleflood — role-aware coordinated attack: colluding peers observe the
      per-round VRF election and aim their frame-storm at the elected
      miners (and, when drawn, their own noisers) instead of flooding
      blind; a fallback block re-elects, and the flood retargets with it.
      Composes with poisoning via `poison_fraction` (attacker ids mirror
      the poisoned-id formula, so one fraction arms both).
  sybil — churn-riding identity recycling: attackers kill themselves on a
      seeded schedule and rejoin as fresh incarnations (new connections,
      new ephemeral ports — the "fresh identity" a P2P transport actually
      grants), attempting to mint fresh admission burst allowances and
      shake off breaker quarantine / stake debits. What they CANNOT forge:
      node keys and the id space are fixed, so stake, debits and breaker
      history — all keyed on the node id or re-derived from chain state —
      follow the recycled identity (the admission plane's overflow-bucket
      and lossless-eviction claims, exercised live).
  hug — threshold-hugging poisoner: modulates its update per round to sit
      just under the Krum-distance / FoolsGold-similarity rejection
      thresholds it can estimate from accepted blocks — it blends its
      poisoned delta toward the observed honest aggregate step, ramps the
      poison component up while blocks keep accepting it and backs off
      when rejected, and decorrelates from fellow attackers with seeded
      per-attacker jitter (FoolsGold keys on sybil mutual similarity).

Campaign hooks live at seams the existing planes already own: the peer
round loop for observation, `faults.FaultInjector` for frame-level
actions, the churn self-kill seam (`membership.ChurnRunner` relaunches)
for identity recycling, and the trainer-delta post-processing point in
the worker flow for adaptive poison.

stdlib-only, like faults.py/admission.py: imported by the config layer.
The float arithmetic of delta shaping happens in peer.py (which owns
numpy); this module only DECIDES — scale factors, jitter seeds, targets.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from biscotti_tpu.runtime import faults

# campaign names (str constants, not an Enum: they ride into JSON traces,
# metric labels and CLI flags as-is)
ROLEFLOOD = "roleflood"
SYBIL = "sybil"
HUG = "hug"
CAMPAIGNS = (ROLEFLOOD, SYBIL, HUG)

CAMPAIGN_METRIC = "biscotti_campaign_actions_total"
CAMPAIGN_HELP = "adversary campaign decisions by campaign and action"

# bounded deterministic action log (snapshot + determinism assertions);
# live runs are short, but a long campaign must not grow memory unbounded
_SCHEDULE_CAP = 4096


def _digest_u48(*parts) -> int:
    h = hashlib.sha256("|".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:6], "big")


@dataclass(frozen=True)
class CampaignPlan:
    """Seeded adversary-campaign configuration (surfaced as
    cfg.campaign_plan). `campaign=""` disables the plane entirely — the
    seed behavior, bit-identical (no campaign objects are built, no
    counters exist, no frame is touched).

    Attacker membership mirrors the reference's poisoned-id formula
    (`parallel/sim._poisoned_ids` → tools/verdicts.poisoned_ids): the top
    `attackers` fraction of node ids, so setting `attackers` equal to
    `poison_fraction` makes the colluding set and the poisoned set the
    SAME peers — the "flood while poisoning" composition is one knob.
    `attacker_node` pins one extra id into the set (the single-attacker
    scenario, and the `chaos --flood-node miner` sentinel's flooder).
    Node 0 is never an attacker: it is the oracle anchor every harness
    measures against, exactly like the churn plane's exemption."""

    campaign: str = ""        # "" disables; roleflood | sybil | hug
    seed: int = -1            # campaign decision seed (-1: protocol seed)
    attackers: float = 0.0    # fraction of the membership, top ids
    attacker_node: int = -1   # pin this id into the attacker set (-1: none)
    # roleflood: targeted frame-replay factor — frames bound for an
    # observed target are written 1 + flood times (the admission plane's
    # flood semantics, docs/ADMISSION.md, but aimed per round)
    flood: int = 20
    # sybil: rounds between identity recycles, and rounds an attacker
    # stays down before its fresh incarnation rejoins
    recycle_period: int = 4
    recycle_down: int = 1
    # hug: initial poison blend scale, multiplicative ramp on observed
    # acceptance, back-off on rejection, clamps, and the per-attacker
    # decorrelation jitter (fraction of the observed honest step norm)
    hug_start: float = 0.25
    hug_up: float = 1.6
    hug_down: float = 0.5
    hug_max: float = 4.0
    hug_min: float = 0.05
    hug_jitter: float = 0.25

    @property
    def enabled(self) -> bool:
        return bool(self.campaign)

    def validate(self) -> None:
        if not self.enabled:
            return
        if self.campaign not in CAMPAIGNS:
            raise ValueError(
                f"campaign_plan.campaign={self.campaign!r} unknown: "
                f"pick from {CAMPAIGNS}")
        if not (0.0 <= self.attackers < 1.0):
            raise ValueError(
                f"campaign_plan.attackers={self.attackers} must be in "
                "[0, 1): it is the membership fraction drawn as attackers")
        if self.attacker_node == 0:
            raise ValueError(
                "campaign_plan.attacker_node=0 is refused: node 0 is the "
                "oracle anchor (same exemption as the churn plane)")
        if self.flood < 0:
            raise ValueError("campaign_plan.flood must be >= 0")
        if self.recycle_period < 2:
            raise ValueError("campaign_plan.recycle_period must be >= 2")
        if not (1 <= self.recycle_down < self.recycle_period):
            raise ValueError(
                "campaign_plan.recycle_down must be in "
                "[1, recycle_period): a recycled attacker has to fit its "
                "rejoin inside the window it was killed in")
        for name, v in (("hug_start", self.hug_start),
                        ("hug_up", self.hug_up),
                        ("hug_down", self.hug_down),
                        ("hug_max", self.hug_max),
                        ("hug_min", self.hug_min)):
            if v <= 0.0:
                raise ValueError(f"campaign_plan.{name} must be > 0")
        if self.hug_up < 1.0 or self.hug_down > 1.0:
            raise ValueError(
                "campaign_plan.hug_up must be >= 1 and hug_down <= 1 "
                "(ramp on acceptance, back off on rejection)")
        if not (self.hug_min <= self.hug_start <= self.hug_max):
            raise ValueError(
                "campaign_plan.hug_start must sit inside "
                "[hug_min, hug_max]")
        if self.hug_jitter < 0.0:
            raise ValueError("campaign_plan.hug_jitter must be >= 0")

    def resolve_seed(self, protocol_seed: int) -> int:
        return protocol_seed if self.seed < 0 else self.seed

    def attacker_ids(self, num_nodes: int) -> frozenset:
        """The colluding set — THE poisoned-id formula
        (tools/verdicts.poisoned_ids, one definition), so `attackers ==
        poison_fraction` makes the colluding and poisoned sets
        identical, plus the pinned id. Pure in the plan fields; node 0
        exempt (the oracle anchor)."""
        from biscotti_tpu.tools.verdicts import poisoned_ids

        out = poisoned_ids(num_nodes, self.attackers)
        if 0 < self.attacker_node < num_nodes:
            out.add(self.attacker_node)
        out.discard(0)
        return frozenset(out)

    def recycle_schedule(self, num_nodes: int, max_rounds: int,
                         protocol_seed: int = 0) -> List[faults.ChurnEvent]:
        """The sybil campaign's deterministic identity-recycling
        timeline, in the churn plane's own event vocabulary so
        `membership.ChurnRunner` (and any supervisor) replays it
        unchanged: per window w >= 1 every attacker gets a KILL at a
        hashed in-window offset and a RESTART `recycle_down` rounds
        later. Window 0 is exempt — attackers launch at genesis (an
        attacker with no history has nothing to ride). Pure in
        (resolved seed, attackers, period, down, num_nodes,
        max_rounds); pass the cluster's protocol seed so a plan left on
        `seed=-1` keys off the same seed the agents resolve."""
        if not self.enabled or self.campaign != SYBIL:
            return []
        ids = self.attacker_ids(num_nodes)
        if not ids or max_rounds <= 0:
            return []
        seed = self.resolve_seed(protocol_seed)
        period = max(2, int(self.recycle_period))
        down = max(1, int(self.recycle_down))
        events: List[faults.ChurnEvent] = []
        for w in range(1, -(-max_rounds // period)):
            start = w * period
            span = max(1, period - down)
            for node in sorted(ids):
                at = start + _digest_u48(
                    "biscotti-campaign-recycle", seed, node, w) % span
                if at >= max_rounds:
                    continue
                events.append(faults.ChurnEvent(
                    round=at, node=node, kind=faults.KILL))
                if at + down < max_rounds:
                    events.append(faults.ChurnEvent(
                        round=at + down, node=node, kind=faults.RESTART))
        events.sort(key=lambda e: (e.round, e.node, e.kind))
        return events


# ------------------------------------------------------------- strategies


class Campaign:
    """One attacker peer's strategy state. Subclasses override the hook
    methods they use; every decision they make is appended to
    `.schedule` — the deterministic (round, action, detail) log the
    layout-invariance tests compare — and counted via `_act` into both
    the in-process tally and `biscotti_campaign_actions_total`."""

    name = ""

    def __init__(self, plan: CampaignPlan, node: int, num_nodes: int,
                 seed: int):
        self.plan = plan
        self.node = node
        self.num_nodes = num_nodes
        self.seed = seed
        self.metrics = None  # telemetry.MetricsRegistry, armed by the peer
        self.counts: Dict[str, int] = {}
        self.targets_hit: Dict[int, int] = {}
        self.schedule: List[Tuple] = []
        self._targets: frozenset = frozenset()

    # ------------------------------------------------------------ tallies

    def _act(self, action: str, n: int = 1) -> None:
        self.counts[action] = self.counts.get(action, 0) + n
        if self.metrics is not None:
            self.metrics.counter(CAMPAIGN_METRIC, CAMPAIGN_HELP).inc(
                n, campaign=self.name, action=action)

    def _log(self, *entry) -> None:
        if len(self.schedule) < _SCHEDULE_CAP:
            self.schedule.append(entry)

    # -------------------------------------------------------------- hooks

    def observe_round(self, it: int, miners: Sequence[int],
                      verifiers: Sequence[int],
                      accepted_last: Optional[bool] = None) -> Dict:
        """Round-start observation: the public committee election this
        peer computed from its own chain (what any participant sees) and
        the fate of our previous submission (readable from the latest
        block). Returns a JSON-clean dict describing this round's
        decisions, traced by the peer as `campaign_round`."""
        return {}

    def observe_noisers(self, it: int, noisers: Sequence[int]) -> None:
        """The attacker's OWN private noiser draw for the round — the
        one committee it can observe beyond the public election."""

    def flood_factor(self, dst: int, msg_type: str) -> int:
        """Extra frame replays toward `dst` (consulted per outbound
        frame by faults.FaultInjector; 0 = untouched). PURE — the
        injector calls `record_flood` only for frames whose storm
        actually fires (the plan's own draw may supersede it)."""
        return 0

    def record_flood(self, dst: int) -> None:
        """One frame toward `dst` was really storm-replayed by this
        campaign (called by the injector AFTER precedence resolved)."""
        self._act("flood_frame")
        self.targets_hit[dst] = self.targets_hit.get(dst, 0) + 1

    def shape(self, it: int) -> Optional[Tuple[float, int, float]]:
        """Adaptive-poison decision for our round-`it` update:
        (blend scale, jitter seed, jitter fraction), or None to leave
        the delta untouched. The peer applies the arithmetic."""
        return None

    def kill_rounds(self, max_rounds: int) -> frozenset:
        """Rounds at which this attacker self-kills (rides the churn
        plane's self-kill seam; the launcher relaunches it)."""
        return frozenset()

    # ------------------------------------------------------------ readout

    def snapshot(self) -> Dict:
        """Structured readout under telemetry_snapshot()["campaign"] —
        `schedule` is the deterministic decision log (pure in seed +
        observed chain state), `actions`/`targets_hit` are execution
        tallies (frame counts may differ across layouts; the schedule
        must not)."""
        return {
            "campaign": self.name,
            "node": self.node,
            "actions": dict(self.counts),
            "targets_hit": {str(t): n
                            for t, n in sorted(self.targets_hit.items())},
            "schedule": [list(e) for e in self.schedule],
        }


class RoleFloodCampaign(Campaign):
    """Role-aware coordinated flood: aim the frame storm at whoever the
    VRF election just made important. Poisoning composes via
    poison_fraction (same id formula — see CampaignPlan docstring)."""

    name = ROLEFLOOD

    def observe_round(self, it, miners, verifiers, accepted_last=None):
        targets = frozenset(m for m in miners if m != self.node)
        self._targets = targets
        self._log(it, "target", sorted(targets))
        self._act("target_round")
        return {"targets": sorted(targets)}

    def observe_noisers(self, it, noisers):
        extra = frozenset(n for n in noisers if n != self.node)
        if extra - self._targets:
            self._targets = self._targets | extra
            self._log(it, "target_noisers", sorted(extra))
            self._act("target_noisers")

    def flood_factor(self, dst, msg_type):
        if self.plan.flood > 0 and dst in self._targets:
            return self.plan.flood
        return 0


class SybilCampaign(Campaign):
    """Churn-riding identity recycling: die on schedule, rejoin fresh.
    The recycle timetable is the plan's pure function; this object only
    counts/logs the kills it observes arriving (the kill itself rides
    the churn self-kill seam in the peer round loop)."""

    name = SYBIL

    def __init__(self, plan, node, num_nodes, seed):
        super().__init__(plan, node, num_nodes, seed)
        self._kills: frozenset = frozenset()

    def kill_rounds(self, max_rounds):
        # called once at agent construction with the run's horizon; the
        # cached set also feeds observe_round's recycle accounting
        self._kills = frozenset(
            e.round for e in self.plan.recycle_schedule(
                self.num_nodes, max_rounds, protocol_seed=self.seed)
            if e.node == self.node and e.kind == faults.KILL)
        return self._kills

    def observe_round(self, it, miners, verifiers, accepted_last=None):
        if it in self._kills:
            self._log(it, "recycle")
            self._act("recycle_kill")
            return {"recycle": True}
        return {}


class HugCampaign(Campaign):
    """Threshold-hugging poisoner: estimate the honest aggregate step
    from accepted blocks, blend the poisoned delta toward it, and walk
    the poison scale against the defense's observed verdicts — up while
    accepted, down when rejected — staying just under the rejection
    threshold it cannot read but can probe. Seeded per-attacker jitter
    decorrelates the colluders (FoolsGold keys on mutual similarity)."""

    name = HUG

    def __init__(self, plan, node, num_nodes, seed):
        super().__init__(plan, node, num_nodes, seed)
        self.scale = float(plan.hug_start)

    def observe_round(self, it, miners, verifiers, accepted_last=None):
        p = self.plan
        if accepted_last is True:
            self.scale = min(p.hug_max, self.scale * p.hug_up)
            self._act("hug_ramp_up")
        elif accepted_last is False:
            self.scale = max(p.hug_min, self.scale * p.hug_down)
            self._act("hug_back_off")
        else:
            self._act("hug_hold")
        self._log(it, "hug", round(self.scale, 6))
        return {"hug_scale": round(self.scale, 6)}

    def shape(self, it):
        jitter_seed = _digest_u48(
            "biscotti-campaign-hug", self.seed, self.node, it)
        return (self.scale, jitter_seed, float(self.plan.hug_jitter))

    def snapshot(self):
        out = super().snapshot()
        out["hug_scale"] = round(self.scale, 6)
        return out


_CAMPAIGN_CLASSES = {
    ROLEFLOOD: RoleFloodCampaign,
    SYBIL: SybilCampaign,
    HUG: HugCampaign,
}


def build(plan: CampaignPlan, node: int, num_nodes: int,
          protocol_seed: int) -> Optional[Campaign]:
    """The campaign strategy for `node`, or None when the plane is
    disabled or `node` is not an attacker (honest peers carry no
    campaign state at all — the disabled path allocates nothing)."""
    if not plan.enabled or node not in plan.attacker_ids(num_nodes):
        return None
    cls = _CAMPAIGN_CLASSES[plan.campaign]
    return cls(plan, node, num_nodes, plan.resolve_seed(protocol_seed))
