"""Wire data plane: negotiated per-payload array codecs.

Biscotti's cost is communication-dominated: every round gossips per-peer
deltas, noise vectors and full blocks (global weights + accepted updates)
to N peers, and the seed runtime shipped all of it as raw float64
("Secure Distributed Training at Scale" and NET-SA, PAPERS.md, both
identify exactly this traffic as the scaling bottleneck). This module is
the codec half of the fix; `messages.py` owns the frame format that
carries the coded buffers and `rpc.py` reassembles chunked frames.

Two planes, one hard invariant:

  * **Protocol plane — explicitly lossy, before commitment.**
    `WireCodec.transform()` projects a worker's delta onto the codec's
    representable set (top-k sparsification with error-feedback
    residuals, f32/bf16 grid rounding) BEFORE quantization, commitment,
    noising and share generation, and `transform_dense()` does the same
    (downcast stages only — sparsifying a global model would zero it)
    for the minted block's `global_w`. Everything cryptographic —
    Pedersen verification, Shamir recovery, block hashes — therefore
    operates on the exact values receivers will decode.
  * **Wire plane — always bit-exact.** `encode_array()` only applies a
    downcast when the array already sits on that grid (checked, not
    assumed), packs top-k output by its zero pattern (a lossless sparse
    encoding of whatever support the transform produced), and zlib is
    lossless by construction. A full-precision payload from a peer that
    never ran the transform simply falls back stage-by-stage; nothing
    is ever rounded in transit. Non-float arrays — int64 Shamir share
    rows, uint8 VSS commitment tensors, packed signatures — are never
    coded at all: crypto-bearing payloads travel verbatim.

Codec names compose with ``+`` (canonical stage order
topk → bf16/f32 → zlib): ``raw64`` (legacy identity), ``f32``/``bf16``
(downcast), ``zlib`` (lossless deflate), ``topk`` (sparsification), e.g.
``f32+zlib`` or ``topk+f32+zlib``. Support is negotiated via a
capabilities set in the `RegisterPeer` hello; senders fall back to
``raw64`` for peers that never advertised (docs/WIRE_PLANE.md).

stdlib + numpy only — no jax, no asyncio: the config layer validates
codec names through `parse_codec` and the bench estimates frame sizes
without pulling the runtime in.
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, FrozenSet, Optional, Tuple

import numpy as np

RAW = "raw64"
CHUNK_CAP = "chunk"  # capability token: peer reassembles continuation chunks

# canonical stage order: sparsify, then downcast, then compress
_STAGE_ORDER = ("topk", "bf16", "f32", "zlib")
_LOSSY = frozenset({"topk", "bf16", "f32"})

RAW_CAPS: FrozenSet[str] = frozenset({RAW})
FULL_CAPS: FrozenSet[str] = frozenset({RAW, CHUNK_CAP, *_STAGE_ORDER})

# deflate level 6: on quantized protocol payloads (update deltas and
# global weights are sums of 10^-precision-grid values) the win over
# level 1 is large (measured ~4x smaller frames on mnist_cnn blocks)
# for single-digit ms per MB — cheap against the RPC round-trips saved
ZLIB_LEVEL = 6

# compression-ratio histogram buckets (raw_bytes / wire_bytes): ratios
# live on a very different scale than the shared latency buckets
RATIO_BUCKETS: Tuple[float, ...] = (
    1.0, 1.25, 1.5, 2.0, 3.0, 5.0, 8.0, 16.0, 32.0, 64.0, 128.0, 512.0,
)

WIRE_BYTES_METRIC = "biscotti_wire_bytes_total"
WIRE_BYTES_HELP = "wire bytes by message type, direction and codec"
RATIO_METRIC = "biscotti_wire_compression_ratio"
RATIO_HELP = "raw-frame bytes over wire bytes, per codec"


class WireCodecError(ValueError):
    """Malformed codec name or corrupt coded payload."""


def parse_codec(name: str) -> Tuple[str, ...]:
    """Validate and canonicalize a codec name into its stage tuple.
    ``raw64`` (and ``""``) parse to the empty tuple. Raises
    WireCodecError on unknown stages, duplicates, or a downcast
    conflict (f32 and bf16 together)."""
    if not name or name == RAW:
        return ()
    stages = name.split("+")
    seen = set(stages)
    if len(seen) != len(stages):
        raise WireCodecError(f"duplicate stage in codec {name!r}")
    unknown = seen - set(_STAGE_ORDER) - {RAW}
    if unknown:
        raise WireCodecError(f"unknown codec stage(s) {sorted(unknown)} "
                             f"in {name!r}")
    if RAW in seen and len(seen) > 1:
        raise WireCodecError(f"{RAW} does not compose: {name!r}")
    if "f32" in seen and "bf16" in seen:
        raise WireCodecError(f"f32 and bf16 conflict in {name!r}")
    if RAW in seen:
        return ()
    return tuple(s for s in _STAGE_ORDER if s in seen)


def canonical(name: str) -> str:
    stages = parse_codec(name)
    return "+".join(stages) if stages else RAW


def capabilities(wire_codec: str) -> FrozenSet[str]:
    """The capability set a peer advertises in its `RegisterPeer` hello.
    A ``raw64``-configured peer advertises ONLY raw64 — strict legacy
    emulation, so mixed-cluster tests (and genuinely old peers, which
    send no capability set at all and default the same way) prove the
    graceful-fallback path for real."""
    if not parse_codec(wire_codec):
        return RAW_CAPS
    return FULL_CAPS


def negotiate(want: str, peer_caps) -> str:
    """The codec to use toward a peer advertising `peer_caps`: the full
    configured pipeline when every stage is supported, else ``raw64``
    (all-or-nothing — a partially-applied lossy pipeline would commit to
    values the wire then cannot carry compactly, for no meaningful win)."""
    try:
        stages = parse_codec(want)
    except WireCodecError:
        return RAW
    if not stages or not all(s in peer_caps for s in stages):
        return RAW
    return "+".join(stages)


# ------------------------------------------------------------- bf16 bits

def _bf16_bits(f32: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even truncation of f32 to bfloat16 bit patterns
    (uint16). Pure bit math — no ml_dtypes dependency."""
    u = np.ascontiguousarray(f32, dtype="<f4").view(np.uint32)
    return (((u + 0x7FFF + ((u >> 16) & 1)) >> 16) & 0xFFFF).astype("<u2")


def _bf16_to_f64(bits: np.ndarray) -> np.ndarray:
    return ((bits.astype(np.uint32) << 16).view("<f4")
            .astype(np.float64))


def _round_bf16(a64: np.ndarray) -> np.ndarray:
    return _bf16_to_f64(_bf16_bits(a64.astype("<f4")))


# --------------------------------------------------------- sparse packing

_TOPK_HDR = struct.Struct("<Q")  # entry count


def _pack_sparse(a: np.ndarray, downcast: Optional[str]) -> Optional[
        Tuple[bytes, Tuple[str, ...]]]:
    """Lossless sparse pack of a 1-D float64 array by its ZERO pattern:
    [u64 k][int32 indices][values]. Values ride downcast iff exactly
    representable. Returns None when the dense path is no bigger."""
    nz = np.flatnonzero(a)
    k = int(nz.size)
    vals = a[nz]
    tag = ["topk"]
    if downcast == "f32":
        v32 = vals.astype("<f4")
        if np.array_equal(v32.astype(np.float64), vals):
            vbuf, tag = v32.tobytes(), ["topk", "f32"]
        else:
            vbuf = vals.astype("<f8").tobytes()
    elif downcast == "bf16":
        bits = _bf16_bits(vals.astype("<f4"))
        if np.array_equal(_bf16_to_f64(bits), vals):
            vbuf, tag = bits.tobytes(), ["topk", "bf16"]
        else:
            vbuf = vals.astype("<f8").tobytes()
    else:
        vbuf = vals.astype("<f8").tobytes()
    packed = _TOPK_HDR.pack(k) + nz.astype("<i4").tobytes() + vbuf
    if len(packed) >= a.nbytes:
        return None
    return packed, tuple(tag)


def _unpack_sparse(raw: bytes, n: int, tag_stages: Tuple[str, ...],
                   shape: Tuple[int, ...]) -> np.ndarray:
    if len(raw) < _TOPK_HDR.size:
        raise WireCodecError("sparse payload truncated")
    (k,) = _TOPK_HDR.unpack(raw[: _TOPK_HDR.size])
    if k > n:
        raise WireCodecError("sparse entry count exceeds array size")
    vsize = 4 if "f32" in tag_stages else 2 if "bf16" in tag_stages else 8
    expect = _TOPK_HDR.size + k * (4 + vsize)
    if len(raw) != expect:
        raise WireCodecError("sparse payload length mismatch")
    idx = np.frombuffer(raw, "<i4", count=k, offset=_TOPK_HDR.size)
    if k and (int(idx.min()) < 0 or int(idx.max()) >= n
              or np.any(np.diff(idx) <= 0)):
        raise WireCodecError("sparse indices out of range or unsorted")
    voff = _TOPK_HDR.size + 4 * k
    if "f32" in tag_stages:
        vals = np.frombuffer(raw, "<f4", count=k,
                             offset=voff).astype(np.float64)
    elif "bf16" in tag_stages:
        vals = _bf16_to_f64(np.frombuffer(raw, "<u2", count=k, offset=voff))
    else:
        vals = np.frombuffer(raw, "<f8", count=k, offset=voff)
    out = np.zeros(n, dtype=np.float64)
    out[idx] = vals
    return out.reshape(shape)


# --------------------------------------------------------------- pipeline

class WireCodec:
    """One parsed codec pipeline. Stateless and shareable: error-feedback
    residuals are the CALLER's per-peer state (`transform` takes and
    returns them) so one registry instance serves every connection."""

    def __init__(self, name: str):
        self.stages = parse_codec(name)
        self.name = "+".join(self.stages) if self.stages else RAW
        self.lossy = any(s in _LOSSY for s in self.stages)
        self.sparsify = "topk" in self.stages
        self.downcast = ("f32" if "f32" in self.stages
                         else "bf16" if "bf16" in self.stages else None)
        self.compress = "zlib" in self.stages

    # ------------------------------------------------- protocol plane

    def transform(self, arr, residual: Optional[np.ndarray] = None,
                  topk_k: int = 0) -> Tuple[np.ndarray,
                                            Optional[np.ndarray]]:
        """Lossy projection of a delta onto this codec's representable
        set, applied BEFORE commitment/noising/sharing. Returns
        (projected float64 array, new error-feedback residual). The
        residual accumulates what top-k dropped (plus the downcast
        error of the kept entries) and is added back into the next
        round's delta, so sparsification error feeds forward instead of
        vanishing (the SGD-with-error-feedback construction the
        compressed-training literature relies on, PAPERS.md). Identity
        for lossless codecs. Idempotent: transform(transform(x)) ==
        transform(x) when the residual is not threaded back in."""
        a = np.ascontiguousarray(np.asarray(arr, dtype=np.float64))
        if not self.lossy:
            return a, residual
        v = a
        if self.sparsify and residual is not None and residual.shape == a.shape:
            v = a + residual
        out = v
        if self.sparsify and 0 < topk_k < v.size:
            keep = np.argpartition(np.abs(v), v.size - topk_k)[-topk_k:]
            out = np.zeros_like(v)
            out[keep] = v[keep]
        if self.downcast == "f32":
            out = out.astype(np.float32).astype(np.float64)
        elif self.downcast == "bf16":
            out = _round_bf16(out)
        new_residual = (v - out) if self.sparsify else residual
        return out, new_residual

    def transform_dense(self, arr) -> np.ndarray:
        """Downcast-only projection for payloads that must stay dense —
        the minted block's `global_w` (sparsifying the global model
        would zero most of it). Rounding the mint onto the downcast
        grid is what makes the wire downcast exact for block gossip,
        so the sealed hash verifies on every receiver."""
        a = np.ascontiguousarray(np.asarray(arr, dtype=np.float64))
        if self.downcast == "f32":
            return a.astype(np.float32).astype(np.float64)
        if self.downcast == "bf16":
            return _round_bf16(a)
        return a

    # ----------------------------------------------------- wire plane

    def encode_array(self, arr: np.ndarray) -> Optional[Tuple[bytes, str]]:
        """Bit-exact wire encoding of one array, or None to send raw.
        Float arrays only (crypto payloads are ints/bytes and must
        travel verbatim); each stage is applied only when exact and
        only while it actually shrinks the payload. Returns
        (payload bytes, applied-stage tag)."""
        if not self.stages or arr.dtype.kind != "f" or arr.size == 0:
            return None
        a = np.ascontiguousarray(arr)
        applied: Tuple[str, ...] = ()
        buf: Optional[bytes] = None
        if self.sparsify and a.ndim == 1 and a.dtype == np.float64:
            sp = _pack_sparse(a, self.downcast)
            if sp is not None:
                buf, applied = sp
        if buf is None:
            if self.downcast and a.dtype == np.float64:
                if self.downcast == "f32":
                    d32 = a.astype("<f4")
                    if np.array_equal(d32.astype(np.float64), a):
                        buf, applied = d32.tobytes(), ("f32",)
                else:
                    bits = _bf16_bits(a.astype("<f4"))
                    if np.array_equal(_bf16_to_f64(bits).reshape(a.shape), a):
                        buf, applied = bits.tobytes(), ("bf16",)
            if buf is None:
                buf = a.tobytes()
        if self.compress:
            z = zlib.compress(buf, ZLIB_LEVEL)
            if len(z) < len(buf):
                buf, applied = z, applied + ("zlib",)
        if not applied or len(buf) >= a.nbytes:
            return None
        return buf, "+".join(applied)


def decode_array(buf, dtype: str, shape: Tuple[int, ...],
                 tag: str) -> np.ndarray:
    """Decode one coded payload back to its declared (dtype, shape).
    `tag` is the per-array applied-stage tag from the frame header;
    hostile tags/payloads raise WireCodecError, never crash. The
    decompression-bomb cap: the inflate is bounded by what the declared
    shape can possibly need (the caller additionally bounds the summed
    declared sizes by MAX_FRAME), so a kilobyte frame cannot be made to
    materialize gigabytes."""
    stages = parse_codec(tag)
    if not stages:
        raise WireCodecError(f"empty codec tag {tag!r}")
    n = 1
    for s in shape:
        n *= int(s)
    out_dtype = np.dtype(dtype)
    data = bytes(buf)
    if "zlib" in stages:
        # worst legitimate inflated size: the sparse pack of a full-
        # support array (8 + n*(4+8)) or the dense buffer (n*itemsize)
        cap = max(n * out_dtype.itemsize, 12 * n + _TOPK_HDR.size)
        d = zlib.decompressobj()
        try:
            data = d.decompress(data, cap + 1)
        except zlib.error as e:
            raise WireCodecError(f"bad zlib stream: {e}") from e
        if len(data) > cap:
            raise WireCodecError("zlib payload inflates past declared size")
        if not d.eof or d.unconsumed_tail or d.unused_data:
            raise WireCodecError("trailing or truncated zlib stream")
    if "topk" in stages:
        if out_dtype != np.float64:
            raise WireCodecError("sparse payloads decode to float64 only")
        return _unpack_sparse(data, n, stages, tuple(int(s) for s in shape))
    if "f32" in stages or "bf16" in stages:
        enc = np.dtype("<f4") if "f32" in stages else np.dtype("<u2")
        if len(data) != n * enc.itemsize:
            raise WireCodecError("downcast payload length mismatch")
        flat = np.frombuffer(data, enc, count=n)
        out = (_bf16_to_f64(flat) if "bf16" in stages
               else flat.astype(np.float64))
        return out.reshape(shape).astype(out_dtype, copy=False)
    # zlib-only: data is the raw little-endian dense buffer
    if len(data) != n * out_dtype.itemsize:
        raise WireCodecError("decompressed payload length mismatch")
    return np.frombuffer(data, out_dtype.newbyteorder("<"),
                         count=n).reshape(shape)


_REGISTRY: Dict[str, WireCodec] = {}


def get(name: str) -> WireCodec:
    """Registry accessor: one shared WireCodec per canonical name.
    Raises WireCodecError on malformed names (config validation calls
    through here, so a typo'd --wire-codec fails at startup)."""
    key = canonical(name)
    wc = _REGISTRY.get(key)
    if wc is None:
        wc = _REGISTRY[key] = WireCodec(key)
    return wc


def observe_ratio(registry, codec: str, raw_bytes: int,
                  wire_bytes: int) -> None:
    """Feed the shared compression-ratio histogram (one definition for
    the RPC pool and the broadcast path in peer.py)."""
    if registry is None or codec == RAW or raw_bytes <= 0 or wire_bytes <= 0:
        return
    registry.histogram(RATIO_METRIC, RATIO_HELP,
                       buckets=RATIO_BUCKETS).observe(
        raw_bytes / wire_bytes, codec=codec)
