"""Peers-as-devices deployment mode — the data plane on the mesh, the
control plane in the runtime (SURVEY §7.1's "same round logic, two
launchers", §5.8's integration of the two planes).

The plain in-process cluster runs N peer agents whose SGD steps each
dispatch their own XLA call. Here ONE sharded XLA program computes EVERY
local peer's delta per round — `shard_map` over a `Mesh` peer axis, each
device holding its peers' shards — while the agents keep speaking the
full protocol (verifier committees, VSS shares, block gossip, stake).
Device peers therefore mint REAL blocks through the runtime; the
reference's closest analogue is 5 OS processes per VM with no sharing at
all (ref: azure/azure-run/runBiscotti.sh nodesInEachVM).

    stepper = BatchStepper(cfg, mesh)           # one per host process
    agents  = [PeerAgent(cfg_i, stepper=stepper) for i in local_ids]

The stepper computes all N deltas at a round's FIRST request (one sharded
dispatch; one all-gather back to host) and serves every other agent from
that batch — peers advance in protocol lockstep, so the batch hit rate is
the worker count.

Launcher CLI (the "second launcher"):
    python -m biscotti_tpu.runtime.device_cluster -t 8 -d mnist \
        --iterations 3   # mesh over all visible devices
"""

from __future__ import annotations

import asyncio
import math
from typing import Dict, Optional

import numpy as np


async def single_flight_memo(cache: Dict, pending: Dict, key, compute):
    """Single-flight async memo shared by the batched device planes
    (BatchStepper here, hive.HiveStepper): the first caller computes
    off-loop, every concurrent waiter receives the VALUE from the future
    itself (never a post-await cache re-read — another peer far enough
    ahead may evict the key between set_result and a waiter resuming),
    and a failed compute raises in every caller. Returns
    (value, computed_here)."""
    if key in cache:
        return cache[key], False
    if key in pending:
        return await pending[key], False
    fut = asyncio.get_running_loop().create_future()
    pending[key] = fut
    try:
        val = await asyncio.to_thread(compute)
    except BaseException as e:
        fut.set_exception(e)
        fut.exception()  # mark retrieved if nobody is waiting
        del pending[key]
        raise
    cache[key] = val
    fut.set_result(val)
    del pending[key]
    return val, True


class BatchStepper:
    """Round-batched sharded SGD: all peers' deltas in one XLA call.

    Thread-compatible with the asyncio agents: `step()` is async and the
    underlying sharded dispatch runs in a worker thread. Per-iteration
    batches are cached (keyed by iteration) and evicted once consumed, so
    memory stays at O(batches_in_flight · N · d)."""

    def __init__(self, cfg, mesh, axis: str = "peers"):
        import jax
        import jax.numpy as jnp
        from biscotti_tpu.utils.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from biscotti_tpu.data import datasets as ds
        from biscotti_tpu.models.trainer import local_step_fn, sample_batch
        from biscotti_tpu.models.zoo import model_for_dataset
        from biscotti_tpu.parallel.sim import _poisoned_ids

        self.cfg = cfg
        self.axis = axis
        self.mesh = mesh
        n = cfg.num_nodes
        n_dev = math.prod(mesh.devices.shape)
        if n % n_dev != 0:
            raise ValueError(f"num_nodes {n} must divide over {n_dev} devices")

        model = model_for_dataset(cfg.dataset,
                                  getattr(cfg, "model_name", ""))
        self.num_params = model.num_params
        mode = "sgd" if model.name == "logreg" else "grad"
        step = local_step_fn(model, mode, clip=cfg.grad_clip,
                             alpha=cfg.logreg_alpha)

        poisoned = _poisoned_ids(n, cfg.poison_fraction)
        xs, ys = [], []
        for i in range(n):
            shard = ds.load_shard(cfg.dataset,
                                  ds.shard_name(cfg.dataset, i, i in poisoned))
            xs.append(shard["x_train"])
            ys.append(shard["y_train"])
        rows = min(len(x) for x in xs)
        x_all = jnp.asarray(np.stack([x[:rows] for x in xs]))
        y_all = jnp.asarray(np.stack([y[:rows] for y in ys]))
        root = jax.random.PRNGKey(cfg.seed)
        batch = min(cfg.batch_size, rows)

        def local_deltas(w, x_loc, y_loc, it):
            pid = jax.lax.axis_index(axis)
            n_loc = x_loc.shape[0]
            gids = pid * n_loc + jnp.arange(n_loc)
            bkey = jax.random.fold_in(root, it)

            def one(gid, xi, yi):
                k = jax.random.fold_in(bkey, gid)
                idx = sample_batch(k, rows, batch)
                return step(w, xi[idx], yi[idx])

            return jax.vmap(one)(gids, x_loc, y_loc)

        mapped = shard_map(
            local_deltas, mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P()),
            out_specs=P(axis), check_vma=False,
        )
        self._step = jax.jit(mapped)
        sharding = NamedSharding(mesh, P(axis))
        self._x = jax.device_put(x_all, sharding)
        self._y = jax.device_put(y_all, sharding)

        self._cache: Dict[int, np.ndarray] = {}
        self._pending: Dict[int, asyncio.Future] = {}
        self._served: Dict[int, int] = {}
        self.batches = 0  # sharded dispatch count (observability/tests)

        # shared convergence metric: every peer scores the SAME model on the
        # SAME global test split each round (peer.py's uniform-convergence
        # requirement), so one evaluation serves the whole cluster. Keyed on
        # (iteration, weight digest) — transiently divergent chains compute
        # their own value, identical chains share one.
        test = ds.load_shard(cfg.dataset, f"{cfg.dataset}_test")
        self._x_test = jnp.asarray(test["x_test"])
        self._y_test = jnp.asarray(test["y_test"])
        self._err_fn = jax.jit(model.error_flat)
        self._eval_cache: Dict[tuple, float] = {}
        self._eval_pending: Dict[tuple, asyncio.Future] = {}
        self.evals = 0  # distinct metric computations (observability/tests)

    async def _memo(self, cache: Dict, pending: Dict, key, compute):
        return await single_flight_memo(cache, pending, key, compute)

    async def step(self, peer_id: int, w: np.ndarray, it: int) -> np.ndarray:
        """This peer's delta for iteration `it`; the first caller computes
        the whole batch on the mesh."""
        import jax.numpy as jnp

        def compute():
            return np.asarray(
                self._step(jnp.asarray(w, jnp.float32), self._x, self._y,
                           it), dtype=np.float64)

        deltas, computed = await self._memo(self._cache, self._pending, it,
                                            compute)
        if computed:
            self.batches += 1
        delta = deltas[peer_id]
        self._served[it] = self._served.get(it, 0) + 1
        if self._served[it] >= self.cfg.num_nodes:
            self._cache.pop(it, None)  # everyone served: evict
        # keep at most a few rounds resident regardless of stragglers
        for old in [k for k in self._cache if k < it - 3]:
            self._cache.pop(old, None)
        return delta

    async def test_error(self, w: np.ndarray, it: int) -> float:
        """Global-test-split error of `w` — computed once per distinct
        (iteration, weights) across the cluster; all other peers are served
        from the memo (they evaluate identical inputs, see __init__)."""
        import hashlib

        import jax.numpy as jnp

        wb = np.ascontiguousarray(w)
        key = (it, hashlib.sha1(wb.tobytes()).hexdigest())

        def compute():
            return float(self._err_fn(jnp.asarray(wb, jnp.float32),
                                      self._x_test, self._y_test))

        err, computed = await self._memo(self._eval_cache,
                                         self._eval_pending, key, compute)
        if computed:
            self.evals += 1
        for old in [k for k in self._eval_cache if k[0] < it - 3]:
            self._eval_cache.pop(old, None)
        return err


async def run_cluster(cfg_base, mesh, iterations: int, log_dir: str = ""):
    """Boot N agents sharing one BatchStepper; returns
    (stepper, agents, results)."""
    import os

    from biscotti_tpu.runtime.peer import PeerAgent

    stepper = BatchStepper(cfg_base, mesh)
    agents = []
    for i in range(cfg_base.num_nodes):
        cfg = cfg_base.replace(node_id=i, max_iterations=iterations)
        agents.append(PeerAgent(
            cfg, stepper=stepper,
            log_path=os.path.join(log_dir, f"events_{i}.jsonl")
            if log_dir else ""))
    results = await asyncio.gather(*(a.run() for a in agents))
    return stepper, agents, results


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="peers-as-devices cluster launcher (SURVEY §7.1)")
    from biscotti_tpu.config import BiscottiConfig

    BiscottiConfig.add_args(ap)
    ap.add_argument("--iterations", type=int, default=3)
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu) — site hooks may "
                         "otherwise pin the default to an accelerator")
    ns = ap.parse_args(argv)
    import os

    if ns.platform:
        os.environ["JAX_PLATFORMS"] = ns.platform
    import jax

    if ns.platform:
        jax.config.update("jax_platforms", ns.platform)
    jax.config.update("jax_enable_x64", True)
    cfg = BiscottiConfig.from_args(ns)

    devices = np.array(jax.devices())
    mesh = jax.sharding.Mesh(devices, ("peers",))
    stepper, agents, results = asyncio.run(
        run_cluster(cfg, mesh, ns.iterations))
    dumps = [r["chain_dump"] for r in results]
    summary = {
        "mode": "peers-as-devices",
        "devices": len(devices),
        "nodes": cfg.num_nodes,
        "sharded_batches": stepper.batches,
        "chains_equal": all(d == dumps[0] for d in dumps),
        "blocks": len(dumps[0].splitlines()) - 1,
    }
    print(json.dumps(summary))
    return 0 if summary["chains_equal"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
