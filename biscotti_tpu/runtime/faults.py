"""Deterministic fault-injection plane + peer-health robustness primitives.

The reference tests robustness only from the OUTSIDE — shell scripts that
`fuser -k` random nodes and open 30 s iptables DROP windows (ref:
DistSys/failAndRestartLocal.sh, blockNode.sh; see tests/test_fault_injection
docstring). Partial faults — a dropped frame, a slow link, duplicated
gossip, a mid-round connection reset — were untested and unreproducible.
This module turns those ad-hoc crash scripts into a seeded chaos plane:

  * `FaultPlan` — a pure function of (seed, src, dst, msg_type, attempt)
    deciding drop / delay / duplicate / reset for every frame the RPC pool
    writes. Same seed ⇒ byte-identical fault schedule, so any chaos run is
    replayable (the determinism contract, docs/FAULT_PLANE.md).
  * `FaultInjector` — a FaultPlan bound to one agent (src id + address→peer
    resolution), tallying and optionally recording every decision so tests
    and artifacts can assert on the schedule itself.
  * `backoff_schedule` — exponential backoff with decorrelated jitter
    (retry sleeps for PeerAgent._call); seeded rng ⇒ reproducible schedule.
  * `HealthLedger` — per-peer consecutive-failure circuit breaker with
    half-open probing, so gossip fan-out and committee RPCs skip dead
    peers instead of burning the round budget re-timing-out on them
    (the retry-with-backoff + peer-health design argued for by Garfield
    [arXiv:2010.05888] and "Secure Distributed Training at Scale"
    [arXiv:2106.11257] — fault tolerance in the communication layer).

Injection happens at the sender's `_Conn` boundary (rpc.Pool), so real TCP
loopback traffic is perturbed — delayed and duplicated frames actually
cross the wire; dropped frames die before the socket exactly as a lossy
network would eat them; resets tear the shared multiplexed connection down
mid-flight. From the caller's perspective a dropped request and a dropped
reply are the same event (a timeout), so sender-side injection covers both
directions of the frame exchange.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# Circuit-breaker states (str constants, not an Enum: they ride into JSON
# trace events and health snapshots as-is)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpenError(ConnectionError):
    """Fast-fail raised instead of dialing a quarantined peer. Subclasses
    ConnectionError so every existing transport-failure except-clause
    (eviction, gather fan-outs, _safe_call) handles it unchanged."""


class ChurnExit(Exception):
    """A peer's own churn schedule told it to die this round (the
    `--fault-churn` self-kill, docs/MEMBERSHIP.md). Raised out of the
    round loop and caught in PeerAgent.run() as a CLEAN early exit — no
    crash dump, sockets released synchronously — so an external launcher
    (tools/chaos --churn, runtime/membership.ChurnRunner, a k8s restart
    policy) can relaunch the process at the scheduled restart round."""

    def __init__(self, round_: int):
        super().__init__(f"churn schedule kill at round {round_}")
        self.round = round_


@dataclass(frozen=True)
class FaultAction:
    """One frame's fate. Precedence when several faults draw true:
    reset > drop > (delay, duplicate, flood) — a reset connection can
    deliver nothing, a dropped frame cannot also arrive twice."""

    drop: bool = False
    duplicate: bool = False
    reset: bool = False
    delay_s: float = 0.0
    # frame-storm replay count (the flood fault kind, docs/ADMISSION.md):
    # the frame is written 1 + flood times back-to-back, turning this
    # peer into a deterministic flooder — the adversary the admission
    # plane's shedding is tested against
    flood: int = 0

    @property
    def benign(self) -> bool:
        return not (self.drop or self.duplicate or self.reset
                    or self.delay_s > 0.0 or self.flood > 0)

    def kind(self) -> str:
        """Compact label for tallies/logs."""
        if self.reset:
            return "reset"
        if self.drop:
            return "drop"
        if self.flood > 0:
            return "flood"
        if self.duplicate and self.delay_s > 0:
            return "delay+dup"
        if self.duplicate:
            return "dup"
        if self.delay_s > 0:
            return "delay"
        return "none"


_BENIGN = FaultAction()


@dataclass(frozen=True)
class ChurnEvent:
    """One scheduled membership change: at the start of `round`, `node` is
    killed / restarted / first launched. Emitted by FaultPlan.churn_schedule
    — a pure function of the seed, so any churn run's exact join/leave
    timeline replays from the flags alone (docs/MEMBERSHIP.md)."""

    round: int
    node: int
    kind: str  # KILL | RESTART | JOIN | MIGRATE


KILL = "kill"
RESTART = "restart"
JOIN = "join"
# live migration (runtime/placement.py, docs/PLACEMENT.md): the runner
# captures a serialized ticket BEFORE the kill and relaunches the fresh
# incarnation from it — unlike RESTART, state survives the move
MIGRATE = "migrate"


@dataclass(frozen=True)
class SlowProfile:
    """One peer's speed profile under the `slow` fault kind
    (docs/STRAGGLERS.md): `compute_factor` multiplies the wall-clock of
    the peer's heavy compute paths (Trainer/stepper SGD step, worker
    commitment/share generation, miner intake crypto — emulated by
    padding each measured segment to factor× its duration), and
    `service_s` is an extra per-RPC service delay the peer's handler
    seam charges every inbound request (applied identically by the TCP
    server dispatch and the hive loopback dispatch, so TCP and
    co-hosted layouts see the same schedule)."""

    compute_factor: float = 1.0
    service_s: float = 0.0
    preset: str = ""

    @property
    def slowed(self) -> bool:
        return self.compute_factor > 1.0 or self.service_s > 0.0


NO_SLOW = SlowProfile()

# Named speed-profile presets for the drawn slow subset (docs/STRAGGLERS.md):
#   tee      — confidential-compute peer, calibrated from "Characterization
#              of GPU TEE Overheads" (arXiv:2501.11771): kernel compute in
#              TEE mode is near-native (<10%), but encrypted CPU↔GPU bounce
#              transfers dominate transfer-bound workloads — and a
#              federated round ships the full model both ways every
#              iteration, exactly that regime. 4× compute (the paper's
#              transfer-dominated small-batch penalty band) + 20 ms
#              per-RPC service latency (encrypted-channel setup per
#              request).
#   bimodal  — half the drawn peers mildly slow (2×), half badly (8×):
#              the two-cluster fleet (e.g. one old GPU generation).
#   longtail — severity v^-0.7 capped at 16×: most drawn peers are
#              modestly slow, a few are severe (the volunteer-fleet tail).
SLOW_PRESETS = ("tee", "bimodal", "longtail")


@dataclass(frozen=True)
class FaultPlan:
    """Seeded link-fault configuration (surfaced as cfg.fault_plan).

    Every probability is per-frame and independent: `action()` is a pure
    function of (seed, src, dst, msg_type, attempt, seq) — no shared RNG
    state, so concurrent tasks and process restarts all see the same
    schedule. `seq` is the per-(dst, msg_type) frame ordinal maintained by
    the FaultInjector: without it, every RegisterBlock gossip post on one
    link (always attempt 0) would share ONE draw and a 10% drop plan would
    blackhole ~10% of LINKS for the whole run instead of dropping ~10% of
    FRAMES on every link. Retries land on a new attempt number (and a new
    seq) and therefore a fresh draw: a retried frame is not doomed to
    re-lose forever.
    """

    seed: int = 0
    drop: float = 0.0       # P(frame silently lost before the socket)
    delay: float = 0.0      # P(frame delayed before the write)
    delay_s: float = 0.05   # max per-frame delay; actual in [½·delay_s, delay_s]
    duplicate: float = 0.0  # P(frame written twice back-to-back)
    reset: float = 0.0      # P(connection torn down instead of writing)
    # flood replay factor: every outbound frame is written 1 + flood
    # times, so an armed peer sustains (1 + flood)× the honest frame rate
    # toward every target — the deterministic frame storm the admission
    # plane's shedding is asserted against (docs/ADMISSION.md). Applied
    # to every frame (no draw needed: replay count is the knob), except
    # frames that reset or drop first.
    flood: int = 0
    # churn: fraction of the membership killed-and-restarted per
    # `churn_period` rounds (0 disables). The schedule — which node goes
    # down at which round, when it comes back, and which nodes JOIN late
    # instead of launching at genesis — is a pure function of the seed
    # (churn_schedule below), so a churn run is replayable exactly like a
    # drop/delay/flood run. Node 0 is never churned: it is the anchor the
    # oracle (and a real deployment's bootstrap list) measures against.
    churn: float = 0.0
    churn_period: int = 10  # rounds per churn window (ISSUE: 20% per 10)
    churn_down: int = 3     # rounds a killed peer stays down
    # membership-timeline seed override (-1: use `seed`). Lets a churn
    # ablation vary the join/leave schedule while the frame-fault
    # schedule (drop/delay/dup/reset/flood, keyed on `seed`) stays
    # fixed — chaos `--churn-seed` rides this, never a plan reseed.
    churn_seed: int = -1
    # slow: fraction of the membership assigned a heterogeneous speed
    # profile (0 disables) — the straggler fault kind
    # (docs/STRAGGLERS.md). Which peers are slow, and how slow, is a
    # pure function of (seed, node) via slow_profile() below, so a
    # straggler run replays from the flags exactly like drop/flood/
    # churn — and because the profile is consulted by the PEER (compute
    # pads + handler service delay), TCP and hive-loopback layouts see
    # the identical schedule by construction. Unlike churn, node 0 is
    # drawable: a slow peer still participates honestly.
    slow: float = 0.0
    slow_factor: float = 4.0   # compute-slowdown multiple for drawn peers
    slow_service_s: float = 0.0  # extra per-RPC service delay for them
    # named preset overriding (slow_factor, slow_service_s) for the
    # drawn subset: "tee" | "bimodal" | "longtail" (see SLOW_PRESETS)
    slow_preset: str = ""
    # pin this node slow regardless of the fraction draw (-1: none) —
    # the deterministic single-straggler scenario (chaos --slow-node)
    slow_node: int = -1

    @property
    def enabled(self) -> bool:
        """Frame-level injection armed? (Churn is NOT a frame fault: it is
        consumed by the launch harness / the peer's own round loop, so a
        churn-only plan does not pay the per-frame draw.)"""
        return (self.drop > 0.0 or self.delay > 0.0 or self.duplicate > 0.0
                or self.reset > 0.0 or self.flood > 0)

    @property
    def churn_enabled(self) -> bool:
        return self.churn > 0.0

    @property
    def slow_enabled(self) -> bool:
        """Heterogeneous speed profiles armed? (Not a frame fault: the
        profile is consumed by the peer's compute pads and handler seam,
        so a slow-only plan does not pay the per-frame draw.)"""
        return self.slow > 0.0 or self.slow_node >= 0

    def slow_profile(self, node: int, num_nodes: int) -> SlowProfile:
        """The deterministic speed profile of `node` — pure in
        (seed, node), so every peer (and every harness) derives the same
        fleet table from the flags alone. Membership draw and severity
        draw are carved from one digest; `slow_node` pins its node into
        the slow set regardless of the fraction."""
        if not self.slow_enabled or not (0 <= node < num_nodes):
            return NO_SLOW
        h = hashlib.sha256(
            f"biscotti-slow|{self.seed}|{node}".encode()).digest()
        u = int.from_bytes(h[:6], "big") / float(1 << 48)
        if node != self.slow_node and u >= self.slow:
            return NO_SLOW
        v = int.from_bytes(h[6:12], "big") / float(1 << 48)
        preset = self.slow_preset
        if preset == "tee":
            factor, service = 4.0, 0.02
        elif preset == "bimodal":
            factor, service = (2.0 if v < 0.5 else 8.0), 0.01
        elif preset == "longtail":
            factor = min(16.0, max(1.0, max(v, 1e-12) ** -0.7))
            service = 0.01
        elif preset:
            raise ValueError(
                f"unknown slow_preset {preset!r}: pick from {SLOW_PRESETS}")
        else:
            factor = max(1.0, float(self.slow_factor))
            service = max(0.0, float(self.slow_service_s))
        return SlowProfile(compute_factor=factor, service_s=service,
                           preset=preset)

    def slow_table(self, num_nodes: int) -> Dict[int, SlowProfile]:
        """Every slowed node's profile — the fleet table chaos reports
        and the obs 'slowest peers' view render."""
        out: Dict[int, SlowProfile] = {}
        for n in range(num_nodes):
            p = self.slow_profile(n, num_nodes)
            if p.slowed:
                out[n] = p
        return out

    def churn_schedule(self, num_nodes: int,
                       max_rounds: int) -> List[ChurnEvent]:
        """Deterministic membership timeline: per `churn_period` window,
        ~`churn`·num_nodes victims are drawn by seeded hash; each victim
        gets a KILL at a hashed offset inside the window and a RESTART
        `churn_down` rounds later (when that still fits the run). A
        window-0 victim instead becomes a late JOINER: it is not launched
        at genesis and JOINs at its drawn round — so one knob exercises
        join, leave, AND rejoin. Events are sorted by (round, node); node
        0 is exempt (the anchor). Same (seed, churn, period, down,
        num_nodes, max_rounds) ⇒ the identical list, always."""
        if not self.churn_enabled or num_nodes <= 1 or max_rounds <= 0:
            return []
        seed = self.seed if self.churn_seed < 0 else self.churn_seed
        period = max(1, int(self.churn_period))
        down = max(1, int(self.churn_down))
        events: List[ChurnEvent] = []
        for w in range(-(-max_rounds // period)):
            start = w * period
            for node in range(1, num_nodes):
                h = hashlib.sha256(
                    f"biscotti-churn|{seed}|{node}|{w}".encode()
                ).digest()
                u = int.from_bytes(h[:6], "big") / float(1 << 48)
                if u >= self.churn:
                    continue
                # drawn offset keeps the kill early enough in the window
                # that the restart (kill + down) lands inside the run for
                # every full window
                span = max(1, period - down)
                at = start + int.from_bytes(h[6:12], "big") % span
                if at >= max_rounds:
                    continue
                if w == 0:
                    # late joiner: skip genesis launch, join at the drawn
                    # round (at=0 degenerates to a genesis launch — skip)
                    if at > 0:
                        events.append(ChurnEvent(round=at, node=node,
                                                 kind=JOIN))
                    continue
                events.append(ChurnEvent(round=at, node=node, kind=KILL))
                if at + down < max_rounds:
                    events.append(ChurnEvent(round=at + down, node=node,
                                             kind=RESTART))
        events.sort(key=lambda e: (e.round, e.node, e.kind))
        return events

    def action(self, src: int, dst: int, msg_type: str,
               attempt: int = 0, seq: int = 0) -> FaultAction:
        """The deterministic fate of one (src→dst, msg_type, attempt, seq)
        frame."""
        if not self.enabled:
            return _BENIGN
        h = hashlib.sha256(
            f"biscotti-fault|{self.seed}|{src}|{dst}|{msg_type}|{attempt}"
            f"|{seq}".encode()).digest()
        # five independent uniforms in [0,1) carved from one digest
        u = [int.from_bytes(h[6 * i: 6 * i + 6], "big") / float(1 << 48)
             for i in range(5)]
        if u[0] < self.reset:
            return FaultAction(reset=True)
        if u[1] < self.drop:
            return FaultAction(drop=True)
        dup = u[2] < self.duplicate
        d = 0.0
        if u[3] < self.delay:
            d = self.delay_s * (0.5 + 0.5 * u[4])
        if not dup and d == 0.0 and self.flood <= 0:
            return _BENIGN
        return FaultAction(duplicate=dup, delay_s=d,
                           flood=max(0, int(self.flood)))


class FaultInjector:
    """A FaultPlan bound to one agent: resolves the pool's (host, port)
    targets back to peer ids and tallies every non-benign decision.
    Attach to `rpc.Pool.faults`; the pool consults it per frame.

    Maintains the per-(dst, msg_type) frame ordinal `seq` that keys each
    frame's draw (see FaultPlan.action): repeated frames of the same type
    on one link — block gossip round after round — each get their own
    independent fate.

    With `record=True` every decision (including benign ones) is appended
    to `.log` as (dst, msg_type, attempt, seq, kind) so a test can replay
    the exact schedule against a fresh plan and assert reproducibility."""

    def __init__(self, plan: FaultPlan, src: int,
                 peer_of: Callable[[str, int], Optional[int]],
                 record: bool = False):
        self.plan = plan
        self.src = src
        self._peer_of = peer_of
        self._seq: Dict[Tuple[int, str], int] = {}
        self.counts: Dict[str, int] = {}
        # optional adversary campaign (runtime/adversary.py): consulted
        # per frame for TARGETED extra replays (the role-aware flood) on
        # top of the plan's static draw — the campaign plane's one
        # frame-level seam, so chaos schedules stay layout-invariant
        # (the draw happens before any loopback shortcut, like every
        # other fault kind)
        self.campaign = None
        # optional telemetry registry (telemetry.MetricsRegistry): armed
        # by the peer agent so injected-fault tallies ride the same
        # scrapeable plane as everything else; `counts` stays as the
        # in-process back-compat view
        self.metrics = None
        self.log: Optional[List[Tuple[int, str, int, int, str]]] = \
            [] if record else None

    def action(self, host: str, port: int, msg_type: str,
               attempt: int = 0) -> FaultAction:
        dst = self._peer_of(host, port)
        if dst is None or dst == self.src:
            return _BENIGN  # unknown target / self-loop: never perturbed
        key = (dst, msg_type)
        seq = self._seq.get(key, 0)
        self._seq[key] = seq + 1
        act = self.plan.action(self.src, dst, msg_type, attempt, seq)
        if self.campaign is not None and not (act.reset or act.drop):
            # role-aware targeted flood: the campaign names this round's
            # targets from the election it observed; a frame bound for
            # one of them is replayed like the static flood kind, same
            # precedence (a reset/dropped frame cannot also storm). The
            # campaign's tallies count only storms that actually FIRE —
            # a plan-level flood >= the campaign's supersedes it
            extra = self.campaign.flood_factor(dst, msg_type)
            if extra > act.flood:
                act = FaultAction(duplicate=act.duplicate,
                                  delay_s=act.delay_s, flood=extra)
                self.campaign.record_flood(dst)
        kind = act.kind()
        if kind != "none":
            self.counts[kind] = self.counts.get(kind, 0) + 1
            if self.metrics is not None:
                self.metrics.counter(
                    "biscotti_faults_injected_total",
                    "frames perturbed by the seeded fault plane").inc(
                    kind=kind, msg_type=msg_type)
        if self.log is not None:
            self.log.append((dst, msg_type, attempt, seq, kind))
        return act


def backoff_schedule(rng, base_s: float, cap_s: float):
    """Generator of retry sleeps: exponential backoff with DECORRELATED
    jitter (each sleep ~ U[base, 3·previous], capped) — spreads synchronized
    retry storms apart while keeping the expected growth exponential.
    `rng` is a `random.Random`; a seeded instance yields a reproducible
    schedule (asserted by tests — the determinism contract extends to the
    retry plane)."""
    prev = base_s
    while True:
        prev = min(cap_s, rng.uniform(base_s, prev * 3.0))
        yield prev


@dataclass
class _PeerHealth:
    state: str = CLOSED
    failures: int = 0        # consecutive transport failures
    opened_at: float = 0.0
    probing: bool = False    # half-open probe in flight
    # lifetime counters (exposed via snapshot())
    opens: int = 0
    closes: int = 0
    fast_fails: int = 0
    successes: int = 0
    total_failures: int = 0


class HealthLedger:
    """Per-peer consecutive-failure circuit breaker with half-open probing.

    closed --K consecutive failures--> open --cooldown elapses--> half_open
    half_open: exactly ONE probe call may proceed; its success closes the
    breaker (failure count reset), its failure re-opens it for another
    cooldown. Any success in any state closes the breaker — one good RPC
    is full rehabilitation (the reference's `alive` set, by contrast,
    evicts on a single timeout and only re-admits on inbound traffic).

    `clock` is injectable so transition tests run on a fake clock.
    """

    def __init__(self, threshold: int = 3, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._peers: Dict[int, _PeerHealth] = {}

    def _h(self, pid: int) -> _PeerHealth:
        h = self._peers.get(pid)
        if h is None:
            h = self._peers[pid] = _PeerHealth()
        return h

    def state(self, pid: int) -> str:
        return self._h(pid).state

    def allow(self, pid: int) -> bool:
        """May a unicast RPC toward `pid` proceed now? Consumes the single
        half-open probe slot when the cooldown has elapsed; callers that
        get False should fail fast (CircuitOpenError) without dialing."""
        h = self._h(pid)
        if h.state == CLOSED:
            return True
        if h.state == OPEN:
            if self._clock() - h.opened_at >= self.cooldown_s:
                h.state = HALF_OPEN
                h.probing = True
                return True
            h.fast_fails += 1
            return False
        # HALF_OPEN: one probe at a time
        if h.probing:
            h.fast_fails += 1
            return False
        h.probing = True
        return True

    def release_probe(self, pid: int) -> None:
        """Return an UNRESOLVED half-open probe slot (the probe call was
        cancelled before any outcome was recorded) — without this the slot
        leaks and the peer stays quarantined until unrelated traffic
        records an outcome for it. No-op in every other state."""
        h = self._peers.get(pid)
        if h is not None and h.state == HALF_OPEN:
            h.probing = False

    def available(self, pid: int) -> bool:
        """Non-consuming view for fan-out target selection (gossip): False
        only while the breaker is open and still cooling down. Does NOT
        claim the half-open probe slot — a gossip post toward a half-open
        peer is itself probe-shaped (its failure re-opens the breaker)."""
        h = self._peers.get(pid)
        if h is None or h.state != OPEN:
            return True
        if self._clock() - h.opened_at >= self.cooldown_s:
            return True
        h.fast_fails += 1
        return False

    def record_success(self, pid: int) -> bool:
        """One RPC toward `pid` completed (or the peer answered, even with a
        protocol-level error — the TRANSPORT is healthy). Returns True iff
        this closed an open/half-open breaker."""
        h = self._h(pid)
        was_tripped = h.state != CLOSED
        h.state = CLOSED
        h.failures = 0
        h.probing = False
        h.successes += 1
        if was_tripped:
            h.closes += 1
        return was_tripped

    def note_inbound(self, pid: int) -> None:
        """Inbound traffic from `pid` is liveness evidence for the
        THEM→US path ONLY — it must not touch the outbound failure
        streak: under an asymmetric partition (their frames reach us,
        ours die) inbound gossip would otherwise zero the streak every
        round and the breaker could never open, leaving each outbound
        RPC to burn its full retry budget. For a TRIPPED breaker it
        expires the cooldown so the very next outbound call becomes the
        half-open probe: a genuinely rejoined peer re-closes on that
        probe's success without waiting out the cooldown, while a
        one-way-partitioned peer fails the probe and stays quarantined."""
        h = self._peers.get(pid)
        if h is None or h.state == CLOSED:
            return
        if h.state == OPEN:
            h.opened_at = self._clock() - self.cooldown_s
        else:  # HALF_OPEN: free a possibly-stale slot; a fresh probe decides
            h.probing = False

    def record_failure(self, pid: int) -> bool:
        """One transport failure (timeout/refused/reset) toward `pid`.
        Returns True iff this TRIPPED the breaker open."""
        h = self._h(pid)
        h.failures += 1
        h.total_failures += 1
        if h.state == HALF_OPEN:
            # the probe itself failed: straight back to open
            h.state = OPEN
            h.opened_at = self._clock()
            h.probing = False
            h.opens += 1
            return True
        if h.state == OPEN:
            # a failure observed while quarantined (e.g. a fan-out post
            # that rode available()'s post-cooldown implicit probe): the
            # peer is demonstrably still dead — RE-ARM the cooldown, or
            # after the first cooldown the quarantine would never
            # re-engage for gossip and every round would re-burn rpc_s
            h.opened_at = self._clock()
            return False
        if h.state == CLOSED and h.failures >= self.threshold:
            h.state = OPEN
            h.opened_at = self._clock()
            h.opens += 1
            return True
        return False

    def snapshot(self) -> Dict[int, Dict[str, object]]:
        """Per-peer health for artifacts / assertions (run() result)."""
        return {
            pid: {
                "state": h.state, "failures": h.failures,
                "opens": h.opens, "closes": h.closes,
                "fast_fails": h.fast_fails, "successes": h.successes,
                "total_failures": h.total_failures,
            }
            for pid, h in self._peers.items()
        }

    def export_state(self) -> Dict[str, Dict[str, object]]:
        """Full breaker state for a migration ticket (runtime/placement.py):
        snapshot() plus the fields it elides because they only matter to a
        LIVE ledger — the open timestamp (exported clock-RELATIVE, as the
        age of the open, so a restore under a different clock re-anchors
        it) and the probe slot. JSON-clean: keys are strings."""
        now = self._clock()
        out: Dict[str, Dict[str, object]] = {}
        for pid, h in self._peers.items():
            out[str(pid)] = {
                "state": h.state, "failures": h.failures,
                "opened_age_s": (round(now - h.opened_at, 6)
                                 if h.state != CLOSED else 0.0),
                "probing": h.probing, "opens": h.opens,
                "closes": h.closes, "fast_fails": h.fast_fails,
                "successes": h.successes,
                "total_failures": h.total_failures,
            }
        return out

    def restore_state(self, state: Dict[str, Dict[str, object]]) -> None:
        """Rehydrate an export into THIS ledger (a migrated peer resumes
        with its quarantine view intact: open breakers stay open with
        their remaining cooldown, streaks and lifetime counters carry
        over). Existing entries for the same peer are overwritten — the
        ticket is the authority on the pre-move state."""
        now = self._clock()
        for pid_s, rec in state.items():
            h = self._h(int(pid_s))
            h.state = str(rec.get("state", CLOSED))
            h.failures = int(rec.get("failures", 0))
            h.opened_at = now - float(rec.get("opened_age_s", 0.0))
            h.probing = bool(rec.get("probing", False))
            h.opens = int(rec.get("opens", 0))
            h.closes = int(rec.get("closes", 0))
            h.fast_fails = int(rec.get("fast_fails", 0))
            h.successes = int(rec.get("successes", 0))
            h.total_failures = int(rec.get("total_failures", 0))
