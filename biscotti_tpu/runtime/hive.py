"""Hive runtime: one process hosts H (hundreds of) lightweight co-hosted
peers that share a single JAX client — the single-box scale wall breaker
(ROADMAP item 1; docs/HIVE.md).

The one-agent-per-peer runtime tops out around N=400 on one box: every
peer is a full asyncio agent with its own JAX dispatch, its own TCP hop
for every frame, and its own copy of every shared tensor (test split,
DP-noise bank). The hive keeps the agents — the full protocol state
machine, committees, chain, crypto — but shares everything an honest
co-hosted deployment can share:

  * **Batched device plane** (`HiveStepper`): within a round, all
    co-hosted workers' local SGD steps run as ONE vmapped (or, over a
    mesh, shard_map'd) XLA call — the `parallel/sim.py` round-step math
    with the `device_cluster.BatchStepper` executor pattern — and DP
    noise draws coalesce into one [H, d] device draw per round instead
    of H presample banks of [iters, d].
  * **Loopback transport fast path** (`LoopbackHub`): RPC between two
    peers in the same hive skips TCP framing AND serialization — the
    destination handler receives read-only views of the caller's
    arrays (the wire plane is bit-exact by design, docs/WIRE_PLANE.md,
    so skipping the encode changes no value a receiver observes).
    Admission control, the seeded fault plane, and byte accounting all
    still apply: the pool draws each frame's fault fate exactly as it
    would for TCP (chaos replay schedules are unchanged), the
    destination's `AdmissionController` budgets each delivery (shed →
    the same retryable BusyError), and the would-be frame size lands in
    `biscotti_wire_bytes_total` under a new `loopback` direction.
  * **Shared memory** — light trainers (models/trainer.py `light=True`):
    co-hosted agents hold no per-peer train shard or noise bank; eval
    splits are process-wide device buffers; a gossiped block's arrays
    are aliased (read-only) by every co-hosted chain instead of being
    re-decoded H times.

Cross-hive traffic — anything toward a peer the hub does not host —
rides the ordinary TCP wire plane with its negotiated codecs, so a
cluster of hives spread across processes/hosts (tools/pod_launch.py
`--peers-per-host`) interoperates frame-for-frame with standalone
agents.

Launcher CLI (one hive = one process; pod_launch spreads many):

    python -m biscotti_tpu.runtime.hive -t 1000 --local 0:1000 \
        -d mnist --iterations 3 -sa 0 -np 0 -vp 1

Prints one JSON line: local chain digests (the cross-hive equality
oracle compares anchors across processes), s/iter, and the honest
per-peer memory account (peak RSS / peers).
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from biscotti_tpu.runtime import codecs as wcodecs
from biscotti_tpu.runtime.rpc import BusyError, RPCError, StaleError

LOOPBACK = "loopback"  # wire-plane direction label for in-process frames

LOOPBACK_RPCS_METRIC = "biscotti_loopback_rpcs_total"
LOOPBACK_RPCS_HELP = "RPCs delivered over the in-process loopback fast path"
LOOPBACK_SECONDS_METRIC = "biscotti_loopback_rpc_seconds"
LOOPBACK_SECONDS_HELP = "loopback reply-bearing RPC latency"


def _ro_view(a) -> np.ndarray:
    """Read-only ndarray view — loopback delivery must preserve the TCP
    path's invariant that a receiver cannot mutate what it was handed
    (frames decode to non-writable frombuffer views); here the arrays
    ALIAS the sender's memory, so the invariant is load-bearing."""
    arr = np.asarray(a)
    v = arr.view()
    v.flags.writeable = False
    return v


def _frame_estimate(meta, arrays) -> int:
    """Bytes this RPC WOULD have cost on the wire (raw64 frame: JSON
    header + raw array payloads + framing) — the loopback direction's
    byte accounting counts avoided traffic honestly rather than zero,
    so bytes/round comparisons between co-hosted and remote layouts
    stay meaningful."""
    n = 64
    try:
        n += len(json.dumps(meta or {}, separators=(",", ":"),
                            default=str))
    except (TypeError, ValueError):
        n += 256
    for a in (arrays or {}).values():
        n += np.asarray(a).nbytes
    return n


def rss_bytes() -> int:
    """Current resident set size of this process (Linux /proc; 0 when
    unavailable)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def rss_peak_bytes() -> int:
    """Peak resident set size (ru_maxrss is KiB on Linux)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


# Monitor samples retained for the drift window (seconds). Long enough
# that allocator sawtooth averages out, short enough that a genuine leak
# moves the gauge within one soak sampling interval (tools/soak.py).
DRIFT_WINDOW_S = 120.0


def drift(values: Sequence[float]) -> float:
    """Windowed drift: median of the newest quarter of ``values`` minus
    median of the oldest quarter.

    A plain last-minus-first delta aliases on GC/allocator sawtooth and
    on a single slow event-loop tick; quarter-medians keep a monotone
    leak visible while one outlier sample stays invisible.  Returns 0
    until there are at least 4 samples (one per quarter)."""
    if len(values) < 4:
        return 0.0
    q = max(1, len(values) // 4)
    import statistics

    return float(statistics.median(values[-q:])
                 - statistics.median(values[:q]))


# --------------------------------------------------------------- transport


class LoopbackEndpoint:
    """One co-hosted peer's in-process RPC surface. Alive exactly while
    the peer's TCP server would accept a connection (same lifecycle —
    a closed peer's loopback callers fall back to TCP and get the
    connection-refused the protocol already handles)."""

    def __init__(self, hub: "LoopbackHub", agent):
        self.hub = hub
        self.agent = agent

    @property
    def alive(self) -> bool:
        return self.agent.server.serving

    # -------------------------------------------------------- delivery

    async def _dispatch(self, msg_type: str, meta, arrays, src):
        """One delivered frame: admission-budgeted, handler-dispatched,
        typed-error mapped exactly as rpc.RPCServer._dispatch would
        surface it to a TCP caller."""
        agent = self.agent
        if not self.alive:
            raise ConnectionError("loopback endpoint closed")
        # budget key parity with RPCServer._admit_key: the TCP path keys
        # on the connection peername (unspoofable); in-process the
        # caller's identity is the pool that delivered the frame — just
        # as unspoofable, and per-peer like an honest pooled connection
        key = ("loop", src)
        reason = agent.admission.try_admit(key, msg_type)
        if reason is not None:
            raise BusyError(f"admission shed: {reason}")
        try:
            if agent.server.service_delay_s > 0.0:
                # slow-peer service emulation, mirrored from
                # RPCServer._dispatch: a co-hosted slow peer serves its
                # loopback callers exactly as slowly as its TCP callers —
                # the layout-invariance the straggler plane promises
                # (docs/STRAGGLERS.md)
                await asyncio.sleep(agent.server.service_delay_s)
            meta2 = dict(meta or {})
            arrays2 = {k: _ro_view(v) for k, v in (arrays or {}).items()}
            # distributed tracing: the loopback dispatch is a transport
            # seam like RPCServer._dispatch — the same receiver-side
            # child span off the frame's wire context, so co-hosted hops
            # appear in the causal tree exactly as TCP hops do (getattr:
            # harness stubs duck-type the server without the hook)
            tele = getattr(agent.server, "telemetry", None)
            span = (tele.rpc_span(msg_type, meta2) if tele is not None
                    else contextlib.nullcontext())
            try:
                with span:
                    return await agent._handle(msg_type, meta2, arrays2)
            except (StaleError, BusyError, RPCError):
                raise
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # handler bug: report, don't kill the caller — the TCP
                # server wraps this identically
                raise RPCError(
                    f"internal: {type(e).__name__}: {e}") from e
        finally:
            agent.admission.release(key)

    def _deliver_bg(self, msg_type, meta, arrays, src,
                    budget: float) -> None:
        """Background delivery for fire-and-forget posts and injected
        duplicate/flood copies: result and errors are discarded, exactly
        like a TCP frame whose reply nobody awaits."""

        async def go():
            try:
                await asyncio.wait_for(
                    self._dispatch(msg_type, meta, arrays, src),
                    max(0.001, budget))
            except asyncio.CancelledError:
                raise
            except Exception:
                pass

        self.hub.track(asyncio.get_running_loop().create_task(go()))

    def _account(self, metrics, msg_type: str, kind: str, meta,
                 arrays) -> None:
        if metrics is None:
            return
        metrics.counter(wcodecs.WIRE_BYTES_METRIC,
                        wcodecs.WIRE_BYTES_HELP).inc(
            _frame_estimate(meta, arrays), msg_type=msg_type,
            direction=LOOPBACK, codec=wcodecs.RAW)
        metrics.counter(LOOPBACK_RPCS_METRIC, LOOPBACK_RPCS_HELP).inc(
            msg_type=msg_type, kind=kind)

    # ------------------------------------------------------ public API

    async def call(self, msg_type: str, meta, arrays, timeout: float,
                   fault=None, src=None, metrics=None):
        """Reply-bearing RPC over the fast path. Fault semantics mirror
        the _Conn boundary: reset → ConnectionError, delay → sleep,
        drop → the caller's deadline expires (the handler never runs),
        duplicate/flood → extra deliveries whose replies are dropped."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        # counted regardless of an injected drop — the TCP path counts
        # outbound bytes once the transport accepted the frame, and an
        # injected drop still paid the send
        self._account(metrics, msg_type, "call", meta, arrays)
        if fault is not None and not fault.benign:
            if fault.reset:
                raise ConnectionError("fault injection: connection reset")
            if fault.delay_s > 0.0:
                await asyncio.sleep(min(fault.delay_s, timeout))
            if fault.drop:
                # frame lost before delivery: the caller waits out its
                # budget exactly as a TCP timeout would
                await asyncio.sleep(max(0.001, deadline - loop.time()))
                raise asyncio.TimeoutError(
                    "fault injection: frame dropped")
            extra = (1 if fault.duplicate else 0) + max(0, fault.flood)
            for _ in range(extra):
                self._deliver_bg(msg_type, meta, arrays, src,
                                 deadline - loop.time())
        t0 = loop.time()
        task = loop.create_task(self._dispatch(msg_type, meta, arrays,
                                               src))
        self.hub.track(task)
        try:
            rmeta, rarrays = await asyncio.wait_for(
                asyncio.shield(task), max(0.001, deadline - loop.time()))
        except asyncio.TimeoutError:
            # the handler keeps running, like an abandoned TCP reply —
            # its state transitions (a registered update, a parked wait)
            # must not be lost to the caller's impatience
            raise
        if metrics is not None:
            metrics.histogram(LOOPBACK_SECONDS_METRIC,
                              LOOPBACK_SECONDS_HELP).observe(
                loop.time() - t0, msg_type=msg_type)
        # reply accounting on the CALLEE's registry (the TCP server
        # counts its outbound reply the same way); arrays go back as
        # read-only views too — the caller must not be able to mutate
        # the callee's chain through an aliased GetBlock body
        self._account(self.agent.server.metrics, msg_type + ".reply",
                      "reply", rmeta, rarrays)
        return dict(rmeta), {k: _ro_view(v)
                             for k, v in (rarrays or {}).items()}

    async def post(self, msg_type: str, meta, arrays, timeout: float,
                   fault=None, src=None, metrics=None) -> None:
        """Fire-and-forget over the fast path (rid-0 semantics: replies
        and handler errors are dropped)."""
        loop = asyncio.get_running_loop()
        self._account(metrics, msg_type, "post", meta, arrays)
        if fault is not None and not fault.benign:
            if fault.reset:
                raise ConnectionError("fault injection: connection reset")
            if fault.delay_s > 0.0:
                await asyncio.sleep(min(fault.delay_s, timeout))
            if fault.drop:
                return  # frame lost before delivery (still counted)
            extra = (1 if fault.duplicate else 0) + max(0, fault.flood)
            for _ in range(extra):
                self._deliver_bg(msg_type, meta, arrays, src, timeout)
        self._deliver_bg(msg_type, meta, arrays, src, timeout)


class LoopbackHub:
    """Per-process registry of co-hosted peers, attached to each member
    agent's `rpc.Pool` (`pool.loopback`). Lookup is by the (host, port)
    the CLUSTER addresses the peer with, so remote peers simply miss and
    ride TCP; a registered peer whose server is not (yet / anymore)
    serving also misses, so startup races and teardown degrade to the
    exact connection-refused behavior the retry/breaker plane already
    handles. Re-registering an id (a relaunched incarnation) replaces
    the endpoint."""

    def __init__(self):
        self._by_addr: Dict[Tuple[str, int], LoopbackEndpoint] = {}
        self._tasks: set = set()

    def register(self, agent) -> LoopbackEndpoint:
        ep = LoopbackEndpoint(self, agent)
        self._by_addr[tuple(agent.peers[agent.id])] = ep
        return ep

    def lookup(self, host: str, port: int) -> Optional[LoopbackEndpoint]:
        ep = self._by_addr.get((host, port))
        return ep if ep is not None and ep.alive else None

    @property
    def local_ids(self) -> frozenset:
        return frozenset(ep.agent.id for ep in self._by_addr.values())

    def track(self, task: asyncio.Task) -> None:
        """Strong ref for background deliveries (the loop only keeps
        weak ones) + exception retrieval on completion."""
        self._tasks.add(task)
        task.add_done_callback(self._done)

    def _done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if not task.cancelled():
            task.exception()  # mark retrieved


# ------------------------------------------------------------ device plane


class UnequalShardsError(ValueError):
    """Co-hosted peers' train shards disagree on row count, so one
    vmapped minibatch draw cannot reproduce each standalone Trainer's
    `sample_batch(key, own_rows, batch)` stream. Hive catches this and
    falls back to per-agent trainers (slower, exact)."""


class HiveStepper:
    """Batched device plane for a hive's LOCAL peer subset: all co-hosted
    workers' SGD deltas in one vmapped XLA call per (iteration, weights),
    DP noise as one [H, d] draw per iteration, and the shared
    convergence metric — the `device_cluster.BatchStepper` executor
    pattern generalized to host a SLICE of the cluster (multi-host
    hives) with Trainer-parity randomness.

    Key derivation matches models/trainer.Trainer exactly — per peer
    `fold_in(PRNGKey(cfg.seed), pid)` split into (noise, batch) keys,
    minibatch key `fold_in(batch_key, it)` — so a hive-hosted peer's
    SGD stream is the same stream its standalone agent would draw
    (deltas agree to float tolerance; the vmapped reduction order is
    the only difference). Noise draws are generated per round
    (`fold_in(noise_key, it)`) instead of indexed from a presample
    bank: distribution-identical to the bank (the same argument
    parallel/sim.py makes), O(H·d) resident instead of O(H·iters·d).

    With a multi-device `mesh` whose size divides H, the delta batch
    runs under shard_map over the peer axis (the make_sharded_round_step
    data plane); otherwise a single-client vmap."""

    def __init__(self, cfg, local_ids: Sequence[int], mesh=None):
        import jax
        import jax.numpy as jnp

        from biscotti_tpu.data import datasets as ds
        from biscotti_tpu.models.trainer import local_step_fn, sample_batch
        from biscotti_tpu.models.zoo import model_for_dataset
        from biscotti_tpu.ops import dp_noise
        from biscotti_tpu.parallel.sim import _poisoned_ids

        self.cfg = cfg
        self.local_ids = sorted(int(i) for i in local_ids)
        self._slot = {pid: i for i, pid in enumerate(self.local_ids)}
        h = len(self.local_ids)

        model = model_for_dataset(cfg.dataset,
                                  getattr(cfg, "model_name", ""))
        self.num_params = model.num_params
        mode = "sgd" if model.name == "logreg" else "grad"
        step = local_step_fn(model, mode, clip=cfg.grad_clip,
                             alpha=cfg.logreg_alpha)

        poisoned = _poisoned_ids(cfg.num_nodes, cfg.poison_fraction)
        xs, ys = [], []
        for pid in self.local_ids:
            shard = ds.load_shard(
                cfg.dataset, ds.shard_name(cfg.dataset, pid,
                                           pid in poisoned))
            xs.append(shard["x_train"])
            ys.append(shard["y_train"])
        sizes = {len(x) for x in xs}
        if len(sizes) > 1:
            # truncating to a common row count would change which rows
            # sample_batch can draw vs the peer's standalone Trainer —
            # the parity this class promises. Hive falls back to
            # per-agent trainers when it catches this.
            raise UnequalShardsError(
                f"co-hosted shards have unequal row counts {sorted(sizes)}; "
                "batched stepping would break Trainer-parity sampling")
        rows = sizes.pop()
        self._x = jnp.asarray(np.stack(xs))
        self._y = jnp.asarray(np.stack(ys))
        batch = min(cfg.batch_size, rows)

        # Trainer-parity per-peer key streams (see class docstring)
        bases = [jax.random.fold_in(jax.random.PRNGKey(cfg.seed), pid)
                 for pid in self.local_ids]
        pairs = [jax.random.split(b) for b in bases]
        self._noise_keys = jnp.stack([p[0] for p in pairs])
        self._batch_keys = jnp.stack([p[1] for p in pairs])

        def one_delta(w, bkey, xi, yi, it):
            k = jax.random.fold_in(bkey, it)
            idx = sample_batch(k, rows, batch)
            return step(w, xi[idx], yi[idx])

        n_dev = 1
        if mesh is not None:
            n_dev = math.prod(mesh.devices.shape)
        if mesh is not None and n_dev > 1 and h % n_dev == 0:
            # peers-across-devices: the make_sharded_round_step data
            # plane — each device computes its peer slice, one gather
            from jax.sharding import NamedSharding, PartitionSpec as P

            from biscotti_tpu.utils.compat import shard_map

            axis = mesh.axis_names[0]

            def local_batch(w, bkeys, x_loc, y_loc, it):
                return jax.vmap(one_delta,
                                in_axes=(None, 0, 0, 0, None))(
                    w, bkeys, x_loc, y_loc, it)

            mapped = shard_map(
                local_batch, mesh=mesh,
                in_specs=(P(), P(axis), P(axis), P(axis), P()),
                out_specs=P(axis), check_vma=False)
            self._deltas = jax.jit(mapped)
            sharding = NamedSharding(mesh, P(axis))
            self._x = jax.device_put(self._x, sharding)
            self._y = jax.device_put(self._y, sharding)
            self._batch_keys = jax.device_put(self._batch_keys, sharding)
        else:

            @jax.jit
            def _deltas(w, bkeys, x, y, it):
                return jax.vmap(one_delta,
                                in_axes=(None, 0, 0, 0, None))(
                    w, bkeys, x, y, it)

            self._deltas = _deltas

        # DP noise: fresh per-round batched draw, Σ_batch σ·N(0,1)
        # scaled by −α/batch like trainer.get_noise / sim._peer_noise.
        # mcmc13 peers keep their per-agent trainer banks (the chain
        # draw doesn't batch trivially) — serves_noise gates that.
        eps_live = cfg.epsilon if (cfg.noising or cfg.dp_in_model) else 0.0
        self._sigma = dp_noise.sigma_for(eps_live, cfg.delta)
        self._noise_alpha = cfg.logreg_alpha if mode == "sgd" else 1.0
        # UNCLAMPED batch size, matching Trainer exactly: presample's
        # sqrt scale and noise_at's 1/batch denominator both use
        # cfg.batch_size even when the shard is smaller than a batch
        self._noise_batch = cfg.batch_size
        self.serves_noise = cfg.dp_mechanism != "mcmc13"

        scale = self._sigma * math.sqrt(cfg.batch_size)

        @jax.jit
        def _noise(nkeys, it):
            def one(k):
                return scale * jax.random.normal(
                    jax.random.fold_in(k, it), (self.num_params,),
                    jnp.float32)

            return jax.vmap(one)(nkeys)

        self._noise_fn = _noise

        # shared convergence metric (identical model × identical global
        # test split — peer.py's uniform-convergence requirement)
        from biscotti_tpu.models.trainer import _shared_eval_arrays

        self._x_test, self._y_test, _, _ = _shared_eval_arrays(cfg.dataset)
        self._err_fn = jax.jit(model.error_flat)

        self._caches: Dict[str, Dict] = {"step": {}, "noise": {},
                                         "eval": {}}
        self._pending: Dict[str, Dict] = {"step": {}, "noise": {},
                                          "eval": {}}
        self.batches = 0  # batched delta dispatches (observability)
        self.noise_batches = 0
        self.evals = 0
        # wall-clock of the last batched SGD dispatch: the straggler
        # plane's compute pad bases a co-hosted slow peer's padding on
        # the batch's REAL cost — a memo-hit caller measures ~0 for its
        # own await, which would otherwise make hive layouts immune to
        # the slowdown TCP layouts emulate (docs/STRAGGLERS.md)
        self.step_cost_s = 0.0

    async def _memo(self, kind: str, key, compute):
        from biscotti_tpu.runtime.device_cluster import single_flight_memo

        return await single_flight_memo(self._caches[kind],
                                        self._pending[kind], key, compute)

    def _evict(self, kind: str, it: int) -> None:
        cache = self._caches[kind]
        for old in [k for k in cache
                    if (k[0] if isinstance(k, tuple) else k) < it - 3]:
            cache.pop(old, None)

    async def step(self, peer_id: int, w: np.ndarray,
                   it: int) -> np.ndarray:
        """This peer's SGD delta for iteration `it`; the first co-hosted
        caller computes the WHOLE hive's batch. Keyed on (it, weight
        digest): transiently forked chains compute their own batch,
        identical chains — the lockstep case — share one."""
        import jax.numpy as jnp

        wb = np.ascontiguousarray(np.asarray(w))
        key = (it, hashlib.sha1(wb.tobytes()).hexdigest())

        def compute():
            t0 = time.perf_counter()
            out = np.asarray(
                self._deltas(jnp.asarray(wb, jnp.float32),
                             self._batch_keys, self._x, self._y, it),
                dtype=np.float64)
            self.step_cost_s = time.perf_counter() - t0
            return out

        deltas, computed = await self._memo("step", key, compute)
        if computed:
            self.batches += 1
        self._evict("step", it)
        return deltas[self._slot[peer_id]]

    async def noise(self, peer_id: int, it: int) -> np.ndarray:
        """This peer's DP noise vector for iteration `it` — one [H, d]
        device draw per round, shared by every co-hosted noiser."""
        if self._sigma == 0.0:
            return np.zeros(self.num_params, np.float64)

        def compute():
            draw = np.asarray(self._noise_fn(self._noise_keys, it),
                              dtype=np.float64)
            return (-self._noise_alpha / self._noise_batch) * draw

        bank, computed = await self._memo("noise", (it,), compute)
        if computed:
            self.noise_batches += 1
        self._evict("noise", it)
        return bank[self._slot[peer_id]]

    async def test_error(self, w: np.ndarray, it: int) -> float:
        """Global-test-split error, computed once per distinct
        (iteration, weights) across the hive."""
        import jax.numpy as jnp

        wb = np.ascontiguousarray(np.asarray(w))
        key = (it, hashlib.sha1(wb.tobytes()).hexdigest())

        def compute():
            return float(self._err_fn(jnp.asarray(wb, jnp.float32),
                                      self._x_test, self._y_test))

        err, computed = await self._memo("eval", key, compute)
        if computed:
            self.evals += 1
        self._evict("eval", it)
        return err


# ----------------------------------------------------------------- launcher


class Hive:
    """One hive: H co-hosted `PeerAgent`s sharing a LoopbackHub, a
    HiveStepper, and one event loop. `local_ids` names the slice of the
    cluster this process hosts (default: all of it — the single-box
    density configuration); the peers file / base-port arithmetic in
    `cfg_base` must describe the WHOLE cluster so cross-hive addresses
    resolve.

    Co-hosted peers are made mutually known at construction (caps +
    liveness), so a genesis hive launch skips the O(H²) intra-hive
    hello storm; hellos toward REMOTE peers still run, which is how a
    late-started hive adopts the cluster's chain."""

    def __init__(self, cfg_base, local_ids: Optional[Sequence[int]] = None,
                 mesh=None, key_dir: str = "", log_dir: str = "",
                 hive_id: str = "", batch_device: bool = True,
                 loopback: bool = True, skip_local_announce: bool = True):
        from biscotti_tpu.runtime.peer import PeerAgent

        self.cfg = cfg_base
        self.local_ids = sorted(local_ids if local_ids is not None
                                else range(cfg_base.num_nodes))
        # loopback=False / batch_device=False are the ablation knobs the
        # density bench A/Bs against: full agents talking real TCP in one
        # process — exactly the pre-hive one-agent-per-peer runtime
        self.hub = LoopbackHub() if loopback else None
        self.stepper = None
        self.stepper_fallback = ""
        if batch_device:
            try:
                self.stepper = HiveStepper(cfg_base, self.local_ids,
                                           mesh=mesh)
            except UnequalShardsError as e:
                # exactness beats batching: per-agent trainers keep the
                # standalone sampling streams when shards are unequal
                self.stepper_fallback = str(e)
        light = self.stepper is not None and self.stepper.serves_noise
        # shared mutable per-hive readout: the monitor task updates it,
        # every member's telemetry_snapshot()["hive"] reads it, the obs
        # CLI groups the cluster table by its id (docs/OBSERVABILITY.md)
        self.info: Dict = {
            "id": hive_id or f"pid{os.getpid()}",
            "peers": len(self.local_ids),
            "rss_bytes": 0, "rss_peak_bytes": 0, "loop_lag_s": 0.0,
            # windowed deltas over DRIFT_WINDOW_S of monitor samples: a
            # leak or creeping starvation shows as sustained positive
            # drift long before the absolute gauges look alarming
            # (tools/soak.py gates on these; docs/SOAK.md)
            "rss_drift_bytes": 0, "loop_lag_drift_s": 0.0,
        }
        self.agents: List[PeerAgent] = []
        for pid in self.local_ids:
            cfg = cfg_base.replace(node_id=pid)
            self.agents.append(PeerAgent(
                cfg, key_dir=key_dir, stepper=self.stepper,
                hive=self.hub, light_trainer=light,
                log_path=os.path.join(log_dir, f"events_{pid}.jsonl")
                if log_dir else ""))
        caps = sorted(self.agents[0].caps) if self.agents else []
        local_set = frozenset(self.local_ids)
        for a in self.agents:
            a.hive_info = self.info
            if skip_local_announce:
                a._announce_skip = local_set
            for pid in self.local_ids:
                if pid != a.id:
                    a._record_caps(pid, caps)

    async def _monitor(self, period: float = 0.25) -> None:
        """Event-loop lag + RSS sampler: co-hosting starvation must be
        VISIBLE (an overloaded hive's lag gauge climbs), not inferred
        from round-time anomalies."""
        loop = asyncio.get_running_loop()
        samples: List[Tuple[float, int, float]] = []
        while True:
            t0 = loop.time()
            await asyncio.sleep(period)
            now = loop.time()
            lag = round(max(0.0, now - t0 - period), 4)
            rss = rss_bytes()
            self.info["loop_lag_s"] = lag
            self.info["rss_bytes"] = rss
            self.info["rss_peak_bytes"] = rss_peak_bytes()
            samples.append((now, rss, lag))
            while samples and now - samples[0][0] > DRIFT_WINDOW_S:
                samples.pop(0)
            self.info["rss_drift_bytes"] = int(
                drift([r for _, r, _ in samples]))
            self.info["loop_lag_drift_s"] = round(
                drift([l for _, _, l in samples]), 4)

    async def run(self) -> List[Dict]:
        mon = asyncio.get_running_loop().create_task(self._monitor())
        try:
            return await asyncio.gather(*(a.run() for a in self.agents))
        finally:
            mon.cancel()


def main(argv=None) -> int:
    import argparse

    from biscotti_tpu.config import BiscottiConfig, Defense

    ap = argparse.ArgumentParser(
        description="hive host: co-hosted lightweight peers, one process")
    BiscottiConfig.add_args(ap)
    ap.add_argument("--local", default="",
                    help="START:COUNT slice of node ids this hive hosts "
                         "(default: the whole cluster)")
    ap.add_argument("--iterations", type=int, default=3)
    ap.add_argument("--key-dir", default="")
    ap.add_argument("--log-dir", default="")
    ap.add_argument("--hive-id", default="")
    ap.add_argument("--no-batch-device", action="store_true",
                    help="ablation: per-agent trainer dispatch instead of "
                         "the hive's batched device plane")
    ap.add_argument("--no-loopback", action="store_true",
                    help="ablation: co-hosted peers talk real TCP (the "
                         "pre-hive one-agent-per-peer runtime)")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform (site hooks may otherwise pin an "
                         "accelerator; the hive's batch is CPU/TPU "
                         "agnostic)")
    ap.add_argument("--dump-chain", action="store_true",
                    help="also print the anchor agent's full chain dump")
    ns = ap.parse_args(argv)

    os.environ["JAX_PLATFORMS"] = ns.platform
    import jax

    jax.config.update("jax_platforms", ns.platform)
    jax.config.update("jax_enable_x64", True)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(repo, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    if getattr(ns, "overlay", 0) and not getattr(ns, "overlay_group", 0):
        # default the aggregation subtree to this hive's co-hosted span —
        # the intra-hive pre-aggregation seam (docs/OVERLAY.md): one
        # interior node per host, leaf->relay offers ride loopback
        ns.overlay_group = (int(ns.local.split(":")[1]) if ns.local
                            else ns.num_nodes)
    cfg = BiscottiConfig.from_args(ns)
    cfg = cfg.replace(
        max_iterations=ns.iterations, convergence_error=0.0,
        timeouts=cfg.timeouts.scaled(
            cfg.num_nodes, cfg.num_verifiers, cfg.num_miners,
            random_sampling=cfg.random_sampling,
            defense_is_krum=cfg.defense == Defense.KRUM))
    local = None
    if ns.local:
        start, count = (int(x) for x in ns.local.split(":"))
        local = range(start, start + count)

    try:  # large hives need many sockets: lift the soft fd limit
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < hard:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except Exception:
        pass

    hive = Hive(cfg, local, key_dir=ns.key_dir, log_dir=ns.log_dir,
                hive_id=ns.hive_id, batch_device=not ns.no_batch_device,
                loopback=not ns.no_loopback)
    t0 = time.time()
    results = asyncio.run(hive.run())
    wall = time.time() - t0

    dumps = [r["chain_dump"] for r in results]
    digests = [hashlib.sha256(d.encode()).hexdigest() for d in dumps]
    anchor = results[0]
    # wire accounting over THIS hive's peers (obs.merge_wire — the one
    # definition): cross-host (TCP-crossing) vs loopback-avoided bytes,
    # so the overlay headline reads straight off the pod_launch artifact
    from biscotti_tpu.tools import obs as _obs

    wire = _obs.merge_wire([r.get("telemetry", {}) for r in results])
    rounds = max(1, len(dumps[0].splitlines()) - 1)
    overlay_tbl = _obs.merge_overlay([r.get("telemetry", {})
                                      for r in results])
    rows = [tuple(x.split(",")) for x in anchor["logs"]]
    if len(rows) >= 2:
        ts = [float(r[2]) for r in rows]
        s_per_iter = (ts[-1] - ts[0]) / (len(ts) - 1)
    else:
        s_per_iter = wall / max(1, ns.iterations)
    peak = rss_peak_bytes()
    summary = {
        "hive": hive.info["id"],
        "nodes": [hive.local_ids[0], hive.local_ids[-1] + 1],
        "peers": len(hive.local_ids),
        "blocks": len(dumps[0].splitlines()) - 1,
        "chains_equal_local": all(d == digests[0] for d in digests),
        "chain_digest": digests[0],
        "wall_s": round(wall, 2),
        "s_per_iter": round(s_per_iter, 4),
        "rss_peak_bytes": peak,
        "rss_per_peer_bytes": int(peak / max(1, len(hive.local_ids))),
        "loop_lag_s": hive.info["loop_lag_s"],
        # reflects reality, not the flag: unequal co-hosted shards fall
        # back to per-agent trainers (UnequalShardsError) and must not
        # masquerade as a batched run in the bench artifact
        "batch_device": hive.stepper is not None,
        "batch_fallback": hive.stepper_fallback or None,
        "loopback": not ns.no_loopback,
        "overlay": bool(cfg.overlay),
        "cross_host_bytes": wire["cross_host_bytes"],
        "cross_host_by_msg_type": dict(sorted(
            wire["out_by_msg_type"].items(), key=lambda kv: -kv[1])[:10]),
        "cross_host_bytes_per_round": round(
            wire["cross_host_bytes"] / rounds, 1),
        "loopback_avoided_bytes_per_round": round(
            wire["loopback_bytes"] / rounds, 1),
        "overlay_aggregated": overlay_tbl["aggregated"],
        "overlay_relayed": overlay_tbl["relayed"],
        "overlay_fallback": overlay_tbl["fallback"],
        "sgd_batches": hive.stepper.batches if hive.stepper else None,
        "final_error": anchor.get("final_error"),
    }
    if ns.dump_chain:
        print("=== CHAIN DUMP ===")
        print(dumps[0])
        print("=== LOGS ===")
    print(json.dumps(summary))
    return 0 if summary["chains_equal_local"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
