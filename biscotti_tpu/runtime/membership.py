"""Dynamic-membership harness: drive a live cluster through a seeded
churn schedule, and judge the outcome on the SURVIVING prefix.

The schedule itself is pure in the seed (faults.FaultPlan.churn_schedule:
kill / restart / join events per (node, round)); this module is the
launcher that makes those events REAL against in-process PeerAgents —
kills tear sockets down mid-round exactly like the hard-kill chaos tests,
restarts and late joins construct a fresh agent (optionally bootstrapping
from its own checkpoint dir, or from a cluster snapshot when
cfg.snapshot_bootstrap is set) and re-announce. `tools/chaos --churn` and
the churn test suite (tests/test_membership.py) both drive clusters
through this one runner, so a failing churn run replays from its flags
(docs/MEMBERSHIP.md §replay).

Multi-process deployments don't need the runner for kills — each peer's
own round loop honors its schedule (`--fault-churn` self-kill,
faults.ChurnExit) and any supervisor (pod_launch, k8s, systemd) handles
the relaunch; the runner exists so single-box tests get BOTH hard-kill
semantics and deterministic relaunches without shelling out.

The oracle here differs from tools/chaos.chain_oracle on purpose: under
churn, a late joiner that snapshot-bootstrapped holds a PRUNED chain (it
never fetched the pre-snapshot blocks — that's the feature), so dumps
cannot be compared line-by-line from genesis. `surviving_prefix_oracle`
aligns dumps per block HEIGHT and requires equality over every height all
peers hold, up to the settled prefix.
"""

from __future__ import annotations

import asyncio
import re
from typing import Callable, Dict, List, Optional, Tuple

from biscotti_tpu.runtime import faults

_ITER_RE = re.compile(r"^iter=(-?\d+) ")


def _dump_heights(dump: str) -> Dict[int, str]:
    """Chain dump → {height: summary line}, skipping non-block lines
    (the pruned-gap marker a snapshot-bootstrapped chain interleaves)."""
    out: Dict[int, str] = {}
    for ln in dump.splitlines():
        m = _ITER_RE.match(ln)
        if m:
            out[int(m.group(1))] = ln
    return out


def surviving_prefix_oracle(results) -> Tuple[bool, int, int]:
    """Chain-equality judged on the surviving prefix: every height that a
    peer holds inside the cluster's settled range must carry the
    identical block on every other peer that holds it. Returns
    (equal, settled_height, real_blocks) like chaos.chain_oracle —
    settled = min over SURVIVORS of (own head − 1): each peer's last
    block may still be in flight at exit, and a peer whose FINAL
    incarnation died mid-run — hard-killed by the runner (`killed`) or
    self-killed by its own schedule with no restart left (`churned`) —
    reports a legitimately low head that must not collapse the checked
    range: its blocks still join the per-height equality check, it just
    doesn't define how far the check reaches.
    real_blocks counts settled non-empty blocks on the anchor (a run
    whose every surviving block is empty carries no training signal and
    must fail)."""
    maps = [_dump_heights(r["chain_dump"]) for r in results]
    alive_maps = [m for m, r in zip(maps, results)
                  if not (r.get("killed") or r.get("churned"))] or maps
    settled = min(max(m) for m in alive_maps) - 1
    equal = True
    for h in range(-1, settled + 1):
        lines = {m[h] for m in maps if h in m}
        if len(lines) > 1:
            equal = False
            break
    anchor = maps[0]
    real = sum(1 for h in range(0, settled + 1)
               if h in anchor and "ndeltas=0" not in anchor[h])
    return equal, settled, real


class ChurnRunner:
    """Run a cluster under a churn schedule, tearing down and relaunching
    live agents.

    `make_agent(node_id)` constructs a fresh PeerAgent for `node_id`
    (the factory decides ckpt dirs, snapshot bootstrap, etc. — a
    restarted node gets a NEW agent, never a resumed object: real churn
    loses all in-memory state). Kills are driven by the VICTIM's own
    height when its schedule self-kill fires (cfg.fault_plan.churn armed
    on the agents), and by the runner as a hard external kill otherwise;
    restarts/joins are driven by the ANCHOR's height — node 0, which the
    schedule never churns."""

    def __init__(self, make_agent: Callable[[int], object],
                 num_nodes: int, schedule: List[faults.ChurnEvent],
                 anchor: int = 0, poll_s: float = 0.1,
                 migrate_factory: Optional[Callable] = None):
        self.make_agent = make_agent
        self.num_nodes = num_nodes
        self.schedule = sorted(schedule,
                               key=lambda e: (e.round, e.node, e.kind))
        self.anchor = anchor
        self.poll_s = poll_s
        self.events_applied: List[Tuple[int, int, str]] = []
        # MIGRATE events relaunch through this (node, ticket) factory so
        # the fresh incarnation rehydrates from the serialized ticket
        # (runtime/placement.py); without one, MIGRATE degrades to
        # RESTART — real churn semantics, state lost — so a schedule
        # built for a migration-aware harness still runs everywhere
        self.migrate_factory = migrate_factory
        self.migrations: List[Dict] = []

    async def _hard_kill(self, agent, task: asyncio.Task) -> None:
        task.cancel()
        try:
            await task
        except BaseException:
            pass
        # the cancel path already released sockets synchronously
        # (run()'s CancelledError handler); belt and braces for agents
        # killed before run() armed that handler
        agent.pool.close()
        agent.server.close_now()

    async def run(self) -> List[Dict]:
        late = {e.node for e in self.schedule if e.kind == faults.JOIN}
        agents: Dict[int, object] = {}
        tasks: Dict[int, asyncio.Task] = {}
        for i in range(self.num_nodes):
            if i in late:
                continue
            agents[i] = self.make_agent(i)
            tasks[i] = asyncio.ensure_future(agents[i].run())
        pending = list(self.schedule)
        try:
            while pending:
                anchor_task = tasks.get(self.anchor)
                if anchor_task is not None and anchor_task.done():
                    break  # anchor finished: remaining events are moot
                height = agents[self.anchor].iteration
                while pending and pending[0].round <= height:
                    ev = pending.pop(0)
                    await self._apply(ev, agents, tasks)
                await asyncio.sleep(self.poll_s)
            results = await asyncio.gather(
                *tasks.values(), return_exceptions=True)
        except BaseException:
            for t in tasks.values():
                t.cancel()
            await asyncio.gather(*tasks.values(), return_exceptions=True)
            raise
        out = []
        for node, res in zip(tasks.keys(), results):
            if isinstance(res, BaseException):
                # a hard-killed agent whose final incarnation never ran
                # to completion: report its last observable state
                a = agents[node]
                out.append({"node": node, "iterations": a.iteration,
                            "converged": a.converged,
                            "chain_dump": a.chain.dump(),
                            "counters": dict(a.counters),
                            "telemetry": a.telemetry_snapshot(),
                            "killed": True})
            else:
                out.append(res)
        return out

    async def _apply(self, ev: faults.ChurnEvent, agents, tasks) -> None:
        self.events_applied.append((ev.round, ev.node, ev.kind))
        if ev.kind == faults.KILL:
            task = tasks.get(ev.node)
            if task is not None and not task.done():
                await self._hard_kill(agents[ev.node], task)
        elif (ev.kind == faults.MIGRATE
                and self.migrate_factory is not None):
            # live migration (docs/PLACEMENT.md): serialize BEFORE the
            # kill — the ticket is the only thing that survives the
            # teardown — then relaunch from it; downtime spans capture
            # through first schedulable relaunch, the window the bench
            # `migration_downtime_s` key regresses on
            import time as _time

            from biscotti_tpu.runtime import placement

            old = tasks.get(ev.node)
            agent = agents.get(ev.node)
            t0 = _time.monotonic()
            ticket = (placement.ticket_from_agent(agent)
                      if agent is not None else None)
            if old is not None and not old.done():
                await self._hard_kill(agent, old)
            agents[ev.node] = self.migrate_factory(ev.node, ticket)
            tasks[ev.node] = asyncio.ensure_future(agents[ev.node].run())
            self.migrations.append({
                "round": ev.round, "node": ev.node,
                "downtime_s": round(_time.monotonic() - t0, 4),
                "ticket_bytes": (placement.ticket_nbytes(ticket)
                                 if ticket is not None else 0)})
        else:  # RESTART / JOIN: fresh agent, fresh incarnation
            old = tasks.get(ev.node)
            if old is not None and not old.done():
                await self._hard_kill(agents[ev.node], old)
            agents[ev.node] = self.make_agent(ev.node)
            tasks[ev.node] = asyncio.ensure_future(agents[ev.node].run())
