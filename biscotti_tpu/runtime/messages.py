"""Wire codec: length-prefixed frames carrying a JSON header + raw or
codec-compressed ndarray payloads.

Replaces the reference's encoding/gob (ref: DistSys/main.go:609-610 gob type
registration; kyber points marshalled to []byte for the wire,
kyber.go:88-168). Dense float/int arrays — the bulk of every message — ride
as raw little-endian bytes after the header, so a d=7,850 update costs
~63 KB, not a JSON blow-up; everything else (ids, iterations, commitments as
hex) is JSON. No pickle anywhere: peers are untrusted
(Byzantine model), and the decoder only materialises declared dtypes/shapes.

Frame:    [u32 BE frame_len][payload]
Payload:  [u32 BE header_len][header JSON][array bytes …]
Header:   {"type": str, "meta": {...}, "arrays": [{"name","dtype","shape",
           ("codec","nbytes")?}], ("codec": str)?}

Wire data plane (runtime/codecs.py, docs/WIRE_PLANE.md): when a codec is
negotiated, eligible float arrays travel as coded payloads — the
descriptor then carries the applied per-array stage tag plus the coded
byte count, and the header's frame-level "codec" names the negotiated
pipeline (the telemetry label). Arrays without a tag are the legacy raw
encoding byte-for-byte, so an old peer's frames decode unchanged and a
raw64-negotiated frame is bit-identical to the seed format.

Distributed tracing (telemetry/tracectx.py): toward peers that
advertised the `trace` capability, meta carries one compact entry
`"_tr": [trace_id, span_id, round]` — the sender's current span, which
the receiver's dispatch span adopts as parent. It is ordinary meta:
this codec neither adds nor strips it, so untraced frames are
byte-identical to the pre-tracing format, and a chunked payload carries
it in the header that rides the head of the continuation run.

Chunked streaming: a payload larger than `chunk_bytes` is emitted as a
run of continuation frames, each payload-prefixed with CHUNK_MAGIC + a
flags byte (bit 0 = last). rpc.FrameStream reassembles the run back into
one payload before decode, enforcing MAX_FRAME on the REASSEMBLED size —
so honest multi-MB payloads never require a single multi-MB socket read
buffer, while the frame cap still bounds total memory. CHUNK_MAGIC is an
impossible header length (> MAX_FRAME), so a pre-chunking decoder rejects
a stray chunk frame as malformed instead of misparsing it; senders only
chunk toward peers that advertised the `chunk` capability.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from biscotti_tpu.runtime import codecs as wcodecs

MAX_FRAME = 256 * 1024 * 1024  # hard cap against hostile length prefixes

# continuation-chunk framing (see module docstring): payload =
# MAGIC(4) + flags(1) + chunk bytes; MIN_CHUNK floors hostile/absurd
# chunk sizes so a reply cannot be shattered into per-byte frames
CHUNK_MAGIC = b"\xff\xff\xff\xff"
CHUNK_LAST = 0x01
CHUNK_OVERHEAD = 4 + 1  # magic + flags, per chunk payload
MIN_CHUNK = 64 * 1024

_ALLOWED_DTYPES = {"float32", "float64", "int32", "int64", "uint8", "bool"}


class CodecError(ValueError):
    pass


def _chunk_frames(payload_parts: list, chunk_bytes: int) -> list:
    """Split one frame payload (a list of buffers) into a run of
    continuation-chunk frames without flattening: chunk bodies are
    sub-views of the original buffers."""
    # clamp so every chunk FRAME (body + magic + flags) stays inside the
    # reader's frame cap — a near-MAX_FRAME chunk size must not produce
    # frames the receiving FrameStream rejects outright
    chunk_bytes = min(max(MIN_CHUNK, int(chunk_bytes)),
                      MAX_FRAME - CHUNK_OVERHEAD)
    views = [memoryview(p) if not isinstance(p, memoryview) else p
             for p in payload_parts]
    chunks: list = []  # list of (body_parts, body_len)
    cur: list = []
    cur_len = 0
    for v in views:
        off = 0
        while off < len(v):
            take = min(len(v) - off, chunk_bytes - cur_len)
            cur.append(v[off: off + take])
            cur_len += take
            off += take
            if cur_len == chunk_bytes:
                chunks.append((cur, cur_len))
                cur, cur_len = [], 0
    chunks.append((cur, cur_len))  # final (possibly empty) chunk
    out: list = []
    for i, (body, blen) in enumerate(chunks):
        last = i == len(chunks) - 1
        out.append(struct.pack(">I", CHUNK_OVERHEAD + blen))
        out.append(CHUNK_MAGIC)
        out.append(bytes([CHUNK_LAST if last else 0]))
        out.extend(body)
    return out


def encode_parts(msg_type: str, meta: Dict[str, Any] | None = None,
                 arrays: Dict[str, np.ndarray] | None = None,
                 codec: Optional[str] = None, chunk_bytes: int = 0,
                 stats: Optional[dict] = None) -> list:
    """Frame as a list of buffers (prefix + header + one memoryview per
    array) for part-wise transport writes — multi-MB payloads (VSS
    commitment tensors, model weights) never get flattened into one big
    bytearray on the event loop. The views alias the caller's arrays:
    callers must not mutate an array between handing it to the codec and
    the write draining (protocol code treats packed arrays as immutable —
    fresh per round).

    `codec` (a negotiated codecs.py pipeline name) compresses eligible
    float arrays; `chunk_bytes` > 0 splits an oversized payload into
    continuation chunks (only toward peers that advertised the `chunk`
    capability). `stats`, when given, is filled with
    {"raw_bytes", "wire_bytes"} for the byte-accounting plane."""
    meta = meta or {}
    arrays = arrays or {}
    wc = (wcodecs.get(codec)
          if codec and codec != wcodecs.RAW else None)
    descs = []
    blobs = []
    nbytes = 0
    raw_bytes = 0
    coded = False
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype.name not in _ALLOWED_DTYPES:
            raise CodecError(f"dtype {arr.dtype} not allowed on the wire")
        raw_bytes += arr.nbytes
        desc = {"name": name, "dtype": arr.dtype.name,
                "shape": list(arr.shape)}
        enc = wc.encode_array(arr) if wc is not None else None
        if enc is not None:
            buf, tag = enc
            desc["codec"] = tag
            desc["nbytes"] = len(buf)
            mv = memoryview(buf)
            coded = True
        else:
            mv = memoryview(arr).cast("B")
        descs.append(desc)
        blobs.append(mv)
        nbytes += len(mv)
    hobj: Dict[str, Any] = {"type": msg_type, "meta": meta, "arrays": descs}
    if coded:
        hobj["codec"] = wc.name
    header = json.dumps(hobj, separators=(",", ":")).encode()
    total = 4 + len(header) + nbytes
    # encoder and reader share ONE bound: payload <= MAX_FRAME — a
    # maximal frame produced here is accepted by FrameStream, and
    # vice versa (the seed's encoder was 4 bytes stricter than its
    # reader, an off-by-frame-prefix asymmetry)
    if total > MAX_FRAME:
        raise CodecError("frame too large")
    payload = [struct.pack(">I", len(header)), header] + blobs
    if chunk_bytes and total > max(MIN_CHUNK, int(chunk_bytes)):
        parts = _chunk_frames(payload, chunk_bytes)
    else:
        parts = [struct.pack(">I", total)] + payload
    if stats is not None:
        stats["raw_bytes"] = 8 + len(header) + raw_bytes
        stats["wire_bytes"] = sum(len(p) for p in parts)
        # the EFFECTIVE frame codec — raw64 when no array actually took
        # a coded path (e.g. a crypto-only RegisterSecret toward a
        # codec-negotiated peer): byte accounting must label what went
        # on the wire, matching what the receiver's header-driven count
        # will say, not what was negotiated
        stats["codec"] = hobj.get("codec", wcodecs.RAW)
    return parts


def encode(msg_type: str, meta: Dict[str, Any] | None = None,
           arrays: Dict[str, np.ndarray] | None = None,
           codec: Optional[str] = None, chunk_bytes: int = 0,
           stats: Optional[dict] = None) -> bytes:
    """One contiguous frame (or run of chunk frames) — for pre-encoded
    broadcast frames written to many peers (encode once, write N
    times); per-call paths use encode_parts."""
    return b"".join(encode_parts(msg_type, meta, arrays, codec=codec,
                                 chunk_bytes=chunk_bytes, stats=stats))


def peek_header(payload) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Parse ONLY the frame's JSON header — no array materialization, no
    zlib inflate. The admission boundary (rpc.RPCServer, docs/ADMISSION.md)
    budgets every frame on (msg_type, meta) BEFORE paying its decode
    cost; without this, a flooder's shed frames would still pin the
    event loop with full-frame decompression. Returns None on any
    malformation (callers drop the connection, exactly as decode's
    CodecError path would). `meta["_wire_codec"]` is set from the header
    the same authoritative way decode sets it."""
    try:
        if len(payload) < 4:
            return None
        (hlen,) = struct.unpack(">I", payload[:4])
        if hlen > len(payload) - 4:
            return None
        header = json.loads(bytes(payload[4: 4 + hlen]).decode())
        msg_type = header["type"]
        meta = header.get("meta", {})
        if not isinstance(msg_type, str) or not isinstance(meta, dict):
            return None
        meta["_wire_codec"] = header.get("codec", wcodecs.RAW)
        return msg_type, meta
    except Exception:
        return None


def decode(payload: bytes) -> Tuple[str, Dict[str, Any], Dict[str, np.ndarray]]:
    """Decode one frame payload (the bytes after the frame-length prefix,
    chunk runs already reassembled by rpc.FrameStream). Raises CodecError
    on any malformation — a Byzantine peer must not be able to crash an
    honest one with a bad frame, inflate a decompression bomb (the summed
    DECLARED decoded sizes are capped at MAX_FRAME before any inflate
    runs, and each coded array's inflate is bounded by its declared
    shape), or smuggle a spoofed codec label (`meta["_wire_codec"]` is
    overwritten from the header, never trusted from meta)."""
    try:
        if len(payload) < 4:
            raise CodecError("short frame")
        (hlen,) = struct.unpack(">I", payload[:4])
        if hlen > len(payload) - 4:
            raise CodecError("header length exceeds frame")
        header = json.loads(payload[4 : 4 + hlen].decode())
        msg_type = header["type"]
        meta = header.get("meta", {})
        if not isinstance(msg_type, str) or not isinstance(meta, dict):
            raise CodecError("malformed header")
        arrays: Dict[str, np.ndarray] = {}
        # toreadonly(): FrameStream may hand us a bytearray-backed frame, and
        # frombuffer over a writable buffer yields writable views — force the
        # read-only invariant regardless of the payload's buffer type
        mv = memoryview(payload).toreadonly()
        off = 4 + hlen
        declared = 0
        for desc in header.get("arrays", []):
            dtype = desc["dtype"]
            if dtype not in _ALLOWED_DTYPES:
                raise CodecError(f"dtype {dtype} not allowed")
            shape = tuple(int(s) for s in desc["shape"])
            if any(s < 0 for s in shape):
                raise CodecError("negative dim")
            count = int(np.prod(shape)) if shape else 1
            nbytes = count * np.dtype(dtype).itemsize
            declared += nbytes
            if declared > MAX_FRAME:
                raise CodecError("declared decoded size exceeds frame cap")
            tag = desc.get("codec")
            if tag:
                enc_n = int(desc["nbytes"])
                if enc_n < 0 or off + enc_n > len(payload):
                    raise CodecError("coded bytes exceed frame")
                try:
                    arrays[desc["name"]] = wcodecs.decode_array(
                        mv[off: off + enc_n], dtype, shape, tag)
                except wcodecs.WireCodecError as e:
                    raise CodecError(f"bad coded array: {e}") from e
                off += enc_n
                continue
            if off + nbytes > len(payload):
                raise CodecError("array bytes exceed frame")
            # zero-copy READ-ONLY view into the frame (frombuffer over
            # bytes is non-writable): receivers that need a mutable or
            # differently-typed array copy at their own call site; an
            # accidental in-place write raises instead of corrupting
            arrays[desc["name"]] = np.frombuffer(
                mv[off: off + nbytes], dtype=dtype).reshape(shape)
            off += nbytes
        # frame-level codec label for the byte-accounting plane —
        # authoritative from the header, squashing any spoofed meta key
        meta["_wire_codec"] = header.get("codec", wcodecs.RAW)
        return msg_type, meta, arrays
    except CodecError:
        raise
    except Exception as e:  # json errors, missing keys, bad shapes …
        raise CodecError(f"bad frame: {e}") from e


# NOTE: frame READING lives in rpc.FrameStream (BufferedProtocol — the
# transport fills each frame's preallocated buffer directly, and
# reassembles continuation-chunk runs); this module owns only the byte
# format (length prefix + encode/decode + chunk splitting).
