"""Wire codec: length-prefixed frames carrying a JSON header + raw ndarray
payloads.

Replaces the reference's encoding/gob (ref: DistSys/main.go:609-610 gob type
registration; kyber points marshalled to []byte for the wire,
kyber.go:88-168). Dense float/int arrays — the bulk of every message — ride
as raw little-endian bytes after the header, so a d=7,850 update costs
~63 KB, not a JSON blow-up; everything else (ids, iterations, commitments as
hex) is JSON. No pickle anywhere: peers are untrusted
(Byzantine model), and the decoder only materialises declared dtypes/shapes.

Frame:    [u32 BE frame_len][payload]
Payload:  [u32 BE header_len][header JSON][array bytes …]
Header:   {"type": str, "meta": {...}, "arrays": [{"name","dtype","shape"}]}
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Tuple

import numpy as np

MAX_FRAME = 256 * 1024 * 1024  # hard cap against hostile length prefixes

_ALLOWED_DTYPES = {"float32", "float64", "int32", "int64", "uint8", "bool"}


class CodecError(ValueError):
    pass


def encode_parts(msg_type: str, meta: Dict[str, Any] | None = None,
                 arrays: Dict[str, np.ndarray] | None = None) -> list:
    """Frame as a list of buffers (prefix + header + one memoryview per
    array) for part-wise transport writes — multi-MB payloads (VSS
    commitment tensors, model weights) never get flattened into one big
    bytearray on the event loop. The views alias the caller's arrays:
    callers must not mutate an array between handing it to the codec and
    the write draining (protocol code treats packed arrays as immutable —
    fresh per round)."""
    meta = meta or {}
    arrays = arrays or {}
    descs = []
    blobs = []
    nbytes = 0
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype.name not in _ALLOWED_DTYPES:
            raise CodecError(f"dtype {arr.dtype} not allowed on the wire")
        descs.append({"name": name, "dtype": arr.dtype.name,
                      "shape": list(arr.shape)})
        mv = memoryview(arr).cast("B")
        blobs.append(mv)
        nbytes += len(mv)
    header = json.dumps({"type": msg_type, "meta": meta, "arrays": descs},
                        separators=(",", ":")).encode()
    total = 4 + len(header) + nbytes
    if total + 4 > MAX_FRAME:
        raise CodecError("frame too large")
    return [struct.pack(">I", total), struct.pack(">I", len(header)),
            header] + blobs


def encode(msg_type: str, meta: Dict[str, Any] | None = None,
           arrays: Dict[str, np.ndarray] | None = None) -> bytes:
    """One contiguous frame — for pre-encoded broadcast frames written to
    many peers (encode once, write N times); per-call paths use
    encode_parts."""
    return b"".join(encode_parts(msg_type, meta, arrays))


def decode(payload: bytes) -> Tuple[str, Dict[str, Any], Dict[str, np.ndarray]]:
    """Decode one frame payload (the bytes after the frame-length prefix).
    Raises CodecError on any malformation — a Byzantine peer must not be
    able to crash an honest one with a bad frame."""
    try:
        if len(payload) < 4:
            raise CodecError("short frame")
        (hlen,) = struct.unpack(">I", payload[:4])
        if hlen > len(payload) - 4:
            raise CodecError("header length exceeds frame")
        header = json.loads(payload[4 : 4 + hlen].decode())
        msg_type = header["type"]
        meta = header.get("meta", {})
        if not isinstance(msg_type, str) or not isinstance(meta, dict):
            raise CodecError("malformed header")
        arrays: Dict[str, np.ndarray] = {}
        # toreadonly(): FrameStream may hand us a bytearray-backed frame, and
        # frombuffer over a writable buffer yields writable views — force the
        # read-only invariant regardless of the payload's buffer type
        mv = memoryview(payload).toreadonly()
        off = 4 + hlen
        for desc in header.get("arrays", []):
            dtype = desc["dtype"]
            if dtype not in _ALLOWED_DTYPES:
                raise CodecError(f"dtype {dtype} not allowed")
            shape = tuple(int(s) for s in desc["shape"])
            if any(s < 0 for s in shape):
                raise CodecError("negative dim")
            count = int(np.prod(shape)) if shape else 1
            nbytes = count * np.dtype(dtype).itemsize
            if off + nbytes > len(payload):
                raise CodecError("array bytes exceed frame")
            # zero-copy READ-ONLY view into the frame (frombuffer over
            # bytes is non-writable): receivers that need a mutable or
            # differently-typed array copy at their own call site; an
            # accidental in-place write raises instead of corrupting
            arrays[desc["name"]] = np.frombuffer(
                mv[off: off + nbytes], dtype=dtype).reshape(shape)
            off += nbytes
        return msg_type, meta, arrays
    except CodecError:
        raise
    except Exception as e:  # json errors, missing keys, bad shapes …
        raise CodecError(f"bad frame: {e}") from e


# NOTE: frame READING lives in rpc.FrameStream (BufferedProtocol — the
# transport fills each frame's preallocated buffer directly); this module
# owns only the byte format (length prefix + encode/decode).
