"""Hierarchical aggregation overlay: the wire plane's O(N) -> O(log N)
cross-host traffic tree (docs/OVERLAY.md).

The hive runtime broke the single-box scale wall, which moved the live
frontier onto bandwidth: gossip and share fan-out are flat — every peer
talks to a constant fraction of the cluster — so bytes/round grows O(N)
while device time is milliseconds. This module derives a deterministic,
seed-derived aggregation tree per round:

  * **leaves** are peers;
  * the **first interior level** is the hive host itself — peers are
    grouped into contiguous id blocks of `cfg.overlay_group`, matching
    the `pod_launch --peers-per-host` layout, so the leaf -> interior hop
    is loopback (nearly free) on a co-hosted deployment;
  * the **root** is the round's elected miner set (the leader mints).

Each group elects one RELAY per round — a pure function of
(seed, iteration, group), so every peer derives the same tree with no
coordination and the relay duty rotates instead of pinning one peer hot.
Interior nodes are ordinary untrusted peers: their admission plans class
relay/aggregate frames as bulk (a hot interior node sheds, it doesn't
melt), and a missing relay degrades to the seed's direct delivery for
its orphaned subtree within the round (the sender falls back on the
first transport failure).

What flows through the tree:

  * secure-agg share fan-out — workers offer their full share/blind/
    commitment tensors to the relay (`OverlayOffer`); the relay sums the
    share rows, sums the blind rows mod q, and homomorphically sums the
    Pedersen commitment grids (crypto/commitments.sum_commitment_grids),
    then sends ONE `RegisterAggregate` per miner; the miner verifies the
    whole subtree against the summed commitment in one RLC check
    (vss_verify_multi, single instance = exact) and falls back to the
    per-update path for exact rejection evidence when it fails;
  * plain-mode update fan-out and the minted-block broadcast — relayed
    verbatim (`RelayFrames`): content is untouched (chains stay
    bit-identical), but a frame crosses TCP once per remote subtree
    instead of once per remote peer.

Per-update verification traffic (Krum/FoolsGold/RONI evidence, verifier
signature quorums, stake debits) stays point-to-point and unaggregated,
so the VERDICT plane is unchanged by construction.

KNOWN RESIDUAL (docs/OVERLAY.md §trust-model): the miner verifies a
subtree against the relay-supplied summed grid; the per-member digest
binding (vss_digest(comms) == signed commitment) is enforceable only
where per-member grids exist — at the relay, not the root. A Byzantine
RELAY can therefore substitute a self-consistent aggregate for its own
subtree while reusing the members' genuine signed metadata. In the
deployed shape this adds no power — the interior level is the members'
own hive host, which already computes their SGD and holds their key
streams — and that is exactly why aggregation is restricted to a
worker's OWN group. Operators forming groups across trust domains are
choosing to trust the rotating relay with its subtree's round
contribution (never with stake, identity, or the verdict plane).

`cfg.overlay` defaults OFF: the disabled configuration produces the
seed's flat fan-out bit-for-bit (every overlay path is gated at the send
site; tests/test_overlay.py guards this).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Sequence, Tuple

import numpy as np

# wire frame types the overlay adds (classed `bulk` by the admission
# plane, runtime/admission.py)
OFFER = "OverlayOffer"
AGGREGATE = "RegisterAggregate"
RELAY = "RelayFrames"

# telemetry (docs/OBSERVABILITY.md §overlay)
DEPTH_GAUGE = "biscotti_overlay_tree_depth"
DEPTH_HELP = "levels in the round's aggregation tree (1 = flat fan-out)"
SUBTREE_GAUGE = "biscotti_overlay_subtree_size"
SUBTREE_HELP = "peers in this peer's overlay subtree (its relay group)"
SAVED_METRIC = "biscotti_overlay_bytes_saved_total"
SAVED_HELP = ("estimated cross-host bytes the overlay avoided "
              "(raw64-frame estimate of the deduplicated sends)")
FRAMES_METRIC = "biscotti_overlay_frames_total"
FRAMES_HELP = "overlay frames by kind (aggregated / relayed / fallback)"


def frame_estimate(meta, arrays) -> int:
    """Bytes this payload would cost as one raw64 frame (JSON header +
    raw array bytes + framing) — the bytes-saved accounting estimates
    avoided traffic the same way the hive's loopback accounting does."""
    n = 64
    try:
        n += len(json.dumps(meta or {}, separators=(",", ":"),
                            default=str))
    except (TypeError, ValueError):
        n += 256
    for a in (arrays or {}).values():
        n += np.asarray(a).nbytes
    return n


class Router:
    """Deterministic tree derivation + routing plans for one peer.

    Groups are contiguous id blocks of `group` peers (the pod_launch
    host layout); the per-round relay inside each group is
    members[H(seed, iteration, gid) % len] — every peer derives the
    identical tree from config alone. Inactive (enabled=False) when the
    overlay flag is off or the group size cannot form a subtree."""

    def __init__(self, overlay: bool, group: int, num_nodes: int,
                 seed: int):
        self.group = int(group)
        self.num_nodes = int(num_nodes)
        self.seed = int(seed)
        self.enabled = bool(overlay) and self.group >= 2
        # leaves -> host relays -> miner root when armed; flat otherwise
        self.depth = 3 if self.enabled else 1

    @classmethod
    def from_config(cls, cfg) -> "Router":
        return cls(cfg.overlay, cfg.overlay_group, cfg.num_nodes, cfg.seed)

    # ------------------------------------------------------- derivation

    def gid_of(self, pid: int) -> int:
        return int(pid) // self.group if self.group else 0

    def members(self, gid: int) -> List[int]:
        lo = gid * self.group
        return list(range(lo, min(lo + self.group, self.num_nodes)))

    def relay(self, gid: int, iteration: int) -> int:
        """The group's relay for `iteration` — seed-derived rotation, so
        the interior duty (and its bandwidth/CPU cost) moves every
        round instead of pinning one peer."""
        mem = self.members(gid)
        h = hashlib.sha256(
            f"biscotti-overlay|{self.seed}|{int(iteration)}|{gid}"
            .encode()).digest()
        return mem[int.from_bytes(h[:8], "little") % len(mem)]

    def my_relay(self, pid: int, iteration: int) -> int:
        return self.relay(self.gid_of(pid), iteration)

    # ---------------------------------------------------------- routing

    def plan(self, targets: Sequence[int], iteration: int,
             self_id: int) -> Tuple[List[int], Dict[int, List[int]]]:
        """Split a fan-out target list into (direct, {relay: targets}).

        A subtree is relayed only when it actually deduplicates traffic
        (>= 2 targets inside it) and the relay is a third party — the
        sender's own group is always direct (those links are loopback or
        same-host already), as is a group whose relay IS the sender."""
        direct: List[int] = []
        relayed: Dict[int, List[int]] = {}
        if not self.enabled:
            return list(targets), relayed
        by_gid: Dict[int, List[int]] = {}
        for t in targets:
            by_gid.setdefault(self.gid_of(t), []).append(int(t))
        my_gid = self.gid_of(self_id)
        for gid, ts in sorted(by_gid.items()):
            if gid == my_gid or len(ts) < 2:
                direct.extend(ts)
                continue
            r = self.relay(gid, iteration)
            if r == self_id:
                direct.extend(ts)
            else:
                relayed.setdefault(r, []).extend(ts)
        return direct, relayed
