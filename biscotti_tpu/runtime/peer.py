"""The peer agent — per-host protocol state machine.

One asyncio process per peer, replacing the reference's Go binary
(ref: DistSys/main.go). The RPC surface is the reference's nine `Peer`
methods (SURVEY.md §2.1 row 2); round math (SGD step, DP noise, Krum/RONI,
share algebra) dispatches to the jitted XLA Trainer/ops layers; EC crypto
(commitments, Schnorr, VRF) runs on the host via biscotti_tpu.crypto.

Round choreography (ref: SURVEY.md §3):
  worker   : compute update → noise from noisers → verifier signatures →
             shares to miners (secure-agg) or update to miners (plain)
  verifier : collect updates to threshold → Krum/RONI on device → release
             parked callers with signatures / rejections
  miner    : collect updates|shares → leader mints block at deadline →
             broadcast; everyone holds an empty-block fallback timer so the
             round ALWAYS advances (ref: main.go:2099-2143)
  noiser   : serve presampled DP noise (ref: honest.go:564-592)

FedSys mode (cfg.fedsys): fixed leader node 0, no committees/crypto, deltas
AVERAGED not summed (ref: FedSys/honest.go:311) — the baseline system as a
config flag.

Single-threaded asyncio replaces the reference's goroutine+mutex web: every
state transition happens on the event loop, so rounds are linearizable by
construction (the races patched ad-hoc in the reference, e.g.
main.go:1481-1482, cannot occur).
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import os
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from biscotti_tpu.config import BiscottiConfig, Defense
from biscotti_tpu.crypto import commitments as cm
from biscotti_tpu.crypto import kernels as devkern
from biscotti_tpu.crypto.vrf import VRFKey
from biscotti_tpu.data import datasets as ds
from biscotti_tpu.ledger.block import Block, BlockData, Update
from biscotti_tpu.ledger.chain import Blockchain, ChainInvariantError
from biscotti_tpu.models.trainer import Trainer
from biscotti_tpu.ops import secretshare as ss
from biscotti_tpu.ops import trust as trustlib
from biscotti_tpu.parallel import roles as R
from biscotti_tpu.parallel.sim import _poisoned_ids
from biscotti_tpu.runtime import admission as adm
from biscotti_tpu.runtime import adversary
from biscotti_tpu.runtime import codecs as wcodecs
from biscotti_tpu.runtime import faults, rpc, wire
from biscotti_tpu.runtime import overlay as ov
from biscotti_tpu.runtime import placement
from biscotti_tpu.runtime import protocol
from biscotti_tpu.runtime import stragglers
from biscotti_tpu.runtime.faults import CircuitOpenError
from biscotti_tpu.runtime.rpc import BusyError, RPCError, StaleError
from biscotti_tpu.telemetry import Telemetry, serve_metrics, tracectx
from biscotti_tpu.tools import keygen


# keyless-mode derived keypairs, cached module-wide: in-process clusters
# construct N agents that each need all N publics — deriving them N² times
# (a base mult each) would dominate small-test startup
_keyless_pub_cache: Dict[Tuple[int, int], Tuple[bytes, bytes]] = {}


def _keyless_pubs(seed: int, node: int) -> Tuple[bytes, bytes]:
    """(schnorr_pub, vrf_noise_pub) for a keyless-mode node. The seeds are
    deterministic in (cfg.seed, id), so every peer can derive every public —
    no integrity in a hostile deployment (pass --key-dir for that), but the
    full verification code path runs in local tests."""
    key = (seed, node)
    if key not in _keyless_pub_cache:
        from biscotti_tpu.crypto import ed25519 as ed

        s_seed = hashlib.sha256(f"schnorr-{seed}-{node}".encode()).digest()
        n_seed = hashlib.sha256(f"vrf-noise-{seed}-{node}".encode()).digest()
        _keyless_pub_cache[key] = (ed.public_key(s_seed),
                                   VRFKey(n_seed).public)
    return _keyless_pub_cache[key]


def _decline_message(iteration: int, sid: int) -> bytes:
    """Domain-separated payload a rejected worker signs to tell miners it
    will not contribute this round (see RoundState.miner_declined)."""
    return (b"biscotti-decline|" + int(iteration).to_bytes(8, "little")
            + int(sid).to_bytes(8, "little"))


def partial_batch_members(batch_of: Dict[int, frozenset],
                          nodes: Sequence[int]) -> List[int]:
    """Sids in `nodes` whose verification batch is NOT fully contained in
    `nodes`. The aggregated VSS check (cm.vss_verify_multi) proves
    consistency of each intake batch AS A WHOLE; error cancellation inside
    a batch is harmless only when the whole batch is aggregated, so an
    aggregate over a partial batch must re-prove exactly these members at
    the aggregation boundary (docs/NATIVE_CRYPTO.md §aggregated-vss)."""
    nset = set(nodes)
    return [n for n in nodes
            if batch_of.get(n) is None or not batch_of[n] <= nset]


@dataclass
class RoundState:
    """Everything scoped to one iteration; rebuilt on every round
    transition (the reference's flushUpdates/flushSecrets,
    ref: main.go:1096-1107)."""

    iteration: int
    verifier_pool: List[Update] = field(default_factory=list)
    verifier_sources: Set[int] = field(default_factory=set)
    krum_decision: Optional[asyncio.Future] = None
    miner_updates: Dict[int, Update] = field(default_factory=dict)
    miner_shares: Dict[int, np.ndarray] = field(default_factory=dict)
    miner_commitments: Dict[int, bytes] = field(default_factory=dict)
    # secure-agg intake is accepted OPTIMISTICALLY (digest + shape +
    # signature checks at intake); the share-vs-commitment VSS check runs
    # ONCE per round as a single batched RLC+MSM over the whole intake just
    # before shares are served/aggregated, with per-worker fallback to
    # identify offenders when the batch fails
    miner_vss: Dict[int, Tuple[np.ndarray, np.ndarray]] = field(
        default_factory=dict)
    # (comms, blinds) retained for sids that passed verification, plus the
    # batch each sid was verified IN: the aggregated check is sound for an
    # aggregate covering a WHOLE batch, so serving any partial batch
    # re-checks exactly the partial members against these records (see
    # docs/NATIVE_CRYPTO.md §aggregated-vss and _ensure_subset_consistent)
    miner_vss_records: Dict[int, Tuple[np.ndarray, np.ndarray]] = field(
        default_factory=dict)
    miner_vss_batch: Dict[int, frozenset] = field(default_factory=dict)
    vss_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # worker-provided verifier signatures, carried into the minted block's
    # update records so block quorums are re-verifiable by every receiver
    # and by future joiners adopting the chain
    miner_sigs: Dict[int, Tuple[List[int], List[bytes]]] = field(
        default_factory=dict)
    # this round's share-point slice for our miner index, FROZEN at round
    # start: the deferred intake verification must never consult the next
    # round's committee if a block lands mid-check
    my_xs: Optional[List[int]] = None
    # sources whose submission failed cryptographic verification this round:
    # carried into the minted block as accepted=False records and debited
    # STAKE_UNIT (ref: honest.go:363-370 debits rejected block updates)
    miner_rejected: Dict[int, Update] = field(default_factory=dict)
    # sampled workers that signed a DECLINE notice (their update was
    # refused by the verifier committee, so they will not contribute):
    # completes the miner's have+rejected >= NUM_SAMPLES mint condition,
    # which otherwise can never fire when Krum approves fewer than the
    # mint target (short pools accept pool − pool//2) and the round rides
    # the full update deadline — observed as ~90 s stalls in ~4% of
    # rounds at N=100
    miner_declined: Set[int] = field(default_factory=set)
    # the one aggregation set this miner will serve this round: releasing
    # aggregates over a SECOND, different subset would let a malicious
    # leader difference the two sums and unmask an individual update
    served_part: Optional[List[int]] = None
    # incremental VSS intake accumulator (cfg.batch_intake,
    # crypto/commitments.VssIntakeBatch): arriving share slices are
    # folded into one running point sum in waves, so mint-time
    # verification is just the RLC settle — the grid-summation lump the
    # one-shot batch check paid on the critical path amortizes across
    # the round's network wait. Consumed (set back to None) when a
    # batch retires; later arrivals start a fresh accumulator.
    vss_accum: Optional[cm.VssIntakeBatch] = None
    # plain-mode intake micro-batch (cfg.batch_intake): updates arriving
    # in a burst after the defense decision wait here ~one event-loop
    # beat and are verified as ONE batched RLC commitment check, with
    # bisection identifying offenders exactly as the sequential
    # recompute would (crypto/commitments.batch_verify_commitments).
    # A LIST, not a per-sid dict: every submission is verified against
    # its OWN payload — a Byzantine double-send with the same source_id
    # but different bytes must not inherit the first copy's verdict
    plain_pending: List[Tuple[Update, asyncio.Future]] = field(
        default_factory=list)
    plain_drainer: Optional[asyncio.Task] = None
    # hierarchical aggregation overlay (cfg.overlay, docs/OVERLAY.md) —
    # MINER side: whole-subtree aggregates accepted via
    # RegisterAggregate. A group entry holds the summed share-row slice,
    # the homomorphically summed commitment grid, and the summed blind
    # tensor; miner_group_of maps each member sid to its group so the
    # mint/serve paths treat a subtree as one atomic intake component
    # (servable whole or not at all — the group sum cannot be subset).
    miner_groups: Dict[frozenset, Dict] = field(default_factory=dict)
    miner_group_of: Dict[int, frozenset] = field(default_factory=dict)
    # RELAY side: co-hosted workers' OverlayOffer payloads buffered until
    # the flush (all expected leaves offered, or the window expired);
    # flushed sids are remembered so a late wave aggregates separately
    # instead of double-counting
    relay_offers: Dict[int, Dict] = field(default_factory=dict)
    relay_flushed: Set[int] = field(default_factory=set)
    relay_task: Optional[asyncio.Task] = None
    block_done: Optional[asyncio.Event] = None
    tasks: List[asyncio.Task] = field(default_factory=list)


class PeerAgent:
    def __init__(self, cfg: BiscottiConfig, key_dir: str = "",
                 log_path: str = "", ckpt_dir: str = "", ckpt_every: int = 10,
                 stepper=None, hive=None, light_trainer: bool = False,
                 ticket: Optional[Dict] = None):
        self.cfg = cfg
        # peers-as-devices mode: a shared BatchStepper (or the hive's
        # HiveStepper) computes ALL local peers' SGD deltas in one
        # batched XLA call per round (runtime/device_cluster.py,
        # runtime/hive.py); None = per-agent trainer dispatch
        self.stepper = stepper
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = max(1, ckpt_every)
        self.id = cfg.node_id
        self.converged = False
        self.total_updates = 0

        poisoned = _poisoned_ids(cfg.num_nodes, cfg.poison_fraction)
        shard = ds.shard_name(cfg.dataset, self.id, self.id in poisoned)
        # light trainers (hive co-hosting) hold no per-peer train shard
        # or noise bank — the shared stepper serves both; eval splits and
        # metric fns remain (models/trainer.py docstring)
        self.trainer = Trainer(cfg.dataset, shard, cfg=cfg, seed=self.id,
                               light=light_trainer)
        self.chain = Blockchain(self.trainer.num_params, cfg.num_nodes,
                                cfg.default_stake)

        # peers: id -> (host, port); file format = host:port per line
        # (ref: peersfile.txt, README.md:49-66)
        self.peers: Dict[int, Tuple[str, int]] = {}
        if cfg.peers_file:
            with open(cfg.peers_file) as f:
                for i, addr in enumerate(a.strip() for a in f if a.strip()):
                    host, port = addr.rsplit(":", 1)
                    self.peers[i] = (host, int(port))
        else:
            for i in range(cfg.num_nodes):
                self.peers[i] = (cfg.my_ip, cfg.port_of(i))
        # membership: evicted peers stop receiving RPCs but keep their slot
        # in the id space (ref: main.go:1479-1482 — peerLookup never shrinks)
        self.alive: Set[int] = set(self.peers)
        # reverse address map for _peer_for_addr; kept in sync with the one
        # mutation site (_h_register_peer address updates)
        self._addr_to_pid: Dict[Tuple[str, int], int] = {
            addr: pid for pid, addr in self.peers.items()}

        # identity keys: from the dealer when provided, else derived
        # deterministically from (seed, id) so local tests need no keygen
        if key_dir:
            all_keys = keygen.load_node_keys(key_dir)
            keys = all_keys[str(self.id)]
            self.schnorr_seed = bytes.fromhex(keys["schnorr_seed"])
            self.noise_vrf = VRFKey(bytes.fromhex(keys["vrf_noise_seed"]))
            self.node_pubs = {
                int(i): bytes.fromhex(k["schnorr_pub"])
                for i, k in all_keys.items()
            }
            self.noise_pubs = {
                int(i): bytes.fromhex(k["vrf_noise_pub"])
                for i, k in all_keys.items()
            }
            self.commit_key = keygen.load_commit_key(key_dir)
        else:
            self.schnorr_seed = hashlib.sha256(
                f"schnorr-{cfg.seed}-{self.id}".encode()).digest()
            self.noise_vrf = VRFKey(hashlib.sha256(
                f"vrf-noise-{cfg.seed}-{self.id}".encode()).digest())
            pubs = {i: _keyless_pubs(cfg.seed, i)
                    for i in range(cfg.num_nodes)}
            self.node_pubs = {i: p[0] for i, p in pubs.items()}
            self.noise_pubs = {i: p[1] for i, p in pubs.items()}
            self.commit_key = None

        self.timeouts = cfg.timeouts  # already-scaled instance may be passed
        self.pool = rpc.Pool()  # persistent multiplexed connections
        # outbound dials must never squat on a cluster LISTEN port: on
        # hosts whose ephemeral range covers the protocol ports a pooled
        # connection could otherwise hold the source port another
        # co-hosted peer needs to bind (rpc.open_frame_stream redials)
        self.pool.avoid_local_ports = frozenset(
            p for _, p in self.peers.values())
        # wire data plane (runtime/codecs.py, docs/WIRE_PLANE.md): the
        # configured codec pipeline, our advertised capability set, and
        # what each peer advertised back (absent = assume legacy raw64).
        # Lossy stages project the delta BEFORE commitment/noising/
        # sharing (see _worker_flow) and the mint rounds global_w onto
        # the downcast grid (see _create_block), so the wire itself is
        # always bit-exact and all crypto survives compression.
        self.wire = wcodecs.get(cfg.wire_codec)
        # versioned protocol plane (runtime/protocol.py,
        # docs/PROTOCOL.md): ONE advertised feature set for every
        # negotiated family — codec stages, chunking, trace stamping,
        # busy-status, snapshot bootstrap, overlay relay — derived from
        # the config and (when --protocol-version pins an old row)
        # capped to that historical version's features. Feature tokens
        # ride the hello's existing `codecs` list: old builds ignore
        # unknown tokens and codec negotiation is all-or-raw64 over the
        # stages alone, so the extension is wire-compatible both ways.
        self.caps = protocol.advertised(cfg)
        # features we speak that a given peer's hello did not grant —
        # re-derived at every hello so the readout tracks restarts;
        # emission (feature_degraded trace + counter) deduped per
        # observed set in _record_caps
        self._degraded_seen: Dict[int, frozenset] = {}
        # hierarchical aggregation overlay (runtime/overlay.py,
        # docs/OVERLAY.md): the deterministic per-round tree this peer
        # routes bulk fan-out through. Inactive (seed-identical flat
        # fan-out) unless cfg.overlay armed a real group size.
        self.overlay = ov.Router.from_config(cfg)
        # relay flush window: how long an interior node waits for the
        # rest of its subtree's offers before shipping a partial
        # aggregate (late offers aggregate as a second wave) — scaled
        # off the share deadline so fast-timeout harness clusters flush
        # promptly while production keeps a wide batching window
        self.overlay_window_s = min(2.0, self.timeouts.share_s / 8)
        self.peer_caps: Dict[int, frozenset] = {}
        # top-k error-feedback residual (what sparsification dropped,
        # fed forward into next round's delta) — per-peer state: each
        # worker owns exactly one, for its own update stream
        self._ef_residual: Optional[np.ndarray] = None
        self._topk_k = max(1, int(round(cfg.wire_topk
                                        * self.trainer.num_params)))
        # per-peer circuit breaker (consecutive transport failures open it;
        # half-open probing re-closes it) — quarantined peers fail fast in
        # _call and are skipped by gossip fan-out instead of burning the
        # round budget re-timing-out (runtime/faults.py)
        self.health = faults.HealthLedger(
            threshold=cfg.breaker_threshold,
            cooldown_s=cfg.breaker_cooldown_s)
        if cfg.fault_plan.enabled:
            # deterministic chaos plane: every outbound frame's fate is a
            # pure function of (fault seed, src, dst, msg_type, attempt)
            self.pool.faults = faults.FaultInjector(
                cfg.fault_plan, self.id, self._peer_for_addr)
        # overload-governance plane (runtime/admission.py): ALWAYS
        # constructed — the inflight/parked accounting and the snapshot
        # schema exist either way — but only an ENABLED plan wires
        # enforcement into the RPC server boundary and arms the
        # slow-loris read deadline; a disabled plan admits everything
        # and parks without bound (the seed behavior)
        self.admission = adm.AdmissionController(cfg.admission_plan)
        # peers that answered BusyError this round: retried with backoff
        # (never breaker-fed) and DEPRIORITIZED by gossip fan-out until
        # the round advances — an overloaded peer gets breathing room,
        # not quarantine
        self._busy_peers: Dict[int, int] = {}
        # with a peers file the PORT layout is the dealer's, not
        # base_port+id arithmetic; the bind ADDRESS stays cfg.my_ip — the
        # peers-file entry is how others reach us, which behind NAT is not
        # a local interface we could bind
        bind_port = (self.peers[self.id][1] if cfg.peers_file
                     else cfg.port_of(self.id))
        self.server = rpc.RPCServer(cfg.my_ip, bind_port, self._handle)
        # straggler-tolerance plane (runtime/stragglers.py,
        # docs/STRAGGLERS.md): this peer's seeded speed profile (the
        # `slow` fault kind — NO_SLOW unless the plan drew us), the
        # adaptive deadline controller (answers the legacy Timeouts
        # constants verbatim until armed AND warmed), and the forensics
        # ledger (waiting-on view, excluded-straggler and stall tallies).
        # The per-RPC service delay lives on the TRANSPORT seam — the
        # TCP server dispatch and the hive loopback dispatch both read
        # server.service_delay_s — so TCP and co-hosted layouts serve
        # identically slow from one seeded schedule.
        self.slow = cfg.fault_plan.slow_profile(self.id, cfg.num_nodes)
        self.server.service_delay_s = self.slow.service_s
        self.deadlines = stragglers.DeadlineController(
            enabled=cfg.adaptive_deadlines, margin=cfg.deadline_margin,
            floor_s=cfg.deadline_floor_s)
        self.straggler = stragglers.StragglerLedger()
        self._round_t0 = time.monotonic()
        self.round = RoundState(iteration=self.chain.next_iteration)
        self.role_map = R.RoleMap({i: 1 for i in range(cfg.num_nodes)})
        self.logs: List[Tuple[int, float, float]] = []  # iter, err, ts
        # per-event counters: every traced protocol event is tallied here so
        # harnesses can assert on security/attack accounting without log
        # scraping (ref: the reference prints attack counters at exit,
        # main.go:1071-1088)
        self.counters: Dict[str, int] = {}
        # unified telemetry plane (biscotti_tpu/telemetry): metrics
        # registry + round-correlated spans + flight recorder. The old
        # per-event write()+flush() JSONL log (`log_path`) becomes the
        # recorder's batched spill; the old ad-hoc PhaseClock lives inside
        # Telemetry and still backs run()'s legacy `phases` key.
        self.tele = Telemetry(node=self.id, enabled=cfg.telemetry,
                              ring=cfg.recorder_ring, spill_path=log_path,
                              spill_batch=cfg.recorder_batch,
                              # per-peer labels (biscotti_breaker_state)
                              # must fit the whole cluster before the
                              # cardinality cap starts collapsing series
                              max_label_sets=max(256, 4 * cfg.num_nodes),
                              trace=cfg.trace)
        # per-phase wall-clock accounting (SURVEY §5.1): totals come back
        # in run()'s result; eval/eval_cost_breakdown.py aggregates them
        self.phases = self.tele.phases
        if cfg.telemetry:
            # transport + fault-plane + admission instrumentation share
            # the registry
            self.pool.metrics = self.tele.registry
            self.server.metrics = self.tele.registry
            if self.pool.faults is not None:
                self.pool.faults.metrics = self.tele.registry
            self.admission.metrics = self.tele.registry
            self.trainer.metrics = self.tele.registry
            self.straggler.metrics = self.tele.registry
        # accelerator-resident crypto plane (crypto/kernels,
        # docs/CRYPTO_KERNELS.md): the arming switch AND the instrument
        # hooks are process-wide — mixed device/CPU peers in ONE process
        # are unsupported (every real deployment runs one config per
        # process; in-process harnesses arm whole clusters), and in a
        # co-hosted harness the LAST-constructed peer's telemetry
        # receives every crypto_device span/observation (aggregate
        # totals stay correct; per-node attribution is a known harness
        # approximation). Armed with telemetry on, the kernel call sites
        # emit `crypto_device` spans + the biscotti_crypto_device_seconds
        # histogram, so profile_round / trace_round can split the crypto
        # critical path into crypto_cpu vs crypto_device. Any
        # non-qualifying construction CLEARS the hooks so a torn-down
        # cluster's telemetry never keeps receiving kernel events.
        devkern.set_enabled(cfg.device_crypto)
        self.device_crypto = cfg.device_crypto and devkern.available()
        self._devkern_span_hook = None
        self._devkern_registry = None
        if cfg.device_crypto and cfg.telemetry:
            self._devkern_span_hook = (
                lambda kernel: self.tele.span("crypto_device",
                                              kernel=kernel))
            self._devkern_registry = self.tele.registry
            devkern.set_metrics_registry(self._devkern_registry)
            devkern.set_span_hook(self._devkern_span_hook)
        else:
            devkern.set_metrics_registry(None)
            devkern.set_span_hook(None)
        # the controller is wired into the server UNCONDITIONALLY so the
        # inflight accounting (and its gauges) is live even in
        # observability-only runs; a DISABLED plan admits everything
        # inside try_admit, so enforcement — and the read deadline —
        # only engage when the plan is armed
        self.server.admission = self.admission
        if cfg.admission_plan.enabled:
            self.server.read_deadline = cfg.admission_plan.read_deadline_s
        # reply-codec capability set for the RPC server: callers request
        # a reply codec via `acodec`, granted iff inside OUR caps
        self.server.caps = self.caps
        # a version pin predating the busy feature sheds with the old
        # build's plain-error reply (no structured retryable status)
        self.server.busy_status = protocol.BUSY in self.caps
        # distributed tracing: arm the transport seams' receiver-side
        # dispatch spans (rpc.RPCServer._dispatch + the hive loopback
        # dispatch both read server.telemetry); None keeps the seed
        # span-free dispatch path
        if self.tele.trace:
            self.server.telemetry = self.tele
        # hive co-hosting (runtime/hive.py, docs/HIVE.md): register with
        # the process-local LoopbackHub and attach it to the pool, so
        # RPCs toward co-hosted peers skip TCP framing and serialization
        # while still flowing through the fault draw, the destination's
        # admission controller, and the wire byte counters. `hive_info`
        # is the hive's shared readout dict (peers, RSS, loop lag),
        # surfaced under telemetry_snapshot()["hive"]; `_announce_skip`
        # names co-hosted peers made mutually known at construction, so
        # a genesis hive launch skips the O(H²) intra-hive hello storm.
        self.hive_info: Optional[Dict] = None
        self._announce_skip: frozenset = frozenset()
        if hive is not None:
            hive.register(self)
            self.pool.loopback = hive
            self.pool.loopback_src = self.id
        self._metrics_server = None
        self._rng = random.Random(cfg.seed * 7919 + self.id)
        # strong refs to fire-and-forget tasks: the loop only keeps weak
        # references, so an unreferenced parked task can be GC'd mid-sleep
        self._bg_tasks: Set[asyncio.Task] = set()
        # speculative next-round worker products (cfg.pipeline +
        # cfg.speculation): the SGD delta (and, when no state-mutating
        # transform sits between them, the quantized update + VSS
        # commitment) for (iteration, base head hash), computed in the
        # background the moment a block lands. Consumed by _worker_flow
        # iff the base still matches; a fork discards it with a traced
        # counter (speculation_discard)
        self._spec: Optional[Dict] = None
        self._spec_task: Optional[asyncio.Task] = None
        self._spec_key: Optional[Tuple[int, bytes]] = None
        # the (it, head) an inflight _spec_task is actually computing
        # for — _claim_spec awaits the task only when ITS target matches
        # (a retargeted _spec_key must not make the worker wait out a
        # doomed stale speculation)
        self._spec_task_key: Optional[Tuple[int, bytes]] = None
        # (iteration, sid) pairs already granted a pipelined
        # pre-verification — caps early-crypto CPU per round (see
        # _pipelined_iteration); pruned at every round start
        self._preverify_gate: Set[Tuple[int, int]] = set()
        # share-point layouts are fixed for the whole run — built once
        # instead of per round / per blind-row evaluation (the xs list
        # was rebuilt on every _vss_blind_rows call and the recovery
        # Vandermonde per mint; ops/secretshare memoizes the matching
        # pseudoinverse)
        self._xs_all = [int(x) - ss.SHARE_OFFSET
                        for x in range(cfg.total_shares)]
        self._xs_arr = np.asarray(self._xs_all, np.int64)
        # block hashes whose verifier quorums this peer already
        # authenticated (_block_quorums_ok memo). Entries are keyed on the
        # COMPUTED hash of the verified block, never the sender's claimed
        # hash, so a relabeled genuine block cannot seed the cache for a
        # forged block that claims the same hash. Insertion-ordered dict =
        # LRU eviction of the stalest entry.
        self._quorum_ok_hashes: Dict[bytes, None] = {}
        # membership plane (docs/MEMBERSHIP.md): the epoch counts this
        # peer's OBSERVED membership transitions — a peer quarantined
        # (left), a quarantined peer rehabilitated or a new hello from a
        # non-alive id (joined), a resharing round run. Local by design
        # (membership in a P2P system is a per-observer view); the gauge
        # + join/leave counters make churn scrapeable mid-run
        self.membership_epoch = 0
        # rounds at which OUR OWN seeded churn schedule kills this peer
        # (--fault-churn; the in-process ChurnRunner instead kills from
        # the outside, which also covers hard-crash semantics)
        self._churn_kills: frozenset = frozenset()
        if cfg.fault_plan.churn_enabled:
            self._churn_kills = frozenset(
                e.round for e in cfg.fault_plan.churn_schedule(
                    cfg.num_nodes, cfg.max_iterations)
                if e.node == self.id and e.kind == faults.KILL)
        # adaptive-adversary campaign plane (runtime/adversary.py,
        # docs/ADVERSARY.md): armed only on the peers the plan draws as
        # attackers — every other peer (and every disabled plan) runs
        # the seed protocol untouched, allocation-free. Decisions are
        # pure functions of (campaign seed, observed protocol state),
        # so a campaign run replays from its flags like any fault run.
        self.campaign = adversary.build(cfg.campaign_plan, self.id,
                                        cfg.num_nodes, cfg.seed)
        # latest round this peer actually submitted an update for — how
        # the campaign reads its own submission's fate out of the next
        # block (absent record after a submission = rejected)
        self._campaign_submitted: int = -1
        if self.campaign is not None:
            if cfg.telemetry:
                self.campaign.metrics = self.tele.registry
            # frame-level actions ride the fault plane's injector seam;
            # construct one even when no frame faults are armed (a
            # disabled plan draws benign for every frame, so only the
            # campaign's targeted replays fire)
            if self.pool.faults is None:
                self.pool.faults = faults.FaultInjector(
                    cfg.fault_plan, self.id, self._peer_for_addr)
                if cfg.telemetry:
                    self.pool.faults.metrics = self.tele.registry
            self.pool.faults.campaign = self.campaign
            # identity recycling rides the churn self-kill seam: the
            # sybil schedule's kills join ours, and the launcher
            # (ChurnRunner / chaos --campaign / any supervisor)
            # relaunches the fresh incarnation
            self._churn_kills = frozenset(
                self._churn_kills
                | self.campaign.kill_rounds(cfg.max_iterations))
        # adaptive defense plane (ops/trust.py, docs/DEFENSES.md): the
        # cross-round TrustLedger is constructed ONLY under
        # --defense ENSEMBLE — every other defense runs the seed verdict
        # path with no ledger object at all (bit-identity guarded by
        # tests/test_trust.py). Independently of the ledger, every
        # verifier records a bounded per-round verdict stream
        # (accept/reject walk + observed magnitudes) so attack-matrix
        # cells carry the hugger's walk as replayable evidence even for
        # the defenses it defeats.
        self.trust: Optional[trustlib.TrustLedger] = (
            trustlib.TrustLedger(cfg.trust_plan, cfg.num_nodes)
            if cfg.defense == Defense.ENSEMBLE else None)
        self._verdict_stream: List[Dict] = []
        # elastic fleet plane (runtime/placement.py, docs/PLACEMENT.md):
        # GetMigrationTicket serves this peer's serialized state ONLY to
        # a caller presenting the drain token its controller installed —
        # None (the default) refuses every request, so an unmanaged peer
        # cannot be drained (or have its EF residual read) over the wire
        self._drain_token: Optional[str] = None
        # genesis DKG deal intake (crypto/dkg.py): dealer id -> verified
        # deal, populated by the DkgDeal RPC during a live ceremony
        self._dkg_deals: Dict[int, object] = {}
        if ticket is not None:
            # migrated incarnation: rehydrate chain (through the guarded
            # snapshot-adoption path), breaker ledger, admission buckets,
            # EF residual, and round position from the controller's
            # ticket — run() then announces and catches up live
            placement.restore_agent(self, ticket)

    # ------------------------------------------------------------ utilities

    @property
    def iteration(self) -> int:
        return self.chain.next_iteration

    def _trace(self, event: str, **kw) -> None:
        """Structured per-round event log (SURVEY.md §5.1: the TPU build's
        replacement for the reference's timestamped text logs). Events go
        to the flight recorder — in-memory ring + BATCHED JSONL spill with
        (wall, monotonic, seq) stamps — not straight to disk: the old
        per-event write()+flush() was two syscalls on the hot path for
        every gossip receipt and share intake. The recorder is flushed at
        round end and on shutdown/crash (telemetry/recorder.py)."""
        self.counters[event] = self.counters.get(event, 0) + 1
        self.tele.event(event, it=self.iteration, **kw)

    # ----------------------------------------------------------- telemetry

    _BREAKER_LEVEL = {faults.CLOSED: 0, faults.HALF_OPEN: 1, faults.OPEN: 2}

    def _refresh_gauges(self) -> None:
        """Pull-model gauges, recomputed at scrape time (Metrics RPC /
        HTTP exposition / run() result) rather than pushed on the hot
        path: round height, liveness, and per-peer breaker state."""
        if not self.tele.enabled:
            return
        reg = self.tele.registry
        reg.gauge("biscotti_round_height",
                  "blockchain iteration this peer is at").set(self.iteration)
        reg.gauge("biscotti_converged",
                  "1 once the convergence threshold was met").set(
            int(self.converged))
        reg.gauge("biscotti_alive_peers",
                  "peers currently in the gossip liveness set").set(
            len(self.alive))
        breaker = reg.gauge(
            "biscotti_breaker_state",
            "per-peer circuit breaker: 0 closed, 1 half-open, 2 open")
        for pid, h in self.health.snapshot().items():
            breaker.set(self._BREAKER_LEVEL.get(h["state"], 2), peer=pid)
        # admission levels, pull-refreshed so a scrape is never stale
        # (the controller also pushes on change)
        reg.gauge(adm.INFLIGHT_GAUGE, adm.INFLIGHT_HELP).set(
            self.admission.inflight_total)
        reg.gauge(adm.PARKED_GAUGE, adm.PARKED_HELP).set(
            len(self.admission.parking))
        # pipelined-round readout (docs/RUNTIME.md §Pipelined rounds):
        # configured overlap depth plus the speculation ledger — hits are
        # rounds whose SGD/commit came precomputed, discards are
        # speculative steps a fork (or head mismatch) threw away
        reg.gauge("biscotti_pipeline_depth",
                  "rounds of cross-round phase overlap (0 = serial)").set(
            self.cfg.pipeline_depth if self.cfg.pipeline else 0)
        reg.gauge("biscotti_speculation_hits",
                  "speculative worker steps consumed by the round").set(
            self.counters.get("speculation_hit", 0))
        reg.gauge("biscotti_speculation_discards",
                  "speculative worker steps discarded on fork/mismatch").set(
            self.counters.get("speculation_discard", 0))
        # overlay plane (docs/OVERLAY.md): tree shape of the armed
        # aggregation overlay — flat (depth 1) when disabled
        if self.overlay.enabled:
            reg.gauge(ov.DEPTH_GAUGE, ov.DEPTH_HELP).set(self.overlay.depth)
            reg.gauge(ov.SUBTREE_GAUGE, ov.SUBTREE_HELP).set(
                len(self.overlay.members(self.overlay.gid_of(self.id))))
        # membership plane (docs/MEMBERSHIP.md): this peer's view of who
        # is in, and how many times that view has changed
        reg.gauge("biscotti_membership_epoch",
                  "observed membership transitions (join/leave/reshare)"
                  ).set(self.membership_epoch)
        # straggler plane (docs/STRAGGLERS.md): this peer's emulated
        # slowdown and the controller's current per-phase deadline
        # decisions — a scrape shows at a glance whether (and how far)
        # the fleet has tightened the legacy constants
        reg.gauge("biscotti_slow_compute_factor",
                  "this peer's emulated compute-slowdown multiple "
                  "(1 = unslowed)").set(self.slow.compute_factor)
        dl = reg.gauge(stragglers.DEADLINE_GAUGE, stragglers.DEADLINE_HELP)
        for ph, row in self.deadlines.snapshot()["phases"].items():
            if "deadline_s" in row:
                dl.set(row["deadline_s"], phase=ph)
        # adaptive defense plane (docs/DEFENSES.md): this verifier's
        # per-peer ledger scores — slow-trust weight x (1 − drift score),
        # zeroed while a peer is flagged or held
        if self.trust is not None:
            tg = reg.gauge(trustlib.TRUST_METRIC, trustlib.TRUST_HELP)
            for pid, score in self.trust.trust_scores().items():
                tg.set(score, peer=str(pid))

    def _release_device_hooks(self) -> None:
        """Teardown half of the device-crypto arming: drop the
        process-global kernel instrument hooks IF this agent installed
        them (identity-compared — a later live agent's hooks are left
        untouched). Without this, the span closure pins the whole agent
        object graph for the process lifetime and a torn-down cluster's
        telemetry keeps receiving kernel events."""
        if self._devkern_span_hook is not None or \
                self._devkern_registry is not None:
            devkern.release_hooks(span_hook=self._devkern_span_hook,
                                  registry=self._devkern_registry)

    def telemetry_snapshot(self) -> Dict:
        """THE public observability readout — one structured dict serving
        the `Metrics` RPC, the run() result's `telemetry` key, the chaos
        CLI, and the test suites (which used to reach into
        `pool.faults.counts` and private peer dicts; docs/OBSERVABILITY.md
        documents the schema). JSON-clean: label keys are strings."""
        self._refresh_gauges()
        return {
            "node": self.id,
            "iter": self.iteration,
            "converged": self.converged,
            "counters": dict(self.counters),
            "phases": self.phases.summary(),
            "health": {str(p): dict(v)
                       for p, v in self.health.snapshot().items()},
            "faults": (dict(self.pool.faults.counts)
                       if self.pool.faults is not None else {}),
            "metrics": self.tele.registry.snapshot(),
            # overload-governance readout (runtime/admission.py): shed
            # tallies by reason, current + peak inflight/parked levels,
            # and the configured caps — the chaos report and the flood
            # acceptance assertions (bounded peaks, nonzero sheds on
            # honest peers) read THIS, not private controller state
            "admission": self.admission.snapshot(),
            # membership plane (docs/MEMBERSHIP.md): epoch + current
            # alive view — the obs CLI's membership column and the churn
            # harness assertions read this
            "membership": {"epoch": self.membership_epoch,
                           "alive": len(self.alive),
                           "pruned_before": self.chain.pruned_before},
            # straggler-tolerance plane (docs/STRAGGLERS.md): this peer's
            # speed profile, the waiting-on view / excluded + stall
            # tallies, and the deadline controller's per-phase state —
            # the obs `waiting-on` column and the chaos `stragglers`
            # report key read exactly this
            "stragglers": {
                "profile": {"compute_factor": self.slow.compute_factor,
                            "service_s": self.slow.service_s,
                            "preset": self.slow.preset,
                            "slowed": self.slow.slowed},
                **self.straggler.snapshot(),
                "deadlines": self.deadlines.snapshot(),
            },
            # the recorder may be real even with telemetry disabled (an
            # explicit spill path keeps the event log alive) — report
            # whatever it actually holds
            "recorder": {"events": getattr(self.tele.recorder, "_seq", 0),
                         "wrapped": self.tele.recorder.wrapped},
            # hive co-hosting readout (runtime/hive.py): the shared
            # per-hive dict (id, co-hosted peer count, RSS, event-loop
            # lag) the obs CLI groups its per-host columns by. None for
            # a standalone agent.
            "hive": dict(self.hive_info) if self.hive_info else None,
            # versioned-protocol readout (docs/PROTOCOL.md): the version
            # this peer speaks (pinned or current), its advertised
            # feature set, and the features currently degraded per peer
            # — the mixed-version matrix and the soak harness read this
            "protocol": protocol.snapshot(self.cfg, self.caps,
                                          self._degraded_seen),
            # aggregation-overlay readout (docs/OVERLAY.md): tree shape
            # plus this peer's aggregated/relayed/fallback tallies — the
            # obs overlay table and the chaos report's `overlay` key
            # merge exactly this
            "overlay": {
                "enabled": self.overlay.enabled,
                "group_size": self.overlay.group,
                "depth": self.overlay.depth,
                "aggregated": self.counters.get(
                    "overlay_aggregate_registered", 0),
                "aggregates_sent": self.counters.get(
                    "overlay_aggregate_sent", 0),
                "offers": (self.counters.get("overlay_offer_sent", 0)
                           + self.counters.get("overlay_offer_local", 0)),
                "relayed": self.counters.get("overlay_relayed_sent", 0),
                "forwarded": self.counters.get(
                    "overlay_relay_forwarded", 0),
                "direct": (self.counters.get("overlay_offer_fallback", 0)
                           + self.counters.get("overlay_relay_fallback",
                                               0)),
                "fallback": (self.counters.get(
                    "overlay_aggregate_refused", 0)
                    + self.counters.get("overlay_fallback_forwarded", 0)),
            },
            # device-crypto readout (docs/CRYPTO_KERNELS.md): present
            # only when --device-crypto is armed, so the disarmed
            # snapshot schema stays byte-identical to the seed. The
            # seconds/calls tallies are the kernel plane's process-wide
            # accumulators (one armed cluster per process).
            **({"device_crypto": {
                "enabled": True,
                "active": devkern.active(),
                "seconds": devkern.device_seconds(),
                "calls": devkern.device_calls(),
            }} if self.cfg.device_crypto else {}),
            # adversary-campaign readout (docs/ADVERSARY.md): present
            # only on an ARMED attacker peer, so the honest/disabled
            # snapshot schema stays byte-identical to the seed. The
            # `schedule` list is the deterministic decision log the
            # layout-invariance tests compare; actions/targets_hit are
            # execution tallies.
            **({"campaign": self.campaign.snapshot()}
               if self.campaign is not None else {}),
            # adaptive-defense readout (docs/DEFENSES.md): present only
            # when the ENSEMBLE ledger is armed or this peer recorded
            # verifier verdicts, so every other snapshot schema stays
            # byte-identical to the seed. `stream` is the per-round
            # accept/reject walk (+ observed magnitudes and, under
            # ENSEMBLE, per-peer scorer votes) that attack-matrix cell
            # rows and obs.merge_trust read; `ledger` is the TrustLedger
            # state the layout-invariance tests compare.
            **({"trust": {
                "defense": self.cfg.defense.value,
                "stream": list(self._verdict_stream),
                **({"ledger": self.trust.snapshot()}
                   if self.trust is not None else {}),
            }} if (self.trust is not None or self._verdict_stream)
               else {}),
        }

    async def _h_metrics(self, meta, arrays):
        """Live exposition over the protocol transport: any peer (or the
        `tools.obs` scraper) can pull this node's Prometheus text + the
        structured snapshot mid-run; `{"tail": n}` additionally returns
        the newest n flight-recorder events. Read-only — safe for any
        caller (it reveals nothing an observer of the gossip plane could
        not already infer)."""
        reply = {"snapshot": self.telemetry_snapshot(),
                 "prom": self.tele.render()}
        tail = int(meta.get("tail", 0) or 0)
        since = meta.get("since_seq")
        if tail > 0 or since is not None:
            # the recorder tolerates unserializable field values (its
            # spill uses default=str) but the wire codec is strict JSON —
            # sanitize the same way before the events enter the reply
            import json as _json

            page = min(tail, 1000) if tail > 0 else 1000
            if since is not None:
                # incremental poll (tools/obs --watch, tools/trace_round):
                # only events past the caller's cursor, a bounded page at
                # a time — re-fetching the full ring every scrape is what
                # this cursor exists to stop. `last_seq` advances the
                # cursor even on an empty page; a first event with
                # seq > since_seq + 1 means the ring wrapped past the
                # cursor (the poller fell behind eviction).
                try:
                    since = max(0, int(since))
                except (TypeError, ValueError):
                    raise RPCError("since_seq must be an integer")
                events = self.tele.recorder.tail_since(since, limit=page)
                reply["last_seq"] = (events[-1]["seq"] if events
                                     else max(since, self.tele.recorder.seq))
            else:
                events = self.tele.recorder.tail(page)
            reply["seq"] = self.tele.recorder.seq
            reply["events"] = _json.loads(_json.dumps(events, default=str))
        return reply, {}

    def _sign(self, message: bytes) -> bytes:
        return cm.schnorr_sign(self.schnorr_seed, message)

    def _quantize_np(self, delta: np.ndarray) -> np.ndarray:
        """Protocol-plane quantization (ref: kyber.go:698-710), done in
        numpy on the host so worker commit and miner re-verify are
        bit-identical regardless of which backend jitted the update."""
        scale = 10.0 ** self.cfg.precision
        return np.trunc(np.asarray(delta, np.float64) * scale).astype(np.int64)

    def _commit(self, q: np.ndarray) -> bytes:
        if self.commit_key is not None:
            return cm.commit_update(q, self.commit_key)
        # keyless local mode: binding-only hash commitment
        return hashlib.sha256(q.tobytes()).digest()

    def _verify_plain_commitment(self, u: Update) -> bool:
        """Miner-side recompute-and-compare (ref: kyber.go:564-577)."""
        q = self._quantize_np(u.delta)
        if self.commit_key is not None:
            return cm.verify_commitment(u.commitment, q, self.commit_key)
        return hashlib.sha256(q.tobytes()).digest() == u.commitment

    @staticmethod
    def _sig_message(commitment: bytes, iteration: int, source_id: int) -> bytes:
        """Domain-separated verifier-approval message. Binding the iteration
        and source prevents cross-round replay of an old approval (the
        commitment alone is round-independent) and signature transplantation
        between sources."""
        return (b"biscotti-approve" + commitment
                + int(iteration).to_bytes(8, "little", signed=True)
                + int(source_id).to_bytes(8, "little", signed=True))

    def _verify_sig_quorum(self, commitment: bytes, iteration: int,
                           source_id: int, signers: List[int],
                           signatures: List[bytes]) -> bool:
        """≥ half the round's verifiers must have Schnorr-signed the
        (commitment, iteration, source) approval message (ref: main.go:1686 —
        the reference counts signatures; its miner-side verify,
        kyber.go:898-925, was written but disabled. Here each claimed
        (signer, sig) pair is actually verified).

        Fast path: the whole quorum in ONE batched RLC Schnorr check
        (cm.batch_schnorr_verify — a single MSM instead of one
        double-mult per signature). Honest quorums are all-valid, so the
        batch passing proves every claimed pair and the count is just
        len(items); any failure falls back to the original per-signature
        loop, whose verdict (count the valid subset, tolerate junk
        entries) is preserved bit-for-bit."""
        msg = self._sig_message(commitment, iteration, source_id)
        verifiers, _, _, _ = self.role_map.committee()
        vset = set(verifiers)
        need = max(1, (len(vset) + 1) // 2)
        items: List[Tuple[bytes, bytes, bytes]] = []
        seen: Set[int] = set()
        for vid, sig in zip(signers, signatures):
            if vid not in vset or vid in seen:
                continue
            pub = self.node_pubs.get(vid)
            if not pub:
                continue
            seen.add(vid)
            items.append((pub, msg, sig))
        if len(items) >= need and cm.batch_schnorr_verify(items):
            return True
        # batch failed (or thin): per-signature scan, EXACTLY the
        # pre-batch semantics — e.g. a duplicate signer whose first
        # entry is junk but whose second is valid still counts here,
        # where the deduped batch above could not see the second
        valid: Set[int] = set()
        for vid, sig in zip(signers, signatures):
            if vid not in vset or vid in valid:
                continue
            pub = self.node_pubs.get(vid)
            if pub and cm.schnorr_verify(pub, msg, sig):
                valid.add(vid)
        return len(valid) >= need

    def _peer_for_addr(self, host: str, port: int) -> Optional[int]:
        """(host, port) → peer id, for the fault plane's per-link keying —
        O(1) off the cached reverse map (the fault plane consults this for
        EVERY outbound frame; a linear scan would be O(N²) comparisons per
        gossip round on the event loop)."""
        return self._addr_to_pid.get((host, port))

    def _grant(self, pid: int) -> frozenset:
        """The negotiated per-peer feature set: our advertised features
        ∩ what `pid`'s hello advertised (raw64 floor; no hello yet =
        assume a legacy build). Every per-peer feature decision — codec,
        chunking, trace stamping, relay routing, snapshot donors —
        consults this grant (runtime/protocol.py, docs/PROTOCOL.md)."""
        return protocol.grant(self.caps, self.peer_caps.get(pid))

    def _wire_to(self, pid: int) -> Tuple[str, int]:
        """(codec, chunk_bytes) to use toward `pid`: the configured
        pipeline when the grant carries every stage, else raw64/
        unchunked — the graceful fallback that keeps legacy (or
        legacy-configured, or version-pinned) peers interoperable."""
        if self.peer_caps.get(pid) is None:
            return wcodecs.RAW, 0
        g = self._grant(pid)
        codec = wcodecs.negotiate(self.cfg.wire_codec, g)
        chunk = self.cfg.wire_chunk_bytes if wcodecs.CHUNK_CAP in g else 0
        return codec, chunk

    def _reply_codec_meta(self, pid: int) -> Dict[str, int]:
        """Meta keys asking `pid` to code/chunk its REPLY (the
        Accept-Encoding of this protocol) — set on calls whose reply
        carries the bulk (GetBlock bodies, RegisterPeer chain
        adoption). Peers that don't understand them ignore them."""
        codec, chunk = self._wire_to(pid)
        out: Dict[str, int] = {}
        if codec != wcodecs.RAW:
            out["acodec"] = codec
        if chunk:
            out["achunk"] = chunk
        return out

    def _peer_traces(self, pid: int) -> bool:
        """True when trace context should ride frames toward `pid`:
        WE trace and the peer advertised the `trace` capability in its
        hello — the same all-or-nothing negotiation the wire codecs use,
        so legacy peers (and mixed clusters) get untouched frames."""
        return self.tele.trace and protocol.TRACE in self._grant(pid)

    def _record_caps(self, pid: int, caps) -> None:
        """Record a peer's advertised capability set from a hello or a
        hello reply. The legacy-hello reset rule lives in ONE place —
        protocol.normalize_hello: a hello WITHOUT a capability set
        resets the entry to raw64-only, so a peer that restarted on a
        legacy build stops receiving coded/stamped/relayed frames
        immediately instead of keeping its previous incarnation's caps.
        Features WE speak that the new hello does not grant are traced
        (`feature_degraded{feature,peer}`) and counted, once per
        observed set — a re-hello with the same caps is silent, an
        upgrade clears the entry, a downgrade re-emits."""
        recorded = protocol.normalize_hello(caps)
        self.peer_caps[pid] = recorded
        lost = protocol.degraded(self.caps, recorded)
        if lost == self._degraded_seen.get(pid, frozenset()):
            return
        self._degraded_seen[pid] = lost
        for feat in sorted(lost):
            self._trace("feature_degraded", feature=feat, peer=pid)
            if self.tele.enabled:
                self.tele.registry.counter(
                    protocol.DEGRADED_METRIC, protocol.DEGRADED_HELP,
                ).inc(feature=feat, peer=str(pid))

    def _peer_busy(self, pid: int) -> bool:
        """True while `pid` is deprioritized for gossip: it answered
        BusyError during the CURRENT round. Round-scoped on purpose —
        overload is transient, and a new round is fresh evidence either
        way (a still-busy peer re-marks itself on the next busy reply)."""
        return self._busy_peers.get(pid) == self.iteration

    def _bump_epoch(self, change: str, peer: Optional[int] = None) -> None:
        """One observed membership transition: epoch++, traced + counted
        (`member_join` / `member_leave` / `reshare_round`) so churn is
        visible on every scrape surface (docs/MEMBERSHIP.md)."""
        self.membership_epoch += 1
        self._trace(f"member_{change}" if change in ("join", "leave")
                    else change,
                    peer=peer, epoch=self.membership_epoch)

    def _record_peer_ok(self, peer_id: int) -> None:
        """One RPC toward `peer_id` proved the transport healthy: reset its
        failure streak and, if the breaker was tripped, close it."""
        if self.health.record_success(peer_id):
            self._trace("breaker_close", peer=peer_id)
            if peer_id not in self.alive:
                # rejoined the live set via OUR outbound probe (no inbound
                # frame announced it first — the inbound seam in _handle
                # owns that case, so one rejoin is never counted twice)
                self._bump_epoch("join", peer_id)
        self.alive.add(peer_id)

    def _record_peer_fail(self, peer_id: int) -> None:
        if self.health.record_failure(peer_id):
            self._trace("breaker_open", peer=peer_id)
            self._bump_epoch("leave", peer_id)

    async def _call(self, peer_id: int, msg_type: str, meta=None, arrays=None,
                    timeout: Optional[float] = None,
                    retries: Optional[int] = None):
        """RPC with the reference's timeout-evict semantics
        (ref: main.go:1460-1487), hardened for partial faults:

        * transport failures (timeout / refused / reset) are RETRIED up to
          cfg.rpc_retries times with exponential backoff + decorrelated
          jitter — a single lost frame no longer costs the round its call
        * protocol replies are FATAL, never retried: RPCError is the
          callee's answer, StaleError is a signal (triggers catch-up) —
          both prove the transport healthy and feed the breaker as success
        * a peer whose breaker is OPEN fails fast with CircuitOpenError
          (a ConnectionError) without dialing; after the cooldown one
          half-open probe decides re-admission (runtime/faults.py)

        Each attempt keys a fresh fault-plane draw (the attempt number is
        part of the schedule), so under injection a retry is a genuinely
        new frame, not a replay of the same doomed one.
        """
        host, port = self.peers[peer_id]
        timeout = timeout or self.timeouts.rpc_s
        if not self.health.allow(peer_id):
            self._trace("rpc_fast_fail", peer=peer_id)
            self.alive.discard(peer_id)
            raise CircuitOpenError(f"peer {peer_id} quarantined")
        # if allow() just granted us the HALF-OPEN probe slot, we must hand
        # it back should this call die before any outcome lands (cancelled,
        # or a non-transport error like a codec bug) — otherwise the slot
        # leaks and the peer stays quarantined forever
        i_am_probe = self.health.state(peer_id) == faults.HALF_OPEN
        attempts = 1 + (self.cfg.rpc_retries if retries is None else retries)
        backoff = faults.backoff_schedule(
            self._rng, self.cfg.rpc_backoff_base_s,
            self.cfg.rpc_backoff_cap_s)
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                # re-checked AFTER the backoff sleep: a CONCURRENT call
                # toward this peer may have tripped the breaker while we
                # slept — dialing anyway would violate the quarantine
                if not self.health.allow(peer_id):
                    break
                if self.health.state(peer_id) == faults.HALF_OPEN:
                    i_am_probe = True  # that allow() claimed the slot
            try:
                codec, chunk = self._wire_to(peer_id)
                # distributed tracing: each ATTEMPT is its own wire
                # exchange, so each gets its own client span whose id
                # rides the frame (`_tr`) — the receiver's dispatch span
                # adopts it as parent, and the request/reply midpoints
                # of exactly this span pair are what trace_round's
                # clock-offset estimator aligns on
                if self._peer_traces(peer_id):
                    ctx = self.tele.new_ctx()
                    send_meta = tracectx.stamp(meta, ctx)
                    span = self.tele.span("rpc_call", it=self.iteration,
                                          ctx=ctx, peer=peer_id,
                                          msg=msg_type)
                else:
                    send_meta, span = meta, contextlib.nullcontext()
                with span:
                    out = await self.pool.call(host, port, msg_type,
                                               send_meta, arrays, timeout,
                                               attempt=attempt, codec=codec,
                                               chunk_bytes=chunk)
                self._record_peer_ok(peer_id)
                return out
            except StaleError:
                # the callee is ahead of us: pull the blocks we're missing
                # in the background (the reference instead parks the CALLEE,
                # main.go:1211-1214; pulling heals faster after partitions)
                self._record_peer_ok(peer_id)
                self._schedule_catch_up(peer_id)
                raise
            except BusyError as e:
                # overload signal, NOT a fault (docs/ADMISSION.md): the
                # busy reply PROVES the transport and the peer healthy, so
                # the breaker must not advance — a busy honest peer must
                # never be quarantined. Retry with the same backoff the
                # transport plane uses, and deprioritize the peer for this
                # round's gossip fan-out so it gets breathing room.
                self._record_peer_ok(peer_id)
                self._busy_peers[peer_id] = self.iteration
                last = e
                if attempt + 1 >= attempts:
                    break
                self._trace("rpc_busy_retry", peer=peer_id, msg=msg_type,
                            attempt=attempt + 1)
                await asyncio.sleep(next(backoff))
            except RPCError:
                self._record_peer_ok(peer_id)
                raise
            except (asyncio.TimeoutError, ConnectionError, OSError) as e:
                last = e
                self._record_peer_fail(peer_id)
                if attempt + 1 >= attempts \
                        or self.health.state(peer_id) != faults.CLOSED:
                    break  # budget spent, or the breaker tripped mid-loop
                self._trace("rpc_retry", peer=peer_id, msg=msg_type,
                            attempt=attempt + 1)
                await asyncio.sleep(next(backoff))
            except BaseException:
                # cancellation, or an error OUTSIDE the transport set (e.g.
                # a codec bug encoding the payload): no breaker outcome was
                # recorded, so a held half-open probe slot must be handed
                # back or the peer stays quarantined indefinitely
                if i_am_probe:
                    self.health.release_probe(peer_id)
                raise
        assert last is not None
        if isinstance(last, BusyError):
            # budget exhausted against a BUSY peer: it is alive and
            # healthy — do not evict it from the gossip liveness set
            self._trace("rpc_busy_give_up", peer=peer_id, msg=msg_type)
            raise last
        self.alive.discard(peer_id)
        raise last

    def _schedule_catch_up(self, pid: int) -> None:
        if getattr(self, "_catching_up", False):
            return
        self._catching_up = True

        async def go():
            try:
                for _ in range(self.cfg.max_iterations):
                    it = self.iteration
                    host, port = self.peers[pid]
                    try:
                        bmeta, barrays = await self.pool.call(
                            host, port, "GetBlock",
                            {"iteration": it,
                             **self._reply_codec_meta(pid)},
                            timeout=self.timeouts.rpc_s)
                    except Exception:
                        break
                    blk = wire.unpack_block(bmeta, barrays)
                    if blk.hash != blk.compute_hash():
                        break
                    self._accept_block(blk, gossip=False)
                    if self.iteration <= it:
                        break  # no progress: stop pulling
                    self._trace("caught_up_block", height=it)
            finally:
                self._catching_up = False

        t = asyncio.get_running_loop().create_task(go())
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    # --------------------------------------------------------------- roles

    def _elect_role_map(self) -> R.RoleMap:
        """The role map the CURRENT chain state elects — pure read, so
        the speculation plane can ask "will I be a worker next round?"
        the moment a block lands, before the round machinery runs
        (ref: main.go:497-527). FedSys: node 0 is the eternal miner
        (ref: FedSys/main.go:758-768)."""
        cfg = self.cfg
        if cfg.fedsys:
            return R.RoleMap.build(cfg.num_nodes, verifiers=[],
                                   miners=[0], noisers=[])
        stake = self.chain.latest_stake_map()
        try:
            verifiers, miners = R.elect_committees(
                stake, self.chain.latest_hash(), cfg.num_verifiers,
                cfg.num_miners, cfg.num_nodes)
        except ValueError:
            # debits can zero out enough nodes that the staked population no
            # longer covers the committees; fall back to a uniform one-
            # ticket lottery — deterministic, so every peer still agrees
            self._trace("lottery_uniform_fallback")
            verifiers, miners = R.elect_committees(
                {i: 1 for i in range(cfg.num_nodes)},
                self.chain.latest_hash(), cfg.num_verifiers,
                cfg.num_miners, cfg.num_nodes)
        return R.RoleMap.build(cfg.num_nodes, verifiers, miners)

    def _compute_roles(self) -> None:
        self.role_map = self._elect_role_map()

    def _noiser_draw(self) -> R.NoiserDraw:
        """Private stake-weighted noiser lottery + the VRF proof that binds
        it to (our key, latest block hash) — noisers verify the proof before
        serving (ref: vrf.go:54-99 returns the proof; the capability its
        returned-but-unchecked proof existed for)."""
        return R.elect_noisers(
            self.noise_vrf, self.chain.latest_stake_map(),
            self.chain.latest_hash(), self.id, self.cfg.num_noisers,
            self.cfg.num_nodes)

    async def _own_noise(self, it: int) -> np.ndarray:
        """This peer's DP noise vector for `it` — from the per-agent
        presample bank, or (hive co-hosting with a light trainer) from
        the shared stepper's batched per-round draw. Deterministic per
        (peer, iteration) either way, so a noiser serves the same vector
        on every request for a round."""
        if self.trainer.light:
            return await self.stepper.noise(self.id, it)
        return self.trainer.get_noise(it)

    # -------------------------------------------------- campaign plane

    def _campaign_observe(self, it: int) -> None:
        """Per-round adversary observation (docs/ADVERSARY.md): feed the
        campaign exactly what a real attacker at this peer can see — the
        public committee election (a pure function of chain state every
        peer computes anyway) and its own submission's fate in the
        latest block — and trace the decisions it returns. Pure in
        (campaign seed, observed chain state), so the same seed yields
        the identical action schedule on any transport layout."""
        verifiers, miners, _, _ = self.role_map.committee()
        accepted_last: Optional[bool] = None
        blk = self.chain.latest
        if self._campaign_submitted >= 0 \
                and blk.iteration == self._campaign_submitted:
            # we submitted for the round this block settled: accepted iff
            # our record rides it with accepted=True (a verifier
            # rejection leaves no record at all — also a False)
            accepted_last = any(u.source_id == self.id and u.accepted
                                for u in blk.data.deltas)
        decided = self.campaign.observe_round(
            it, miners=sorted(miners), verifiers=list(verifiers),
            accepted_last=accepted_last)
        if decided:
            self._trace("campaign_round", campaign=self.campaign.name,
                        **decided)

    def _campaign_honest_step(self) -> Optional[np.ndarray]:
        """The attacker's estimate of one honest accepted delta: the
        latest block's applied aggregate (global_w difference). Under
        the default sum aggregation (Biscotti SUMS accepted deltas, see
        _create_block) that difference is divided by the accepted
        count; TRIMMED_MEAN applies a per-coordinate MEAN, so the
        difference is already one-delta scale. Chain-derived only —
        nothing here an observer of the gossip plane could not
        compute (the aggregation rule is public config)."""
        cur = self.chain.latest
        if cur.iteration < 0:
            return None
        prev = self.chain.get_block(cur.iteration - 1)
        if prev is None:
            return None  # pruned away (snapshot-bootstrapped attacker)
        n_acc = sum(1 for u in cur.data.deltas if u.accepted)
        if n_acc == 0:
            return None
        step = cur.data.global_w - prev.data.global_w
        if self.cfg.defense == Defense.TRIMMED_MEAN:
            return step
        return step / float(n_acc)

    def _campaign_shape(self, it: int, delta: np.ndarray) -> np.ndarray:
        """Adaptive-poison post-processing of OUR OWN delta (the one
        thing an attacker may always tamper with): blend toward the
        observed honest step at the campaign's current scale, plus the
        seeded per-attacker decorrelation jitter. The campaign decides
        (scale, jitter seed, jitter fraction); the arithmetic lives
        here where numpy does."""
        sh = self.campaign.shape(it)
        if sh is None:
            return delta
        scale, jitter_seed, jitter_frac = sh
        est = self._campaign_honest_step()
        if est is None:
            est = np.zeros_like(delta)
        shaped = est + scale * (delta - est)
        if jitter_frac > 0.0:
            rng = np.random.default_rng(jitter_seed)
            j = rng.standard_normal(delta.shape)
            nj = float(np.linalg.norm(j))
            ref = float(np.linalg.norm(est)) or float(np.linalg.norm(delta))
            if nj > 0.0 and ref > 0.0:
                shaped = shaped + j * (jitter_frac * ref / nj)
        self._trace("campaign_poison", scale=round(float(scale), 4))
        return np.asarray(shaped, delta.dtype)

    # ------------------------------------------------- straggler plane

    async def _slow_pad(self, base_s: float) -> None:
        """Compute-slowdown emulation (docs/STRAGGLERS.md): pad a just-
        measured compute segment to `compute_factor` x its duration. The
        pad is an event-loop sleep, so a slow peer's compute takes
        longer WITHOUT burning host CPU other co-hosted peers need —
        and because it is derived from the measured duration, chains
        and protocol bytes are bit-identical to the unslowed run; only
        the timing changes. No-op for an unslowed profile."""
        f = self.slow.compute_factor
        if f > 1.0 and base_s > 0.0:
            await asyncio.sleep(base_s * (f - 1.0))

    def _deadline(self, phase: str, legacy: float) -> float:
        """One deadline decision through the controller, traced when it
        tightens the legacy constant (scrape-visible via the
        biscotti_deadline_seconds gauge in _refresh_gauges)."""
        decided = self.deadlines.deadline(phase, legacy)
        if decided < legacy:
            self._trace("deadline_adaptive", phase=phase,
                        deadline_s=round(decided, 3), legacy_s=legacy)
        return decided

    async def _gather_quorum(self, phase: str, calls: Dict[int, object],
                             need: int, legacy_s: float) -> int:
        """Collection-point fan-out with partial-quorum graceful
        degradation. `calls` maps peer id -> coroutine returning truthy
        on success (its side effects carry the actual payload). Plane
        DISARMED (cfg.adaptive_deadlines off): plain gather over the
        same coroutines — the seed behavior, to the await. Armed: wait
        for everyone until the phase's soft deadline (the controller's
        estimate, clamped to `legacy_s`), then proceed the moment
        `need` successes exist, CANCELLING the laggards — each counted
        in biscotti_straggler_excluded_total{phase} and traced. A
        cancelled _call records no breaker outcome (its BaseException
        path hands back any probe slot), and nothing here touches
        stake: an excluded honest straggler is an observability event,
        never evidence. The waiting-on view tracks the pending set
        either way; completed-phase durations feed the controller so a
        later adaptive run warms up from history. Returns the success
        count."""
        if not calls:
            return 0
        tasks = {pid: asyncio.ensure_future(c) for pid, c in calls.items()}
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        armed = self.cfg.adaptive_deadlines
        soft_s = self._deadline(phase, legacy_s) if armed else legacy_s

        def successes() -> int:
            return sum(1 for t in tasks.values()
                       if t.done() and not t.cancelled()
                       and t.exception() is None and t.result())

        try:
            while True:
                pending = {pid: t for pid, t in tasks.items()
                           if not t.done()}
                self.straggler.waiting(phase, pending)
                if not pending:
                    # everyone answered: a full observation the
                    # controller learns the phase's distribution from
                    self.deadlines.observe(phase, loop.time() - t0)
                    break
                elapsed = loop.time() - t0
                if armed and elapsed >= soft_s and successes() >= need:
                    excluded = sorted(pending)
                    for t in pending.values():
                        t.cancel()
                    await asyncio.gather(*pending.values(),
                                         return_exceptions=True)
                    self.straggler.exclude(phase, excluded)
                    self._trace("straggler_excluded", phase=phase,
                                peers=excluded,
                                waited_s=round(elapsed, 3))
                    break
                timeout = (max(0.02, soft_s - elapsed)
                           if (armed and elapsed < soft_s) else None)
                await asyncio.wait(pending.values(), timeout=timeout,
                                   return_when=(
                                       asyncio.FIRST_COMPLETED
                                       if elapsed >= soft_s and armed
                                       else asyncio.ALL_COMPLETED))
        finally:
            self.straggler.clear(phase)
            for t in tasks.values():
                if not t.done():
                    t.cancel()
        return successes()

    # ---------------------------------------------------------- RPC surface

    async def _handle(self, msg_type, meta, arrays):
        # any inbound RPC proves the caller is reachable: re-admit it to the
        # gossip set (eviction is otherwise permanent, so a peer that
        # recovered from a partition or restart would never again receive
        # pushes from us; ref parity gap — main.go:1479-1482 only re-adds
        # on RegisterPeer)
        src = meta.get("source_id")
        if src is not None:
            try:
                src = int(src)
                if src in self.peers:
                    if src not in self.alive and src != self.id:
                        # first frame from outside our live view: a late
                        # joiner's hello, a restart, or an evicted peer
                        # resurfacing — a membership transition, observed
                        # at the earliest possible point (this seam runs
                        # before any handler, so the hello-path check in
                        # _h_register_peer would always see it alive)
                        self._bump_epoch("join", src)
                    self.alive.add(src)
                    # inbound traffic is liveness evidence for the THEM→US
                    # path only: it expires a tripped breaker's cooldown so
                    # our next outbound call probes immediately (a restart's
                    # announce re-admits without waiting out the cooldown),
                    # but it must NOT reset the outbound failure streak — an
                    # asymmetrically partitioned peer (reachable inbound,
                    # dead outbound) has to stay quarantinable
                    self.health.note_inbound(src)
            except (TypeError, ValueError):
                pass
        dispatch = {
            "RegisterPeer": self._h_register_peer,
            "RegisterBlock": self._h_register_block,
            "AdvertiseBlock": self._h_advertise_block,
            "GetBlock": self._h_get_block,
            "RegisterUpdate": self._h_register_update,
            "RegisterSecret": self._h_register_secret,
            "RegisterDecline": self._h_register_decline,
            "RequestNoise": self._h_request_noise,
            "VerifyUpdateKRUM": self._h_verify_update,
            "VerifyUpdateRONI": self._h_verify_update,
            "GetUpdateList": self._h_get_update_list,
            "GetMinerPart": self._h_get_miner_part,
            "GetSnapshot": self._h_get_snapshot,
            "GetReshareDeal": self._h_get_reshare_deal,
            "Metrics": self._h_metrics,
            # hierarchical aggregation overlay (docs/OVERLAY.md)
            "OverlayOffer": self._h_overlay_offer,
            "RegisterAggregate": self._h_register_aggregate,
            "RelayFrames": self._h_relay_frames,
            # elastic fleet plane (docs/PLACEMENT.md)
            "GetMigrationTicket": self._h_get_migration_ticket,
            "DkgDeal": self._h_dkg_deal,
        }
        h = dispatch.get(msg_type)
        if h is None or not protocol.serves(self.caps, msg_type):
            # second arm: a --protocol-version pin answers feature-gated
            # messages introduced after its row exactly like the old
            # build it emulates — unknown method (runtime/protocol.py)
            raise RPCError(f"unknown method {msg_type}")
        return await h(meta, arrays)

    async def _wait_for_iteration(self, it: int, budget: float = 30.0) -> None:
        """Park a future-iteration message until we catch up
        (ref: main.go:1211-1214, krum.go:240-243). Iterations past the
        run's absolute end are refused IMMEDIATELY — parking them would
        let one hostile packet pin a handler task for the full budget.
        Anything inside [0, max_iterations] stays parkable: a peer far
        behind can legitimately leap there via one chain adoption.

        Parking is a COUNTED, CAPPED resource (runtime/admission.py):
        with an enabled admission plan, the lot sheds its OLDEST waiter
        (woken into a retryable BusyError) instead of growing without
        bound — the pre-admission behavior let one hostile peer park
        thousands of 30-second handler tasks for free."""
        if it > self.cfg.max_iterations:
            raise RPCError("iteration beyond reachable horizon")
        if self.iteration >= it:
            return  # no wait, no parking accounting
        tok = self.admission.park("wait_iteration")
        try:
            deadline = time.monotonic() + budget
            while self.iteration < it:
                if tok.shed is not None:
                    raise BusyError("parked waiter shed: " + tok.shed)
                if time.monotonic() > deadline:
                    raise RPCError("caller too far ahead")
                await asyncio.sleep(0.05)
        finally:
            self.admission.unpark(tok)

    async def _wait_round_ready(self, it: int, budget: float = 30.0) -> RoundState:
        """Park until OUR round state for iteration `it` exists — callers may
        race ahead of a peer that is still bootstrapping or mid-transition
        (the reference blocks such callers the same way, krum.go:240-243).
        Returns the ready RoundState; raises StaleError if we are already
        past `it`. Parked time is budgeted by the admission plane's
        parking lot, same as _wait_for_iteration."""
        await self._wait_for_iteration(it, budget)
        if self.iteration > it:
            raise StaleError()
        st = self.round
        if st.iteration == it and st.krum_decision is not None:
            return st  # fast path: round already live, no parking
        tok = self.admission.park("wait_round_ready")
        try:
            deadline = time.monotonic() + budget
            while True:
                if self.iteration > it:
                    raise StaleError()
                st = self.round
                if st.iteration == it and st.krum_decision is not None:
                    return st
                if tok.shed is not None:
                    raise BusyError("parked waiter shed: " + tok.shed)
                if time.monotonic() > deadline:
                    raise RPCError("round never became ready")
                await asyncio.sleep(0.02)
        finally:
            self.admission.unpark(tok)

    async def _h_register_peer(self, meta, arrays):
        """Join/announce: record the caller, return our chain so they can
        adopt the longest one (ref: main.go:950-1024 — which returns the
        full chain unconditionally; at bootstrap that is N² chain bodies
        on the wire, ~30 s of pure encode at N=150 single-box). The caller
        states how many blocks it already holds and we reply with the
        chain only when ours is strictly longer — peers at the same height
        converge through block gossip and the advertise/pull catch-up, not
        the join path."""
        pid = int(meta["source_id"])
        if "host" in meta and "port" in meta:
            self.peers[pid] = (meta["host"], int(meta["port"]))
            self._addr_to_pid[self.peers[pid]] = pid
            self.pool.avoid_local_ports = frozenset(
                p for _, p in self.peers.values())
        self.alive.add(pid)  # join transitions bump in _handle's seam
        # wire-plane negotiation: record the caller's codec capability
        # set (absent in a legacy hello → it stays raw64-only) and
        # advertise ours in the reply, so both ends of a first contact
        # leave knowing what the other can decode
        self._record_caps(pid, meta.get("codecs"))
        # omit iff our chain would LOSE fork choice against the caller's
        # claimed key — same (weight, length) rule as maybe_adopt, so an
        # isolation survivor padded with empty blocks (long but light)
        # still receives the heavier honest chain. Claims are advisory:
        # overclaiming only denies the claimant a chain it would have
        # refused to adopt anyway; the adopted chain itself is verified.
        caller_key = (int(meta.get("have_weight", 0)),
                      int(meta.get("have_blocks", 0)))
        # `no_chain`: a snapshot-bootstrapping joiner's hello — it will
        # pull a sealed suffix via GetSnapshot instead, so replying with
        # the full chain here would silently re-pay exactly the genesis
        # replay the snapshot path exists to avoid. A PRUNED server also
        # omits: its gap-containing chain decodes as a contiguous
        # candidate the receiver's quorum gate is guaranteed to refuse,
        # so shipping it is pure wasted bulk — the caller should pull
        # GetSnapshot (clusters mixing snapshot_bootstrap=0 joiners with
        # all-pruned peers have no announce-path catch-up by design;
        # docs/MEMBERSHIP.md §snapshot).
        if meta.get("no_chain") or self.chain.pruned_before \
                or self.chain.adoption_key() <= caller_key:
            return {"chain_omitted": True,
                    "snapshot_available": bool(self.chain.pruned_before),
                    "codecs": sorted(self.caps)}, {}
        cmeta, carrays = wire.pack_chain(self.chain.blocks)
        cmeta["codecs"] = sorted(self.caps)
        return cmeta, carrays

    async def _h_register_block(self, meta, arrays):
        blk = wire.unpack_block(meta, arrays)
        self._accept_block(blk, gossip=True)
        return {}, {}

    async def _h_advertise_block(self, meta, arrays):
        """Header-only gossip: pull the body from the advertiser iff we do
        not already hold this block (see _gossip_block). An advert AHEAD
        of our round means we also miss ancestors (a lost broadcast frame
        for an earlier block) — a single-height pull could not extend the
        chain, so catch up block-by-block from the advertiser instead."""
        it = int(meta["iteration"])
        h = bytes.fromhex(meta.get("hash", ""))
        src = int(meta.get("source_id", -1))
        have = self.chain.get_block(it)
        if have is not None and have.hash == h:
            return {}, {}
        if src not in self.peers:
            return {}, {}
        if it > self.iteration:
            self._schedule_catch_up(src)
            return {}, {}

        async def pull():
            try:
                if self.overlay.enabled:
                    # overlay pull backoff (docs/OVERLAY.md): with the
                    # tree armed, our subtree's relay is most likely
                    # mid-forward of this very body — an instant pull
                    # would re-fetch it cross-host and undo the
                    # deduplication (observed as a GetBlock.reply storm
                    # when the minter's OWN hive advertises over
                    # loopback before the remote relay finishes its 50
                    # co-hosted deliveries). Poll the chain for a
                    # bounded window, jittered so expiring waiters don't
                    # stampede; a dead relay costs a few seconds of
                    # extra latency, never the round.
                    deadline = (time.monotonic() + 3.0
                                + 1.5 * self._rng.random())
                    while time.monotonic() < deadline:
                        have2 = self.chain.get_block(it)
                        if have2 is not None and have2.hash == h:
                            return
                        if self.iteration > it:
                            return
                        await asyncio.sleep(0.25)
                bmeta, barrays = await self._call(
                    src, "GetBlock",
                    {"iteration": it, **self._reply_codec_meta(src)},
                    timeout=self.timeouts.rpc_s)
                blk = wire.unpack_block(bmeta, barrays)
                if blk.hash == blk.compute_hash():
                    self._accept_block(blk, gossip=True)
            except Exception:
                pass

        t = asyncio.get_running_loop().create_task(pull())
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)
        return {}, {}

    async def _h_get_block(self, meta, arrays):
        """Serve a block body to a puller (the chain doubles as the block
        store; ref: the reference serves its chain via RegisterPeer,
        main.go:431-433 — this is the single-block variant)."""
        it = int(meta["iteration"])
        blk = self.chain.get_block(it)
        if blk is None:
            raise RPCError(f"no block at iteration {it}")
        return wire.pack_block(blk)

    # ----------------------------------------------- membership: snapshot

    async def _h_get_snapshot(self, meta, arrays):
        """Serve a chain SNAPSHOT to a bootstrapping joiner
        (docs/MEMBERSHIP.md): genesis + the last `snapshot_tail`+1 sealed
        blocks — the +1 is the trust-anchor base whose stake map seeds
        the suffix's quorum verification — plus an advisory weight claim
        for the pruned-away range. Bulk-classed at admission, chunked by
        the wire plane like any oversized reply; read-only and safe for
        any caller (the chain is public gossip either way). The joiner
        names the tail it wants (its own snapshot_tail); absent, the
        server's policy applies — over-asking merely degrades toward the
        full chain RegisterPeer would have served anyway."""
        chain = self.chain
        tail = max(1, int(meta.get("tail", 0) or 0)
                   or self.cfg.snapshot_tail)
        suffix = chain.blocks[1:]
        dropped: List[Block] = []
        if len(suffix) > tail + 1:
            dropped = suffix[:-(tail + 1)]
            suffix = suffix[-(tail + 1):]
        pruned_weight = (chain.pruned_weight
                         + sum(1 for b in dropped if not b.is_empty()))
        cmeta, carrays = wire.pack_chain([chain.blocks[0]] + suffix)
        cmeta["snapshot"] = {
            "pruned_weight": pruned_weight,
            "base_height": suffix[0].iteration if suffix else -1,
        }
        self._trace("snapshot_served",
                    base=cmeta["snapshot"]["base_height"],
                    blocks=len(suffix))
        return cmeta, carrays

    # ------------------------------------- elastic fleet: migration, DKG

    async def _h_get_migration_ticket(self, meta, arrays):
        """Serve this peer's migration ticket to its placement
        supervisor (docs/PLACEMENT.md). Token-gated and one-shot: the
        supervisor installs a drain token on this agent out of band
        (controller seam / supervisor process boundary) before asking;
        any caller without it — which includes every ordinary peer,
        since tickets carry the breaker ledger, admission buckets and
        EF residual — gets a refusal, not state."""
        token = str(meta.get("token", ""))
        if not self._drain_token or token != self._drain_token:
            raise RPCError("migration not authorized")
        self._drain_token = None  # one-shot: a replayed drain is refused
        ticket = placement.ticket_from_agent(self)
        self._trace("migration_ticket_served",
                    height=int(self.chain.latest.iteration),
                    nbytes=placement.ticket_nbytes(ticket))
        return placement.ticket_wire(ticket)

    async def _h_dkg_deal(self, meta, arrays):
        """Accept one dealer's genesis deal (crypto/dkg.py): rebuild
        it, verify every share row against the dealer's own Pedersen
        grid, and store it for ceremony aggregation. A failing deal is
        a LOUD verdict — counted, traced, and reported back to the
        dealer — never a silent drop, because aggregation excludes it
        from the transcript and the dealer must learn why."""
        from biscotti_tpu.crypto import dkg

        dealer = int(meta.get("dealer_id", -1))
        try:
            deal = dkg.DkgDeal(
                dealer_id=dealer,
                comms=np.asarray(arrays["comms"], dtype=np.uint8),
                xs=[int(x) for x in meta.get("xs", [])],
                rows=np.asarray(arrays["rows"], dtype=np.int64),
                blind_rows=np.asarray(arrays["blind_rows"],
                                      dtype=np.uint8))
            ok = dkg.verify_deal(deal)
        except Exception:
            ok = False
        verdict = "verified" if ok else "rejected"
        if ok:
            self._dkg_deals[dealer] = deal
        if self.tele.enabled:
            self.tele.registry.counter(
                dkg.DEALS_METRIC, dkg.DEALS_HELP).inc(verdict=verdict)
        self._trace("dkg_deal", dealer=dealer, verdict=verdict)
        return {"verdict": verdict, "dealer": dealer}

    async def _snapshot_bootstrap(self) -> bool:
        """Joiner half of the snapshot handshake: pull GetSnapshot from
        peers (seeded-random order) until one validated snapshot adopts.
        The preceding hello carried `no_chain`, so NO pre-snapshot block
        ever crosses the wire for this peer — asserted by the wire byte
        accounting (GetSnapshot.reply vs GetBlock.reply) in the
        acceptance test.

        The suffix's quorums verify against the BASE block's own carried
        stake map, so a lone Byzantine donor could otherwise fabricate
        base + committee + quorums wholesale: before adopting, the base
        block's hash is corroborated by an INDEPENDENT peer (one
        GetBlock at the base height — a single block, not history).
        Capture now needs the donor AND the sampled corroborator to
        collude; clusters with fewer than two other peers have nobody to
        cross-check against and skip the step (genesis replay via the
        announce path remains the fallback either way)."""
        order = sorted(p for p in self.peers if p != self.id)
        self._rng.shuffle(order)
        for pid in order:
            if protocol.SNAPSHOT not in self._grant(pid):
                # the donor's hello did not grant the snapshot feature
                # (old build / version pin): it would answer GetSnapshot
                # with unknown-method — skip it without the wasted RPC.
                # The announce already recorded every peer's hello, so
                # an all-legacy fleet exhausts the order and falls back
                # to the announce path's genesis replay.
                self._trace("snapshot_refused",
                            reason="feature_ungranted", peer=pid)
                continue
            try:
                rmeta, rarrays = await self._call(
                    pid, "GetSnapshot",
                    {"source_id": self.id,
                     "tail": self.cfg.snapshot_tail,
                     **self._reply_codec_meta(pid)})
            except Exception:
                continue
            try:
                blocks = wire.unpack_chain(rmeta, rarrays)
            except Exception:
                # a malformed reply must cost the DONOR its turn, never
                # crash the joiner's run()
                self._trace("snapshot_refused", reason="undecodable",
                            peer=pid)
                continue
            claim = int((rmeta.get("snapshot") or {})
                        .get("pruned_weight", 0) or 0)
            base = blocks[1].iteration if len(blocks) >= 2 else -1
            if base > 0 and len(order) >= 2:
                ok = await self._corroborate_base(blocks[1], pid, order)
                if not ok:
                    self._trace("snapshot_refused",
                                reason="base_uncorroborated", peer=pid)
                    continue
            # validation + adoption run ON the event loop: the suffix is
            # at most snapshot_tail+1 blocks (bounded work), and the
            # chain mutation must never race the live RPC handlers that
            # read self.chain between awaits
            if self._adopt_snapshot(blocks, claim, pid):
                return True
        return False

    async def _corroborate_base(self, base: Block, donor: int,
                                order: List[int]) -> bool:
        """Ask peers OTHER than the snapshot's donor for the block at the
        base height and compare hashes. The first peer that answers
        decides; peers that are unreachable or pruned below the base are
        skipped. Returns False when the answer disagrees (fork or
        fabrication) or nobody could answer."""
        for other in order:
            if other == donor:
                continue
            try:
                bmeta, barrays = await self._call(
                    other, "GetBlock",
                    {"iteration": int(base.iteration),
                     "source_id": self.id,
                     **self._reply_codec_meta(other)},
                    timeout=self.timeouts.rpc_s)
            except Exception:
                continue  # unreachable / pruned: ask the next peer
            try:
                blk = wire.unpack_block(bmeta, barrays)
            except Exception:
                continue  # undecodable corroborator: ask the next peer
            return blk.hash == base.hash
        return False

    def _adopt_candidate(self, blocks: List[Block],
                         source: Optional[int] = None,
                         quorums_ok: Optional[bool] = None) -> bool:
        """Full-chain adoption with TRACED refusal reasons — the one gate
        every chain offered to a (re)joining peer passes through
        (announce replies, contiguous snapshots): genesis hash pinned,
        fork-choice weight, quorum authentication, then maybe_adopt's
        structural verify. Refusals land in the flight recorder as
        `chain_refused{reason=…}` so a rejoin that kept its old history
        is diagnosable from a scrape, not a debugger."""
        if not blocks:
            return False
        if blocks[0].hash != self.chain.blocks[0].hash:
            self._trace("chain_refused", reason="genesis_mismatch",
                        peer=source)
            return False
        other = Blockchain.__new__(Blockchain)
        other.blocks = blocks
        if other.adoption_key() <= self.chain.adoption_key():
            self._trace("chain_refused", reason="not_heavier", peer=source)
            return False
        # `quorums_ok` lets an async caller precompute the expensive
        # batched-signature sweep in a worker thread (read-only, so
        # thread-safe) while THIS method — which mutates self.chain —
        # always runs on the event loop, never racing the live handlers
        if (self._chain_quorums_ok(blocks)
                if quorums_ok is None else quorums_ok) is not True:
            self._trace("chain_refused", reason="quorum_unauthenticated",
                        peer=source)
            return False
        return self.chain.maybe_adopt(other)

    def _adopt_snapshot(self, blocks: List[Block], pruned_weight: int,
                        source: Optional[int] = None) -> bool:
        """Validate + adopt one GetSnapshot reply. Same refusal logic as
        a checkpoint restore / live adoption, extended to the sealed
        suffix: the genesis hash must be OURS (a foreign cluster's
        snapshot is refused outright), the suffix must be structurally
        sealed (hashes + links), and every block above the trust-anchor
        base must carry verifier quorums valid under the committee its
        carried parent state elects. The base block itself is the
        snapshot's trust anchor — unverifiable without the pruned
        history by construction; its integrity is pinned by the quorums
        sealed on top of it (docs/MEMBERSHIP.md §trust-model)."""
        if len(blocks) < 2 or blocks[0].iteration != -1:
            self._trace("snapshot_refused", reason="malformed", peer=source)
            return False
        if blocks[0].hash != self.chain.blocks[0].hash:
            self._trace("snapshot_refused", reason="genesis_mismatch",
                        peer=source)
            return False
        base = blocks[1].iteration
        if base <= 0:
            # contiguous from genesis (short chain): ordinary adoption —
            # full quorum verification, no trust anchor involved
            if self._adopt_candidate(blocks, source):
                self._trace("snapshot_adopted", base=0,
                            height=self.chain.latest.iteration)
                return True
            return False
        cand = Blockchain.__new__(Blockchain)
        cand.blocks = blocks
        cand.pruned_before = base
        # the weight claim is advisory but STICKY (it enters our own
        # adoption_key forever): clamp it to the pruned range's length —
        # one non-empty block per pruned height is the physical maximum —
        # so a Byzantine donor's pruned_weight=10**9 cannot make every
        # future honest chain offer lose fork choice as "not_heavier"
        cand.pruned_weight = max(0, min(int(pruned_weight), base))
        try:
            cand.verify()
        except ChainInvariantError as e:
            self._trace("snapshot_refused", reason=f"structure: {e}",
                        peer=source)
            return False
        for i in range(2, len(blocks)):
            if not self._block_quorums_ok(blocks[i],
                                          blocks[i - 1].stake_map,
                                          blocks[i - 1].hash):
                self._trace("snapshot_refused",
                            reason="quorum_unauthenticated",
                            height=blocks[i].iteration, peer=source)
                return False
        if cand.adoption_key() <= self.chain.adoption_key():
            self._trace("snapshot_refused", reason="not_heavier",
                        peer=source)
            return False
        self.chain.blocks = blocks
        self.chain.pruned_before = base
        self.chain.pruned_weight = cand.pruned_weight
        self._trace("snapshot_adopted", base=base,
                    height=self.chain.latest.iteration)
        return True

    def _accept_block(self, blk: Block, gossip: bool,
                      minted: bool = False) -> None:
        if blk.iteration > self.iteration:
            # future block: we're behind — park it and retry as we catch up
            # (ref: main.go:1300-1320 sleep-loop)
            t = asyncio.get_running_loop().create_task(self._late_accept(blk))
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
            return
        if not minted and not blk.is_empty():
            # authenticate a FOREIGN non-empty block's verifier quorums
            # against the committee its parent state elects — a Byzantine
            # leader cannot mint fake contributions into the ledger
            parent = self.chain.get_block(blk.iteration - 1)
            if parent is None or not self._block_quorums_ok(
                    blk, parent.stake_map, parent.hash):
                self._trace("block_quorum_rejected", height=blk.iteration)
                return
        changed = self.chain.consider_block(blk)
        if changed:
            self._trace("block_accepted", height=blk.iteration,
                        empty=blk.is_empty(), hash=blk.hash.hex()[:16])
            if self.round.block_done and blk.iteration >= self.round.iteration:
                self.round.block_done.set()
            # the instant the head moves is the widest overlap window:
            # start next round's speculative worker precompute NOW, while
            # this round still evaluates convergence and tears down
            self._maybe_speculate()
            if gossip:
                # minted here → full fan-out; received → bounded re-gossip
                self._gossip_block(blk, full=minted)

    async def _late_accept(self, blk: Block, budget: float = 20.0) -> None:
        deadline = time.monotonic() + budget
        while self.iteration < blk.iteration and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if blk.iteration <= self.iteration:
            self._accept_block(blk, gossip=False)

    def _gossip_block(self, blk: Block, full: bool = False) -> None:
        """Block propagation, two-tier. The MINTER pushes the full block to
        every live peer (ref: main.go:1410-1418), encoding the frame ONCE
        and writing the same bytes to each connection. RECEIVERS do not
        re-broadcast the multi-MB body (the reference re-gossips whole
        blocks on append, main.go:1390 — O(N²) bodies); they advertise the
        (iteration, hash) header to a log-sized random subset, and anyone
        missing the block pulls it. Same epidemic coverage, but the body
        crosses the wire O(N) times instead of O(N·fanout)."""
        # deliver to the FULL membership, not the alive subset: `alive` is
        # a liveness heuristic evicted on any transient RPC timeout, and a
        # quiet worker that never calls us back would otherwise drop out of
        # every gossip target draw and strand on its block timer (observed
        # at N=50+ under load). A truly dead target costs one fast failed
        # dial; a mislabeled live one gets its block. The one exception is
        # a QUARANTINED peer (breaker open, cooling down): it already
        # failed `breaker_threshold` consecutive times moments ago, so the
        # fan-out skips it until a half-open probe — or its own inbound
        # rejoin traffic — re-admits it.
        targets = []
        busy_targets = []
        for pid in self.peers:
            if pid == self.id:
                continue
            if not self.health.available(pid):
                self._trace("gossip_skip_quarantined", peer=pid)
                continue
            # a peer that answered BusyError THIS round is deprioritized,
            # not dropped: full pushes still deliver (last, so fresh peers
            # drain first), but the advertise fan-out samples it only when
            # fresh targets cannot fill the draw — epidemic coverage still
            # reaches it through other peers' re-gossip
            if self._peer_busy(pid):
                self._trace("gossip_deprioritize_busy", peer=pid)
                busy_targets.append(pid)
            else:
                targets.append(pid)
        if full:
            from biscotti_tpu.runtime import messages as msgs

            meta, arrays = wire.pack_block(blk)
            meta["rid"] = 0
            # encode once PER CODEC GROUP, not per peer: targets that
            # negotiated the same (codec, chunking) share one frame, so
            # a homogeneous cluster still pays a single encode while a
            # mixed cluster's raw64 stragglers get their own legacy copy
            # (frame bytes, effective codec) per group — the effective
            # codec (from encode stats) labels the byte accounting, so
            # a block whose arrays all fell back to raw counts as raw64
            # fresh targets first, busy ones last: every peer still gets
            # the block (it is a push they need to advance), but a peer
            # shedding load is not first in line for a multi-MB frame
            targets = targets + busy_targets
            # hive loopback partition (runtime/hive.py): co-hosted targets
            # get the SAME block object via post_direct — no frame encode
            # at all, the dominant broadcast cost — while remote targets
            # share one encode per codec group as before. The partition is
            # re-checked at send time inside push(): a co-hosted peer that
            # died in between gets the ConnectionError a closed TCP socket
            # would raise, never a silent drop.
            # distributed tracing: the broadcast inherits the CURRENT
            # span (the mint / the handler that accepted the block) as
            # the receivers' parent — stamped once per traced group, so
            # the encode-once-per-group optimization survives and
            # untraced/legacy groups keep byte-identical frames
            wctx = tracectx.current() if self.tele.trace else None
            meta_tr = tracectx.stamp(meta, wctx) if wctx is not None \
                else meta
            loopback_pids = frozenset(
                pid for pid in targets
                if self.pool.loopback_endpoint(*self.peers[pid]) is not None)
            # overlay down-path (docs/OVERLAY.md): remote targets sharing
            # a subtree get the block THROUGH that subtree's relay — the
            # multi-MB body crosses TCP once per remote subtree instead
            # of once per remote peer; a failed relay falls back to the
            # direct pushes below for exactly its orphaned targets
            relayed_plan: Dict[int, List[int]] = {}
            if self.overlay.enabled:
                _, relayed_plan = self.overlay.plan(
                    [p for p in targets if p not in loopback_pids],
                    blk.iteration, self.id)
            relayed_pids = frozenset(t for ts in relayed_plan.values()
                                     for t in ts)
            frames: Dict[Tuple[str, int, bool], Tuple[bytes, str]] = {}
            group: Dict[int, Tuple[str, int, bool]] = {}
            for pid in targets:
                if pid in loopback_pids or pid in relayed_pids:
                    continue
                traced = wctx is not None and self._peer_traces(pid)
                key = self._wire_to(pid) + (traced,)
                group[pid] = key
                if key not in frames:
                    codec, chunk, traced = key
                    stats: Dict[str, int] = {}
                    frame = msgs.encode(
                        "RegisterBlock", meta_tr if traced else meta,
                        arrays,
                        codec=None if codec == wcodecs.RAW else codec,
                        chunk_bytes=chunk, stats=stats)
                    eff = str(stats.get("codec", wcodecs.RAW))
                    frames[key] = (frame, eff)
                    wcodecs.observe_ratio(
                        self.pool.metrics, eff,
                        stats["raw_bytes"], stats["wire_bytes"])

            async def push(pid):
                host, port = self.peers[pid]
                try:
                    if pid in loopback_pids:
                        await self.pool.post_direct(
                            host, port, "RegisterBlock",
                            meta_tr if self._peer_traces(pid) else meta,
                            arrays, timeout=self.timeouts.rpc_s)
                    else:
                        frame, eff = frames[group[pid]]
                        await self.pool.post(host, port, frame,
                                             timeout=self.timeouts.rpc_s,
                                             msg_type="RegisterBlock",
                                             codec=eff)
                except Exception:
                    self.alive.discard(pid)
                    self._record_peer_fail(pid)
                else:
                    # a drained post only proves the OS accepted the bytes
                    # — a wedged peer's socket buffers still drain fine —
                    # so it may keep a CLOSED streak clean but must never
                    # rehabilitate a tripped breaker (that would flap the
                    # quarantine every gossip round); only a reply-bearing
                    # _call closes it
                    if self.health.state(pid) == faults.CLOSED:
                        self._record_peer_ok(pid)
                    else:
                        self.alive.add(pid)

            # gossip outlives the round on purpose (stragglers still need
            # the block); _bg_tasks holds the strong ref and the bounded
            # send in rpc.py caps each task's lifetime at rpc_s
            loop_now = asyncio.get_running_loop()
            # relay frames FIRST: the remote subtrees' forwards race the
            # advert re-gossip our own loopback deliveries will trigger,
            # so the cross-host copies get the head start
            for relay, ts in relayed_plan.items():
                t = loop_now.create_task(self._relay_send(
                    relay, "RegisterBlock", meta, arrays, ts,
                    blk.iteration, timeout=self.timeouts.rpc_s))
                self._bg_tasks.add(t)
                t.add_done_callback(self._bg_tasks.discard)
            for pid in targets:
                if pid in relayed_pids:
                    continue
                t = loop_now.create_task(push(pid))
                self._bg_tasks.add(t)
                t.add_done_callback(self._bg_tasks.discard)
            return

        import math

        population = len(targets) + len(busy_targets)
        fanout = max(3, int(math.log2(max(2, population))) + 1)
        if len(targets) > fanout:
            targets = self._rng.sample(targets, fanout)
        elif len(targets) < fanout and busy_targets:
            # fresh targets cannot fill the draw: top up from the busy
            # set rather than shrinking coverage below the epidemic bound
            need = min(fanout - len(targets), len(busy_targets))
            targets = targets + self._rng.sample(busy_targets, need)
        ad = {"iteration": blk.iteration, "hash": blk.hash.hex(),
              "source_id": self.id}

        async def advertise(pid):
            try:
                await self._call(pid, "AdvertiseBlock", ad,
                                 timeout=self.timeouts.rpc_s)
            except Exception:
                pass

        for pid in targets:
            t = asyncio.get_running_loop().create_task(advertise(pid))
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)

    def _reject_source(self, st: RoundState, sid: int, it: int,
                       commitment: bytes, reason: str) -> None:
        """Record a cryptographically invalid submission: carried into the
        minted block as an accepted=False record and debited STAKE_UNIT
        (ref: honest.go:363-370)."""
        st.miner_rejected[sid] = Update(
            source_id=sid, iteration=it, delta=np.zeros(0, np.float64),
            commitment=commitment, accepted=False)
        self._trace("submission_rejected", source=sid, reason=reason)

    def _pipelined_iteration(self, it: int, source) -> bool:
        """True when this frame may pre-verify: a near-future round
        (ahead of the current one by at most pipeline_depth), a KNOWN
        peer id, and the first such frame for (it, sid). The expensive
        committee-INDEPENDENT checks (the O(d) commitment recompute,
        VSS digests) then run before the handler parks for the round,
        overlapping the current round's mining; committee-dependent
        checks (signature quorums) still wait for the election.

        The (known peer, once per (it, sid)) gate bounds the
        pre-verification CPU at num_nodes·depth checks per round — the
        same order the round itself pays — so replayed or sid-spoofed
        future frames cannot turn early verification into a free MSM
        amplifier (they just park, and the post-round-start path with
        its dedup/role gates handles them as before)."""
        if not (self.cfg.pipeline
                and self.iteration < it
                <= self.iteration + self.cfg.pipeline_depth):
            return False
        try:
            sid = int(source)
        except (TypeError, ValueError):
            return False
        if sid not in self.peers:
            return False
        key = (it, sid)
        if key in self._preverify_gate:
            return False
        self._preverify_gate.add(key)
        return True

    async def _h_register_update(self, meta, arrays):
        """Miner intake, plain mode (ref: main.go:420-436). The commitment
        is recomputed from the received delta (ref: kyber.go:564-577) and
        the verifier signature quorum is checked before acceptance.

        Pipelined (cfg.pipeline): a submission for the NEXT round runs
        its commitment recompute — the O(d) MSM that dominates plain
        intake — immediately, while this peer is still mining the
        current round; only the quorum check (needs the next committee)
        waits. Batched (cfg.batch_intake): concurrent same-round
        submissions wait one event-loop beat and are verified as ONE
        RLC batch with bisection fallback (_drain_plain_batch) — one
        ~d-point MSM per micro-batch instead of one per update. Both
        paths produce bit-identical accept/reject verdicts and identical
        round state to the sequential loop they replace."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        pre_ok: Optional[bool] = None
        u: Optional[Update] = None
        if (not self.cfg.fedsys
                and self._pipelined_iteration(it, meta.get("source_id"))):
            u = wire.unpack_update(meta, arrays)
            if len(u.delta) == self.trainer.num_params:
                with self.tele.span("miner_verify", it=it):
                    pre_ok = await asyncio.to_thread(
                        self._verify_plain_commitment, u)
                self._trace("intake_preverified", source=u.source_id,
                            ok=pre_ok)
        st = await self._wait_round_ready(it)
        if not self.role_map.is_miner(self.id):
            raise RPCError("not a miner this round")
        if u is None:  # the pre-verified path already decoded this payload
            u = wire.unpack_update(meta, arrays)
        if len(u.delta) != self.trainer.num_params:
            raise RPCError("bad update dimension")
        if u.source_id in st.miner_updates or u.source_id in st.miner_rejected:
            return {}, {}
        why = ""
        if not self.cfg.fedsys:  # FedSys carries no crypto (ref: FedSys/)
            if pre_ok is not None:
                commit_ok = pre_ok
            elif self.cfg.batch_intake:
                commit_ok = await self._plain_commit_batched(st, u)
            else:
                with self.tele.span("miner_verify", it=it):
                    commit_ok = await asyncio.to_thread(
                        self._verify_plain_commitment, u)
            if not commit_ok:
                why = "commitment recompute mismatch"
            else:
                with self.tele.span("sig_check", it=it):
                    quorum_ok = (not self.cfg.verification
                                 or await asyncio.to_thread(
                                     self._verify_sig_quorum, u.commitment,
                                     it, u.source_id, u.signers,
                                     u.signatures))
                if not quorum_ok:
                    why = "verifier signature quorum failed"
        if why:
            self._reject_source(st, u.source_id, it, u.commitment, why)
            raise RPCError(f"update rejected: {why}")
        st.miner_updates.setdefault(u.source_id, u)
        self._trace("update_registered", source=u.source_id,
                    have=len(st.miner_updates))
        return {}, {}

    async def _plain_commit_batched(self, st: RoundState, u: Update) -> bool:
        """Park this update in the round's micro-batch and await its
        commitment verdict (cfg.batch_intake). The first parker spawns
        the drainer; everyone arriving within the batch window shares
        one RLC check — but every submission is verified against its own
        payload (no verdict sharing, even for a repeated source_id)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        st.plain_pending.append((u, fut))
        if st.plain_drainer is None or st.plain_drainer.done():
            st.plain_drainer = loop.create_task(self._drain_plain_batch(st))
        return await asyncio.shield(fut)

    async def _drain_plain_batch(self, st: RoundState) -> None:
        """Verify every parked plain-mode update in one batched RLC
        commitment check; on batch failure bisection narrows to the
        exact per-update recompute verdicts (find_bad_commitments), so
        the offender set — and the stake debits it feeds — is identical
        to the sequential path's. Keyless mode (hash commitments) has no
        RLC structure; it verifies per update inside one thread hop.
        Hardened: any unexpected error in the batch machinery falls back
        to the exact sequential recompute per update, and parked futures
        are ALWAYS resolved — one malformed submission must not hang the
        honest batch behind it."""
        await asyncio.sleep(0.02)  # micro-batch window: let a burst land
        while st.plain_pending:
            batch, st.plain_pending = st.plain_pending, []
            updates = [u for u, _ in batch]

            def run() -> List[bool]:
                try:
                    if self.commit_key is not None:
                        items = [(u.commitment, self._quantize_np(u.delta))
                                 for u in updates]
                        if cm.batch_verify_commitments(items,
                                                       self.commit_key):
                            return [True] * len(updates)
                        bad = set(cm.find_bad_commitments(items,
                                                          self.commit_key))
                        return [i not in bad for i in range(len(updates))]
                except Exception:
                    pass  # exact per-update fallback below
                return [self._verify_plain_commitment(u) for u in updates]

            try:
                with self.tele.span("miner_verify", it=st.iteration):
                    verdicts = await asyncio.to_thread(run)
            except BaseException as e:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            RPCError(f"intake verification failed: "
                                     f"{type(e).__name__}"))
                raise
            self._trace("plain_batch_verified", n=len(updates),
                        bad=sum(1 for v in verdicts if not v))
            for (_, fut), ok in zip(batch, verdicts):
                if not fut.done():
                    fut.set_result(ok)

    async def _h_register_decline(self, meta, arrays):
        """A sampled worker whose update the verifier committee refused
        notifies the miners it will not contribute this round. The notice
        only shrinks the expected-contributor count (it injects nothing),
        and it must carry the worker's own Schnorr signature — otherwise
        an attacker could decline OTHER peers into early, thin blocks."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        st = await self._wait_round_ready(it)
        if not self.role_map.is_miner(self.id):
            raise RPCError("not a miner this round")
        sid = int(meta["source_id"])
        if not self.role_map.is_vanilla(sid):
            # only this round's WORKERS are expected contributors; a
            # committee member's self-decline would inflate the accounted
            # count and mint early, excluding in-flight honest updates
            raise RPCError("decline from a non-contributor")
        sig = bytes.fromhex(meta.get("sig", ""))
        pub = self.node_pubs.get(sid)
        if pub is None or not await asyncio.to_thread(
                cm.schnorr_verify, pub, _decline_message(it, sid), sig):
            raise RPCError("bad decline signature")
        st.miner_declined.add(sid)
        return {}, {}

    async def _h_register_secret(self, meta, arrays):
        """Miner intake, secure-agg mode: one share-row slice per
        contributor (ref: main.go:256-286, 330-367). Intake itself checks
        the cheap invariants — tensor shapes, commitment digest, verifier
        signature quorum; the share-vs-commitment VSS check is deferred to
        _verify_intake, which settles the WHOLE round's intake in one
        batched RLC+MSM before any share is served or aggregated (ref:
        kyber.go:650-673 verifySecret ran a pairing per share at intake).
        Nothing unverified can reach aggregation — it can only sit parked
        in this round's state until the batch check runs.

        Pipelined (cfg.pipeline): a next-round submission runs its
        committee-independent checks (shapes, VSS digest) before parking
        for the round; with cfg.batch_intake the registered slice is
        additionally folded into the round's VSS accumulator in the
        background, so the grid summation the mint-time batch check
        needs amortizes across the intake window (_kick_intake_fold)."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        basic: Optional[Tuple[bool, str]] = None
        commitment = bytes.fromhex(meta.get("commitment", ""))
        if self._pipelined_iteration(it, meta.get("source_id")):
            with self.tele.span("intake_validate", it=it):
                basic = await asyncio.to_thread(
                    self._check_secret_basic, commitment, arrays)
            self._trace("intake_preverified", source=meta.get("source_id"),
                        ok=basic[0])
        st = await self._wait_round_ready(it)
        if not self.role_map.is_miner(self.id):
            raise RPCError("not a miner this round")
        sid = int(meta["source_id"])
        if sid in st.miner_shares or sid in st.miner_rejected \
                or sid in st.miner_group_of:
            return {}, {}
        rows = np.asarray(arrays.get("share_rows", np.zeros(0)), dtype=np.int64)
        expect = (self.cfg.shares_per_miner,
                  ss.num_chunks(self.trainer.num_params, self.cfg.poly_size))
        if rows.shape != expect:
            raise RPCError(f"bad share shape {rows.shape} != {expect}")
        if basic is None:
            with self.tele.span("intake_validate", it=it):
                basic = await asyncio.to_thread(
                    self._check_secret_basic, commitment, arrays)
        ok, why = basic
        if ok:
            with self.tele.span("sig_check", it=it):
                ok, why = await asyncio.to_thread(
                    self._check_secret_quorum, commitment, meta)
        if not ok:
            self._reject_source(st, sid, it, commitment, why)
            raise RPCError(f"secret rejected: {why}")
        st.miner_shares.setdefault(sid, rows)
        st.miner_commitments[sid] = commitment
        st.miner_vss[sid] = (np.asarray(arrays["comms"], np.uint8),
                             np.asarray(arrays["blind_rows"], np.uint8))
        try:
            st.miner_sigs[sid] = (
                [int(x) for x in meta.get("signers", [])],
                [bytes.fromhex(s) for s in meta.get("signatures", [])],
            )
        except (ValueError, TypeError):
            pass  # quorum already checked above; records stay sig-less
        self._trace("secret_registered", source=sid,
                    have=len(st.miner_shares))
        if self.cfg.pipeline and self.cfg.batch_intake:
            # fold the freshly registered slice (and any other pending
            # ones) into the round's VSS accumulator while the round's
            # network wait is still running — the summation lump the
            # mint-time settle would otherwise pay
            self._kick_intake_fold(st)
        return {}, {}

    def _kick_intake_fold(self, st: RoundState) -> None:
        """Debounced background incremental _verify_intake pass: at most
        one in flight (the vss_lock serializes the work; the guard keeps
        a burst of arrivals from stacking N no-op tasks)."""
        if st.vss_lock.locked():
            return  # a fold/settle pass is already running; it will sweep

        async def go():
            try:
                await self._verify_intake(st, finalize=False)
            except Exception:
                pass  # next finalize pass repeats the sweep

        t = asyncio.get_running_loop().create_task(go())
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    def _check_secret_basic(self, commitment: bytes,
                            arrays) -> Tuple[bool, str]:
        """Committee-INDEPENDENT intake checks for one RegisterSecret
        payload (runs off the event loop): tensor shapes and the VSS
        digest binding. Safe to run for a near-future round before its
        committee exists — the pipelined half of the old
        _check_secret_intake."""
        cfg = self.cfg
        comms = arrays.get("comms")
        blind_rows = arrays.get("blind_rows")
        if comms is None or blind_rows is None:
            return False, "missing VSS tensors"
        comms = np.asarray(comms, np.uint8)
        # the polynomial degree is bound by the protocol, not the sender: a
        # higher-degree commitment would pass pointwise VSS checks while
        # making poly_size-column least-squares recovery return garbage
        c_expect = ss.num_chunks(self.trainer.num_params, cfg.poly_size)
        if comms.shape != (c_expect, cfg.poly_size, 64):
            return False, f"bad commitment tensor shape {comms.shape}"
        if np.asarray(blind_rows).shape != (cfg.shares_per_miner, c_expect, 32):
            return False, "bad blind tensor shape"
        if cm.vss_digest(comms) != commitment:
            return False, "commitment digest mismatch"
        return True, ""

    def _check_secret_quorum(self, commitment: bytes,
                             meta) -> Tuple[bool, str]:
        """Committee-DEPENDENT half of the intake check: the verifier
        signature quorum over the commitment digest (needs this round's
        elected committee, so it always runs after _wait_round_ready)."""
        if not self.cfg.verification:
            return True, ""
        try:
            signers = [int(x) for x in meta.get("signers", [])]
            sigs = [bytes.fromhex(s) for s in meta.get("signatures", [])]
        except (ValueError, TypeError):
            return False, "malformed signature metadata"
        if not self._verify_sig_quorum(commitment, int(meta["iteration"]),
                                       int(meta["source_id"]),
                                       signers, sigs):
            return False, "verifier signature quorum failed"
        return True, ""

    def _check_secret_intake(self, commitment: bytes, meta,
                             arrays) -> Tuple[bool, str]:
        """Cheap intake checks for one RegisterSecret payload — the
        composed (basic + quorum) form, kept for callers and tests that
        exercise the whole gate in one hop; the share-vs-commitment VSS
        check itself is deferred to the round's batched verification
        (_verify_intake)."""
        ok, why = self._check_secret_basic(commitment, arrays)
        if not ok:
            return ok, why
        return self._check_secret_quorum(commitment, meta)

    def _committee_for(self, stake_map: Dict[int, int],
                       prev_hash: bytes) -> List[int]:
        """The verifier committee a given (stake, hash) state elects —
        deterministic, so any peer can recompute ANY round's committee from
        chain data alone (including a candidate chain's own rounds)."""
        cfg = self.cfg
        try:
            verifiers, _ = R.elect_committees(
                stake_map, prev_hash, cfg.num_verifiers, cfg.num_miners,
                cfg.num_nodes)
        except ValueError:
            verifiers, _ = R.elect_committees(
                {i: 1 for i in range(cfg.num_nodes)}, prev_hash,
                cfg.num_verifiers, cfg.num_miners, cfg.num_nodes)
        return verifiers

    def _block_quorums_ok(self, blk: Block, stake_map: Dict[int, int],
                          prev_hash: bytes) -> bool:
        """Authenticate a block's accepted updates: each must carry a
        Schnorr quorum (≥ half) from the verifier committee that the
        parent state elects. One batched RLC check covers the whole block
        (commitments.batch_schnorr_verify). This is what makes chain
        WEIGHT unforgeable: minting a non-empty block requires genuine
        signatures from elected verifiers, not just sealing bytes (the
        reference's corresponding check existed but was disabled,
        main.go:269-277)."""
        cfg = self.cfg
        if not cfg.verification or cfg.fedsys:
            return True  # these modes carry no signatures (ref parity)
        accepted = [u for u in blk.data.deltas if u.accepted]
        if not accepted:
            return True
        # a block hash covers its quorum payload (sealed over updates incl
        # signatures), so a hash this peer already authenticated needs no
        # re-verification — duplicate gossip receipts and every catch-up
        # chain pull otherwise re-pay the whole batched check (measured
        # ~2.3 verifications per peer per block at N=100)
        if (blk.hash in self._quorum_ok_hashes
                and blk.hash == blk.compute_hash()):
            # memo entries are keyed on computed hashes, and the recompute
            # (one SHA-256, vs the Schnorr batch the memo saves) binds this
            # block's CONTENT to the claimed hash locally — the hit no
            # longer relies on consider_block/chain.verify enforcing the
            # binding downstream; refresh its LRU position
            self._quorum_ok_hashes.pop(blk.hash)
            self._quorum_ok_hashes[blk.hash] = None
            return True
        vset = set(self._committee_for(stake_map, prev_hash))
        need = max(1, (len(vset) + 1) // 2)
        items: List[Tuple[bytes, bytes, bytes]] = []
        for u in accepted:
            seen: Set[int] = set()
            per_update = []
            for vid, sig in zip(u.signers, u.signatures):
                if vid not in vset or vid in seen:
                    continue
                pub = self.node_pubs.get(vid)
                if not pub:
                    continue
                seen.add(vid)
                per_update.append(
                    (pub, self._sig_message(u.commitment, blk.iteration,
                                            u.source_id), sig))
            if len(per_update) < need:
                return False
            items.extend(per_update)
        if cm.batch_schnorr_verify(items):
            # bind the memo entry to the block CONTENTS: only a block whose
            # claimed hash IS its computed hash may seed the cache.
            # Otherwise a Byzantine peer could send the round's genuine
            # block relabeled with a forged block's hash (quorum verifies,
            # claimed hash enters the memo, consider_block drops it on the
            # hash mismatch) and then pass the self-consistent forged block
            # through the memo without a single signature being checked.
            if blk.hash == blk.compute_hash():
                self._quorum_ok_hashes[blk.hash] = None
                while len(self._quorum_ok_hashes) > 512:
                    # evict the least-recently-confirmed entry, never the
                    # one just added (set.pop's arbitrary choice could)
                    self._quorum_ok_hashes.pop(
                        next(iter(self._quorum_ok_hashes)))
            return True
        # batch failed: at least one signature is forged — per-item scan
        # would identify it, but for acceptance a single failure damns the
        # block either way
        return False

    def _chain_quorums_ok(self, blocks: List[Block],
                          pruned_before: int = 0) -> bool:
        """Authenticate every non-empty block of a CANDIDATE chain against
        the committees the chain itself elects (parent stake map + parent
        hash). Run before maybe_adopt: without it, chain weight — and
        therefore fork choice — would be forgeable by anyone. A PRUNED
        chain (pruned_before > 0, e.g. a snapshot-bootstrapped peer's own
        checkpoint on restore) starts the check ABOVE the trust-anchor
        base: blocks[1] sits across the gap, so its quorums cannot be
        verified against genesis — same trust model as _adopt_snapshot,
        which sealed that base when the chain was first adopted."""
        start = 2 if pruned_before else 1
        for i in range(start, len(blocks)):
            if not self._block_quorums_ok(blocks[i], blocks[i - 1].stake_map,
                                          blocks[i - 1].hash):
                self._trace("candidate_chain_rejected",
                            height=blocks[i].iteration)
                return False
        return True

    def _my_share_xs(self) -> List[int]:
        _, miners, _, _ = self.role_map.committee()
        idx = sorted(miners).index(self.id)
        sl = ss.miner_rows(self.cfg.total_shares, idx, len(miners))
        return self._xs_all[sl]

    def _sec_sources(self, st: RoundState) -> Set[int]:
        """Every sid whose shares this miner holds — directly registered
        plus members of accepted overlay subtree aggregates."""
        return set(st.miner_shares) | set(st.miner_group_of)

    def _sec_decompose(self, st: RoundState, nodes: Sequence[int]):
        """Decompose an aggregation set into its intake COMPONENTS:
        whole overlay subtree aggregates plus direct sids. Returns
        (rows_list, rec_list) where rows_list holds each component's
        share-row slice and rec_list its (comms, blinds) VSS record
        (None for keyless direct intake) — summation over components
        equals the seed's per-sid summation by associativity, so
        aggregates, reshare deals, and recovered updates are
        bit-identical to the flat path. Returns None when `nodes`
        splits a subtree (the group sum cannot be subset) or names a
        sid this miner does not hold."""
        remaining = set(int(n) for n in nodes)
        rows: List[np.ndarray] = []
        recs: List = []
        for g, rec in st.miner_groups.items():
            inter = g & remaining
            if not inter:
                continue
            if inter != g:
                return None
            rows.append(rec["rows"])
            recs.append((rec["comms"], rec["blinds"]))
            remaining -= g
        for n in sorted(remaining):
            r = st.miner_shares.get(n)
            if r is None:
                return None
            rows.append(r)
            recs.append(st.miner_vss_records.get(n))
        return rows, recs

    async def _verify_intake(self, st: RoundState,
                             finalize: bool = True) -> None:
        """Round-batched VSS verification of every pending share slice: one
        RLC+MSM for the whole intake; per-worker fallback identifies and
        rejects offenders (ref: kyber.go:650-673 checks share-by-share with
        a pairing each — same capability, amortized to one group equation
        per ROUND here). Guarded so concurrent GetUpdateList/GetMinerPart
        callers share one pass; shares that arrive WHILE a batch is being
        checked stay pending and are verified by the next sweep of the
        loop — only the sids actually covered by a batch are retired.

        cfg.batch_intake swaps the one-shot group check for the
        incremental accumulator (cm.VssIntakeBatch): pending slices are
        booked + folded in waves (`finalize=False`, kicked per arrival
        when pipelining), and the mint/serve-time call (`finalize=True`)
        only settles the accumulated set — the RLC scalar chain and one
        MSM, the sole crypto left on the critical path. Group semantics,
        retirement bookkeeping, and rejection evidence are identical to
        the one-shot path."""
        if not st.miner_vss and not (finalize and st.vss_accum is not None):
            return
        async with st.vss_lock:
            if not self.cfg.batch_intake:
                if not finalize:
                    return  # seed behavior: one lump at mint/serve time
                await self._verify_intake_oneshot(st)
                return
            while st.miner_vss:
                if st.my_xs is None:
                    st.miner_vss.clear()
                    return
                pending = {
                    sid: (comms, blinds)
                    for sid, (comms, blinds) in st.miner_vss.items()
                    if sid in st.miner_shares
                }
                if not pending:
                    st.miner_vss.clear()
                    return
                if st.vss_accum is None:
                    cfg = self.cfg
                    st.vss_accum = cm.VssIntakeBatch(
                        cfg.shares_per_miner,
                        ss.num_chunks(self.trainer.num_params, cfg.poly_size),
                        cfg.poly_size)
                acc = st.vss_accum
                t0_fold = time.monotonic()
                with self.tele.span("intake_fold", it=st.iteration):
                    for sid, (comms, blinds) in pending.items():
                        booked = await asyncio.to_thread(
                            acc.add, sid, comms, st.miner_shares[sid], blinds)
                        if not booked:
                            self._vss_reject(st, sid,
                                             "share rows fail VSS "
                                             "verification")
                    for sid in await asyncio.to_thread(acc.fold):
                        self._vss_reject(st, sid,
                                         "share rows fail VSS verification")
                await self._slow_pad(time.monotonic() - t0_fold)
                for sid in pending:
                    st.miner_vss.pop(sid, None)
            if not finalize:
                return
            acc = st.vss_accum
            if acc is None or not len(acc):
                return
            xs = st.my_xs
            if xs is None:
                st.vss_accum = None
                return
            t0_mv = time.monotonic()
            with self.tele.span("miner_verify", it=st.iteration):
                ok = await asyncio.to_thread(acc.verify, xs)
            await self._slow_pad(time.monotonic() - t0_mv)
            members = acc.members()
            self._trace("vss_batch_settled", n=len(members), ok=ok)
            if ok:
                # the whole accumulated set is consistent AS A GROUP —
                # same retirement bookkeeping as the one-shot batch
                batch = frozenset(members)
                for sid, (comms, _rows, blinds) in members.items():
                    st.miner_vss_records[sid] = (comms, blinds)
                    st.miner_vss_batch[sid] = batch
            else:
                for sid, (comms, rows, blinds) in members.items():
                    if await asyncio.to_thread(cm.vss_verify_multi,
                                               [(comms, xs, rows, blinds)]):
                        st.miner_vss_records[sid] = (comms, blinds)
                        st.miner_vss_batch[sid] = frozenset((sid,))
                        continue
                    self._vss_reject(st, sid,
                                     "share rows fail VSS verification")
            # retired: later arrivals start a fresh accumulator (and a
            # fresh batch, exactly like a second one-shot sweep would)
            st.vss_accum = None

    def _vss_reject(self, st: RoundState, sid: int, why: str) -> None:
        st.miner_shares.pop(sid, None)
        commitment = st.miner_commitments.pop(sid, b"")
        self._reject_source(st, sid, st.iteration, commitment, why)

    async def _verify_intake_oneshot(self, st: RoundState) -> None:
        """The pre-accumulator verification body (cfg.batch_intake off):
        one vss_verify_multi lump per sweep — kept verbatim as the seed
        round schedule the disabled configuration must reproduce."""
        while st.miner_vss:
            xs = st.my_xs
            if xs is None:
                st.miner_vss.clear()
                return
            pending = {
                sid: (comms, xs, st.miner_shares[sid], blinds)
                for sid, (comms, blinds) in st.miner_vss.items()
                if sid in st.miner_shares
            }
            if not pending:
                st.miner_vss.clear()
                return
            t0_mv = time.monotonic()
            with self.tele.span("miner_verify", it=st.iteration):
                ok = await asyncio.to_thread(
                    cm.vss_verify_multi, list(pending.values()))
            await self._slow_pad(time.monotonic() - t0_mv)
            self._trace("vss_batch_settled", n=len(pending), ok=ok)
            if ok:
                # the whole batch is consistent AS A GROUP: remember who
                # was verified together, so partial-batch aggregates are
                # re-checked at the aggregation boundary
                batch = frozenset(pending)
                for sid, inst in pending.items():
                    st.miner_vss_records[sid] = (inst[0], inst[3])
                    st.miner_vss_batch[sid] = batch
            else:
                for sid, inst in pending.items():
                    if await asyncio.to_thread(cm.vss_verify_multi,
                                               [inst]):
                        # single-instance checks are exact — the sid is
                        # individually consistent, a singleton batch
                        st.miner_vss_records[sid] = (inst[0], inst[3])
                        st.miner_vss_batch[sid] = frozenset((sid,))
                        continue
                    st.miner_shares.pop(sid, None)
                    commitment = st.miner_commitments.pop(sid, b"")
                    self._reject_source(st, sid, st.iteration, commitment,
                                        "share rows fail VSS verification")
            for sid in pending:
                st.miner_vss.pop(sid, None)

    async def _ensure_subset_consistent(self, st: RoundState,
                                        nodes: List[int]) -> bool:
        """Aggregation-boundary VSS re-check: True iff the aggregate over
        `nodes` provably equals the sum of their committed values. Whole
        verified batches pass for free; members of partially-included
        batches are re-proved as a group of their own (a coalition whose
        errors cancelled inside the intake batch cannot cancel here,
        because the check now runs over EXACTLY the aggregation set).
        Offenders surfaced by a failed re-check are rejected and debited
        like any intake failure."""
        if st.my_xs is None or not self.cfg.secure_agg:
            return True
        # overlay subtree aggregates are servable only WHOLE — the group
        # sum cannot be subset. A set that splits one drops the whole
        # subtree from the servable intake (a state gap like the
        # missing-records path below, never verification evidence: no
        # debit) so callers that shrink the set and retry always make
        # progress. Fully-covered groups pass through: their batch
        # (== their membership) is inside `nodes`, the exact condition
        # the aggregated intake check is sound for.
        nset = set(nodes)
        for g in list(st.miner_groups):
            inter = g & nset
            if inter and inter != g:
                st.miner_groups.pop(g, None)
                for sid in g:
                    st.miner_group_of.pop(sid, None)
                    st.miner_vss_batch.pop(sid, None)
                self._trace("overlay_group_dropped", n=len(g))
                return False
        pending = partial_batch_members(st.miner_vss_batch, nodes)
        if not pending:
            return True
        xs = st.my_xs
        insts: Dict[int, tuple] = {}
        for sid in pending:
            rec = st.miner_vss_records.get(sid)
            rows = st.miner_shares.get(sid)
            if rec is None or rows is None:
                # cannot re-prove without the retained records: drop the
                # sid from the servable set (no debit — this is a state
                # gap, not verification evidence) so callers that shrink
                # the set and retry always make progress
                st.miner_shares.pop(sid, None)
                st.miner_vss_batch.pop(sid, None)
                return False
            insts[sid] = (rec[0], xs, rows, rec[1])
        t0_mv = time.monotonic()
        with self.tele.span("miner_verify", it=st.iteration):
            ok = await asyncio.to_thread(cm.vss_verify_multi,
                                         list(insts.values()))
        await self._slow_pad(time.monotonic() - t0_mv)
        if ok:
            return True
        for sid, inst in insts.items():
            if await asyncio.to_thread(cm.vss_verify_multi, [inst]):
                continue
            st.miner_shares.pop(sid, None)
            st.miner_vss_records.pop(sid, None)
            st.miner_vss_batch.pop(sid, None)
            commitment = st.miner_commitments.pop(sid, b"")
            self._reject_source(st, sid, st.iteration, commitment,
                                "share rows fail aggregation-boundary "
                                "VSS re-check")
        return False

    async def _h_request_noise(self, meta, arrays):
        """Noiser serving its presampled DP noise for the round
        (ref: main.go:239-248 → honest.go:564-592) — but only after
        verifying the requester's lottery proof: the VRF output must verify
        under the requester's noise key over OUR latest block hash, and the
        draw it determines must actually include us. A peer who fabricates
        its noiser set (e.g. to collect noise vectors it can cancel) is
        refused (enforces the proof from ref vrf.go:54-99)."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        await self._wait_for_iteration(it)
        if it < self.iteration:
            raise StaleError()
        sid = int(meta.get("source_id", -1))
        try:
            draw = R.NoiserDraw(
                noisers=[int(x) for x in meta.get("noisers", [])],
                output=bytes.fromhex(meta.get("vrf_output", "")),
                proof=bytes.fromhex(meta.get("vrf_proof", "")),
            )
        except ValueError:
            raise RPCError("malformed noiser draw")
        pub = self.noise_pubs.get(sid)
        ok = (
            pub is not None
            and self.id in draw.noisers
            and sid != self.id
            and await asyncio.to_thread(
                R.verify_noiser_draw, pub, self.chain.latest_stake_map(),
                self.chain.latest_hash(), sid, draw, self.cfg.num_nodes)
        )
        if not ok:
            self._trace("noise_draw_rejected", source=sid)
            raise RPCError("noiser lottery proof failed verification")
        noise = await self._own_noise(it)
        return {}, {"noise": noise}

    async def _h_verify_update(self, meta, arrays):
        """Verifier: park until the round's defense decision resolves, then
        sign or reject (ref: DistSys/krum.go:227-365)."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        st = await self._wait_round_ready(it)
        if not self.role_map.is_verifier(self.id):
            raise RPCError("not a verifier this round")
        u = wire.unpack_update(meta, arrays)
        vec = u.noised_delta if u.noised_delta is not None else u.delta
        if vec is None or len(vec) != self.trainer.num_params:
            raise RPCError("bad update dimension")
        if u.source_id not in st.verifier_sources:
            st.verifier_sources.add(u.source_id)
            st.verifier_pool.append(u)
            self._trace("verify_request", source=u.source_id,
                        pool=len(st.verifier_pool),
                        thresh=self.cfg.krum_update_thresh)
            if len(st.verifier_pool) >= self.cfg.krum_update_thresh:
                # threshold-triggered decision: its latency from round
                # start is the krum timer's adaptive signal (timeout-
                # path decisions are NOT observed — see _miner_flow)
                if st.iteration == self.round.iteration:
                    self.deadlines.observe(
                        stragglers.KRUM,
                        time.monotonic() - self._round_t0)
                self._decide_round()
        accepted = await asyncio.wait_for(
            asyncio.shield(st.krum_decision), self.timeouts.krum_s * 2)
        if u.source_id in accepted:
            sig = self._sign(self._sig_message(u.commitment, it, u.source_id))
            return {"signature": sig.hex()}, {}
        raise RPCError("rejected by defense")

    def _decide_round(self) -> None:
        """Run the defense over the collected pool and release every parked
        caller (ref: krum.go:296-336). Colluding poisoners on the committee
        rubber-stamp each other (ref: krum.go:47-58)."""
        st = self.round
        if st.krum_decision is None or st.krum_decision.done():
            return
        pool = sorted(st.verifier_pool, key=lambda u: u.source_id)
        if self.cfg.krum_sample_size and len(pool) > self.cfg.krum_sample_size:
            rng = random.Random(st.iteration)  # deterministic, ref krum.go:370
            pool = sorted(rng.sample(pool, self.cfg.krum_sample_size),
                          key=lambda u: u.source_id)
        accepted: Set[int] = set()
        votes_detail: Optional[List[List[str]]] = None
        vecs: Optional[np.ndarray] = None
        if pool:
            import jax.numpy as jnp

            from biscotti_tpu.ops.krum import default_num_adversaries, krum_accept_mask
            from biscotti_tpu.ops.roni import roni_accept_mask

            vecs = np.stack([
                u.noised_delta if u.noised_delta is not None else u.delta
                for u in pool
            ])
            if self.cfg.defense == Defense.KRUM and len(pool) > 2:
                mask = np.asarray(krum_accept_mask(
                    jnp.asarray(vecs, jnp.float32),
                    default_num_adversaries(len(pool))))
            elif self.cfg.defense == Defense.MULTIKRUM and len(pool) > 2:
                from biscotti_tpu.ops.robust_agg import multikrum_accept_mask

                mask = np.asarray(multikrum_accept_mask(
                    jnp.asarray(vecs, jnp.float32),
                    default_num_adversaries(len(pool))))
            elif self.cfg.defense == Defense.FOOLSGOLD and len(pool) > 2:
                from biscotti_tpu.ops.robust_agg import foolsgold_accept_mask

                mask = np.asarray(foolsgold_accept_mask(
                    jnp.asarray(vecs, jnp.float32),
                    self.cfg.fg_min_cluster))
            elif self.cfg.defense == Defense.ENSEMBLE and len(pool) > 2:
                mask, votes_detail = self._ensemble_mask(
                    st.iteration, pool, vecs)
            elif self.cfg.defense == Defense.RONI:
                mask = np.asarray(roni_accept_mask(
                    self.trainer.model,
                    jnp.asarray(self.chain.latest_gradient(), jnp.float32),
                    jnp.asarray(vecs, jnp.float32),
                    self.trainer.x_test, self.trainer.y_test,
                    self.cfg.roni_threshold))
            else:
                mask = np.ones(len(pool), dtype=bool)
            accepted = {u.source_id for u, m in zip(pool, mask) if m}
        from biscotti_tpu.ops.krum import collusion_accept_override

        if collusion_accept_override(self.id, self.cfg.num_nodes,
                                     self.cfg.poison_fraction):
            poisoners = _poisoned_ids(self.cfg.num_nodes,
                                      self.cfg.poison_fraction)
            accepted |= {u.source_id for u in st.verifier_pool
                         if u.source_id in poisoners}
        self._trace("defense_decided", pool=len(pool),
                    accepted=sorted(accepted))
        if pool:
            self._verdict_record(st.iteration, pool, vecs, accepted,
                                 votes_detail)
        st.krum_decision.set_result(accepted)

    def _verdict_record(self, it: int, pool: List[Update],
                        vecs: np.ndarray, accepted: Set[int],
                        votes: Optional[List[List[str]]]) -> None:
        """Append one verdict-stream row: this verifier's per-peer
        accept/reject walk plus the observed delta magnitudes — the
        replayable artifact evidence behind every attack-matrix cell
        (docs/DEFENSES.md §Evidence). Recorded for EVERY defense decision
        so the hugger's scale walk is visible in the cells it wins, not
        only where ENSEMBLE suppresses it. Bounded by
        trust_plan.stream_cap; ENSEMBLE rows also carry per-peer scorer
        votes."""
        if len(self._verdict_stream) >= self.cfg.trust_plan.stream_cap:
            return
        norms = np.linalg.norm(np.asarray(vecs, np.float64), axis=1)
        row: Dict = {
            "it": it,
            "src": [u.source_id for u in pool],
            "norm": [round(float(x), 5) for x in norms],
            "accept": [int(u.source_id in accepted) for u in pool],
        }
        if votes is not None:
            row["votes"] = votes
        self._verdict_stream.append(row)

    def _trust_sync_chain(self) -> None:
        """Fold newly-settled real blocks into the TrustLedger's chain
        walk. Each block's electorate is re-derived from its predecessor
        (the same common coin every peer runs), so eligibility — and
        therefore the absence-means-rejected inference, the same one the
        hug campaign itself runs on — is a pure function of the committed
        chain. A pruned/unknown predecessor yields an unknown electorate
        and that block contributes no absence signal."""
        for blk in self.chain.blocks:
            if blk.iteration < 0 or blk.iteration <= self.trust.synced_it:
                continue
            records = {u.source_id: bool(u.accepted)
                       for u in blk.data.deltas}
            committee: Optional[Set[int]] = None
            prev = self.chain.get_block(blk.iteration - 1)
            if prev is not None:
                try:
                    vs, ms = R.elect_committees(
                        dict(prev.stake_map), prev.hash,
                        self.cfg.num_verifiers, self.cfg.num_miners,
                        self.cfg.num_nodes)
                    committee = set(vs) | set(ms)
                except ValueError:
                    committee = None
            self.trust.sync_block(blk.iteration, records, committee)

    def _ensemble_mask(self, it: int, pool: List[Update],
                       vecs: np.ndarray,
                       ) -> Tuple[np.ndarray, List[List[str]]]:
        """ENSEMBLE defense decision (ops/trust.py, docs/DEFENSES.md):
        sync the ledger against the committed chain, compute the
        geometry/similarity inputs (Krum scores + keep mask on device,
        cosine matrix and kept-centroid residuals in float64 host math so
        the ledger's decision is layout-deterministic), then let the
        TrustLedger compose the vetoes into one accept mask."""
        import jax.numpy as jnp

        from biscotti_tpu.ops.krum import (default_num_adversaries,
                                           krum_accept_mask, krum_scores)

        self._trust_sync_chain()
        x32 = jnp.asarray(vecs, jnp.float32)
        f = default_num_adversaries(len(pool))
        scores = [float(s) for s in np.asarray(krum_scores(x32, f))]
        keep = [bool(b) for b in np.asarray(krum_accept_mask(x32, f))]
        v64 = np.asarray(vecs, np.float64)
        norms = np.linalg.norm(v64, axis=1)
        unit = v64 / np.maximum(norms, 1e-12)[:, None]
        cos = unit @ unit.T
        np.fill_diagonal(cos, -1.0)
        kept_rows = v64[np.asarray(keep)] if any(keep) else v64
        centroid = kept_rows.mean(axis=0)
        residuals = np.linalg.norm(v64 - centroid[None, :], axis=1)
        ids = [u.source_id for u in pool]
        accepts, votes, detail = self.trust.decide(
            it, ids, [float(n) for n in norms],
            [float(r) for r in residuals], scores, keep, cos.tolist())
        if self.tele.enabled:
            ctr = self.tele.registry.counter(trustlib.VOTES_METRIC,
                                             trustlib.VOTES_HELP)
            for vlist, ok in zip(votes, accepts):
                for scorer in vlist:
                    ctr.inc(scorer=scorer, vote="reject")
                ctr.inc(scorer="ensemble",
                        vote="accept" if ok else "reject")
        self._trace("trust_decided", pool=len(pool),
                    rejected=sorted(pid for pid, ok in zip(ids, accepts)
                                    if not ok),
                    sim_bar=round(detail["sim_bar"], 4),
                    ref_geo=round(detail["ref_geo"], 6))
        return np.asarray(accepts, dtype=bool), votes

    @staticmethod
    def _part_message(kind: str, iteration: int, nodes: Sequence[int]) -> bytes:
        """Domain-separated leader-request message for share-release RPCs."""
        payload = f"biscotti-{kind}:{iteration}:" \
                  f"{','.join(str(n) for n in nodes)}"
        return hashlib.sha256(payload.encode()).digest()

    def _check_leader_request(self, kind: str, it: int,
                              nodes: Sequence[int], meta) -> None:
        """Share-release RPCs must come from the round's leader miner,
        proven by a Schnorr signature — without this ANY caller could pull
        aggregated share rows and difference subsets to unmask individual
        updates (the reference shares this weakness; ADVICE round-1 low)."""
        if not self.cfg.verification or self.cfg.fedsys:
            return  # signature-less modes (ref parity)
        _, miners, _, _ = self.role_map.committee()
        leader = self._miner_leader(sorted(miners))
        src = int(meta.get("source_id", -1))
        if src != leader:
            raise RPCError("share release restricted to the leader miner")
        try:
            sig = bytes.fromhex(meta.get("sig", ""))
        except ValueError:
            raise RPCError("malformed leader signature")
        pub = self.node_pubs.get(leader)
        if not pub or not cm.schnorr_verify(
                pub, self._part_message(kind, it, nodes), sig):
            raise RPCError("leader signature failed verification")

    async def _h_get_update_list(self, meta, arrays):
        """Leader-miner asks which sources this miner holds shares for
        (ref: main.go:438-457, 2237-2277)."""
        it = int(meta["iteration"])
        st = await self._wait_round_ready(it, budget=self.timeouts.rpc_s / 2)
        self._check_leader_request("update-list", it, [], meta)
        await self._verify_intake(st)
        srcs = sorted(self._sec_sources(st))
        return {"sources": srcs, "rejected": sorted(st.miner_rejected)}, {}

    async def _h_get_miner_part(self, meta, arrays):
        """Leader-miner collects this miner's share slice, aggregated over
        the agreed node list (ref: main.go:459-485, kyber.go:244-287).
        Release conditions: leader-signed request, a minimum aggregation
        set (an aggregate over one node IS that node's update), and at most
        ONE distinct set per round (a second subset could be differenced
        against the first to isolate an individual)."""
        it = int(meta["iteration"])
        st = await self._wait_round_ready(it, budget=self.timeouts.rpc_s / 2)
        nodes = [int(x) for x in meta["nodes"]]
        self._check_leader_request("miner-part", it, nodes, meta)
        await self._verify_intake(st)
        if len(set(nodes)) != len(nodes):
            # [v, v] would pass the size floor yet aggregate to 2·share_v
            raise RPCError("duplicate nodes in aggregation set")
        srcs = self._sec_sources(st)
        if not all(n in srcs for n in nodes):
            raise RPCError("missing shares for requested nodes")
        if len(nodes) < min(2, len(srcs)):
            raise RPCError("aggregation set below privacy floor")
        if st.served_part is not None and st.served_part != sorted(nodes):
            raise RPCError("a different aggregation set was already served")
        # KNOWN RESIDUAL (documented, strictly better than the reference,
        # which serves any subset to any caller any number of times): the
        # once-only guard is per-miner, and the share layout's 2× row
        # redundancy (TOTAL_SHARES = 2·POLY_SIZE) means any ⌈M/2⌉ miners'
        # rows suffice for recovery — a malicious leader could serve set S
        # to one disjoint miner half and S∖{v} to the other and difference
        # the two aggregates. Structural fixes (future work): redundancy
        # < 2× forces any two recovering miner subsets to overlap in a
        # miner whose once-only guard then fires; or an explicit signed
        # set-agreement round among miners.
        if not await self._ensure_subset_consistent(st, nodes):
            raise RPCError("aggregation set fails VSS re-check")
        decomp = self._sec_decompose(st, nodes)
        if decomp is None:
            raise RPCError("aggregation set splits an overlay subtree")
        st.served_part = sorted(nodes)
        stack = np.stack(decomp[0])
        agg = np.asarray(ss.aggregate_shares(stack))
        return {"nodes": nodes}, {"agg_rows": agg}

    # ---------------------------------------------- membership: resharing

    def _reshare_context(self, it: int) -> bytes:
        """Domain-separated deal context: binds every sub-deal to (this
        chain head, this round) so deals — like intake commitments —
        can never be replayed across rounds or forks."""
        return (self.chain.latest_hash()
                + int(it).to_bytes(8, "little") + b"|reshare")

    def _build_reshare_deal(self, st: RoundState, nodes: List[int],
                            xs_new: List[int], it: int) -> Dict[str, np.ndarray]:
        """Holder half of the distributed resharing round
        (docs/MEMBERSHIP.md §resharing): sub-share every row of OUR
        aggregated slice over `xs_new` as a fresh Shamir instance whose
        constant term is the row value, commit each sub-polynomial with
        the constant blinding coefficient pinned to our aggregated blind
        (crypto/commitments.reshare_commit_row) — that pin is what lets
        any recipient verify the deal homomorphically against the
        ORIGINAL workers' commitments, no dealer anywhere. Runs off the
        event loop (O(R·C·k) fixed-base commits)."""
        rows_c, recs_c = self._sec_decompose(st, nodes)
        agg_rows = np.asarray(ss.aggregate_shares(np.stack(rows_c)))  # [R, C]
        agg_blinds = cm.sum_blind_rows(
            [rec[1] for rec in recs_c])                    # [R][C] ints
        ctx = self._reshare_context(it)
        coeffs = ss.reshare_coeffs(agg_rows, self.cfg.poly_size,
                                   self.schnorr_seed, ctx)
        sub = ss.reshare_subshares(coeffs, xs_new)          # [S', R, C]
        r_rows = agg_rows.shape[0]
        sub_comms = np.zeros((r_rows,) + (coeffs.shape[1],
                                          self.cfg.poly_size, 64), np.uint8)
        sub_blinds = np.zeros((r_rows, len(xs_new), coeffs.shape[1], 32),
                              np.uint8)
        for r in range(r_rows):
            # per-row context: reusing one blind XOF stream across rows
            # would let an observer difference two rows' commitments and
            # cancel the H term (the Feldman leak the blinds exist for)
            comms_r, blinds_r = cm.reshare_commit_row(
                coeffs[r], agg_blinds[r], self.schnorr_seed,
                ctx + r.to_bytes(4, "little"))
            sub_comms[r] = comms_r
            sub_blinds[r] = cm.vss_blind_rows(blinds_r, xs_new)
        return {"sub_rows": sub, "sub_comms": sub_comms,
                "sub_blinds": sub_blinds}

    async def _h_get_reshare_deal(self, meta, arrays):
        """Surviving share-holder serves its re-deal to the resharing
        coordinator (the round leader) after a membership epoch bump.
        Release conditions mirror GetMinerPart exactly — leader-signed
        request (the signature covers the node set AND the new point
        layout), privacy floor, at most ONE aggregation set per round
        (shared `served_part` guard: a leader cannot pull a reshare deal
        for one subset and a share slice for another and difference
        them), aggregation-boundary VSS re-check."""
        it = int(meta["iteration"])
        st = await self._wait_round_ready(it, budget=self.timeouts.rpc_s / 2)
        nodes = [int(x) for x in meta["nodes"]]
        xs_new = [int(x) for x in meta["xs_new"]]
        # the length prefix pins the nodes/xs_new boundary inside the
        # signed flat list — without it, sign(n + xs) for one split is
        # byte-identical to a shifted split of the same ints
        self._check_leader_request("reshare", it,
                                   [len(nodes)] + nodes + xs_new, meta)
        await self._verify_intake(st)
        if len(set(nodes)) != len(nodes):
            raise RPCError("duplicate nodes in aggregation set")
        if len(set(xs_new)) != len(xs_new) or \
                len(xs_new) < self.cfg.poly_size:
            raise RPCError("reshare point layout degenerate")
        if any(abs(x) > 4 * self.cfg.total_shares for x in xs_new):
            # hostile far-out points would blow the exact-int64 bound of
            # the sub-share evaluation (ops/secretshare.RESHARE_COEF_BOUND)
            raise RPCError("reshare points outside the exactness bound")
        srcs = self._sec_sources(st)
        if not all(n in srcs for n in nodes):
            raise RPCError("missing shares for requested nodes")
        if len(nodes) < min(2, len(srcs)):
            raise RPCError("aggregation set below privacy floor")
        if st.served_part is not None and st.served_part != sorted(nodes):
            raise RPCError("a different aggregation set was already served")
        if not await self._ensure_subset_consistent(st, nodes):
            raise RPCError("aggregation set fails VSS re-check")
        decomp = self._sec_decompose(st, nodes)
        if decomp is None or any(rec is None for rec in decomp[1]):
            # plain hash-commitment mode (keyless) carries no VSS records
            # to re-deal against — resharing is a secure-agg capability —
            # and an overlay-split set has no per-component records either
            raise RPCError("no VSS records to reshare")
        st.served_part = sorted(nodes)
        with self.tele.span("reshare_deal", it=it):
            deal = await asyncio.to_thread(self._build_reshare_deal, st,
                                           nodes, xs_new, it)
        self._trace("reshare_deal_served", rows=int(deal["sub_rows"].shape[1]))
        return {"nodes": nodes}, deal

    def _verify_reshare_deal(self, grid_sum: np.ndarray, xs_old: List[int],
                             xs_new: List[int],
                             deal: Dict) -> Optional[np.ndarray]:
        """Coordinator-side check of one holder's re-deal: every row's
        sub-commitments must equal the homomorphic evaluation of the
        summed ORIGINAL commitments at the holder's old point, and every
        sub-share must verify against its sub-commitments
        (crypto/commitments.reshare_verify_deal). Returns the holder's
        reconstructed row values [R, C] (the exact material the seed
        protocol would have pulled via GetMinerPart) or None."""
        sub_rows = np.asarray(deal["sub_rows"], np.int64)
        sub_comms = np.asarray(deal["sub_comms"], np.uint8)
        sub_blinds = np.asarray(deal["sub_blinds"], np.uint8)
        r_rows = len(xs_old)
        k = self.cfg.poly_size
        c_chunks = grid_sum.shape[0]
        if (sub_rows.shape != (len(xs_new), r_rows, c_chunks)
                or sub_comms.shape != (r_rows, c_chunks, k, 64)
                or sub_blinds.shape != (r_rows, len(xs_new), c_chunks, 32)):
            return None
        for r in range(r_rows):
            if not cm.reshare_verify_deal(grid_sum, xs_old[r], sub_comms[r],
                                          xs_new, sub_rows[:, r, :],
                                          sub_blinds[r]):
                return None
        try:
            return ss.reshare_recover_rows(sub_rows, xs_new, k)
        except ValueError:
            return None

    async def _reshare_recover(self, st: RoundState, miners: List[int],
                               reachable: List[int], nodes: List[int],
                               it: int) -> Optional[np.ndarray]:
        """The distributed resharing round (docs/MEMBERSHIP.md): a miner
        died after share intake, so the committee's share layout no
        longer covers recovery by the seed protocol. The leader — acting
        as the new epoch's coordinator — collects a verifiable RE-DEAL
        of every surviving holder's aggregated slice (GetReshareDeal),
        checks each against the homomorphically-evaluated original
        commitments, reconstructs the surviving rows from the re-dealt
        material alone, and completes recovery when ≥ poly_size rows
        survive (r=2 redundancy tolerates half the committee, r=1.5 a
        third). Returns the recovered aggregate, or None → empty block,
        exactly the seed outcome."""
        cfg = self.cfg
        per = cfg.shares_per_miner
        if len(reachable) * per < cfg.poly_size:
            self._trace("reshare_short", survivors=len(reachable))
            return None
        decomp = self._sec_decompose(st, nodes)
        if decomp is None or any(rec is None for rec in decomp[1]):
            self._trace("reshare_short", reason="missing vss records")
            return None
        grids = [rec[0] for rec in decomp[1]]
        self._bump_epoch("reshare_round")
        xs_new = list(self._xs_all)
        with self.tele.span("reshare_verify", it=it):
            grid_sum = await asyncio.to_thread(cm.sum_commitment_grids,
                                               grids)
        if grid_sum is None:
            return None
        # our own slice needs no re-deal: the coordinator holds it
        rows_parts: List[np.ndarray] = []
        xs_parts: List[int] = []
        own_idx = miners.index(self.id)
        rows_parts.append(np.asarray(ss.aggregate_shares(
            np.stack(decomp[0]))))
        xs_parts.extend(self._xs_all[ss.miner_rows(cfg.total_shares,
                                                   own_idx, len(miners))])
        sig = self._sign(self._part_message(
            "reshare", it, [len(nodes)] + nodes + xs_new)).hex()
        for m in reachable:
            if m == self.id:
                continue
            idx = miners.index(m)
            xs_m = self._xs_all[ss.miner_rows(cfg.total_shares, idx,
                                              len(miners))]
            try:
                _, deal = await self._call(m, "GetReshareDeal", {
                    "iteration": it, "nodes": nodes, "xs_new": xs_new,
                    "source_id": self.id, "sig": sig,
                })
            except Exception:
                self._trace("reshare_deal_failed", peer=m)
                continue
            with self.tele.span("reshare_verify", it=it):
                y_rows = await asyncio.to_thread(
                    self._verify_reshare_deal, grid_sum, list(xs_m),
                    xs_new, deal)
            if y_rows is None:
                self._trace("reshare_deal_rejected", peer=m)
                continue
            rows_parts.append(y_rows)
            xs_parts.extend(xs_m)
        if len(xs_parts) < cfg.poly_size:
            self._trace("reshare_short", rows=len(xs_parts))
            return None
        full = np.concatenate(rows_parts)
        with self.tele.span("recovery", it=it):
            agg = np.asarray(ss.recover_update(
                full, np.asarray(xs_parts, np.int64),
                self.trainer.num_params, cfg.poly_size, cfg.precision))
        self._trace("reshare_recovered", rows=len(xs_parts),
                    survivors=len(reachable))
        return agg

    # --------------------------------------------------- speculation plane

    def _maybe_speculate(self) -> None:
        """Kick the speculative next-round worker precompute the moment a
        block lands (cfg.pipeline + cfg.speculation): SGD off the fresh
        head — and, when no state-mutating transform sits between the
        delta and the commitment, the quantize + VSS commit too — runs
        in the background while this peer still evaluates convergence,
        flushes telemetry, and elects the next committees. One slot,
        keyed (iteration, head hash); a stale unconsumed slot is a
        speculative step a fork threw away (speculation_discard)."""
        cfg = self.cfg
        if not (cfg.pipeline and cfg.speculation) or cfg.fedsys:
            return
        if self.stepper is not None:
            # peers-as-devices mode memoizes the batched SGD per
            # ITERATION (device_cluster._memo): a speculative call off a
            # head that later forks would poison the whole co-hosted
            # group's cache for the real round — speculation stays a
            # per-agent-trainer feature
            return
        it = self.iteration
        if it >= cfg.max_iterations or self.converged:
            return
        head = self.chain.latest_hash()
        key = (it, head)
        if self._spec_key == key and (
                self._spec is not None
                or (self._spec_task is not None
                    and not self._spec_task.done())):
            return  # already speculated (or speculating) off this head
        if self._spec is not None:
            # an unconsumed speculative step against a superseded head:
            # the fork/rollback case the counter exists for
            self._spec = None
            self._trace("speculation_discard")
        self._spec_key = key
        if self._spec_task is not None and not self._spec_task.done():
            # one speculative step in flight at a time: a catch-up storm
            # accepting N blocks back-to-back must not fan out N SGD
            # threads. The inflight task's store-guard drops its stale
            # result; the NEXT block accept (or the round itself)
            # proceeds serially — a missed speculation, never a wrong one
            return
        t = asyncio.get_running_loop().create_task(self._speculate(it, head))
        self._spec_task = t
        self._spec_task_key = key
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    async def _speculate(self, it: int, head: bytes) -> None:
        cfg = self.cfg
        try:
            if not self._elect_role_map().is_vanilla(self.id):
                return  # committee duty next round: nothing to precompute
            w = self.chain.latest_gradient()
            with self.tele.span("spec_sgd", it=it):
                delta = await asyncio.to_thread(self.trainer.private_fun,
                                                w, it)
            if self.chain.latest_hash() != head:
                self._trace("speculation_discard")
                return
            spec: Dict = {"it": it, "base": head, "delta": delta}
            if (cfg.secure_agg and not cfg.fedsys and not cfg.dp_in_model
                    and not self.wire.lossy):
                # delta reaches quantization unchanged on this config, so
                # the VSS chunk commitments are speculatable too — the
                # dominant worker-crypto cost. The context is pinned to
                # the speculated head, and _worker_flow re-checks q
                # equality before reuse, so a hit is bit-identical to
                # the serial computation.
                q = self._quantize_np(delta)
                with self.tele.span("spec_commit", it=it):
                    vss = await asyncio.to_thread(self._vss_build, q, it,
                                                  head)
                if self.chain.latest_hash() != head:
                    self._trace("speculation_discard")
                    return
                spec["q"] = q
                spec["vss"] = vss
            if self._spec_key == (it, head):
                self._spec = spec
                self._trace("speculation_ready")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._trace("speculation_error",
                        error=f"{type(e).__name__}: {e}")

    async def _claim_spec(self, it: int) -> Optional[Dict]:
        """Hand the speculative products to the round's worker flow iff
        they were computed off exactly the head this round builds on;
        anything else is discarded with the traced counter. Awaits an
        in-flight matching speculation first — that is the same work the
        serial path would do inline, already mid-flight."""
        t = self._spec_task
        if (t is not None and not t.done()
                and self._spec_task_key == (it, self.chain.latest_hash())):
            # the inflight task is computing for EXACTLY this head:
            # awaiting it is the same work the serial path would do
            # inline. A task retargeted away (fork mid-speculation) is
            # NOT awaited — its result is doomed, the serial path below
            # proceeds immediately
            await asyncio.shield(t)
        spec, self._spec = self._spec, None
        if spec is None:
            return None
        if spec["it"] == it and spec["base"] == self.chain.latest_hash():
            self._trace("speculation_hit")
            return spec
        self._trace("speculation_discard")
        return None

    # --------------------------------------------------------------- worker

    async def _worker_flow(self) -> None:
        cfg = self.cfg
        it = self.iteration
        st = self.round
        spec = None
        if cfg.pipeline and cfg.speculation and not cfg.fedsys:
            spec = await self._claim_spec(it)
        w = self.chain.latest_gradient()
        # heavy device call off the event loop: in-process clusters share one
        # loop, and a blocked loop starves every peer's timers
        t0_sgd = time.monotonic()
        with self.tele.span("sgd", it=it):
            if spec is not None:
                delta = spec["delta"]  # precomputed off this exact head
            elif self.stepper is not None:
                delta = await self.stepper.step(self.id, w, it)
            else:
                delta = await asyncio.to_thread(self.trainer.private_fun,
                                                w, it)
        if spec is None:
            # straggler plane (docs/STRAGGLERS.md): a slow peer's SGD step
            # takes compute_factor x as long. Hive co-hosting memo-hits
            # measure ~0 for their own await, so the pad bases on the
            # shared batch's real cost there — TCP and loopback layouts
            # slow identically.
            base = time.monotonic() - t0_sgd
            if self.stepper is not None:
                base = max(base, getattr(self.stepper, "step_cost_s", 0.0))
            await self._slow_pad(base)
        self.total_updates += 1

        if self.campaign is not None:
            # adaptive-poison seam (docs/ADVERSARY.md): the campaign may
            # reshape OUR OWN delta before quantize/commit/noise/share —
            # everything downstream (Pedersen verification, Shamir
            # recovery, defense scoring) operates on the shaped values,
            # exactly as it would on any delta a hostile trainer emits.
            # Recording the submission round is how the campaign reads
            # its own fate out of the next block.
            delta = self._campaign_shape(it, delta)
            self._campaign_submitted = it

        noise = None
        if cfg.dp_in_model:
            delta = delta + await self._own_noise(it)
        if self.wire.lossy:
            # lossy-before-commit (docs/WIRE_PLANE.md): project the delta
            # onto the codec's representable set NOW — the quantization,
            # Pedersen commitment, DP noising and Shamir shares below all
            # operate on the projected values, which the wire then
            # carries bit-exactly. Top-k keeps an error-feedback residual
            # that folds what this round dropped into the next delta.
            delta, self._ef_residual = self.wire.transform(
                delta, residual=self._ef_residual, topk_k=self._topk_k)
        noised = delta
        if cfg.noising and not cfg.fedsys:
            draw = self._noiser_draw()
            if self.campaign is not None:
                # the one committee an attacker can observe beyond the
                # public election: its OWN private noiser draw — the
                # roleflood campaign adds the drawn noisers to this
                # round's flood targets (docs/ADVERSARY.md)
                self.campaign.observe_noisers(it, draw.noisers)
            nmeta = {
                "iteration": it, "source_id": self.id,
                "noisers": list(draw.noisers),
                "vrf_output": draw.output.hex(),
                "vrf_proof": draw.proof.hex(),
            }
            got: Dict[int, np.ndarray] = {}
            if cfg.adaptive_deadlines:
                # partial-quorum noise collection (docs/STRAGGLERS.md):
                # concurrent fan-out that proceeds with >= 1 vector once
                # the phase's soft deadline passes — one straggling
                # noiser no longer pins the worker for rpc_s x retries.
                # Excluded noisers are counted, never breaker-fed (the
                # cancelled _call records no outcome).
                async def ask_noise(nid):
                    try:
                        _, arrs = await self._call(nid, "RequestNoise",
                                                   nmeta)
                        got[nid] = np.asarray(arrs["noise"], np.float64)
                        return True
                    except Exception:
                        return False

                await self._gather_quorum(
                    stragglers.NOISE,
                    {nid: ask_noise(nid) for nid in draw.noisers},
                    need=1, legacy_s=self.timeouts.rpc_s)
            else:
                for nid in draw.noisers:
                    try:
                        _, arrs = await self._call(nid, "RequestNoise",
                                                   nmeta)
                        got[nid] = np.asarray(arrs["noise"], np.float64)
                    except Exception:
                        continue
            # averaged in draw order (NOT completion order) so the armed
            # fan-out's float reduction is deterministic in the
            # collected set
            used = [n for n in draw.noisers if n in got]
            vectors = [got[n] for n in used]
            if vectors:
                noise = np.mean(vectors, axis=0)
                noised = delta + noise
            # privacy-attack accounting (ref: main.go:1026-1057,1138-1144):
            # colluders are the top `colluders%` of node ids (id ≥
            # collusion_threshold); when a colluding verifier sees our
            # noised delta AND every noiser whose vector actually masks
            # it colludes, the colluders cancel the noise and recover the
            # raw update — counted over the USED set, not the drawn one:
            # a partial-quorum proceed (or a failed honest noiser on the
            # seed path) that leaves only colluders' vectors in the mean
            # is a real breach the drawn-set check would miss
            if cfg.colluders > 0:
                verifiers_now, _, _, _ = self.role_map.committee()
                thresh = cfg.collusion_threshold
                if (any(v >= thresh for v in verifiers_now)
                        and used
                        and all(n >= thresh for n in used)):
                    self._trace("unmasked_update")

        q = self._quantize_np(delta)
        vss = None
        if cfg.secure_agg and not cfg.fedsys:
            # commitment = digest over the per-chunk Pedersen VSS coefficient
            # commitments: the exact object miners verify share rows against,
            # so verifier signatures and share verification bind together
            if (spec is not None and spec.get("vss") is not None
                    and np.array_equal(spec["q"], q)):
                # speculated off this exact head AND the quantized update
                # matches bit-for-bit: the precomputed commitment IS the
                # serial one (same q, same context)
                vss = spec["vss"]
            else:
                t0_c = time.monotonic()
                with self.tele.span("crypto_commit", it=it):
                    vss = await asyncio.to_thread(self._vss_build, q, it)
                await self._slow_pad(time.monotonic() - t0_c)
            commitment = cm.vss_digest(vss[0])
        else:
            t0_c = time.monotonic()
            with self.tele.span("crypto_commit", it=it):
                commitment = await asyncio.to_thread(self._commit, q)
            await self._slow_pad(time.monotonic() - t0_c)
        u = Update(source_id=self.id, iteration=it, delta=delta,
                   commitment=commitment, noise=noise, noised_delta=noised)

        approved = True
        if cfg.verification and not cfg.fedsys:
            verifiers, _, _, _ = self.role_map.committee()
            # verifiers see ONLY the noised copy + commitment: the raw delta
            # is exactly what DP noising and share-based aggregation hide
            # (ref: SURVEY §2.3 row 21 — NoisedDelta to verifiers, Delta to
            # miners)
            # noised copy travels f32: the defense kernels score in f32 on
            # device either way (_decide_round casts), every verifier sees
            # identical bytes (determinism holds), and the dominant
            # verifier-bound payload halves
            redacted = Update(source_id=self.id, iteration=it,
                              delta=np.zeros(0, np.float64),
                              commitment=commitment,
                              noised_delta=np.asarray(noised, np.float32))
            meta, arrays = wire.pack_update(redacted)
            sigs: List[Tuple[int, bytes]] = []

            async def ask(v):
                try:
                    rmeta, _ = await self._call(
                        v, "VerifyUpdateKRUM" if cfg.defense == Defense.KRUM
                        else "VerifyUpdateRONI", meta, arrays,
                        timeout=self.timeouts.krum_s * 2 + self.timeouts.rpc_s)
                    sigs.append((v, bytes.fromhex(rmeta["signature"])))
                    return True
                except Exception as e:
                    self._trace("verify_call_failed", verifier=v,
                                error=f"{type(e).__name__}: {e}")
                    return False

            # partial-quorum signature collection (docs/STRAGGLERS.md):
            # disarmed this is a plain gather over the same coroutines
            # (seed behavior); armed, the fan-out proceeds once the
            # approval quorum is in hand after the phase's soft deadline
            # instead of waiting out a straggling verifier's full
            # krum_s*2+rpc_s budget
            with self.tele.span("verify_wait", it=it):
                await self._gather_quorum(
                    stragglers.VERIFY, {v: ask(v) for v in verifiers},
                    need=max(1, (len(verifiers) + 1) // 2),
                    legacy_s=self.timeouts.krum_s * 2 + self.timeouts.rpc_s)
            # approved iff ≥ half the verifiers signed (ref: main.go:1686)
            approved = len(sigs) >= max(1, (len(verifiers) + 1) // 2)
            u.signers = [v for v, _ in sigs]
            u.signatures = [s for _, s in sigs]
        if not approved:
            self._trace("update_rejected")
            # signed decline notice to the miners: completes their
            # expected-contributor count so the round mints as soon as
            # every sampled worker is accounted for, instead of riding
            # the update deadline (see RoundState.miner_declined)
            _, miners, _, _ = self.role_map.committee()
            dmeta = {
                "iteration": it, "source_id": self.id,
                "sig": self._sign(_decline_message(it, self.id)).hex(),
            }
            await asyncio.gather(*(
                self._safe_call(m, "RegisterDecline", dmeta)
                for m in sorted(miners)
            ))
            return

        _, miners, _, _ = self.role_map.committee()
        if cfg.secure_agg and not cfg.fedsys:
            comms, blind_bytes, c_chunks = vss
            t0_sh = time.monotonic()
            with self.tele.span("share_gen", it=it):
                blind_rows = await asyncio.to_thread(
                    self._vss_blind_rows, blind_bytes, c_chunks)
                shares = np.asarray(ss.make_shares(
                    np.asarray(q), cfg.poly_size, cfg.total_shares))
            await self._slow_pad(time.monotonic() - t0_sh)
            # overlay up-path (docs/OVERLAY.md): hand the full tensors to
            # this round's subtree relay (loopback-free in a hive), which
            # pre-aggregates the whole subtree into one frame per miner.
            # Any failure falls through to the seed's direct fan-out.
            sent = await self._overlay_submit_secret(
                it, commitment, u, shares, blind_rows, comms)
            if not sent:
                for idx, m in enumerate(sorted(miners)):
                    sl = ss.miner_rows(cfg.total_shares, idx, len(miners))
                    try:
                        await self._call(m, "RegisterSecret", {
                            "iteration": it, "source_id": self.id,
                            "miner_index": idx,
                            "commitment": commitment.hex(),
                            "signers": list(u.signers),
                            "signatures": [s.hex() for s in u.signatures],
                        }, self._secret_arrays(shares, blind_rows, comms,
                                               sl))
                    except Exception:
                        pass
        else:
            meta, arrays = wire.pack_update(u)
            meta["iteration"] = it
            # send to every miner: only the leader (max id) mints, so the
            # update must reach it (the reference's first-miner-wins race,
            # main.go:1777-1845, maps onto our single-leader mint). With
            # the overlay armed, miners sharing a remote subtree receive
            # the frame via that subtree's relay — one TCP crossing per
            # subtree, direct fallback on relay failure.
            await self._overlay_fanout("RegisterUpdate", meta, arrays,
                                       sorted(miners), it)
        self._trace("update_sent", secure_agg=cfg.secure_agg)

    def _vss_build(self, q: np.ndarray, it: int,
                   head: Optional[bytes] = None) -> Tuple[np.ndarray, bytes, int]:
        """Pedersen-VSS commitments for every polynomial chunk of the
        quantized update, bound to this round via the (block hash,
        iteration) context. Returns (comms uint8 [C,k,64] affine pairs,
        packed blind coefficients, chunk count). The blinding-SHARE tensor
        is evaluated later, post-approval (_vss_blind_rows): only accepted
        updates ship shares, so rejected workers skip that cost.
        `head` pins the context hash for the speculative caller, which
        must not race a mid-build chain advance; None reads the live
        chain (the serial path)."""
        cfg = self.cfg
        c = ss.num_chunks(len(q), cfg.poly_size)
        padded = np.zeros(c * cfg.poly_size, np.int64)
        padded[: len(q)] = q
        chunks = padded.reshape(c, cfg.poly_size)
        context = ((head if head is not None else self.chain.latest_hash())
                   + int(it).to_bytes(8, "little"))
        comms, blind_bytes = cm.vss_commit_chunks_bytes(
            chunks, self.schnorr_seed, context)
        return comms, blind_bytes, c

    def _vss_blind_rows(self, blind_bytes: bytes, c: int) -> np.ndarray:
        """Blinding-polynomial share tensor uint8 [S,C,32] for all share
        points (the post-approval half of _vss_build)."""
        return cm.vss_blind_rows_bytes(blind_bytes, c, self.cfg.poly_size,
                                       self._xs_all)

    def _secret_arrays(self, shares: np.ndarray, blind_rows: np.ndarray,
                       comms: np.ndarray, sl: slice) -> Dict[str, np.ndarray]:
        """Per-miner RegisterSecret payload — seam overridden by Byzantine
        test peers to inject corrupted tensors."""
        return {"share_rows": shares[sl], "blind_rows": blind_rows[sl],
                "comms": comms}

    async def _safe_call(self, pid, msg_type, meta=None, arrays=None) -> bool:
        try:
            await self._call(pid, msg_type, meta, arrays)
            return True
        except Exception:
            return False

    # ------------------------------------------- aggregation overlay plane
    # (runtime/overlay.py, docs/OVERLAY.md). Every method below is gated
    # on the armed Router: with cfg.overlay off, none of these run and
    # the round's traffic schedule is the seed's, bit for bit.

    def _overlay_saved(self, frames_avoided: int, meta, arrays) -> None:
        """Tick the bytes-saved estimate: `frames_avoided` copies of this
        payload did NOT cross the wire because the tree deduplicated or
        aggregated them."""
        if frames_avoided <= 0:
            return
        self.tele.registry.counter(ov.SAVED_METRIC, ov.SAVED_HELP).inc(
            frames_avoided * ov.frame_estimate(meta, arrays))

    async def _overlay_submit_secret(self, it: int, commitment: bytes,
                                     u: Update, shares: np.ndarray,
                                     blind_rows: np.ndarray,
                                     comms: np.ndarray) -> bool:
        """Worker half of the secure-agg up-path: hand the FULL share /
        blind / commitment tensors to this round's subtree relay in one
        frame (loopback-free when co-hosted). Returns False — caller
        falls back to the seed's per-miner fan-out — whenever the
        overlay is off, the subtree is trivial, or the relay is
        unreachable (the missing-interior-node degradation)."""
        if not self.overlay.enabled:
            return False
        gid = self.overlay.gid_of(self.id)
        workers = [n for n in self.overlay.members(gid)
                   if self.role_map.is_vanilla(n)]
        if len(workers) < 2:
            # a lone contributor has nothing to combine with: the relay
            # hop would add latency without deduplicating anything
            return False
        relay = self.overlay.relay(gid, it)
        offer_meta = {
            "iteration": it, "source_id": self.id,
            "commitment": commitment.hex(),
            "signers": list(u.signers),
            "signatures": [s.hex() for s in u.signatures],
        }
        offer = {
            "commitment": commitment.hex(),
            "signers": list(u.signers),
            "signatures": [s.hex() for s in u.signatures],
            "shares": np.asarray(shares, np.int64),
            "blinds": np.asarray(blind_rows, np.uint8),
            "comms": np.asarray(comms, np.uint8),
        }
        if relay == self.id:
            st = self.round
            if st.iteration != it:
                return False
            self._relay_book_offer(st, self.id, offer)
            self._trace("overlay_offer_local")
            return True
        if protocol.RELAY not in self._grant(relay):
            # the relay's hello did not grant the relay feature (old
            # build / version pin): seed per-miner fan-out, no wasted RPC
            self._trace("overlay_offer_fallback", relay=relay,
                        error="feature_ungranted")
            return False
        try:
            await self._call(relay, "OverlayOffer", offer_meta, {
                "share_rows": offer["shares"],
                "blind_rows": offer["blinds"],
                "comms": offer["comms"],
            })
        except Exception as e:
            self._trace("overlay_offer_fallback", relay=relay,
                        error=type(e).__name__)
            return False
        self._trace("overlay_offer_sent", relay=relay)
        return True

    async def _h_overlay_offer(self, meta, arrays):
        """Relay intake: one subtree leaf's full secure-agg tensors.
        Only leaves of OUR subtree may offer, and only to the peer the
        seed-derived rotation names relay this round; the digest binding
        is checked here (cheap) so one garbage offer cannot poison — and
        thereby fall back — the whole subtree's aggregate. Everything
        else (signature quorums, the share-vs-commitment check) is the
        MINER's job, exactly as on the direct path."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        st = await self._wait_round_ready(it)
        if not (self.overlay.enabled and self.cfg.secure_agg):
            raise RPCError("overlay aggregation disabled")
        sid = int(meta["source_id"])
        gid = self.overlay.gid_of(self.id)
        if self.overlay.gid_of(sid) != gid or sid == self.id:
            raise RPCError("offer outside this relay's subtree")
        if self.overlay.relay(gid, it) != self.id:
            raise RPCError("not this round's relay")
        cfg = self.cfg
        c = ss.num_chunks(self.trainer.num_params, cfg.poly_size)
        shares = np.asarray(arrays.get("share_rows", np.zeros(0)), np.int64)
        blinds = np.asarray(arrays.get("blind_rows", np.zeros(0)), np.uint8)
        comms = np.asarray(arrays.get("comms", np.zeros(0)), np.uint8)
        if shares.shape != (cfg.total_shares, c) \
                or blinds.shape != (cfg.total_shares, c, 32) \
                or comms.shape != (c, cfg.poly_size, 64):
            raise RPCError("bad offer tensor shapes")
        commitment = bytes.fromhex(meta.get("commitment", ""))
        if cm.vss_digest(comms) != commitment:
            raise RPCError("commitment digest mismatch")
        self._relay_book_offer(st, sid, {
            "commitment": meta.get("commitment", ""),
            "signers": [int(x) for x in meta.get("signers", [])],
            "signatures": [str(s) for s in meta.get("signatures", [])],
            "shares": shares, "blinds": blinds, "comms": comms,
        })
        return {}, {}

    def _relay_book_offer(self, st: RoundState, sid: int,
                          offer: Dict) -> None:
        if sid in st.relay_offers or sid in st.relay_flushed:
            return  # duplicate offer: first wins, like miner intake
        st.relay_offers[sid] = offer
        self._relay_last_offer = asyncio.get_running_loop().time()
        if st.relay_task is None or st.relay_task.done():
            t = asyncio.get_running_loop().create_task(
                self._relay_flush_loop(st))
            st.relay_task = t
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)

    async def _relay_flush_loop(self, st: RoundState) -> None:
        """Wait for the rest of the subtree's offers. Flush the moment
        every expected leaf (this round's vanilla workers in the group)
        is accounted for; otherwise flush once the offer burst stops
        (no new offer for one debounce beat — the verifier releases all
        approved workers at once, so offers arrive as one burst and a
        leaf that DECLINED will simply never offer), with the window as
        the hard cap. The debounce must stay well inside the miner's
        post-quorum grace (~1 s): a relay waiting a full window for a
        decliner would otherwise hold honest shares past the mint. Late
        offers re-arm the loop and aggregate as their own wave (the
        miner accepts disjoint groups)."""
        loop = asyncio.get_running_loop()
        grp = self.overlay.members(self.overlay.gid_of(self.id))
        expected = {n for n in grp if self.role_map.is_vanilla(n)}
        deadline = loop.time() + self.overlay_window_s
        debounce_s = 0.25
        try:
            # outer loop: an offer booked WHILE a flush's RPCs are in
            # flight sees relay_task still alive and arms no new task —
            # it would be silently stranded unless this loop re-checks
            # the buffer after every flush
            while True:
                while loop.time() < deadline:
                    if self.round is not st or (st.block_done is not None
                                                and st.block_done.is_set()):
                        break
                    if expected <= (st.relay_offers.keys()
                                    | st.relay_flushed):
                        break
                    last = getattr(self, "_relay_last_offer", loop.time())
                    if st.relay_offers and loop.time() - last >= debounce_s:
                        break
                    await asyncio.sleep(0.05)
                await self._relay_flush(st)
                if not st.relay_offers or self.round is not st:
                    return
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._trace("overlay_relay_error",
                        error=f"{type(e).__name__}: {e}")

    async def _relay_flush(self, st: RoundState) -> None:
        """Interior-node combine: sum the buffered leaves' share rows,
        blind rows (mod q) and Pedersen commitment grids (point-wise —
        additively homomorphic), then ship ONE RegisterAggregate per
        miner. A miner that refuses the aggregate (RLC failure, member
        conflict) gets the buffered per-member frames instead — the
        exact per-update path, so rejection evidence is unchanged."""
        offers, st.relay_offers = st.relay_offers, {}
        if not offers or self.round is not st:
            return
        members = sorted(offers)
        st.relay_flushed |= set(members)
        cfg = self.cfg
        _, miners, _, _ = self.role_map.committee()
        miners = sorted(miners)

        def build():
            grids = cm.sum_commitment_grids(
                [offers[n]["comms"] for n in members])
            blinds = cm.sum_blind_row_tensors(
                [offers[n]["blinds"] for n in members])
            rows = np.asarray(ss.aggregate_shares(
                np.stack([offers[n]["shares"] for n in members])))
            return grids, blinds, rows

        with self.tele.span("overlay_aggregate", it=st.iteration):
            comms_sum, blinds_sum, rows_sum = await asyncio.to_thread(build)
        member_meta = [{"source_id": n,
                        "commitment": offers[n]["commitment"],
                        "signers": offers[n]["signers"],
                        "signatures": offers[n]["signatures"]}
                       for n in members]
        for idx, m in enumerate(miners):
            sl = ss.miner_rows(cfg.total_shares, idx, len(miners))
            ok = False
            if (comms_sum is not None and len(members) >= 2
                    and protocol.RELAY not in self._grant(m)):
                # the miner's hello did not grant the relay feature (old
                # build / version pin): skip straight to the per-member
                # forwarding below without burning an RPC on a refusal
                self._trace("overlay_aggregate_refused", miner=m,
                            error="feature_ungranted")
            elif comms_sum is not None and len(members) >= 2:
                try:
                    await self._call(m, "RegisterAggregate", {
                        "iteration": st.iteration, "source_id": self.id,
                        "miner_index": idx, "members": member_meta,
                    }, {"agg_rows": rows_sum[sl],
                        "agg_blinds": blinds_sum[sl],
                        "agg_comms": comms_sum})
                    ok = True
                except Exception as e:
                    self._trace("overlay_aggregate_refused", miner=m,
                                error=type(e).__name__)
            if ok:
                self._trace("overlay_aggregate_sent", miner=m,
                            n=len(members))
                self._overlay_saved(
                    len(members) - 1,
                    member_meta[0],
                    {"share_rows": rows_sum[sl],
                     "blind_rows": blinds_sum[sl],
                     "comms": comms_sum})
                continue
            # fallback: forward the buffered per-member frames — bit-
            # equivalent to the workers' own direct sends, so the miner's
            # per-update verification (and its bisection evidence on a
            # corrupted member) applies unchanged
            for n in members:
                o = offers[n]
                self._trace("overlay_fallback_forwarded", miner=m, source=n)
                await self._safe_call(m, "RegisterSecret", {
                    "iteration": st.iteration, "source_id": n,
                    "miner_index": idx, "commitment": o["commitment"],
                    "signers": o["signers"], "signatures": o["signatures"],
                }, {"share_rows": o["shares"][sl],
                    "blind_rows": o["blinds"][sl], "comms": o["comms"]})

    async def _h_register_aggregate(self, meta, arrays):
        """Miner intake of one subtree aggregate: per-member signature
        quorums are checked INDIVIDUALLY (unaggregated, so defense
        verdicts and stake accounting are unchanged), then the whole
        subtree settles in ONE share-vs-commitment RLC check against
        the homomorphically summed grid — W verifications collapse to
        one per subtree. Refusals are ordinary RPCErrors: the relay
        falls back to per-member delivery and the exact per-update
        machinery assigns blame.

        The summed grid is relay-supplied: the per-member digest binding
        is enforced at the RELAY (which holds the per-member grids), not
        here — the documented overlay residual (runtime/overlay.py
        KNOWN RESIDUAL, docs/OVERLAY.md §trust-model): a Byzantine relay
        can substitute its own subtree's aggregate, which in the
        deployed intra-hive shape adds nothing to what the members' own
        host could already do."""
        it = int(meta["iteration"])
        if it < self.iteration:
            raise StaleError()
        st = await self._wait_round_ready(it)
        if not self.role_map.is_miner(self.id):
            raise RPCError("not a miner this round")
        if not (self.overlay.enabled and self.cfg.secure_agg):
            raise RPCError("overlay aggregation disabled")
        mm = meta.get("members") or []
        try:
            members = [int(x["source_id"]) for x in mm]
        except (TypeError, KeyError, ValueError):
            raise RPCError("malformed member metadata")
        if not members or len(set(members)) != len(members):
            raise RPCError("bad member list")
        if any(n not in self.peers for n in members):
            raise RPCError("unknown member")
        conflicts = sorted(n for n in members
                           if n in st.miner_shares
                           or n in st.miner_group_of
                           or n in st.miner_rejected)
        if conflicts:
            raise RPCError(f"members already registered: {conflicts}")
        cfg = self.cfg
        c = ss.num_chunks(self.trainer.num_params, cfg.poly_size)
        rows = np.asarray(arrays.get("agg_rows", np.zeros(0)), np.int64)
        blinds = np.asarray(arrays.get("agg_blinds", np.zeros(0)), np.uint8)
        comms = np.asarray(arrays.get("agg_comms", np.zeros(0)), np.uint8)
        if rows.shape != (cfg.shares_per_miner, c) \
                or blinds.shape != (cfg.shares_per_miner, c, 32) \
                or comms.shape != (c, cfg.poly_size, 64):
            raise RPCError("bad aggregate tensor shapes")
        if cfg.verification:
            # all member quorums in ONE thread hop: a 50-leaf subtree
            # must not serialize 50 to_thread round-trips on the
            # round-critical intake path (each check is itself a batched
            # RLC Schnorr verify inside _verify_sig_quorum)
            def check_quorums() -> str:
                for x in mm:
                    commitment = bytes.fromhex(str(x.get("commitment", "")))
                    ok, why = self._check_secret_quorum(
                        commitment,
                        {"iteration": it, "source_id": x["source_id"],
                         "signers": x.get("signers", []),
                         "signatures": x.get("signatures", [])})
                    if not ok:
                        return f"member {x['source_id']}: {why}"
                return ""
            with self.tele.span("sig_check", it=it):
                bad = await asyncio.to_thread(check_quorums)
            if bad:
                raise RPCError(bad)
        xs = st.my_xs
        if xs is None:
            raise RPCError("share layout not armed")
        t0_mv = time.monotonic()
        with self.tele.span("miner_verify", it=it):
            ok = await asyncio.to_thread(
                cm.vss_verify_multi, [(comms, xs, rows, blinds)])
        await self._slow_pad(time.monotonic() - t0_mv)
        if not ok:
            self._trace("overlay_aggregate_rejected", n=len(members))
            raise RPCError("aggregate fails the RLC consistency check")
        g = frozenset(members)
        st.miner_groups[g] = {"rows": rows, "comms": comms,
                              "blinds": blinds}
        for x in mm:
            n = int(x["source_id"])
            st.miner_group_of[n] = g
            st.miner_commitments[n] = bytes.fromhex(
                str(x.get("commitment", "")))
            try:
                st.miner_sigs[n] = (
                    [int(s) for s in x.get("signers", [])],
                    [bytes.fromhex(s) for s in x.get("signatures", [])])
            except (ValueError, TypeError):
                pass
            st.miner_vss_batch[n] = g
        self._trace("overlay_aggregate_registered", n=len(members),
                    have=len(self._sec_sources(st)))
        self.tele.registry.counter(ov.FRAMES_METRIC, ov.FRAMES_HELP).inc(
            kind="aggregated")
        return {}, {}

    async def _relay_send(self, relay: int, inner_type: str, meta, arrays,
                          ts: List[int], it: int,
                          timeout: Optional[float] = None) -> None:
        """One deduplicated fan-out leg: ship the frame to `relay` for
        forwarding to `ts`. On ANY failure the orphaned targets get the
        seed path's direct sends — the missing-interior-node
        degradation, shared by the update and block broadcast paths."""
        if protocol.RELAY not in self._grant(relay):
            # relay feature ungranted (old build / version pin): the
            # whole leg degrades to direct sends without a wasted RPC
            self._trace("overlay_relay_fallback", relay=relay,
                        error="feature_ungranted")
            await asyncio.gather(*(
                self._safe_call(t, inner_type, meta, arrays) for t in ts))
            return
        try:
            await self._call(relay, "RelayFrames", {
                "iteration": it, "source_id": self.id,
                "inner_type": inner_type, "inner_meta": meta,
                "targets": ts,
            }, arrays, timeout=timeout)
        except Exception as e:
            self._trace("overlay_relay_fallback", relay=relay,
                        error=type(e).__name__)
            await asyncio.gather(*(
                self._safe_call(t, inner_type, meta, arrays) for t in ts))
            return
        self._trace("overlay_relayed_sent", relay=relay, targets=len(ts))
        self._overlay_saved(len(ts) - 1, meta, arrays)
        self.tele.registry.counter(ov.FRAMES_METRIC,
                                   ov.FRAMES_HELP).inc(kind="relayed")

    async def _overlay_fanout(self, msg_type: str, meta, arrays,
                              targets: List[int], it: int) -> None:
        """Overlay-aware push fan-out for verbatim frames: targets that
        share a remote subtree receive the frame through that subtree's
        relay (one TCP crossing per subtree); everything else — and any
        subtree whose relay fails — goes direct, the seed path."""
        direct, relayed = self.overlay.plan(targets, it, self.id)
        await asyncio.gather(
            *(self._safe_call(t, msg_type, meta, arrays) for t in direct),
            *(self._relay_send(r, msg_type, meta, arrays, ts, it)
              for r, ts in relayed.items()))

    async def _h_relay_frames(self, meta, arrays):
        """Interior-node forwarding of a verbatim frame to leaves of OUR
        subtree. The inner type is whitelisted to the two push frames
        the overlay deduplicates; every receiver re-validates the
        forwarded content exactly as it would a direct send, so a
        Byzantine relay can at worst drop (the round's existing
        degradation), never forge. Forwarding is scheduled and the ACK
        returned immediately — custody semantics match a fire-and-
        forget post."""
        if not self.overlay.enabled:
            raise RPCError("overlay disabled")
        inner_type = str(meta.get("inner_type", ""))
        if inner_type not in ("RegisterUpdate", "RegisterBlock"):
            raise RPCError("inner type not relayable")
        inner_meta = meta.get("inner_meta")
        if not isinstance(inner_meta, dict):
            raise RPCError("malformed inner meta")
        try:
            targets = [int(x) for x in meta.get("targets", [])]
        except (TypeError, ValueError):
            raise RPCError("malformed target list")
        grp = set(self.overlay.members(self.overlay.gid_of(self.id)))
        if not targets or len(set(targets)) != len(targets) \
                or any(t not in grp for t in targets):
            raise RPCError("targets outside this relay's subtree")

        async def forward(t: int):
            try:
                if t == self.id:
                    await self._handle(inner_type, dict(inner_meta),
                                       arrays)
                else:
                    await self._call(t, inner_type, dict(inner_meta),
                                     arrays)
                self._trace("overlay_relay_forwarded", target=t,
                            inner=inner_type)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # receiver-side verdicts are the receiver's business

        loop = asyncio.get_running_loop()
        for t in targets:
            task = loop.create_task(forward(t))
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
        return {"targets": len(targets)}, {}

    # ---------------------------------------------------------------- miner

    def _miner_leader(self, miners: List[int]) -> int:
        """Leader = max node id among miners (ref: main.go:2027-2045)."""
        return max(miners)

    async def _miner_flow(self) -> None:
        cfg = self.cfg
        it = self.iteration
        st = self.round
        _, miners, _, _ = self.role_map.committee()
        sec = cfg.secure_agg and not cfg.fedsys
        phase = stragglers.SHARE if sec else stragglers.UPDATE
        legacy = self.timeouts.share_s if sec else self.timeouts.update_s
        # adaptive intake deadline (docs/STRAGGLERS.md): disarmed (or
        # unwarmed) the controller answers `legacy` verbatim; armed, a
        # fleet whose intakes historically complete in seconds stops
        # riding the 90 s constant when a worker dies mid-round
        deadline = self._deadline(phase, legacy)
        # both intake paths trigger at NUM_SAMPLES/2 — Krum approves about
        # half the pool (f=0.5·n), so a full-sample target would always ride
        # the deadline (ref: main.go:345-363 shares, main.go:1222-1230
        # updates); FedSys's leader waits for the full sample count
        # (ref: FedSys/main.go:530-558)
        target = (max(1, cfg.num_samples) if cfg.fedsys
                  else max(1, cfg.num_samples // 2))
        expected = [n for n in self.peers
                    if self.role_map.is_vanilla(n) or cfg.fedsys]
        t0 = time.monotonic()
        grace_until = None
        accounted_set: Set[int] = set()
        # the intake wait is a tracing-only span: under the cross-peer
        # timeline the miner's "waiting for shares" window is a real
        # critical-path segment (parked), not untraced dead air
        with self.tele.trace_span("intake_wait", it=it):
            try:
                while time.monotonic() - t0 < deadline:
                    have_keys = (self._sec_sources(st) if sec
                                 else set(st.miner_updates))
                    have = len(have_keys)
                    # every expected contributor has responded — a
                    # submission, a provably bad one, or a signed decline
                    # (verifier-refused workers, RegisterDecline): mint at
                    # once. Union-counted so a Byzantine worker both
                    # declining and submitting is one peer.
                    accounted_set = (have_keys | st.miner_rejected.keys()
                                     | st.miner_declined)
                    accounted = len(accounted_set)
                    # stall forensics: while blocked, publish exactly who
                    # this intake is waiting on (the obs `waiting-on`
                    # column)
                    self.straggler.waiting(
                        phase, (n for n in expected
                                if n not in accounted_set
                                and n != self.id))
                    if accounted >= cfg.num_samples:
                        break
                    if have >= target:
                        # quorum reached — hold a short straggler window
                        # so same-instant submissions (and their
                        # rejections) land in this block rather than
                        # silently missing the round
                        if grace_until is None:
                            grace_until = time.monotonic() + min(
                                1.0, deadline / 4)
                        elif time.monotonic() >= grace_until:
                            break
                    if st.block_done and st.block_done.is_set():
                        return  # someone else minted first
                    await asyncio.sleep(0.05)
            finally:
                self.straggler.clear(phase)
        # feed the controller BOTH outcomes: a satisfied intake records
        # its real completion time, and an EXPIRED one records the full
        # wait (== the deadline) — so a fleet that slowed past the
        # adapted budget grows it back geometrically (×margin per
        # expired round, ceiling = the legacy constant) instead of
        # freezing a too-tight estimate forever and minting short with
        # honest workers excluded every round; once intakes complete
        # again, real observations pull the estimate back down
        self.deadlines.observe(phase, time.monotonic() - t0)
        if self.id != self._miner_leader(miners):
            return  # non-leader miners rely on the block timer fallback
        if st.block_done and st.block_done.is_set():
            return
        # straggler accounting at mint: the sampling design expects
        # `num_samples` contributors — mints short of that proceeded
        # without honest stragglers. Counted by the SHORTFALL (not every
        # unaccounted worker: with sample_percent < 1 the design itself
        # expects fewer responders than workers), traced with the
        # candidate ids, NEVER debited (only provably-bad commitments
        # are) and never breaker evidence — the ISSUE's
        # honest-straggler-never-quarantined contract.
        shortfall = cfg.num_samples - len(accounted_set)
        if shortfall > 0 and (self._sec_sources(st) if sec
                              else st.miner_updates):
            missing = sorted(n for n in expected
                             if n not in accounted_set and n != self.id)
            self.straggler.exclude(phase, missing[:shortfall])
            self._trace("straggler_excluded", phase=phase,
                        peers=missing, short=shortfall,
                        waited_s=round(time.monotonic() - t0, 3))
        # tracing-only composite span: the recovery/verify child spans
        # inside _create_block hang off it, and the broadcast below
        # stamps it as the receivers' parent — the settle leg of the
        # cross-peer causal tree (the gossip fan-out reads the CURRENT
        # context, which inside this block is the mint span)
        with self.tele.trace_span("mint", it=it):
            blk = await self._create_block()
            if blk is not None:
                self._accept_block(blk, gossip=True, minted=True)

    async def _create_block(self) -> Optional[Block]:
        cfg = self.cfg
        st = self.round
        it = self.iteration
        w = self.chain.latest_gradient()
        stake = self.chain.latest_stake_map()

        # Debits are backed ONLY by this leader's own verification evidence
        # (st.miner_rejected): trusting other miners' claimed rejection
        # lists would let a single Byzantine miner zero out arbitrary
        # nodes' stake every round.
        if cfg.secure_agg and not cfg.fedsys:
            # settle our own intake's VSS verification before agreeing on
            # the contributor set (other miners settle theirs when we call
            # GetUpdateList/GetMinerPart on them); rejected_ids is
            # snapshotted AFTER the aggregation-boundary re-check below so
            # offenders it surfaces are debited too
            await self._verify_intake(st)
            _, miners, _, _ = self.role_map.committee()
            miners = sorted(miners)
            # 1. agree on the contributor set: intersection across miners.
            # Miners that fail the exchange are tracked as LOST: with the
            # resharing plane armed (cfg.reshare) the round can still
            # recover from the survivors' re-dealt shares — the seed
            # behavior (a lost miner empties the intersection and the
            # round) remains when resharing is off.
            node_sets = [self._sec_sources(st)]
            reachable = [self.id]
            for m in miners:
                if m == self.id:
                    continue
                try:
                    rmeta, _ = await self._call(m, "GetUpdateList", {
                        "iteration": it, "source_id": self.id,
                        "sig": self._sign(self._part_message(
                            "update-list", it, [])).hex(),
                    })
                    node_sets.append(set(int(x) for x in rmeta["sources"]))
                    reachable.append(m)
                except Exception:
                    if not self.cfg.reshare:
                        node_sets.append(set())
            lost = [m for m in miners if m not in reachable]
            if lost and self.cfg.reshare:
                self._trace("miner_lost", peers=sorted(lost))
            nodes = sorted(set.intersection(*node_sets)) if node_sets else []
            # aggregation-boundary re-check (docs §aggregated-vss): when
            # the agreed set covers the leader's intake batch only
            # partially, the partial members are re-proved; offenders the
            # re-check surfaces are rejected with LEADER evidence (so the
            # minted block debits them), dropped from the set, and the
            # remainder re-proved — colluders whose corruptions cancelled
            # inside the intake batch are caught the moment the agreed
            # set splits the coalition. Terminates: every False iteration
            # removes at least one sid from miner_shares.
            while nodes and not await self._ensure_subset_consistent(
                    st, nodes):
                nodes = [n for n in nodes if n in self._sec_sources(st)]
            rejected_ids = set(st.miner_rejected)
            agg = np.zeros(self.trainer.num_params, np.float64)
            if nodes and lost and self.cfg.reshare:
                # membership epoch bump: the committee lost a holder
                # mid-round — run the distributed resharing round over
                # the survivors and recover from the re-dealt shares
                recovered = await self._reshare_recover(st, miners,
                                                        reachable, nodes,
                                                        it)
                if recovered is None:
                    return self._empty_block()
                agg = recovered
            elif nodes:
                # 2. gather every miner's aggregated slice
                slices: Dict[int, np.ndarray] = {}
                ok = True
                for idx, m in enumerate(miners):
                    if m == self.id:
                        decomp = self._sec_decompose(st, nodes)
                        if decomp is None:
                            return self._empty_block()
                        slices[idx] = np.asarray(ss.aggregate_shares(
                            np.stack(decomp[0])))
                        continue
                    try:
                        _, arrs = await self._call(m, "GetMinerPart", {
                            "iteration": it, "nodes": nodes,
                            "source_id": self.id,
                            "sig": self._sign(self._part_message(
                                "miner-part", it, nodes)).hex(),
                        })
                        slices[idx] = np.asarray(arrs["agg_rows"], np.int64)
                    except Exception:
                        ok = False
                if not ok or len(slices) != len(miners):
                    # a miner died BETWEEN set agreement and slice
                    # collection: same epoch bump, same resharing round —
                    # survivors re-deal and recovery proceeds without the
                    # lost rows (the guard inside _h_get_reshare_deal
                    # accepts the identical aggregation set it already
                    # served a plain slice for, and refuses any other)
                    if not self.cfg.reshare:
                        return self._empty_block()
                    survivors = [self.id] + [
                        m for i, m in enumerate(miners)
                        if m != self.id and i in slices]
                    self._trace("miner_lost", peers=sorted(
                        m for m in miners if m not in survivors))
                    recovered = await self._reshare_recover(
                        st, miners, survivors, nodes, it)
                    if recovered is None:
                        return self._empty_block()
                    agg = recovered
                else:
                    # 3. reassemble rows and recover the aggregate
                    full = np.concatenate([slices[i]
                                           for i in range(len(miners))])
                    xs = self._xs_arr
                    t0_rec = time.monotonic()
                    with self.tele.span("recovery", it=it):
                        agg = np.asarray(ss.recover_update(
                            full, xs, self.trainer.num_params,
                            cfg.poly_size, cfg.precision))
                    await self._slow_pad(time.monotonic() - t0_rec)
            deltas = [Update(source_id=n, iteration=it,
                             delta=np.zeros(0, np.float64),
                             commitment=self.round.miner_commitments.get(n, b""),
                             accepted=True,
                             signers=st.miner_sigs.get(n, ([], []))[0],
                             signatures=st.miner_sigs.get(n, ([], []))[1])
                      for n in nodes]
            contributors = list(nodes)
        else:
            rejected_ids = set(st.miner_rejected)
            updates = [st.miner_updates[k] for k in sorted(st.miner_updates)]
            agg = np.zeros(self.trainer.num_params, np.float64)
            if updates:
                mat = np.stack([u.delta for u in updates])
                if cfg.fedsys:
                    agg = mat.mean(axis=0)  # FedSys averages (FedSys/honest.go:311)
                elif cfg.defense == Defense.TRIMMED_MEAN:
                    # non-IID-robust aggregation (ops/robust_agg.py):
                    # deterministic over the sorted update set, so every
                    # miner computes the identical aggregate and the
                    # chain-equality oracle holds. Only reachable with
                    # secure_agg off (config.__post_init__ enforces the
                    # shares-vs-order-statistics incompatibility).
                    # Applied for ALL n >= 1 — degraded rounds carrying
                    # 1–2 updates (exactly what the fault plane produces)
                    # must not silently lapse to an undefended sum; the
                    # kernel clamps its trim to keep >= 1 element, so for
                    # n <= 2 it degenerates to the (sum-scaled) mean,
                    # traced below for artifact visibility (ADVICE r5).
                    import jax.numpy as jnp

                    from biscotti_tpu.ops.robust_agg import trimmed_mean_aggregate

                    if len(updates) <= 2:
                        self._trace("trimmed_mean_degenerate",
                                    n=len(updates))
                    agg = np.asarray(trimmed_mean_aggregate(
                        jnp.asarray(mat, jnp.float32), cfg.trim_fraction),
                        np.float64)
                else:
                    agg = mat.sum(axis=0)  # Biscotti sums (honest.go:360-375)
                for u in updates:
                    u.accepted = True
                    # noise / noised_delta are worker→verifier transport
                    # fields; carrying them in the minted block doubles its
                    # wire size for no reader (the delta is the receipt)
                    u.noise = None
                    u.noised_delta = None
                    if cfg.fedsys:
                        # the reference's FedSys broadcasts the MODEL only
                        # (RegisterModel, FedSys/main.go:612-647) — there
                        # is no ledger receipt of individual deltas. Keep
                        # the contributor record, drop the array: a full
                        # delta list made the block ~70x larger than the
                        # model it carries
                        u.delta = np.zeros(0, np.float64)
            deltas = updates
            contributors = [u.source_id for u in updates]

        rejected_ids -= set(contributors)
        if not contributors and not rejected_ids:
            return self._empty_block()
        # rejected submissions ride in the block as accepted=False records
        # and are debited, mirroring the reference's block-level stake
        # update (ref: honest.go:363-370: +STAKE_UNIT accepted, − rejected);
        # stake is floored at zero so repeat offenders cannot push the
        # lottery ticket pool negative
        deltas = deltas + [st.miner_rejected[n] for n in sorted(rejected_ids)]
        new_stake = dict(stake)
        for n in contributors:
            new_stake[n] = new_stake.get(n, 0) + cfg.stake_unit
        for n in rejected_ids:
            new_stake[n] = max(0, new_stake.get(n, 0) - cfg.stake_unit)
        blk = Block(
            # mint onto the codec's downcast grid (transform_dense is the
            # identity for raw64/zlib): the sealed hash then covers values
            # an f32/bf16 wire carries exactly, so every receiver's hash
            # check passes regardless of which codec its link negotiated.
            # Never sparsified — topk applies to per-round deltas only.
            data=BlockData(iteration=it,
                           global_w=self.wire.transform_dense(w + agg),
                           deltas=deltas),
            prev_hash=self.chain.latest_hash(),
            stake_map=new_stake,
        ).seal()
        self._trace("block_minted", contributors=len(contributors),
                    rejected=len(rejected_ids))
        return blk

    def _empty_block(self) -> Block:
        """Round-advancing empty block (ref: main.go:2099-2143)."""
        return Block(
            data=BlockData(iteration=self.iteration,
                           global_w=self.chain.latest_gradient()),
            prev_hash=self.chain.latest_hash(),
            stake_map=self.chain.latest_stake_map(),
        ).seal()

    # ----------------------------------------------------------- main loop

    async def _run_round(self) -> None:
        cfg = self.cfg
        self._compute_roles()
        it = self.iteration
        loop = asyncio.get_running_loop()
        self.round = RoundState(
            iteration=it,
            krum_decision=loop.create_future(),
            block_done=asyncio.Event(),
        )
        if self._preverify_gate:
            # entries for settled rounds are dead weight; live near-future
            # entries survive so their one-shot grant still holds
            self._preverify_gate = {k for k in self._preverify_gate
                                    if k[0] >= it}
        st = self.round
        if self.role_map.is_miner(self.id) and self.cfg.secure_agg:
            st.my_xs = self._my_share_xs()
        self._round_t0 = time.monotonic()
        if self.tele.trace:
            # root the round's causal tree: every peer derives the SAME
            # trace id for iteration `it` (pure function of the protocol
            # seed), so the N per-peer trees stitch into one cluster-wide
            # round trace. The root context is installed on THIS task, and
            # create_task's context copy threads it into the worker/miner
            # flows, watchdogs, and gossip pushes below; the round_start
            # event below carries the root span id (its `parent` field),
            # which is how trace_round finds each peer's root.
            self.tele.round_root(tracectx.trace_id_for(cfg.seed, it), it)
        self._trace("round_start",
                    verifier=self.role_map.is_verifier(self.id),
                    miner=self.role_map.is_miner(self.id))

        # adversary observation hook (docs/ADVERSARY.md): an armed
        # campaign sees what any participant at this peer sees — the
        # public election just computed above and the latest block —
        # and fixes this round's actions (flood targets, recycle,
        # poison scale) BEFORE any of them fire (the self-kill below
        # included, so a recycle is counted before it executes)
        if self.campaign is not None:
            self._campaign_observe(it)

        # seeded churn self-kill (--fault-churn, docs/MEMBERSHIP.md): this
        # round is OUR scheduled death — exit cleanly so the launcher can
        # relaunch us at the scheduled restart round. The in-process
        # ChurnRunner kills from the outside instead (hard-crash
        # semantics); both ride the same replayable schedule.
        if it in self._churn_kills:
            self._trace("churn_self_kill", height=it)
            raise faults.ChurnExit(it)

        # random self-crash fault injection (ref: main.go:54-55,1117-1120)
        if cfg.fail_prob > 0 and self._rng.random() < cfg.fail_prob:
            self._trace("self_crash")
            os._exit(17)

        work = []
        if self.role_map.is_verifier(self.id):
            async def krum_timer():
                # adaptive defense-decision timer (docs/STRAGGLERS.md):
                # disarmed/unwarmed = the legacy krum_s fallback verbatim
                await asyncio.sleep(self._deadline(stragglers.KRUM,
                                                   self.timeouts.krum_s))
                self._decide_round()  # timeout fallback (ref: krum.go:178-224)
            work.append(loop.create_task(krum_timer()))
        if self.role_map.is_miner(self.id):
            work.append(loop.create_task(self._miner_flow()))
        if self.role_map.is_vanilla(self.id) or cfg.fedsys:
            if not (cfg.fedsys and self.id == 0):
                work.append(loop.create_task(self._worker_flow()))

        # block deadline: every peer advances the round no matter what
        # (ref: main.go:2326-2355 startBlockDeadlineTimer). Armed, the
        # controller shrinks this toward the fleet's observed round times
        # (clamped to [floor, block_s]) — a dead miner costs the cluster
        # roughly one typical round, not the full 300 s constant.
        block_dl = self._deadline(stragglers.BLOCK, self.timeouts.block_s)
        _, _miners_now, _, _ = self.role_map.committee()
        leader = self._miner_leader(sorted(_miners_now)) \
            if _miners_now else None

        async def stall_watchdog():
            # stall forensics (always-on, read-only): a round stuck past
            # half its block deadline records WHICH phase it is blocked
            # on and WHOM it awaits — biscotti_round_stalls_total{phase}
            # plus a traced event carrying the peer ids, so a wedged
            # production round is diagnosable from a scrape instead of a
            # post-mortem log dig
            await asyncio.sleep(max(0.05, block_dl / 2))
            if st.block_done.is_set() or self.iteration != it:
                return
            waiting = {ph: ps for ph, ps in
                       self.straggler.waiting_on.items() if ps}
            if waiting:
                ph, peers = next(iter(waiting.items()))
            else:
                ph, peers = stragglers.BLOCK, \
                    ([leader] if leader is not None
                     and leader != self.id else [])
            self.straggler.stall(ph, peers, it)
            self._trace("round_stall", phase=ph, peers=sorted(peers),
                        after_s=round(block_dl / 2, 3))

        work.append(loop.create_task(stall_watchdog()))
        st.tasks.extend(work)

        try:
            self.straggler.waiting(
                stragglers.BLOCK,
                [leader] if leader is not None and leader != self.id
                else [])
            # tracing-only: the block wait is most of a non-miner's round
            # — under the timeline it is an explicit parked segment
            with self.tele.trace_span("block_wait", it=it):
                await asyncio.wait_for(st.block_done.wait(), block_dl)
            self.straggler.clear(stragglers.BLOCK)
            # a block landed: the completed round duration is the
            # controller's primary signal for next round's block budget
            self.deadlines.observe(stragglers.BLOCK,
                                   time.monotonic() - self._round_t0)
            self._empty_fallbacks = 0
        except asyncio.TimeoutError:
            self.straggler.clear(stragglers.BLOCK)
            if self.iteration == it:
                # before minting an empty block, try pulling the round's
                # block from a few peers — if the network minted one and
                # only our copy of the gossip was lost, this re-joins the
                # consensus chain instead of forking onto an empty one
                pulled = False
                candidates = [p for p in self.peers if p != self.id]
                for pid in self._rng.sample(candidates,
                                            min(3, len(candidates))):
                    try:
                        bmeta, barrays = await self._call(
                            pid, "GetBlock",
                            {"iteration": it,
                             **self._reply_codec_meta(pid)},
                            timeout=min(5.0, self.timeouts.rpc_s))
                        blk = wire.unpack_block(bmeta, barrays)
                        if blk.hash == blk.compute_hash():
                            self._accept_block(blk, gossip=True)
                            if self.iteration != it:
                                self._trace("block_timeout_pull_recovered")
                                # a successful pull is proof of connectivity
                                # — don't let earlier fallbacks accumulate
                                # into a spurious isolation re-announce
                                self._empty_fallbacks = 0
                                pulled = True
                                break
                    except Exception:
                        continue
                if not pulled and self.iteration == it:
                    self._trace("block_timeout_empty_fallback")
                    self._empty_fallbacks = getattr(
                        self, "_empty_fallbacks", 0) + 1
                    self._accept_block(self._empty_block(), gossip=True,
                                       minted=True)
        if not st.krum_decision.done():
            st.krum_decision.set_result(set())
        for t in work:
            if not t.done():
                t.cancel()
        await asyncio.gather(*work, return_exceptions=True)

        # convergence must be a *uniform* decision: every peer evaluates the
        # same model on the same global test split, so all peers exit at the
        # same height and the chain-equality oracle holds (the reference
        # likewise scores the shared global data, ref: honest.go:141-162)
        with self.tele.span("metrics", it=it):
            if self.stepper is not None and hasattr(self.stepper,
                                                    "test_error"):
                # co-located peers share one evaluation: identical model ×
                # identical global split (the uniformity the oracle needs)
                err = await self.stepper.test_error(
                    self.chain.latest_gradient(), it)
            else:
                err = await asyncio.to_thread(self.trainer.test_error,
                                              self.chain.latest_gradient())
        self.logs.append((it, err, time.time()))
        # height pins the event to the round just finished: the implicit
        # iter stamp has already advanced past the accepted block, which
        # would credit this round's end to the NEXT round's ledger
        # (tools/profile_round keys its wall-clock table on it)
        self._trace("round_end", error=err, height=it)
        if err < cfg.convergence_error:
            self.converged = True
        # round boundary = the recorder's durability point (its spill is
        # batched, not per-event) and a natural moment to refresh the
        # scrape gauges so a mid-run `Metrics` pull is never a round stale
        self._refresh_gauges()
        self.tele.flush()

    async def _announce(self, want_chain: bool = True) -> None:
        """Bootstrap: register with every peer concurrently, adopt the
        longest chain seen (ref: main.go:926-1024 — the reference announces
        serially; at N=100 a serial announce storm alone costs whole
        rounds, so the fan-out runs as one gather). A snapshot-
        bootstrapping joiner announces with `want_chain=False` (wire flag
        `no_chain`): the hello still registers it everywhere, but chain
        bodies stay off the wire — catch-up comes from GetSnapshot.

        Concurrency is bounded to the pool's connection cap: an unbounded
        gather keeps every dialed connection busy at once, so LRU eviction
        cannot close any of them and the CLUSTER transiently holds O(N²)
        sockets — observed blowing the 20k fd limit at N≳150 single-box
        (fedsys's star topology made it visible first, but the spike is
        mode-independent). Bounded, the working set stays ≈ pool cap per
        peer and eviction keeps up."""
        sem = asyncio.Semaphore(self.pool.max_conns)

        async def one(pid: int) -> None:
            try:
                async with sem:
                    w, ln = self.chain.adoption_key()
                    hello = {"source_id": self.id,
                             "host": self.peers[self.id][0],
                             "port": self.peers[self.id][1],
                             "have_weight": w, "have_blocks": ln,
                             # wire-plane hello: what we can decode, plus
                             # a reply-codec ask for the chain body
                             # (honoured only by capable peers, ignored
                             # by legacy ones)
                             "codecs": sorted(self.caps),
                             **self._reply_codec_meta(pid)}
                    if not want_chain:
                        hello["no_chain"] = True
                    cmeta, carrays = await self._call(pid, "RegisterPeer",
                                                      hello)
                self._record_caps(pid, cmeta.get("codecs"))
                if not want_chain:
                    return
                blocks = wire.unpack_chain(cmeta, carrays)
                if blocks:
                    # quorum sweep off-loop (read-only); the adoption —
                    # the chain MUTATION — on the loop, where no handler
                    # can observe a half-swapped chain
                    ok = await asyncio.to_thread(self._chain_quorums_ok,
                                                 blocks)
                    self._adopt_candidate(blocks, pid, quorums_ok=ok)
            except Exception:
                pass

        # co-hosted peers (hive mode) were made mutually known — caps +
        # liveness — at construction; REMOTE peers still get the hello,
        # which is how a late-started hive adopts the cluster's chain
        await asyncio.gather(*(one(pid) for pid in sorted(self.peers)
                               if pid != self.id
                               and pid not in self._announce_skip))

    async def run(self) -> Dict:
        # resume from the newest on-disk snapshot, then let longest-chain
        # adoption advance us further (SURVEY §5.4: the chain IS the
        # checkpoint; the snapshot only survives full-network restarts)
        if self.ckpt_dir:
            from biscotti_tpu.utils import checkpoint as ckpt

            # newest snapshot first, older ones as fallback: a torn newest
            # write must not discard an intact older snapshot. Any corrupt
            # snapshot (bad zip, bad json, structurally wrong manifest,
            # failed chain verify) is skipped, never a startup crash —
            # worst case we start from genesis and longest-chain adoption
            # catches us up from live peers.
            for step in reversed(ckpt.list_steps(self.ckpt_dir)):
                try:
                    restored = ckpt.load(self.ckpt_dir, step=step)
                except Exception as e:
                    self._trace("checkpoint_rejected", step=step,
                                error=f"{type(e).__name__}: {e}")
                    continue
                # same guards as live-network adoption: heavier, verified,
                # quorum-authenticated, grown from OUR genesis — a stale/
                # foreign ckpt-dir (different dims / num_nodes / stake)
                # hashes to a different genesis and is refused, as is an
                # empty chain or one with forged contributions
                if self._chain_quorums_ok(restored.blocks,
                                          restored.pruned_before) \
                        and self.chain.maybe_adopt(restored):
                    self._trace("checkpoint_restored",
                                height=self.chain.latest.iteration)
                    break
                self._trace("checkpoint_rejected", step=step,
                            error="not adoptable")
        if self.device_crypto:
            # compile the device-crypto ladders at this deployment's
            # bucket shapes BEFORE the first round: XLA compile time
            # belongs to startup, not inside a round deadline (a cold
            # compile under a fast-timeout harness turns rounds empty).
            # Concurrent co-hosted peers share the jit cache; the thread
            # hop keeps the event loop serving while it builds.
            ck = ss.num_chunks(self.trainer.num_params,
                               self.cfg.poly_size) * self.cfg.poly_size
            await asyncio.to_thread(devkern.prewarm, ck)
            self._trace("device_crypto_prewarmed", grid_points=ck)
        await self.server.start()
        if self.cfg.metrics_port:
            # optional HTTP exposition beside the RPC server: stock
            # Prometheus (or curl) can scrape this peer with no protocol
            # codec — same +node_id port layout as base_port
            self._metrics_server = await serve_metrics(
                self._render_metrics, self.cfg.my_ip,
                self.cfg.metrics_port + self.id)
        if self.id != 0:
            if self.cfg.snapshot_bootstrap \
                    and protocol.SNAPSHOT in self.caps:
                # membership plane: hello everywhere WITHOUT chain bodies,
                # then catch up from one peer's sealed snapshot — the
                # pre-snapshot history never crosses the wire. A
                # --protocol-version pin predating the snapshot feature
                # joins like the old build: full-chain announce.
                await self._announce(want_chain=False)
                await self._snapshot_bootstrap()
            else:
                await self._announce()
        # a RELAUNCHED incarnation rebuilds the same churn schedule from
        # the same flags — kill rounds at or below the history it just
        # adopted (checkpoint restore and/or announce) were already
        # executed by the previous incarnation and must not re-fire: a
        # supervisor-relaunched peer re-traversing its own kill round
        # would otherwise die again in a clean-exit loop. A genesis
        # launch adopts nothing, so its full schedule survives this.
        self._churn_kills = frozenset(r for r in self._churn_kills
                                      if r > self.iteration)
        try:
            while not self.converged \
                    and self.iteration < self.cfg.max_iterations:
                await self._run_round()
                # two consecutive rounds advanced only by our own
                # timeout-minted empty blocks: we are likely isolated
                # (partition survivor or gossip-evicted) — re-announce to
                # re-adopt the longest chain and re-enter peers' gossip
                # sets (the reference can only heal via its startup
                # announce; ref: localTest.sh's partition test was left
                # commented out)
                if getattr(self, "_empty_fallbacks", 0) >= 2:
                    self._trace("isolation_reannounce")
                    await self._announce()
                    self._empty_fallbacks = 0
                if self.ckpt_dir and self.iteration % self.ckpt_every == 0:
                    from biscotti_tpu.utils import checkpoint as ckpt

                    await asyncio.to_thread(ckpt.save, self.chain,
                                            self.ckpt_dir)
                    await asyncio.to_thread(ckpt.prune, self.ckpt_dir, 3)
        except faults.ChurnExit:
            # scheduled self-kill (--fault-churn): an abrupt but CLEAN
            # exit — sockets released synchronously so the relaunched
            # incarnation can rebind immediately, spill drained, NO crash
            # dump (scripted chaos is not a failure). The launcher
            # relaunches at the scheduled restart round; rejoin then goes
            # through checkpoint restore + announce (or snapshot
            # bootstrap) like any other restart.
            self.server.close_now()
            self.pool.close()
            if self._metrics_server is not None:
                self._metrics_server.close()
            snapshot = self.telemetry_snapshot()
            self._release_device_hooks()
            self.tele.close()
            return self._result(snapshot, churned=True)
        except asyncio.CancelledError:
            # routine teardown (a harness cancelling the task, Ctrl-C):
            # drain the batched spill so the event log is complete, but a
            # cancellation is not a crash — no forensic dump. The RPC
            # server's listen socket is released SYNCHRONOUSLY: left to
            # GC it stays bound for an unbounded grace period, and the
            # next cluster on this port fails its bind
            self.server.close_now()
            self.pool.close()
            if self._metrics_server is not None:
                self._metrics_server.close()
            self._release_device_hooks()
            self.tele.close()
            raise
        except BaseException as e:
            # crash path: the last `recorder_ring` events before the
            # exception are exactly the forensic record the reference
            # never had — dump the ring beside the spill file and flush
            # whatever the batch buffer still holds, then re-raise
            self.tele.crash_dump(reason=f"{type(e).__name__}: {e}")
            self.server.close_now()
            self.pool.close()
            if self._metrics_server is not None:
                self._metrics_server.close()
            self._release_device_hooks()
            self.tele.close()
            raise
        dump = self.chain.dump()
        # Linger before tearing down: the FINAL round's block gossip has no
        # later round to heal it — a peer that missed the push must pull
        # the body from someone still serving GetBlock. Finish our own
        # outbound gossip/advert tasks (bounded) and keep the server up for
        # a short grace window so stragglers' pulls land; without this, a
        # single dropped broadcast frame in the last round stranded peers
        # on their 300 s block timer at N=100 while everyone who could have
        # served the block had already exited.
        if self._bg_tasks:
            await asyncio.wait(list(self._bg_tasks),
                               timeout=min(5.0, self.timeouts.rpc_s))
        await asyncio.sleep(min(2.0, self.timeouts.rpc_s / 3))
        self.pool.close()
        await self.server.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
        snapshot = self.telemetry_snapshot()
        self._release_device_hooks()
        self.tele.close()  # final flush of the batched spill
        return self._result(snapshot, chain_dump=dump)

    def _result(self, snapshot: Dict, chain_dump: Optional[str] = None,
                **extra) -> Dict:
        """The run() result schema, shared by the normal exit and the
        churn self-kill exit (which additionally flags `churned`)."""
        out = {
            "node": self.id,
            "iterations": self.iteration,
            "converged": self.converged,
            "chain_dump": (chain_dump if chain_dump is not None
                           else self.chain.dump()),
            "final_error": self.logs[-1][1] if self.logs else float("nan"),
            "logs": [f"{i},{e:.6f},{t:.6f}" for i, e, t in self.logs],
            # attack/security accounting, printed at exit by the reference
            # (ref: main.go:1071-1088) — here returned structured
            "counters": dict(self.counters),
            "phases": self.phases.summary(),
            # robustness accounting: per-peer breaker states/opens/closes/
            # fast-fails, and (when the fault plane is armed) the injected
            # fault tallies — chaos harnesses assert on these
            "health": self.health.snapshot(),
            "faults": (dict(self.pool.faults.counts)
                       if self.pool.faults is not None else {}),
            # the unified readout (same schema the Metrics RPC serves):
            # chaos harnesses, eval drivers, and tools/obs.py consume
            # this; the flat keys above stay as the back-compat view
            "telemetry": snapshot,
        }
        out.update(extra)
        return out

    def _render_metrics(self) -> str:
        """Prometheus page for the optional HTTP endpoint — gauges are
        refreshed per scrape (pull model, see _refresh_gauges)."""
        self._refresh_gauges()
        return self.tele.render()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="biscotti-tpu peer agent")
    BiscottiConfig.add_args(ap)
    ap.add_argument("--key-dir", default="")
    ap.add_argument("--log-dir", default="")
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--ckpt-every", type=int, default=10)
    ns = ap.parse_args(argv)
    # share math (ops/secretshare.py) silently wraps in int32 without x64;
    # enable it at the process entrypoint, before any jax use (in-process
    # embedders must do this themselves — secretshare fails loudly if not)
    import jax

    jax.config.update("jax_enable_x64", True)
    cfg = BiscottiConfig.from_args(ns)
    cfg = cfg.replace(timeouts=cfg.timeouts.scaled(
        cfg.num_nodes, cfg.num_verifiers, cfg.num_miners,
        random_sampling=cfg.random_sampling,
        defense_is_krum=cfg.defense == Defense.KRUM))
    log_path = (os.path.join(ns.log_dir, f"events_{cfg.node_id}.jsonl")
                if ns.log_dir else "")
    ckpt_dir = (os.path.join(ns.ckpt_dir, f"node_{cfg.node_id}")
                if ns.ckpt_dir else "")
    agent = PeerAgent(cfg, key_dir=ns.key_dir, log_path=log_path,
                      ckpt_dir=ckpt_dir, ckpt_every=ns.ckpt_every)
    result = asyncio.run(agent.run())
    print("=== CHAIN DUMP ===")
    print(result["chain_dump"])
    print("=== LOGS ===")
    for line in result["logs"]:
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
