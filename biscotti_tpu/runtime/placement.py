"""Elastic fleet plane: load-aware placement + live peer migration.

The hive runtime (docs/HIVE.md) broke the single-box wall, but placement
froze at launch: a hot or slow host kept its peers forever. This module
makes co-hosted peers MOVABLE — the controller drains a live peer from
one hive, serializes it into a *migration ticket* (chain via the
snapshot-bootstrap representation, breaker ledger, admission buckets,
error-feedback residual), and resumes it on another hive with identity,
stake, and round position intact. The surviving-prefix oracle
(runtime/membership.py) is the correctness instrument: a rebalance that
forks the chain or debits honest stake fails its run.

Design rules, inherited from the fault/admission/campaign planes:

* **Decisions are pure and seeded.** `decide(plan, signals, round_idx)`
  is a pure function of the placement seed, the decision round, and
  signals the planes already export — hive RSS / loop-lag drift gauges
  (runtime/hive.py monitor), admission shed rates (docs/ADMISSION.md),
  straggler speed profiles (docs/STRAGGLERS.md) — so every rebalance
  replays from its flags like a fault run.
* **Default OFF is bit-identical.** A disabled `PlacementPlan`
  constructs no controller, emits no `biscotti_migration_*` metric, and
  leaves the seed schedule untouched (tests/test_placement.py guards
  this the same way test_adversary.py guards campaigns).
* **The layout helper is shared.** `hive_layout` is the ONE function
  that maps a cluster onto hosts; `tools/pod_launch` (launcher AND
  supervisor) and the overlay's contiguous-group assumption both
  consume it, so a supervisor-resized host cannot silently break
  `--overlay-group` alignment (`aligned_overlay_group`).

stdlib-only at module level, like `faults.py`/`admission.py`: the config
layer imports `PlacementPlan` from here, so numpy / asyncio / the wire
plane load lazily inside the functions that need them.
"""

from __future__ import annotations

import json
import random
import time
from dataclasses import dataclass, field
from math import gcd
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Metric families (docs/OBSERVABILITY.md; the tier-1 metric lint checks
# name + label sets both directions).
MOVES_METRIC = "biscotti_migration_moves_total"
MOVES_HELP = ("live peer migrations applied by the placement controller, "
              "by the dominant pressure signal that triggered the move")
DOWNTIME_METRIC = "biscotti_migration_downtime_seconds"
DOWNTIME_HELP = ("per-move wall-clock between drain start and the "
                 "relaunched incarnation's task start")
TICKET_BYTES_METRIC = "biscotti_migration_ticket_bytes"
TICKET_HELP = ("serialized migration-ticket size per move (chain suffix "
               "+ breaker/admission exports + EF residual)")


# --------------------------------------------------------------- layout


def hive_layout(num_nodes: int, num_hosts: int,
                per_host: int = 0) -> List[Tuple[int, int]]:
    """THE host layout: contiguous `(start, count)` peer ranges, one per
    host. With `per_host` pinned (pod_launch's `--peers-per-host`),
    every host gets exactly that many and the cluster size is their sum;
    otherwise `num_nodes` splits as evenly as contiguity allows (the
    first `num_nodes % num_hosts` hosts take one extra). Both the
    launcher and the overlay-group derivation consume THIS function —
    duplicating the arithmetic is how a resized host silently breaks
    the overlay's contiguous-group assumption."""
    hosts = int(num_hosts)
    if hosts < 1:
        raise ValueError("hive_layout needs >= 1 host")
    out: List[Tuple[int, int]] = []
    start = 0
    if per_host:
        for _ in range(hosts):
            out.append((start, int(per_host)))
            start += int(per_host)
        return out
    n = int(num_nodes)
    base, extra = divmod(n, hosts)
    for h in range(hosts):
        count = base + (1 if h < extra else 0)
        out.append((start, count))
        start += count
    return out


def aligned_overlay_group(layout: Sequence[Tuple[int, int]]) -> int:
    """The largest overlay group size that keeps every contiguous group
    inside one host of `layout`: the gcd of the per-host counts (group i
    spans ids [i*g, (i+1)*g), so any host boundary must be a multiple of
    g). Uniform layouts get the whole host as one group — exactly what
    pod_launch passed before — while a supervisor-resized, uneven fleet
    degrades to a smaller aligned group instead of a straddling one."""
    counts = [c for _, c in layout if c > 0]
    if not counts:
        return 1
    g = 0
    for c in counts:
        g = gcd(g, int(c))
    return max(1, g)


# ----------------------------------------------------------------- plan


@dataclass
class PlacementPlan:
    """Seeded load-aware placement (docs/PLACEMENT.md). Disabled by
    default: no controller is constructed and behavior is bit-identical
    to the static fleet."""

    enabled: bool = False
    # decision seed: `decide` is a pure function of (seed, round,
    # signals) — a failing rebalance replays from its flags
    seed: int = 0
    # decision cadence in anchor rounds, and the per-decision move cap
    interval: int = 2
    max_moves: int = 2
    # pressure thresholds; 0 disables the corresponding signal
    rss_hot_bytes: int = 0          # absolute hive RSS
    rss_drift_hot_bytes: int = 0    # windowed RSS drift (leak shape)
    lag_hot_s: float = 0.05         # hive event-loop lag
    shed_hot: float = 0.25          # admission shed fraction of frames
    slow_hot: float = 1.5           # straggler compute-factor multiple
    # never drain a hive below this many peers
    min_hive_peers: int = 1

    def validate(self) -> None:
        if not self.enabled:
            return
        if self.interval < 1:
            raise ValueError("placement_plan.interval must be >= 1")
        if self.max_moves < 1:
            raise ValueError("placement_plan.max_moves must be >= 1")
        if self.min_hive_peers < 1:
            raise ValueError("placement_plan.min_hive_peers must be >= 1")
        for name in ("rss_hot_bytes", "rss_drift_hot_bytes", "lag_hot_s",
                     "shed_hot", "slow_hot"):
            if float(getattr(self, name)) < 0:
                raise ValueError(f"placement_plan.{name} must be >= 0")


@dataclass
class HostSignals:
    """One host's observed load — every field is a signal some plane
    already exports (hive monitor gauges, admission snapshot, straggler
    profiles, trace_round critical path); the controller invents no new
    measurement, it only reads."""

    hive_id: str
    peers: Tuple[int, ...]
    rss_bytes: int = 0
    rss_drift_bytes: int = 0
    loop_lag_s: float = 0.0
    loop_lag_drift_s: float = 0.0
    shed_rate: float = 0.0            # shed frames / admitted+shed frames
    slow_factors: Dict[int, float] = field(default_factory=dict)
    critical_path_s: float = 0.0      # trace_round attribution (optional)


@dataclass(frozen=True)
class Move:
    """One placement decision: relocate `node` from hive `src` to `dst`
    because of the dominant pressure `reason`."""

    node: int
    src: str
    dst: str
    reason: str


def host_pressure(plan: PlacementPlan,
                  sig: HostSignals) -> Tuple[float, str]:
    """Composite normalized pressure of one host, with the DOMINANT
    signal named (it becomes the move's `reason` label). Each armed
    threshold contributes `observed/threshold - 1` when exceeded; an
    idle host scores <= 0. Pure arithmetic — no clocks, no randomness."""
    contributions: List[Tuple[float, str]] = []
    if plan.rss_hot_bytes > 0 and sig.rss_bytes > 0:
        contributions.append(
            (sig.rss_bytes / plan.rss_hot_bytes - 1.0, "rss"))
    if plan.rss_drift_hot_bytes > 0 and sig.rss_drift_bytes > 0:
        contributions.append(
            (sig.rss_drift_bytes / plan.rss_drift_hot_bytes - 1.0,
             "rss_drift"))
    if plan.lag_hot_s > 0 and sig.loop_lag_s > 0:
        contributions.append(
            (sig.loop_lag_s / plan.lag_hot_s - 1.0, "loop_lag"))
    if plan.shed_hot > 0 and sig.shed_rate > 0:
        contributions.append(
            (sig.shed_rate / plan.shed_hot - 1.0, "shed"))
    if plan.slow_hot > 0 and sig.slow_factors:
        worst = max(sig.slow_factors.values())
        contributions.append((worst / plan.slow_hot - 1.0, "slow"))
    if not contributions:
        return 0.0, "none"
    total = sum(max(0.0, c) for c, _ in contributions)
    dominant = max(contributions, key=lambda t: t[0])
    return (total if total > 0 else max(c for c, _ in contributions),
            dominant[1])


def decide(plan: PlacementPlan, signals: Sequence[HostSignals],
           round_idx: int) -> List[Move]:
    """The placement decision: up to `plan.max_moves` relocations from
    hot hosts to the coldest host, PURE in (plan.seed, round_idx,
    signals). Victim selection prefers the hot host's slowest peer (a
    straggler dragging a loaded host is the highest-value move); ties
    break through the seeded RNG so equal clusters still rebalance
    deterministically. A disabled plan — or a fleet with nowhere to
    move to — returns no moves."""
    if not plan.enabled or len(signals) < 2:
        return []
    rng = random.Random((int(plan.seed) * 9973 + int(round_idx)) & 0x7FFFFFFF)
    # mutable working view: peers move between hosts as moves accrue so
    # one decision point cannot overshoot into oscillation
    work = {s.hive_id: {"sig": s, "peers": list(s.peers),
                        "pressure": host_pressure(plan, s)}
            for s in signals}
    moves: List[Move] = []
    for _ in range(plan.max_moves):
        ranked = sorted(work.values(),
                        key=lambda w: (-w["pressure"][0], w["sig"].hive_id))
        hot = next((w for w in ranked
                    if w["pressure"][0] > 0
                    and len(w["peers"]) > plan.min_hive_peers), None)
        if hot is None:
            break
        cold = min((w for w in ranked if w is not hot),
                   key=lambda w: (w["pressure"][0], len(w["peers"]),
                                  w["sig"].hive_id))
        if cold["pressure"][0] >= hot["pressure"][0]:
            break  # nowhere meaningfully colder
        slow = hot["sig"].slow_factors
        worst = max((slow.get(p, 1.0) for p in hot["peers"]), default=1.0)
        candidates = [p for p in hot["peers"]
                      if slow.get(p, 1.0) >= worst] or hot["peers"]
        victim = candidates[rng.randrange(len(candidates))]
        moves.append(Move(node=int(victim), src=hot["sig"].hive_id,
                          dst=cold["sig"].hive_id,
                          reason=hot["pressure"][1]))
        hot["peers"].remove(victim)
        cold["peers"].append(victim)
        # proportional relief: shedding 1 of P peers sheds ~1/P of the
        # host's pressure (and loads the destination by the same grain)
        relief = hot["pressure"][0] / max(1, len(hot["peers"]) + 1)
        hot["pressure"] = (hot["pressure"][0] - relief, hot["pressure"][1])
        cold["pressure"] = (cold["pressure"][0] + relief,
                            cold["pressure"][1])
    return moves


# --------------------------------------------------------------- tickets


def ticket_from_agent(agent) -> Dict:
    """Serialize a LIVE peer into a migration ticket: the chain in its
    snapshot-bootstrap representation (wire.pack_chain — the PR 7 path,
    so a pruned chain migrates pruned), the breaker ledger, the
    admission buckets, the top-k error-feedback residual, and the round
    position. Identity keys are NOT in the ticket: keyed deployments
    read them from the shared key_dir and keyless ones re-derive from
    (seed, id) — a ticket on the wire must never be a key-exfiltration
    channel. Must run on the owning event loop (the chain is only ever
    mutated there, so the capture is consistent)."""
    from biscotti_tpu.runtime import wire

    cmeta, carrays = wire.pack_chain(agent.chain.blocks)
    ef = agent._ef_residual
    return {
        "node": int(agent.id),
        "iteration": int(agent.iteration),
        "pruned_weight": int(agent.chain.pruned_weight),
        "pruned_before": int(agent.chain.pruned_before),
        "membership_epoch": int(agent.membership_epoch),
        "health": agent.health.export_state(),
        "admission": agent.admission.export_state(),
        "chain_meta": cmeta,
        "chain_arrays": carrays,
        "ef_residual": None if ef is None else ef,
    }


def ticket_nbytes(ticket: Dict) -> int:
    """Wire-size estimate of one ticket: array payloads + JSON meta —
    what the `biscotti_migration_ticket_bytes` histogram observes and
    the bench's `migration_bytes` key regresses."""
    n = 0
    for arr in ticket.get("chain_arrays", {}).values():
        n += int(getattr(arr, "nbytes", 0))
    ef = ticket.get("ef_residual")
    if ef is not None:
        n += int(getattr(ef, "nbytes", 0))
    meta = {k: v for k, v in ticket.items()
            if k not in ("chain_arrays", "ef_residual")}
    n += len(json.dumps(meta, default=str).encode())
    return n


def ticket_wire(ticket: Dict) -> Tuple[Dict, Dict]:
    """Split a ticket into the (meta, arrays) shape the
    GetMigrationTicket RPC serves: arrays carry the chain payload plus
    the EF residual (when present) under a reserved key the chain codec
    never emits."""
    meta = {k: v for k, v in ticket.items()
            if k not in ("chain_arrays", "ef_residual")}
    arrays = dict(ticket.get("chain_arrays", {}))
    ef = ticket.get("ef_residual")
    if ef is not None:
        arrays["__ef_residual__"] = ef
    return meta, arrays


def ticket_unwire(meta: Dict, arrays: Dict) -> Dict:
    """Reassemble a ticket from a GetMigrationTicket reply — the
    supervisor-side inverse of `ticket_wire`."""
    arrays = dict(arrays)
    ef = arrays.pop("__ef_residual__", None)
    ticket = dict(meta)
    ticket["chain_arrays"] = arrays
    ticket["ef_residual"] = ef
    return ticket


def restore_agent(agent, ticket: Dict) -> bool:
    """Rehydrate a fresh PeerAgent from a ticket (the `ticket=`
    constructor seam): adopt the carried chain through the SAME guarded
    path a snapshot donor's reply takes (_adopt_snapshot — genesis pin,
    quorum authentication, structural verify; a forged ticket is refused
    exactly like a forged snapshot), then restore breaker state,
    admission buckets, EF residual, and the membership epoch. Returns
    True when the chain was adopted (a genesis-height ticket has nothing
    to adopt and still restores the ledgers)."""
    import numpy as np

    from biscotti_tpu.runtime import wire

    blocks = wire.unpack_chain(ticket["chain_meta"],
                               ticket["chain_arrays"])
    adopted = False
    if len(blocks) >= 2:
        adopted = agent._adopt_snapshot(
            blocks, int(ticket.get("pruned_weight", 0)),
            source=int(ticket.get("node", -1)))
    agent.health.restore_state(ticket.get("health", {}))
    agent.admission.restore_state(ticket.get("admission", {}))
    ef = ticket.get("ef_residual")
    if ef is not None:
        agent._ef_residual = np.asarray(ef)
    agent.membership_epoch = max(agent.membership_epoch,
                                 int(ticket.get("membership_epoch", 0)))
    agent._trace("migration_restored",
                 height=int(agent.chain.latest.iteration),
                 adopted=bool(adopted))
    return adopted


# ------------------------------------------------------------ controller


def default_signals(assignment: Dict[int, str],
                    agents: Dict[int, object]) -> List[HostSignals]:
    """Signals derived from live in-process agents: the hive monitor's
    shared readout (when the agents are hive-hosted), each agent's
    admission snapshot, and its seeded straggler profile. Supervisors
    scraping remote processes build HostSignals from the Metrics RPC
    instead (tools/pod_launch --supervise)."""
    by_hive: Dict[str, List[int]] = {}
    for node, hid in sorted(assignment.items()):
        by_hive.setdefault(hid, []).append(node)
    out: List[HostSignals] = []
    for hid, nodes in sorted(by_hive.items()):
        rss = drift = 0
        lag = lag_drift = 0.0
        shed = admitted = 0
        slow: Dict[int, float] = {}
        for n in nodes:
            a = agents.get(n)
            if a is None:
                continue
            info = getattr(a, "hive_info", None)
            if info:
                rss = max(rss, int(info.get("rss_bytes", 0)))
                drift = max(drift, int(info.get("rss_drift_bytes", 0)))
                lag = max(lag, float(info.get("loop_lag_s", 0.0)))
                lag_drift = max(lag_drift,
                                float(info.get("loop_lag_drift_s", 0.0)))
            snap = a.admission.snapshot()
            shed += int(snap.get("shed_total", 0))
            admitted += int(snap.get("inflight_peak", 0)) + 1
            factor = float(getattr(a.slow, "compute_factor", 1.0))
            if factor != 1.0:
                slow[n] = factor
        out.append(HostSignals(
            hive_id=hid, peers=tuple(nodes), rss_bytes=rss,
            rss_drift_bytes=drift, loop_lag_s=lag,
            loop_lag_drift_s=lag_drift,
            shed_rate=shed / max(1, shed + admitted),
            slow_factors=slow))
    return out


class PlacementController:
    """Drive a live cluster under a placement plan — the elastic-fleet
    sibling of membership.ChurnRunner (and deliberately shaped like it:
    anchor-height decision points, hard drains, fresh incarnations).

    `make_agent(node_id, hive_id, ticket)` constructs an agent for
    `node_id` placed on `hive_id`; `ticket` is None at initial launch
    and a migration ticket on every relocation (the factory passes it to
    PeerAgent(..., ticket=...) so the incarnation resumes instead of
    rejoining cold). `signals_fn(assignment, agents)` produces the
    HostSignals each decision point reads — defaulting to
    `default_signals` over the live agents; tests inject synthetic
    signal sequences through it, which is the controller seam the
    ISSUE's test satellite names."""

    def __init__(self, make_agent: Callable[[int, str, Optional[Dict]],
                                            object],
                 assignment: Dict[int, str], plan: PlacementPlan,
                 signals_fn: Optional[Callable[[Dict[int, str],
                                               Dict[int, object]],
                                              List[HostSignals]]] = None,
                 anchor: int = 0, poll_s: float = 0.1, registry=None):
        if not plan.enabled:
            # the bit-identity guard is structural: a disabled plan never
            # reaches a controller object at all
            raise ValueError("PlacementController requires an enabled "
                             "PlacementPlan (--placement)")
        self.make_agent = make_agent
        self.assignment = dict(assignment)
        self.plan = plan
        self.signals_fn = signals_fn or default_signals
        self.anchor = anchor
        self.poll_s = poll_s
        self.registry = registry
        self.moves_applied: List[Tuple[int, int, str, str]] = []
        self.downtimes_s: List[float] = []
        self.ticket_bytes: List[int] = []

    # ------------------------------------------------------------ moves

    async def _hard_kill(self, agent, task) -> None:
        task.cancel()
        try:
            await task
        except BaseException:
            pass
        agent.pool.close()
        agent.server.close_now()

    async def migrate(self, mv: Move, agents: Dict[int, object],
                      tasks: Dict[int, object], round_idx: int) -> bool:
        """Apply one move: capture the ticket from the LIVE agent (on
        the loop, so the chain view is consistent), hard-drain the old
        incarnation, relaunch on the destination with the ticket. Public
        — the mid-intake degradation tests drive this seam directly."""
        import asyncio

        agent = agents.get(mv.node)
        task = tasks.get(mv.node)
        if agent is None or task is None or task.done():
            return False
        t0 = time.monotonic()
        ticket = ticket_from_agent(agent)
        nbytes = ticket_nbytes(ticket)
        await self._hard_kill(agent, task)
        self.assignment[mv.node] = mv.dst
        agents[mv.node] = self.make_agent(mv.node, mv.dst, ticket)
        tasks[mv.node] = asyncio.ensure_future(agents[mv.node].run())
        downtime = time.monotonic() - t0
        self.moves_applied.append((int(round_idx), int(mv.node),
                                   mv.src, mv.dst))
        self.downtimes_s.append(downtime)
        self.ticket_bytes.append(nbytes)
        if self.registry is not None:
            self.registry.counter(MOVES_METRIC, MOVES_HELP).inc(
                reason=mv.reason)
            self.registry.histogram(DOWNTIME_METRIC,
                                    DOWNTIME_HELP).observe(downtime)
            self.registry.histogram(TICKET_BYTES_METRIC,
                                    TICKET_HELP).observe(float(nbytes))
        return True

    # -------------------------------------------------------------- run

    async def run(self) -> List[Dict]:
        import asyncio

        agents: Dict[int, object] = {}
        tasks: Dict[int, object] = {}
        for node, hid in sorted(self.assignment.items()):
            agents[node] = self.make_agent(node, hid, None)
            tasks[node] = asyncio.ensure_future(agents[node].run())
        next_decision = self.plan.interval
        try:
            while True:
                anchor_task = tasks.get(self.anchor)
                if anchor_task is not None and anchor_task.done():
                    break
                height = agents[self.anchor].iteration
                if height >= next_decision:
                    round_idx = next_decision
                    next_decision += self.plan.interval
                    signals = self.signals_fn(dict(self.assignment),
                                              agents)
                    for mv in decide(self.plan, signals, round_idx):
                        await self.migrate(mv, agents, tasks, round_idx)
                await asyncio.sleep(self.poll_s)
            results = await asyncio.gather(*tasks.values(),
                                           return_exceptions=True)
        except BaseException:
            for t in tasks.values():
                t.cancel()
            await asyncio.gather(*tasks.values(), return_exceptions=True)
            raise
        out = []
        for node, res in zip(tasks.keys(), results):
            if isinstance(res, BaseException):
                a = agents[node]
                out.append({"node": node, "iterations": a.iteration,
                            "converged": a.converged,
                            "chain_dump": a.chain.dump(),
                            "counters": dict(a.counters),
                            "telemetry": a.telemetry_snapshot(),
                            "killed": True})
            else:
                out.append(res)
        for r in out:
            r["hive"] = self.assignment.get(int(r["node"]))
            r["migrations"] = sum(1 for _, n, _, _ in self.moves_applied
                                  if n == int(r["node"]))
        return sorted(out, key=lambda r: int(r["node"]))

    def summary(self) -> Dict:
        """Replayable record of what the controller did — chaos/soak
        reports embed this next to the churn/upgrade timelines."""
        return {
            "enabled": True,
            "seed": self.plan.seed,
            "interval": self.plan.interval,
            "moves": [[r, n, s, d] for r, n, s, d in self.moves_applied],
            "downtime_s": [round(d, 4) for d in self.downtimes_s],
            "ticket_bytes": list(self.ticket_bytes),
            "assignment": {str(k): v
                           for k, v in sorted(self.assignment.items())},
        }
