"""Versioned protocol plane: ONE registry of every negotiated feature and
every RPC message type, with the version row each entered the protocol at.

Before this module the capability contract lived in two ad-hoc instances —
wire codecs (runtime/codecs.py, PR 3) and trace stamping (telemetry/
tracectx.py, PR 12) — each with its own copy of the legacy-hello reset
rule.  This module is now the single source of truth:

* ``FEATURES`` maps feature id -> the protocol version that introduced it.
  A peer advertises its feature set as extra tokens on the RegisterPeer
  hello's existing ``codecs`` list (old builds ignore unknown tokens and
  ``codecs.negotiate`` is all-or-raw64 over the *codec* stages only, so
  the extension is wire-compatible in both directions).
* ``MESSAGES`` maps every RPC message type -> (version, gating feature).
  The tier-1 protocol lint (tests/test_protocol_lint.py) asserts both
  tables cover the dispatch table in peer.py and docs/PROTOCOL.md —
  an unregistered frame evolution fails the suite.
* ``normalize_hello`` defines the legacy-hello reset semantics in exactly
  one place: a hello (or reply) without a well-formed capability list is
  a peer on a pre-negotiation build, and its grant collapses to
  ``LEGACY_CAPS`` (raw64 only).
* ``advertised(cfg)`` derives a config's advertised set, optionally
  pinned to a historical version row (``--protocol-version N`` = "old
  build" emulation for the mixed-version matrix and rolling upgrades).
* ``grant`` / ``degraded`` derive the per-peer negotiated set and the
  features lost against it (traced as ``feature_degraded{feature,peer}``
  and counted by the caller — see PeerAgent._record_caps).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, Optional, Sequence

from . import codecs as wcodecs

# Feature ids.  TRACE must stay equal to telemetry.tracectx.TRACE_CAP —
# guarded by tests so the two planes cannot drift apart.
RAW = wcodecs.RAW          # "raw64": the seed dialect, never negotiable away
TRACE = "trace"            # cross-peer span-context stamping (PR 12)
BUSY = "busy"              # structured admission busy-status replies (PR 4)
SNAPSHOT = "snapshot"      # pruned-chain snapshot bootstrap (PR 7)
RELAY = "relay"            # overlay relay/aggregate frames (PR 11)
PROTO = "proto"            # structured protocol-version advertisement (PR 18)
MIGRATE = "migrate"        # live-peer migration tickets (placement plane)
DKG = "dkg"                # dealerless genesis deal exchange (crypto/dkg.py)

# The grant of a peer on a pre-negotiation build (or a malformed hello).
LEGACY_CAPS: FrozenSet[str] = wcodecs.RAW_CAPS

# Metric family for features lost against a peer's advertised set
# (emitted by PeerAgent._record_caps; row in docs/OBSERVABILITY.md).
DEGRADED_METRIC = "biscotti_feature_degraded_total"
DEGRADED_HELP = ("features this node speaks that a peer's hello did not "
                 "grant (per feature, per peer; deduped per observed set)")


@dataclass(frozen=True)
class Feature:
    """One negotiated protocol feature: id + the version row it entered."""
    id: str
    version: int
    summary: str


@dataclass(frozen=True)
class Message:
    """One RPC message type: introduction version + gating feature.

    ``feature`` is empty for messages any build must serve; a non-empty
    feature means a peer whose own advertised set lacks it answers the
    message exactly like an old build: ``unknown method``.
    """
    name: str
    version: int
    feature: str
    summary: str


def _features(rows: Iterable[Feature]) -> Dict[str, Feature]:
    out: Dict[str, Feature] = {}
    for f in rows:
        if f.id in out:
            raise ValueError(f"duplicate feature id {f.id!r}")
        out[f.id] = f
    return out


FEATURES: Dict[str, Feature] = _features([
    Feature(RAW, 0, "seed base64 wire dialect (always granted)"),
    Feature("topk", 2, "top-k sparsification codec stage"),
    Feature("bf16", 2, "bfloat16 downcast codec stage"),
    Feature("f32", 2, "float32 downcast codec stage"),
    Feature("zlib", 2, "deflate codec stage"),
    Feature(wcodecs.CHUNK_CAP, 2, "chunked streaming of oversized frames"),
    Feature(BUSY, 3, "retryable busy-status shed replies"),
    Feature(SNAPSHOT, 4, "pruned-chain snapshot bootstrap for joiners"),
    Feature(RELAY, 5, "overlay relay + aggregated subtree intake"),
    Feature(TRACE, 6, "cross-peer trace-context stamping"),
    Feature(PROTO, 7, "structured protocol-version advertisement"),
    Feature(MIGRATE, 8, "live-peer migration ticket serving"),
    Feature(DKG, 8, "dealerless genesis deal exchange"),
])

MESSAGES: Dict[str, Message] = {m.name: m for m in [
    # --- version 0: the seed protocol -----------------------------------
    Message("RegisterPeer", 0, "", "membership hello; carries the capability list"),
    Message("RegisterBlock", 0, "", "full block push"),
    Message("AdvertiseBlock", 0, "", "block digest advertisement"),
    Message("GetBlock", 0, "", "block pull by iteration"),
    Message("RegisterUpdate", 0, "", "plain-mode worker update submission"),
    Message("RegisterSecret", 0, "", "secure-agg share submission"),
    Message("RegisterDecline", 0, "", "worker round decline"),
    Message("RequestNoise", 0, "", "peer noise-vector pull"),
    Message("VerifyUpdateKRUM", 0, "", "KRUM verification request"),
    Message("VerifyUpdateRONI", 0, "", "RONI verification request"),
    Message("GetUpdateList", 0, "", "miner accepted-update list pull"),
    Message("GetMinerPart", 0, "", "miner partial-aggregate pull"),
    # --- version 1: telemetry plane (PR 2) ------------------------------
    Message("Metrics", 1, "", "read-only metrics/trace-tail scrape"),
    # --- version 4: dynamic membership (PR 7) ---------------------------
    Message("GetSnapshot", 4, SNAPSHOT, "pruned-chain bootstrap pull"),
    Message("GetReshareDeal", 4, SNAPSHOT, "verifiable re-deal collection"),
    # --- version 5: aggregation overlay (PR 11) -------------------------
    Message("OverlayOffer", 5, RELAY, "subtree share hand-off to the relay"),
    Message("RegisterAggregate", 5, RELAY, "summed subtree intake at the miner"),
    Message("RelayFrames", 5, RELAY, "verbatim frame relay across one tree hop"),
    # --- version 8: elastic fleet plane (placement + genesis DKG) -------
    Message("GetMigrationTicket", 8, MIGRATE,
            "serialize a live peer for relocation (placement controller)"),
    Message("DkgDeal", 8, DKG,
            "Pedersen-committed genesis deal delivery/verification"),
]}

CURRENT_VERSION: int = max(
    max(f.version for f in FEATURES.values()),
    max(m.version for m in MESSAGES.values()),
)


def version_row(version: int) -> FrozenSet[str]:
    """Every feature available at ``version`` (the cumulative row)."""
    if not 0 <= version <= CURRENT_VERSION:
        raise ValueError(
            f"protocol version {version} outside [0, {CURRENT_VERSION}]")
    return frozenset(f.id for f in FEATURES.values() if f.version <= version)


def effective_version(cfg) -> int:
    """The version a config speaks: CURRENT unless pinned to an old row."""
    pin = getattr(cfg, "protocol_version", -1)
    return CURRENT_VERSION if pin < 0 else pin


def advertised(cfg) -> FrozenSet[str]:
    """The feature set a config advertises on its RegisterPeer hello.

    The version row caps what MAY be advertised; the config gates what
    IS: codec stages follow ``wire_codec``, trace follows ``cfg.trace``,
    relay follows ``cfg.overlay``.  busy/snapshot/proto are capability
    statements about the build, not the config, so they ride every row
    that contains them.
    """
    row = version_row(effective_version(cfg))
    out = {RAW}
    out |= wcodecs.capabilities(cfg.wire_codec) & row
    if getattr(cfg, "trace", False):
        out |= {TRACE} & row
    if getattr(cfg, "overlay", False):
        out |= {RELAY} & row
    out |= {BUSY, SNAPSHOT, PROTO, MIGRATE, DKG} & row
    return frozenset(out)


def normalize_hello(caps) -> FrozenSet[str]:
    """THE legacy-hello reset rule (one definition for every family).

    A well-formed capability list round-trips; anything else — absent
    key, None, scalar junk — is a peer on a pre-negotiation build and
    resets the grant to ``LEGACY_CAPS``.  A restarted legacy incarnation
    therefore stops receiving coded/stamped/relayed frames instead of
    breaking its link forever.
    """
    if isinstance(caps, (list, tuple, set, frozenset)):
        return frozenset(str(c) for c in caps)
    return LEGACY_CAPS


def grant(own: FrozenSet[str],
          recorded: Optional[FrozenSet[str]]) -> FrozenSet[str]:
    """The negotiated per-peer feature set: own ∩ theirs (raw64 floor).

    ``recorded is None`` means no hello yet — assume a legacy build.
    """
    if recorded is None:
        recorded = LEGACY_CAPS
    return (own & recorded) | {RAW}


def degraded(own: FrozenSet[str],
             recorded: Optional[FrozenSet[str]]) -> FrozenSet[str]:
    """Features this node speaks that the peer's hello did not grant."""
    return frozenset(own - grant(own, recorded) - {RAW})


def serves(own: FrozenSet[str], msg_type: str) -> bool:
    """Whether a build advertising ``own`` serves ``msg_type`` at all.

    Unregistered message types are served (the dispatch table is the
    authority for those — and the protocol lint fails the suite if one
    exists); feature-gated messages follow the own-build feature, so a
    ``--protocol-version`` pin answers them exactly like the old build:
    unknown method.
    """
    m = MESSAGES.get(msg_type)
    return m is None or not m.feature or m.feature in own


def snapshot(cfg, own: FrozenSet[str],
             degraded_by_peer: Dict[int, FrozenSet[str]]) -> dict:
    """The telemetry readout: version, advertised set, degradations."""
    return {
        "version": effective_version(cfg),
        "current": CURRENT_VERSION,
        "advertised": sorted(own),
        "degraded": {int(p): sorted(f)
                     for p, f in sorted(degraded_by_peer.items()) if f},
    }
