"""Asyncio RPC layer: pooled multiplexed connections, per-call timeouts,
typed errors.

Mirrors the reference's transport semantics (SURVEY.md §2.1 row 2, §5.8):
  * per-call `select{reply, timeout}` guard (ref: DistSys/main.go:1447-1489)
    — every call wraps its roundtrip in `asyncio.wait_for`
  * the callee can reply with a *stale* error that callers treat as a
    signal, not a failure (ref: DistSys/main.go:140,380-383 staleError)
  * dead peers surface as TimeoutError/ConnectionError so the membership
    layer can evict them (ref: main.go:1468-1487)

Design departure from the reference, on purpose: the reference dials a
fresh TCP connection for every RPC (`rpc.Dial` per call) — at N=100 full
mesh that is thousands of handshakes per round and was a scale bottleneck.
Here each peer keeps ONE persistent connection per (host, port), and
concurrent calls multiplex over it with request-id correlation (`rid`);
a timed-out call abandons its future while the connection stays usable
(late replies to abandoned rids are dropped). Connection failure fails all
in-flight calls on it and redials lazily on next use.

Chaos plane: `Pool.faults` optionally holds a `faults.FaultInjector`; every
outbound frame (calls AND fire-and-forget posts) then gets a deterministic
drop/delay/duplicate/reset decision keyed on (src, dst, msg_type, attempt),
applied at the `_Conn` boundary so real TCP traffic is perturbed (see
faults.py and docs/FAULT_PLANE.md).

Server side: one asyncio task per connection, frames dispatched to a single
handler coroutine `handle(msg_type, meta, arrays) -> (meta, arrays)`.
Handlers may block (e.g. a verifier parking a caller until the round's Krum
resolves, ref: DistSys/krum.go:330-336) — each request runs as its own task
so a parked call never stalls the connection's other requests, and replies
carry the request's rid so out-of-order completion is fine.
"""

from __future__ import annotations

import asyncio
import contextlib
import errno
import struct
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import numpy as np

from biscotti_tpu.runtime import codecs as wcodecs
from biscotti_tpu.runtime import messages as msgs

_U32 = struct.Struct(">I").unpack

Handler = Callable[
    [str, Dict[str, Any], Dict[str, np.ndarray]],
    Awaitable[Tuple[Dict[str, Any], Dict[str, np.ndarray]]],
]


class RPCError(RuntimeError):
    """Remote handler returned an error (meta carries the reason)."""

    def __init__(self, reason: str, stale: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.stale = stale


class StaleError(RPCError):
    """The callee is past this message's iteration (ref: main.go:380-383)."""

    def __init__(self, reason: str = "stale iteration"):
        super().__init__(reason, stale=True)


class BusyError(RPCError):
    """Retryable overload signal (wire meta: {"error": ..., "busy": true}):
    the callee SHED this request under its admission plan rather than
    queue it without bound (runtime/admission.py, docs/ADMISSION.md).
    Unlike a transport failure it proves the peer alive and healthy —
    clients retry with backoff and must NOT advance the circuit breaker
    (a busy honest peer must never get quarantined)."""

    def __init__(self, reason: str = "server busy"):
        super().__init__(reason)
        self.busy = True


class FrameStream(asyncio.BufferedProtocol):
    """Framed connection over asyncio's zero-copy receive path.

    StreamReader's readexactly accumulates every incoming chunk into its
    internal bytearray and then slices the frame back out — at CNN dims
    (10.5 MB commitment grids × W workers × M miners per round) that
    buffer churn profiled as the single largest non-crypto cost of a
    round (~10 s per 3 rounds at N=30). BufferedProtocol instead asks US
    for the receive buffer: once a frame's length prefix is parsed, the
    payload bytes land directly in that frame's own preallocated
    bytearray (one copy, kernel→frame), which the codec then wraps
    zero-copy. Header bytes and small frames assemble through a bounded
    scratch (≤64 KiB extra copy per frame).

    Back-pressure both ways: ≥8 parsed-but-unconsumed frames pauses the
    transport's reading; writes respect pause_writing via `drain()`.

    Chunked streaming (docs/WIRE_PLANE.md): a payload beginning with
    messages.CHUNK_MAGIC is a continuation chunk — its body is appended
    to the in-progress reassembly buffer instead of being queued, and
    the final chunk (flags bit 0) releases the whole reassembled payload
    as ONE frame. MAX_FRAME is enforced on the REASSEMBLED size, and the
    buffer grows with the bytes actually received, so peak allocation
    tracks real traffic instead of a hostile length prefix.
    """

    _SCRATCH = 65536
    _QUEUE_HIGH = 8
    _CLOSED = object()  # queue sentinel

    def __init__(self, on_connected=None, read_deadline: float = 0.0):
        self.transport: Optional[asyncio.Transport] = None
        self._on_connected = on_connected
        self._acc = bytearray()
        self._scratch = bytearray(self._SCRATCH)
        self._payload: Optional[bytearray] = None
        self._got = 0
        self._need = 0
        self._reasm: Optional[bytearray] = None  # chunk reassembly buffer
        self._frames: asyncio.Queue = asyncio.Queue()
        self._exc: Optional[Exception] = None
        self._closed = False
        self._read_paused = False
        self._w_waiters: list = []
        self._w_paused = False
        # read/header deadline (admission plane, docs/ADMISSION.md):
        # once a frame STARTS — a header byte, a partial payload, an
        # unfinished chunk-reassembly run — it must COMPLETE within this
        # many seconds or the connection is dropped. Progress-per-byte
        # deliberately does NOT reset the clock (a slow-loris dribbling
        # one header byte per tick would otherwise pin the connection
        # and its reassembly buffer forever), but each COMPLETED frame —
        # including every continuation chunk of a reassembly run — does:
        # a legitimate chunked multi-MB transfer only needs one chunk
        # per window, while a dribbler must pay a full frame per window.
        # Time spent with reading PAUSED by our own backpressure also
        # counts as progress — the peer must not be blamed for our
        # queue. 0 disables (client default).
        self._read_deadline = float(read_deadline)
        self._frame_t0: Optional[float] = None
        self._deadline_handle = None
        self._progress_seq = 0  # bumped per completed frame/chunk

    # ------------------------------------------------ protocol callbacks

    def connection_made(self, transport) -> None:
        self.transport = transport
        if self._read_deadline > 0:
            loop = asyncio.get_running_loop()
            self._deadline_handle = loop.call_later(
                self._read_deadline / 2, self._deadline_tick)
        if self._on_connected is not None:
            asyncio.get_running_loop().create_task(self._on_connected(self))

    def _mid_frame(self) -> bool:
        return (self._payload is not None or len(self._acc) > 0
                or self._reasm is not None)

    def _mark_frame_progress(self, completed: bool) -> None:
        """Called after every receive/parse step: start the per-frame
        deadline clock when partial state appears, restart it whenever a
        frame or continuation chunk COMPLETED, clear it when the stream
        is back at a frame boundary."""
        if self._read_deadline <= 0:
            return
        if not self._mid_frame():
            self._frame_t0 = None
        elif completed or self._frame_t0 is None:
            self._frame_t0 = asyncio.get_running_loop().time()

    def _deadline_tick(self) -> None:
        if self._closed or self.transport is None:
            return
        loop = asyncio.get_running_loop()
        if self._read_paused and self._frame_t0 is not None:
            # WE paused reading (queue backpressure): the peer cannot
            # make progress — don't bill it for our slowness
            self._frame_t0 = loop.time()
        if (self._frame_t0 is not None
                and loop.time() - self._frame_t0 >= self._read_deadline):
            self._protocol_error(ConnectionError(
                "read deadline: frame incomplete after "
                f"{self._read_deadline:.1f}s"))
            return
        self._deadline_handle = loop.call_later(
            self._read_deadline / 2, self._deadline_tick)

    def get_buffer(self, sizehint: int) -> memoryview:
        if self._payload is not None:
            return memoryview(self._payload)[self._got:]
        return memoryview(self._scratch)

    def buffer_updated(self, nbytes: int) -> None:
        seq0 = self._progress_seq
        if self._payload is not None:
            self._got += nbytes
            if self._got >= self._need:
                payload = self._payload
                self._payload = None
                self._got = self._need = 0
                self._enqueue(payload)
            self._mark_frame_progress(self._progress_seq != seq0)
            return
        self._acc += memoryview(self._scratch)[:nbytes]
        self._drain_acc()
        self._mark_frame_progress(self._progress_seq != seq0)

    def _drain_acc(self) -> None:
        while True:
            if len(self._acc) < 4:
                return
            (n,) = _U32(self._acc[:4])
            if n > msgs.MAX_FRAME:
                self._protocol_error(
                    ConnectionError("frame length exceeds cap"))
                return
            if len(self._acc) - 4 >= n:
                frame = bytes(self._acc[4: 4 + n])
                del self._acc[: 4 + n]
                self._enqueue(frame)
                continue
            # large frame: preallocate and let the transport fill it
            self._need = n
            self._payload = bytearray(n)
            body = memoryview(self._acc)[4:]
            self._payload[: len(body)] = body
            self._got = len(body)
            self._acc = bytearray()
            return

    def _enqueue(self, frame) -> None:
        self._progress_seq += 1  # a complete frame payload (or chunk)
        if (len(frame) >= msgs.CHUNK_OVERHEAD
                and bytes(memoryview(frame)[:4]) == msgs.CHUNK_MAGIC):
            # continuation chunk: accumulate; only the final chunk of the
            # run surfaces as a frame (cap checked on the reassembled size)
            buf = self._reasm if self._reasm is not None else bytearray()
            body = memoryview(frame)[msgs.CHUNK_OVERHEAD:]
            if len(buf) + len(body) > msgs.MAX_FRAME:
                self._reasm = None
                self._protocol_error(
                    ConnectionError("reassembled frame exceeds cap"))
                return
            buf += body
            if not (frame[4] & msgs.CHUNK_LAST):
                self._reasm = buf
                return
            self._reasm = None
            frame = buf
        self._frames.put_nowait(frame)
        if (not self._read_paused
                and self._frames.qsize() >= self._QUEUE_HIGH
                and self.transport is not None):
            try:
                self.transport.pause_reading()
                self._read_paused = True
            except RuntimeError:
                pass

    def _protocol_error(self, exc: Exception) -> None:
        self._exc = exc
        if self.transport is not None:
            self.transport.close()

    def connection_lost(self, exc) -> None:
        self._closed = True
        if self._deadline_handle is not None:
            self._deadline_handle.cancel()
            self._deadline_handle = None
        if self._exc is None:
            self._exc = exc or ConnectionError("connection closed")
        self._frames.put_nowait(self._CLOSED)
        for w in self._w_waiters:
            if not w.done():
                w.set_exception(self._exc)
                w.exception()  # mark retrieved
        self._w_waiters.clear()

    def pause_writing(self) -> None:
        self._w_paused = True

    def resume_writing(self) -> None:
        self._w_paused = False
        for w in self._w_waiters:
            if not w.done():
                w.set_result(None)
        self._w_waiters.clear()

    # ------------------------------------------------------- public API

    @property
    def alive(self) -> bool:
        return (self.transport is not None and not self._closed
                and not self.transport.is_closing())

    async def next_frame(self):
        """One frame payload (bytes for small frames, bytearray for
        direct-filled large ones); raises on EOF/protocol error."""
        if self._read_paused and self._frames.qsize() < self._QUEUE_HIGH:
            try:
                self.transport.resume_reading()
                self._read_paused = False
            except RuntimeError:
                pass
        frame = await self._frames.get()
        if frame is self._CLOSED:
            self._frames.put_nowait(self._CLOSED)  # keep EOF sticky
            raise (self._exc
                   if self._exc is not None
                   else ConnectionError("connection closed"))
        return frame

    def write_parts(self, parts) -> None:
        if self.transport is None or self.transport.is_closing():
            raise ConnectionError("connection closed")
        for p in parts:
            self.transport.write(p)

    async def drain(self) -> None:
        if not self._w_paused or self._closed:
            return
        w = asyncio.get_running_loop().create_future()
        self._w_waiters.append(w)
        await w

    def close(self) -> None:
        if self.transport is not None:
            self.transport.close()


async def open_frame_stream(host: str, port: int,
                            avoid_local_ports=frozenset()) -> FrameStream:
    """Dial a peer. `avoid_local_ports` lists LISTEN ports of the local
    cluster: on hosts whose ephemeral range covers the protocol ports
    (ip_local_port_range 16000-65535 here), the kernel can hand an
    outbound socket the very source port a co-hosted peer needs to bind
    — and a pooled connection then squats on it for the whole run. When
    the assigned source port is one of those, redial; the doomed sockets
    are held until a clean one lands so the kernel cannot re-deal the
    same port, then closed."""
    loop = asyncio.get_running_loop()
    doomed = []
    try:
        for attempt in range(16):
            tr, proto = await loop.create_connection(lambda: FrameStream(),
                                                     host, port)
            sockname = tr.get_extra_info("sockname")
            if (not avoid_local_ports or sockname is None
                    or sockname[1] not in avoid_local_ports
                    or attempt == 15):  # budget spent: squat over failure
                return proto
            doomed.append(tr)
        raise AssertionError("unreachable")
    finally:
        for tr in doomed:
            tr.close()


class RPCServer:
    def __init__(self, host: str, port: int, handler: Handler):
        self.host = host
        self.port = port
        self.handler = handler
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()
        # wire-plane knobs, set by the owning peer: `caps` bounds which
        # reply codecs a caller's `acodec` request may select (defaults
        # to legacy raw64-only so a bare RPCServer behaves like the
        # seed); `metrics` ticks inbound/outbound byte counters
        self.caps = wcodecs.RAW_CAPS
        self.metrics = None
        # protocol plane (runtime/protocol.py): whether shed replies
        # carry the structured retryable `busy` status. True by default
        # (bare harness servers keep today's behavior); the owning peer
        # clears it when a --protocol-version pin predates the busy
        # feature, emulating the old build's plain-error shed reply.
        self.busy_status = True
        # overload-governance knobs (runtime/admission.py), set by the
        # owning peer when its AdmissionPlan is enabled: `admission` is
        # the AdmissionController consulted per decoded frame (None =
        # admit everything, the seed behavior); `read_deadline` arms
        # FrameStream's mid-frame deadline on inbound connections
        self.admission = None
        self.read_deadline = 0.0
        # distributed tracing (telemetry/tracectx.py, docs/OBSERVABILITY.md
        # §Distributed tracing): when the owning peer armed tracing, this
        # holds its Telemetry and every dispatched RPC runs inside a
        # child span adopted from the frame's wire context — the
        # receiver half of the cross-peer causal link. None (default) =
        # the seed dispatch path, span-free.
        self.telemetry = None
        # straggler plane (runtime/stragglers.py, docs/STRAGGLERS.md):
        # extra per-RPC service delay charged before every handler
        # dispatch when this peer carries a slow speed profile. Owned by
        # the TRANSPORT seam (here and mirrored by the hive loopback
        # dispatch) so TCP and co-hosted layouts serve identically slow.
        self.service_delay_s = 0.0

    async def start(self, bind_budget_s: float = 10.0) -> None:
        """Bind the listen socket, retrying transient EADDRINUSE.

        On hosts whose ephemeral range covers the protocol ports (this
        box: ip_local_port_range 16000-65535, protocol ports 8000+/25xxx
        in harnesses), any peer's OUTBOUND connection can be randomly
        assigned the very source port another peer is about to LISTEN
        on; SO_REUSEADDR does not help against an active socket. The
        collision is transient — the client socket moves on within the
        connection's lifetime — so a brief retry turns a startup crash
        into a short delay. A port genuinely held by another server
        still fails, after `bind_budget_s`."""
        loop = asyncio.get_running_loop()
        deadline = time.monotonic() + bind_budget_s
        while True:
            try:
                self._server = await loop.create_server(
                    lambda: FrameStream(on_connected=self._on_conn,
                                        read_deadline=self.read_deadline),
                    self.host, self.port)
                return
            except OSError as e:
                if e.errno != errno.EADDRINUSE \
                        or time.monotonic() >= deadline:
                    raise
                await asyncio.sleep(0.2)

    @property
    def serving(self) -> bool:
        """True while the listen socket would accept a connection — the
        hive's loopback endpoints share this lifecycle (a closed peer's
        co-hosted callers must get connection-refused, not delivery)."""
        return self._server is not None and self._server.is_serving()

    def close_now(self) -> None:
        """Synchronous teardown: release the LISTENING socket immediately
        and cancel live handlers, without awaiting wait_closed(). For
        exception/cancellation paths that cannot await — leaving the
        listen fd to garbage collection keeps the port bound for an
        unbounded grace period (observed as address-already-in-use
        flakes when back-to-back harness clusters reuse a port)."""
        if self._server is not None:
            self._server.close()
        for t in list(self._conn_tasks):
            t.cancel()

    async def stop(self) -> None:
        # cancel live connection handlers BEFORE wait_closed(): since 3.12
        # wait_closed waits for every handler to finish, and handlers on
        # persistent pooled connections run until the remote side closes —
        # waiting first would deadlock two peers stopping simultaneously
        self.close_now()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass

    @staticmethod
    def _admit_key(stream: FrameStream):
        """Budget key for one inbound frame: the CONNECTION identity
        (transport peername), never the frame's claimed `source_id` —
        meta is unauthenticated, so keying on the claimed id would let a
        Byzantine peer spoof a victim's id and drain the victim's
        buckets, starving its legitimate traffic. The peername is
        TCP-level and unspoofable; honest peers multiplex everything
        over ONE pooled connection, so per-connection IS per-peer for
        them, while a Byzantine peer fanning out connections is bounded
        by the controller's bucket-table cap and the global inflight
        cap."""
        peername = (stream.transport.get_extra_info("peername")
                    if stream.transport is not None else None)
        return ("conn", peername if peername is not None else id(stream))

    def _shed_reply(self, msg_type, meta, reason, stream):
        """Busy reply for a shed reply-bearing call — small, encoded
        inline, and NOT drained: a flooder that refuses to read its own
        busy replies must not be able to park the read loop on its
        socket's backpressure. Once the transport signals pause_writing
        (the peer stopped draining), further notifications are DROPPED
        instead of buffered — otherwise the reply path itself would be
        the unbounded-memory vector this plane exists to close; the
        peer's calls simply time out, which under overload is truthful.
        Safe without the write lock: write_parts is synchronous, so
        frames never interleave — the lock only orders write+drain
        pairs for handler replies."""
        rid = meta.get("rid")
        if not rid:
            return  # fire-and-forget: nobody is waiting for a reply
        if stream._w_paused or not stream.alive:
            return  # peer not draining: drop the notification
        reply = {"error": f"admission shed: {reason}", "rid": rid}
        if self.busy_status:
            reply["busy"] = True
        parts = msgs.encode_parts(msg_type + ".reply", reply, {})
        try:
            stream.write_parts(parts)
        except (ConnectionError, OSError):
            pass

    async def _on_conn(self, stream: FrameStream) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        write_lock = asyncio.Lock()
        pending: set = set()
        try:
            while True:
                try:
                    payload = await stream.next_frame()
                except (ConnectionError, OSError):
                    break
                key = None
                if self.admission is not None:
                    # overload governance (docs/ADMISSION.md): the frame
                    # is budgeted on its PEEKED header alone — over-budget
                    # work is SHED with a retryable busy status BEFORE
                    # paying the full decode (array materialization, zlib
                    # inflate), so a flood's per-frame cost to this peer
                    # is one small JSON parse, not a decompression
                    peek = msgs.peek_header(payload)
                    if peek is None:
                        break  # malformed header: drop the connection
                    msg_type, pmeta = peek
                    if self.metrics is not None:
                        self.metrics.counter(
                            wcodecs.WIRE_BYTES_METRIC,
                            wcodecs.WIRE_BYTES_HELP).inc(
                            len(payload), msg_type=msg_type,
                            direction="in",
                            codec=pmeta.get("_wire_codec", wcodecs.RAW))
                    key = self._admit_key(stream)
                    reason = self.admission.try_admit(key, msg_type)
                    if reason is not None:
                        self._shed_reply(msg_type, pmeta, reason, stream)
                        continue
                try:
                    msg_type, meta, arrays = msgs.decode(payload)
                except msgs.CodecError:
                    if key is not None:
                        self.admission.release(key)
                    break  # hostile/garbled peer: drop the connection
                if self.admission is None and self.metrics is not None:
                    self.metrics.counter(
                        wcodecs.WIRE_BYTES_METRIC,
                        wcodecs.WIRE_BYTES_HELP).inc(
                        len(payload), msg_type=msg_type, direction="in",
                        codec=meta.get("_wire_codec", wcodecs.RAW))
                t = asyncio.create_task(self._dispatch(
                    msg_type, meta, arrays, stream, write_lock))
                if key is not None:
                    t.add_done_callback(
                        lambda _t, k=key: self.admission.release(k))
                pending.add(t)
                t.add_done_callback(pending.discard)
        finally:
            for t in pending:
                t.cancel()
            stream.close()
            self._conn_tasks.discard(task)

    async def _dispatch(self, msg_type, meta, arrays, stream, write_lock):
        rid = meta.get("rid")
        try:
            if self.service_delay_s > 0.0:
                # slow-peer service emulation (docs/STRAGGLERS.md): a
                # confidential-compute / overloaded host takes longer to
                # SERVE each request — charged here, after admission
                # (shedding stays cheap) and before the handler, so the
                # caller's observed latency grows exactly like a genuinely
                # slow service's would
                await asyncio.sleep(self.service_delay_s)
            span = (self.telemetry.rpc_span(msg_type, meta)
                    if self.telemetry is not None
                    else contextlib.nullcontext())
            with span:
                rmeta, rarrays = await self.handler(msg_type, meta, arrays)
        except StaleError as e:
            rmeta, rarrays = {"error": e.reason, "stale": True}, {}
        except BusyError as e:
            # a handler shed mid-flight (e.g. its parked wait was evicted
            # by the parking cap): same retryable wire status as a
            # boundary shed
            rmeta, rarrays = {"error": e.reason, "busy": True}, {}
        except RPCError as e:
            rmeta, rarrays = {"error": e.reason}, {}
        except asyncio.CancelledError:
            raise
        except Exception as e:  # handler bug: report, don't kill the peer
            rmeta, rarrays = {"error": f"internal: {type(e).__name__}: {e}"}, {}
        rmeta = dict(rmeta)
        rmeta["rid"] = rid
        # reply codec: honour the caller's `acodec`/`achunk` request iff
        # every stage sits inside OUR advertised capability set (a
        # raw64-configured peer ignores both — legacy emulation), with a
        # hard floor on chunk size so a hostile achunk cannot shatter a
        # reply into per-byte frames
        codec = wcodecs.negotiate(str(meta.get("acodec") or ""), self.caps)
        achunk = 0
        if wcodecs.CHUNK_CAP in self.caps:
            try:
                achunk = int(meta.get("achunk", 0) or 0)
            except (TypeError, ValueError):
                achunk = 0
            achunk = 0 if achunk <= 0 else max(achunk, msgs.MIN_CHUNK)
        stats: Optional[dict] = {} if self.metrics is not None else None
        try:
            parts = msgs.encode_parts(
                msg_type + ".reply", rmeta, rarrays,
                codec=None if codec == wcodecs.RAW else codec,
                chunk_bytes=achunk, stats=stats)
        except msgs.CodecError:
            # a coded reply that fails to encode must not eat the reply:
            # fall back to the legacy raw frame
            parts = msgs.encode_parts(msg_type + ".reply", rmeta, rarrays,
                                      stats=stats)
            codec = wcodecs.RAW
        if self.metrics is not None:
            eff = stats.get("codec", wcodecs.RAW)
            self.metrics.counter(wcodecs.WIRE_BYTES_METRIC,
                                 wcodecs.WIRE_BYTES_HELP).inc(
                stats["wire_bytes"], msg_type=msg_type + ".reply",
                direction="out", codec=eff)
            wcodecs.observe_ratio(self.metrics, eff,
                                  stats["raw_bytes"], stats["wire_bytes"])
        async with write_lock:
            try:
                stream.write_parts(parts)
                await stream.drain()
            except (ConnectionError, OSError):
                pass


class _Conn:
    """One persistent multiplexed client connection."""

    def __init__(self, stream: FrameStream):
        self.stream = stream
        self.pending: Dict[int, asyncio.Future] = {}
        self.next_rid = 1
        self.write_lock = asyncio.Lock()
        self.sending = 0  # in-flight fire-and-forget writes (see _send)
        self.metrics = None  # set by Pool: inbound reply byte accounting
        self.reader_task = asyncio.create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                payload = await self.stream.next_frame()
                try:
                    mtype, rmeta, rarrays = msgs.decode(payload)
                except msgs.CodecError:
                    break  # garbled peer: tear the connection down
                if self.metrics is not None:
                    self.metrics.counter(
                        wcodecs.WIRE_BYTES_METRIC,
                        wcodecs.WIRE_BYTES_HELP).inc(
                        len(payload), msg_type=mtype, direction="in",
                        codec=rmeta.get("_wire_codec", wcodecs.RAW))
                fut = self.pending.pop(rmeta.get("rid"), None)
                if fut is not None and not fut.done():
                    fut.set_result((rmeta, rarrays))
                # unknown rid: reply to an abandoned (timed-out) call — drop
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        finally:
            self._fail_all(ConnectionError("connection lost"))
            self.stream.close()

    def _fail_all(self, exc: Exception) -> None:
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
                fut.exception()  # mark retrieved: abandoned callers are fine
        self.pending.clear()

    @property
    def alive(self) -> bool:
        return not self.reader_task.done() and self.stream.alive

    async def _send_parts(self, parts, timeout: float, fault=None) -> None:
        """Part-wise bounded write (see _send): each buffer goes to the
        transport as-is — large array payloads ride their memoryviews
        straight from the codec with no event-loop flattening copy.

        `fault` (a faults.FaultAction, None when the fault plane is off)
        perturbs THIS frame at the connection boundary: a reset tears the
        shared multiplexed connection down mid-flight (all in-flight calls
        fail, next use redials), a delay holds the frame before the write,
        a drop consumes it before the socket (the caller's await then times
        out, exactly as if the network ate it), a duplicate writes the same
        bytes twice back-to-back (receiver-idempotency exercise)."""
        self.sending += 1
        try:
            if fault is not None and not fault.benign:
                if fault.reset:
                    self.close()
                    raise ConnectionError("fault injection: connection reset")
                if fault.delay_s > 0.0:
                    await asyncio.sleep(fault.delay_s)
                if fault.drop:
                    return  # frame lost before the wire
            async with self.write_lock:
                t0 = asyncio.get_running_loop().time()
                self.stream.write_parts(parts)
                await asyncio.wait_for(self.stream.drain(), timeout)
                if fault is not None and fault.duplicate:
                    # the duplicate rides the SAME budget as the original:
                    # a fresh full timeout here would let one faulted frame
                    # hold the shared write_lock ~2x the bound and push
                    # every queued sender past its own deadline
                    left = max(0.001, timeout - (
                        asyncio.get_running_loop().time() - t0))
                    self.stream.write_parts(parts)
                    await asyncio.wait_for(self.stream.drain(), left)
                if fault is not None and fault.flood > 0:
                    # frame storm: replay the same bytes `flood` more
                    # times back-to-back — this peer becomes a seeded
                    # flooder sustaining (1+flood)x the honest frame rate
                    # on this link. The storm shares the ORIGINAL frame's
                    # timeout budget; replays that outrun it (a receiver
                    # exerting backpressure) are abandoned, exactly like
                    # a real flooder hitting a full socket.
                    for _ in range(fault.flood):
                        left = max(0.001, timeout - (
                            asyncio.get_running_loop().time() - t0))
                        self.stream.write_parts(parts)
                        await asyncio.wait_for(self.stream.drain(), left)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            self.close()
            raise
        finally:
            self.sending -= 1

    async def _send(self, frame: bytes, timeout: float, fault=None) -> None:
        """Bounded write: a peer that stops draining (full receive buffer,
        long GIL hold) must not wedge the write lock forever — on timeout
        the connection is torn down so queued callers fail fast and the
        next use redials. `sending` marks the conn busy for the pool's LRU
        eviction: fire-and-forget posts (rid 0) never register in
        `pending`, so without it a broadcast fanning out past the pool cap
        evicts its own conns MID-DRAIN and silently drops frames — at
        N=100 that lost the minted block for every peer beyond the cap."""
        await self._send_parts([frame], timeout, fault=fault)

    async def roundtrip(self, msg_type, meta, arrays, timeout, fault=None,
                        codec=None, chunk_bytes=0, account=None):
        rid = self.next_rid
        self.next_rid += 1
        fut = asyncio.get_running_loop().create_future()
        self.pending[rid] = fut
        meta2 = dict(meta or {})
        meta2["rid"] = rid
        stats: Optional[dict] = {} if account is not None else None
        parts = msgs.encode_parts(msg_type, meta2, arrays, codec=codec,
                                  chunk_bytes=chunk_bytes, stats=stats)
        deadline = asyncio.get_running_loop().time() + timeout
        try:
            await self._send_parts(parts, timeout, fault=fault)
            if account is not None:
                # counted once the transport accepted the frame (an
                # injected drop still counts: the peer DID spend the
                # encode and hand the bytes over)
                account(stats)
            remaining = max(0.001, deadline - asyncio.get_running_loop().time())
            return await asyncio.wait_for(fut, remaining)
        finally:
            self.pending.pop(rid, None)

    def close(self) -> None:
        self.reader_task.cancel()
        self.stream.close()


class Pool:
    """Per-agent connection pool: one persistent connection per (host,
    port), multiplexing concurrent calls (see module docstring).

    LRU-capped: a peer's working set is small (its committees, the
    leader, gossip targets), but the bootstrap announce dials EVERY peer
    once — without a cap the cluster holds O(N²) sockets and blows the
    file-descriptor limit around N≈100–120 (observed: 'Too many open
    files' at N=120 under the default 20k ulimit, ≈2·N² fds). Idle
    least-recently-used connections are closed beyond `max_conns`;
    in-flight ones are never evicted, and the next use simply redials."""

    def __init__(self, max_conns: int = 32,
                 latency: Optional[Callable[[str, int], float]] = None):
        from collections import OrderedDict

        self._conns: "OrderedDict[Tuple[str, int], _Conn]" = OrderedDict()
        self._dialing: Dict[Tuple[str, int], asyncio.Task] = {}
        self.max_conns = max_conns
        # Optional per-link latency model (host, port) -> seconds, applied
        # to every call/post toward that link: the WAN/geo harness runs
        # loopback clusters with the reference's multi-region operating
        # point (ref: global-deploy-eval, multi-DC Azure) by charging each
        # cross-"region" RPC its round-trip here. None = loopback (no-op).
        self.latency = latency
        # Optional deterministic fault plane (faults.FaultInjector): when
        # set, every outbound frame's fate — drop/delay/duplicate/reset —
        # is decided per (src, dst, msg_type, attempt) and applied at the
        # _Conn boundary so real TCP traffic is perturbed, not mocked.
        self.faults = None
        # Optional telemetry registry (telemetry.MetricsRegistry): when
        # set, every call/post ticks per-msg_type frame counters and
        # reply-bearing calls feed a client-side latency histogram —
        # round latency becomes attributable to transport vs. compute
        # per link (the Garfield-style breakdown, PAPERS.md).
        self.metrics = None
        # LISTEN ports of the local cluster (set by the peer agent):
        # outbound dials refuse a kernel-assigned source port from this
        # set — on hosts whose ephemeral range covers the protocol
        # ports, a persistent pooled connection could otherwise squat on
        # a port a co-hosted peer needs to bind (see open_frame_stream)
        self.avoid_local_ports: frozenset = frozenset()
        # Hive loopback fast path (runtime/hive.py, docs/HIVE.md): when a
        # LoopbackHub is attached, calls/posts toward a CO-HOSTED peer
        # skip TCP framing and serialization entirely — the hub delivers
        # (meta, arrays) straight into the destination's handler, still
        # flowing through this pool's fault-plane draw, the destination's
        # admission controller, and the wire byte counters (a `loopback`
        # direction). `loopback_src` is the owning peer's id (the hub
        # keys admission budgets and fault schedules on it).
        self.loopback = None
        self.loopback_src: Optional[int] = None

    def _evict(self, exempt: Optional[Tuple[str, int]] = None) -> None:
        # drop dead connections regardless of the cap, then close idle
        # LRU ones until within bounds (busy conns are skipped, as is the
        # freshly-dialed `exempt` conn: it looks idle only because its
        # first RPC has not registered in pending yet — with >max_conns
        # dials in flight, the N=100 announce fan-out, evicting it would
        # hand its caller a closed conn)
        for k in [k for k, c in self._conns.items() if not c.alive]:
            self._conns.pop(k).close()
        excess = len(self._conns) - self.max_conns
        if excess <= 0:
            return
        for k in list(self._conns.keys()):
            if excess <= 0:
                break
            if k == exempt:
                continue
            c = self._conns[k]
            if c.pending or c.sending:
                continue
            del self._conns[k]
            c.close()
            excess -= 1

    async def _dial(self, key: Tuple[str, int]) -> _Conn:
        conn = _Conn(await open_frame_stream(
            *key, avoid_local_ports=self.avoid_local_ports))
        conn.metrics = self.metrics
        self._conns[key] = conn
        self._conns.move_to_end(key)
        self._evict(exempt=key)
        return conn

    async def _get(self, host: str, port: int, timeout: float) -> _Conn:
        """Concurrent callers to one peer SHARE a single in-flight dial
        (shielded, so each caller's timeout cancels only its own wait) —
        holding a lock across the dial would serialize N callers into
        N × timeout worst-case latency against a dead peer."""
        key = (host, port)
        conn = self._conns.get(key)
        if conn is not None and conn.alive:
            self._conns.move_to_end(key)
            return conn
        task = self._dialing.get(key)
        if task is None or task.done():
            task = asyncio.ensure_future(self._dial(key))
            self._dialing[key] = task
        return await asyncio.wait_for(asyncio.shield(task), timeout)

    def _account_out(self, msg_type: str):
        """Outbound byte-accounting closure for one call (None when
        telemetry is off): wire bytes counter + compression ratio,
        labeled with the frame's EFFECTIVE codec from encode stats (a
        frame whose arrays all fell back to raw counts as raw64, so
        both directions and the ratio histogram agree)."""
        m = self.metrics
        if m is None:
            return None

        def account(stats: dict) -> None:
            eff = stats.get("codec", wcodecs.RAW)
            m.counter(wcodecs.WIRE_BYTES_METRIC,
                      wcodecs.WIRE_BYTES_HELP).inc(
                stats["wire_bytes"], msg_type=msg_type, direction="out",
                codec=eff)
            wcodecs.observe_ratio(m, eff, stats["raw_bytes"],
                                  stats["wire_bytes"])

        return account

    async def call(self, host: str, port: int, msg_type: str,
                   meta: Dict[str, Any] | None = None,
                   arrays: Dict[str, np.ndarray] | None = None,
                   timeout: float = 120.0, attempt: int = 0,
                   codec: str = wcodecs.RAW, chunk_bytes: int = 0):
        # one deadline covers dial + send + reply: dialing must not grant
        # the roundtrip a second full budget
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        if self.latency is not None:
            d = self.latency(host, port)
            if d > 0:  # request + reply each ride the link once
                await asyncio.sleep(d)
        fault = (self.faults.action(host, port, msg_type, attempt)
                 if self.faults is not None else None)
        if self.loopback is not None:
            # co-hosted destination: deliver in-process (the fault draw
            # above already consumed this frame's schedule slot, so a
            # chaos run's per-link fate sequence is identical either way)
            ep = self.loopback.lookup(host, port)
            if ep is not None:
                # remaining budget, not the full timeout: the latency
                # sleep above already spent part of the one deadline
                # that covers dial + send + reply (TCP-path contract)
                return await ep.call(msg_type, meta, arrays,
                                     max(0.001, deadline - loop.time()),
                                     fault=fault, src=self.loopback_src,
                                     metrics=self.metrics)
        m = self.metrics
        t0 = loop.time()
        try:
            conn = await self._get(host, port, timeout)
            if m is not None:
                # counted only once a connection exists: a refused dial
                # never put a frame on the wire and must not inflate the
                # outbound-traffic attribution
                m.counter("biscotti_rpc_frames_total",
                          "outbound RPC frames by method and kind").inc(
                    msg_type=msg_type, kind="call")
            remaining = max(0.001, deadline - loop.time())
            rmeta, rarrays = await conn.roundtrip(
                msg_type, meta, arrays, remaining, fault=fault,
                codec=None if codec == wcodecs.RAW else codec,
                chunk_bytes=chunk_bytes,
                account=self._account_out(msg_type))
        except BaseException as e:
            # cancellation is the CALLER giving up (shutdown, a superseding
            # deadline), not the transport failing — keep it out of the
            # failure counter the dashboards alert on
            if m is not None and not isinstance(e, asyncio.CancelledError):
                m.counter("biscotti_rpc_transport_failures_total",
                          "calls that died in transport (timeout/refused/"
                          "reset)").inc(msg_type=msg_type,
                                        kind=type(e).__name__)
            raise
        if m is not None:
            # any reply — including a protocol error — proves the
            # transport round-trip; the histogram measures the wire+peer
            # latency the retry/breaker plane acts on
            m.histogram("biscotti_rpc_client_seconds",
                        "reply-bearing RPC round-trip latency").observe(
                loop.time() - t0, msg_type=msg_type)
        if rmeta.get("error"):
            if rmeta.get("stale"):
                raise StaleError(rmeta["error"])
            if rmeta.get("busy"):
                raise BusyError(rmeta["error"])
            raise RPCError(rmeta["error"])
        return rmeta, rarrays

    async def post(self, host: str, port: int, frame: bytes,
                   timeout: float = 120.0, msg_type: str = "post",
                   attempt: int = 0, codec: str = wcodecs.RAW) -> None:
        """Fire-and-forget a PRE-ENCODED frame (rid 0: any reply is dropped
        by the reader). Lets a broadcast encode its payload once and write
        the same bytes to every peer — at N=100 the per-peer re-encode of a
        multi-MB block was the event loop's dominant cost. `msg_type` and
        `codec` only key the fault plane's draw and the byte accounting
        (the frame already carries both)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        if self.latency is not None:
            d = self.latency(host, port)
            if d > 0:
                await asyncio.sleep(d / 2)  # one-way: no reply to wait for
        fault = (self.faults.action(host, port, msg_type, attempt)
                 if self.faults is not None else None)
        if self.loopback is not None:
            ep = self.loopback.lookup(host, port)
            if ep is not None:
                # pre-encoded frame toward a co-hosted peer (a caller
                # that didn't partition targets first): decode once and
                # deliver in-process — the encode is sunk, the TCP hop
                # and the receiver's decode/admission-peek copies aren't.
                # Broadcast paths avoid even the encode via post_direct.
                mt, dmeta, darrays = msgs.decode(frame)
                await ep.post(mt, dmeta, darrays,
                              max(0.001, deadline - loop.time()),
                              fault=fault, src=self.loopback_src,
                              metrics=self.metrics)
                return
        conn = await self._get(host, port, timeout)
        if self.metrics is not None:
            self.metrics.counter("biscotti_rpc_frames_total",
                                 "outbound RPC frames by method and kind"
                                 ).inc(msg_type=msg_type, kind="post")
            self.metrics.counter(wcodecs.WIRE_BYTES_METRIC,
                                 wcodecs.WIRE_BYTES_HELP).inc(
                len(frame), msg_type=msg_type, direction="out", codec=codec)
        await conn._send(frame, max(0.001, deadline - loop.time()),
                         fault=fault)

    def loopback_endpoint(self, host: str, port: int):
        """The co-hosted endpoint for (host, port), or None when the
        target is remote / not currently serving — broadcast paths use
        this to partition targets so co-hosted peers never pay the frame
        encode at all (runtime/hive.py)."""
        if self.loopback is None:
            return None
        return self.loopback.lookup(host, port)

    async def post_direct(self, host: str, port: int, msg_type: str,
                          meta: Dict[str, Any] | None = None,
                          arrays: Dict[str, np.ndarray] | None = None,
                          timeout: float = 120.0, attempt: int = 0) -> None:
        """Fire-and-forget toward a CO-HOSTED peer without any
        serialization: the hive broadcast fast path (gossip pushes the
        same block object to every local peer; remote peers get the
        encoded frame via `post`). Raises ConnectionError when the
        target is not loopback-local — callers partition targets with
        `loopback_endpoint` first, and a peer that died in between gets
        the same transport failure a closed TCP socket would raise."""
        ep = self.loopback_endpoint(host, port)
        if ep is None:
            raise ConnectionError(f"{host}:{port} is not loopback-local")
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        if self.latency is not None:
            d = self.latency(host, port)
            if d > 0:
                await asyncio.sleep(d / 2)
        fault = (self.faults.action(host, port, msg_type, attempt)
                 if self.faults is not None else None)
        await ep.post(msg_type, meta, arrays,
                      max(0.001, deadline - loop.time()), fault=fault,
                      src=self.loopback_src, metrics=self.metrics)

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
        for task in self._dialing.values():
            task.cancel()
        self._dialing.clear()


def geo_latency(node_id: int, base_port: int, regions: int, n: int,
                rtt_s: float) -> Callable[[str, int], float]:
    """Per-link latency model for the WAN/geo operating point (assign the
    result to Pool.latency): peers split into `regions` contiguous blocks
    ("datacenters"); an RPC whose two ends sit in different regions pays
    the cross-region round trip, intra-region traffic stays
    loopback-fast. Mirrors the reference's multi-DC Azure deployment
    (ref: global-deploy-eval/biscottiParsedResults — 87.0 s/iter Biscotti
    @ 100 nodes multi-region, BASELINE.md rows 8-11)."""
    my_region = node_id * regions // n

    def lat(host: str, port: int) -> float:
        peer = port - base_port
        if not (0 <= peer < n):
            return 0.0
        return rtt_s if (peer * regions // n) != my_region else 0.0

    return lat


async def call(host: str, port: int, msg_type: str,
               meta: Dict[str, Any] | None = None,
               arrays: Dict[str, np.ndarray] | None = None,
               timeout: float = 120.0) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """One-shot convenience call (dial, request, close) for tools and
    tests; the runtime uses a persistent `Pool`."""

    async def _roundtrip():
        stream = await open_frame_stream(host, port)
        try:
            meta2 = dict(meta or {})
            meta2["rid"] = 0
            stream.write_parts([msgs.encode(msg_type, meta2, arrays)])
            await stream.drain()
            payload = await stream.next_frame()
            _, rmeta, rarrays = msgs.decode(payload)
            return rmeta, rarrays
        finally:
            stream.close()

    rmeta, rarrays = await asyncio.wait_for(_roundtrip(), timeout)
    if rmeta.get("error"):
        if rmeta.get("stale"):
            raise StaleError(rmeta["error"])
        if rmeta.get("busy"):
            raise BusyError(rmeta["error"])
        raise RPCError(rmeta["error"])
    return rmeta, rarrays
