"""Asyncio RPC layer: per-call dial, per-call timeout, typed errors.

Mirrors the reference's transport semantics (SURVEY.md §2.1 row 2, §5.8):
  * one TCP dial per call with a `select{reply, timeout}` guard
    (ref: DistSys/main.go:1447-1489) — `call()` wraps the dial+roundtrip in
    `asyncio.wait_for`
  * the callee can reply with a *stale* error that callers treat as a
    signal, not a failure (ref: DistSys/main.go:140,380-383 staleError)
  * dead peers surface as TimeoutError/ConnectionError so the membership
    layer can evict them (ref: main.go:1468-1487)

Server side: one asyncio task per connection, frames dispatched to a single
handler coroutine `handle(msg_type, meta, arrays) -> (meta, arrays)`.
Handlers may block (e.g. a verifier parking a caller until the round's Krum
resolves, ref: DistSys/krum.go:330-336) — each request runs as its own task
so a parked call never stalls the connection's other requests.
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import numpy as np

from biscotti_tpu.runtime import messages as msgs

Handler = Callable[
    [str, Dict[str, Any], Dict[str, np.ndarray]],
    Awaitable[Tuple[Dict[str, Any], Dict[str, np.ndarray]]],
]


class RPCError(RuntimeError):
    """Remote handler returned an error (meta carries the reason)."""

    def __init__(self, reason: str, stale: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.stale = stale


class StaleError(RPCError):
    """The callee is past this message's iteration (ref: main.go:380-383)."""

    def __init__(self, reason: str = "stale iteration"):
        super().__init__(reason, stale=True)


class RPCServer:
    def __init__(self, host: str, port: int, handler: Handler):
        self.host = host
        self.port = port
        self.handler = handler
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host,
                                                  self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for t in list(self._conn_tasks):
            t.cancel()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        write_lock = asyncio.Lock()
        pending: set = set()
        try:
            while True:
                try:
                    payload = await msgs.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                try:
                    msg_type, meta, arrays = msgs.decode(payload)
                except msgs.CodecError:
                    break  # hostile/garbled peer: drop the connection
                t = asyncio.create_task(
                    self._dispatch(msg_type, meta, arrays, writer, write_lock)
                )
                pending.add(t)
                t.add_done_callback(pending.discard)
        finally:
            for t in pending:
                t.cancel()
            writer.close()
            self._conn_tasks.discard(task)

    async def _dispatch(self, msg_type, meta, arrays, writer, write_lock):
        rid = meta.get("rid")
        try:
            rmeta, rarrays = await self.handler(msg_type, meta, arrays)
        except StaleError as e:
            rmeta, rarrays = {"error": e.reason, "stale": True}, {}
        except RPCError as e:
            rmeta, rarrays = {"error": e.reason}, {}
        except asyncio.CancelledError:
            raise
        except Exception as e:  # handler bug: report, don't kill the peer
            rmeta, rarrays = {"error": f"internal: {type(e).__name__}: {e}"}, {}
        rmeta = dict(rmeta)
        rmeta["rid"] = rid
        frame = msgs.encode(msg_type + ".reply", rmeta, rarrays)
        async with write_lock:
            try:
                writer.write(frame)
                await writer.drain()
            except ConnectionError:
                pass


async def call(host: str, port: int, msg_type: str,
               meta: Dict[str, Any] | None = None,
               arrays: Dict[str, np.ndarray] | None = None,
               timeout: float = 120.0) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Dial, send one request, await the reply, close. Raises
    asyncio.TimeoutError / ConnectionError on dead peers, StaleError /
    RPCError on remote-signalled failures."""

    async def _roundtrip():
        reader, writer = await asyncio.open_connection(host, port)
        try:
            meta2 = dict(meta or {})
            meta2["rid"] = 0
            writer.write(msgs.encode(msg_type, meta2, arrays))
            await writer.drain()
            payload = await msgs.read_frame(reader)
            _, rmeta, rarrays = msgs.decode(payload)
            return rmeta, rarrays
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    rmeta, rarrays = await asyncio.wait_for(_roundtrip(), timeout)
    if rmeta.get("error"):
        if rmeta.get("stale"):
            raise StaleError(rmeta["error"])
        raise RPCError(rmeta["error"])
    return rmeta, rarrays
