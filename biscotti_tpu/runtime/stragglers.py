"""Straggler-tolerance plane: adaptive round deadlines + stall forensics.

The reference advances rounds on FIXED deadline constants sized for a
homogeneous 100-node fleet (`Timeouts.block_s=300`, `update_s=90`;
ref: DistSys/main.go:28-36) — a healthy fast cluster waits the full 300 s
on a dead miner, while a slow-but-honest fleet gets silently cut out of
rounds it could have finished. This module replaces the blind constants
with a per-peer **DeadlineController**: each deadline-bearing phase
(block wait, miner update/share intake, verifier krum timer, worker
collection fan-outs) feeds its observed durations into an EWMA + rolling
p95, and the NEXT round's deadline becomes

    clamp(max(ewma, p95) * margin,  floor_s,  legacy constant)

so the legacy constant is the ceiling the controller can only tighten
(never exceed — the reference's scaled() budget stays the worst case) and
the floor keeps a burst of fast rounds from collapsing the deadline below
network jitter. Until `min_samples` observations exist the controller
answers the legacy constant verbatim: warm-up is bit-identical seed
behavior, and so is the disabled controller (cfg.adaptive_deadlines=0).

Stall forensics ride along (armed or not): collection points publish
WHAT they are waiting on (phase + peer ids), a per-round watchdog counts
rounds stuck past half their block deadline
(`biscotti_round_stalls_total{phase}`), and partial-quorum proceeds count
the honest stragglers they left behind
(`biscotti_straggler_excluded_total{phase}`) — exclusions are an
observability event, NEVER breaker or stake evidence (the BusyError
precedent, docs/ADMISSION.md).

stdlib-only by design, like faults.py/admission.py: imported next to the
config layer and by the telemetry-off path.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional

# deadline-bearing phase names (one vocabulary for the controller, the
# metrics labels, the waiting-on readout, and the docs)
BLOCK = "block"      # everyone: round-advancing block wait
UPDATE = "update"    # miner: plain-mode update intake
SHARE = "share"      # miner: secure-agg share intake
KRUM = "krum"        # verifier: defense-decision timer
VERIFY = "verify"    # worker: verifier-signature fan-out
NOISE = "noise"      # worker: noiser-response fan-out

EXCLUDED_METRIC = "biscotti_straggler_excluded_total"
EXCLUDED_HELP = ("honest stragglers a partial-quorum collection point "
                 "proceeded without (never breaker/stake evidence)")
STALLS_METRIC = "biscotti_round_stalls_total"
STALLS_HELP = "rounds observed stuck past the stall threshold, by phase"
DEADLINE_GAUGE = "biscotti_deadline_seconds"
DEADLINE_HELP = "current adaptive deadline decision per phase"


class DeadlineController:
    """Per-peer adaptive deadline state (see module docstring).

    `observe(phase, dt)` feeds one completed-phase duration;
    `deadline(phase, legacy)` answers the budget the NEXT wait on that
    phase should use, recording the decision for the snapshot/trace
    surfaces. `clock` is injectable for tests (history timestamps only —
    the math itself is clock-free).
    """

    def __init__(self, enabled: bool = False, margin: float = 1.5,
                 floor_s: float = 1.0, quantile: float = 0.95,
                 window: int = 64, min_samples: int = 3,
                 alpha: float = 0.2,
                 clock: Callable[[], float] = time.monotonic,
                 history: int = 128):
        self.enabled = bool(enabled)
        self.margin = max(1.0, float(margin))
        self.floor_s = max(0.0, float(floor_s))
        self.quantile = min(1.0, max(0.0, float(quantile)))
        self.window = max(4, int(window))
        self.min_samples = max(1, int(min_samples))
        self.alpha = min(1.0, max(0.0, float(alpha)))
        self._clock = clock
        self._samples: Dict[str, deque] = {}
        self._ewma: Dict[str, float] = {}
        self._last: Dict[str, Dict] = {}     # last decision per phase
        # bounded decision log (chaos report "deadline history")
        self.history: deque = deque(maxlen=max(8, int(history)))

    # ------------------------------------------------------------ intake

    def observe(self, phase: str, dt: float) -> None:
        """One completed phase duration (seconds). Cheap: a deque append
        and one multiply — safe on the hot path whether or not the
        controller is enabled (observations are how a later --adaptive-
        deadlines restart would warm up instantly from a chaos rerun)."""
        dt = max(0.0, float(dt))
        q = self._samples.get(phase)
        if q is None:
            q = self._samples[phase] = deque(maxlen=self.window)
            self._ewma[phase] = dt
        else:
            a = self.alpha
            self._ewma[phase] = a * dt + (1.0 - a) * self._ewma[phase]
        q.append(dt)

    # ----------------------------------------------------------- readout

    def p95(self, phase: str) -> Optional[float]:
        q = self._samples.get(phase)
        if not q:
            return None
        s = sorted(q)
        # index of the quantile-crossing sample (ceil rank, 0-based)
        idx = min(len(s) - 1, max(0, int(self.quantile * len(s) + 0.999) - 1))
        return s[idx]

    def estimate(self, phase: str) -> Optional[float]:
        """The controller's raw duration estimate: max(EWMA, p95) — EWMA
        tracks drift, the windowed p95 keeps one fast burst from
        forgetting the distribution's tail."""
        p = self.p95(phase)
        if p is None:
            return None
        return max(self._ewma.get(phase, p), p)

    def deadline(self, phase: str, legacy: float) -> float:
        """The budget the next `phase` wait should use, with the decision
        recorded (snapshot + history). Disabled, or short of
        `min_samples` observations: the legacy constant verbatim — the
        bit-identity contract."""
        decided = float(legacy)
        est = self.estimate(phase)
        samples = len(self._samples.get(phase, ()))
        adaptive = (self.enabled and est is not None
                    and samples >= self.min_samples)
        if adaptive:
            decided = min(float(legacy),
                          max(self.floor_s, est * self.margin))
        rec = {"phase": phase, "deadline_s": round(decided, 4),
               "legacy_s": float(legacy), "adaptive": adaptive,
               "samples": samples,
               "est_s": round(est, 4) if est is not None else None}
        if self._last.get(phase, {}).get("deadline_s") != rec["deadline_s"] \
                or self._last.get(phase, {}).get("adaptive") != adaptive:
            self.history.append({**rec, "ts": self._clock()})
        self._last[phase] = rec
        return decided

    def snapshot(self) -> Dict:
        """Structured readout for telemetry_snapshot()["stragglers"]:
        per-phase sample stats + the last decision, plus the bounded
        decision history."""
        phases: Dict[str, Dict] = {}
        for phase, q in self._samples.items():
            phases[phase] = {
                "samples": len(q),
                "ewma_s": round(self._ewma.get(phase, 0.0), 4),
                "p95_s": round(self.p95(phase) or 0.0, 4),
            }
            last = self._last.get(phase)
            if last is not None:
                phases[phase].update(
                    deadline_s=last["deadline_s"],
                    adaptive=last["adaptive"])
        return {"enabled": self.enabled, "margin": self.margin,
                "floor_s": self.floor_s, "phases": phases,
                "history": list(self.history)}


class StragglerLedger:
    """Per-peer straggler forensics: who each collection point is
    currently waiting on, how many honest stragglers partial-quorum
    proceeds excluded, and how many rounds stalled. One instance per
    agent; `metrics` (a telemetry registry) is attached by the peer so
    every tally is scrape-visible."""

    def __init__(self):
        self.metrics = None
        self.excluded: Dict[str, int] = {}     # phase -> count
        self.stalls: Dict[str, int] = {}       # phase -> count
        # live waiting-on view: phase -> sorted awaited peer ids. Entries
        # are set while a collection point is blocked and cleared when it
        # resolves — the obs cluster table's `waiting-on` column.
        self.waiting_on: Dict[str, List[int]] = {}
        self.last_stall: Optional[Dict] = None

    # ------------------------------------------------------- bookkeeping

    def waiting(self, phase: str, peers) -> None:
        peers = sorted(int(p) for p in peers)
        if peers:
            self.waiting_on[phase] = peers
        else:
            self.waiting_on.pop(phase, None)

    def clear(self, phase: str) -> None:
        self.waiting_on.pop(phase, None)

    def exclude(self, phase: str, peers) -> int:
        n = len(list(peers))
        if n <= 0:
            return 0
        self.excluded[phase] = self.excluded.get(phase, 0) + n
        if self.metrics is not None:
            self.metrics.counter(EXCLUDED_METRIC, EXCLUDED_HELP).inc(
                n, phase=phase)
        return n

    def stall(self, phase: str, peers, height: int) -> None:
        self.stalls[phase] = self.stalls.get(phase, 0) + 1
        self.last_stall = {"phase": phase,
                           "peers": sorted(int(p) for p in peers),
                           "height": int(height)}
        if self.metrics is not None:
            self.metrics.counter(STALLS_METRIC, STALLS_HELP).inc(phase=phase)

    # ----------------------------------------------------------- readout

    def snapshot(self) -> Dict:
        return {
            "excluded": dict(self.excluded),
            "stalls": dict(self.stalls),
            "waiting_on": {ph: list(ps)
                           for ph, ps in self.waiting_on.items()},
            "last_stall": dict(self.last_stall) if self.last_stall else None,
        }
