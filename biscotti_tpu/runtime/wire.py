"""Block/Update <-> wire conversion for the runtime codec.

The reference gob-encodes its structs directly (ref: DistSys/main.go:609-610);
our codec separates JSON metadata from raw array payloads, so blocks and
updates need explicit packers. All byte fields travel as hex in metadata.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from biscotti_tpu.ledger.block import Block, BlockData, Update


def _as_f64(a: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """float64 view of a decoded payload, ZERO-COPY when the wire already
    delivered float64 (the common case: messages.decode hands back
    read-only views into the frame buffer, and an `asarray(..., f64)`
    on a differently-typed array is the only thing that should ever
    copy). Codec-decoded arrays (runtime/codecs.py) arrive as float64
    already, so coded frames stay on the no-copy path too."""
    if a is None:
        return None
    a = np.asarray(a)
    return a if a.dtype == np.float64 else a.astype(np.float64)


def pack_update(u: Update, prefix: str = "u") -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    meta = {
        "source_id": u.source_id,
        "iteration": u.iteration,
        "commitment": u.commitment.hex(),
        "accepted": u.accepted,
        "signatures": [s.hex() for s in u.signatures],
        "signers": list(u.signers),
        "has_noise": u.noise is not None,
        "has_noised": u.noised_delta is not None,
    }
    arrays = {f"{prefix}.delta": u.delta}
    if u.noise is not None:
        arrays[f"{prefix}.noise"] = u.noise
    if u.noised_delta is not None:
        arrays[f"{prefix}.noised"] = u.noised_delta
    return meta, arrays


def unpack_update(meta: Dict[str, Any], arrays: Dict[str, np.ndarray],
                  prefix: str = "u") -> Update:
    return Update(
        source_id=int(meta["source_id"]),
        iteration=int(meta["iteration"]),
        delta=_as_f64(arrays[f"{prefix}.delta"]),
        commitment=bytes.fromhex(meta.get("commitment", "")),
        noise=_as_f64(arrays[f"{prefix}.noise"])
        if meta.get("has_noise") else None,
        noised_delta=_as_f64(arrays[f"{prefix}.noised"])
        if meta.get("has_noised") else None,
        accepted=bool(meta.get("accepted", False)),
        signatures=[bytes.fromhex(s) for s in meta.get("signatures", [])],
        signers=[int(s) for s in meta.get("signers", [])],
    )


def pack_block(blk: Block) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    metas: List[Dict[str, Any]] = []
    arrays: Dict[str, np.ndarray] = {"global_w": blk.data.global_w}
    for i, u in enumerate(blk.data.deltas):
        m, a = pack_update(u, prefix=f"d{i}")
        metas.append(m)
        arrays.update(a)
    meta = {
        "iteration": blk.data.iteration,
        "prev_hash": blk.prev_hash.hex(),
        "hash": blk.hash.hex(),
        "timestamp": blk.timestamp,
        "stake_map": {str(k): v for k, v in blk.stake_map.items()},
        "deltas": metas,
    }
    return meta, arrays


def unpack_block(meta: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> Block:
    deltas = [
        unpack_update(m, arrays, prefix=f"d{i}")
        for i, m in enumerate(meta.get("deltas", []))
    ]
    blk = Block(
        data=BlockData(
            iteration=int(meta["iteration"]),
            global_w=_as_f64(arrays["global_w"]),
            deltas=deltas,
        ),
        prev_hash=bytes.fromhex(meta["prev_hash"]),
        stake_map={int(k): int(v) for k, v in meta.get("stake_map", {}).items()},
        timestamp=int(meta.get("timestamp", 0)),
    )
    blk.hash = bytes.fromhex(meta.get("hash", ""))
    return blk


def pack_chain(blocks: List[Block]) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    metas = []
    arrays: Dict[str, np.ndarray] = {}
    for i, blk in enumerate(blocks):
        m, a = pack_block(blk)
        metas.append(m)
        arrays.update({f"b{i}.{k}": v for k, v in a.items()})
    return {"blocks": metas}, arrays


def unpack_chain(meta: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> List[Block]:
    out = []
    for i, m in enumerate(meta.get("blocks", [])):
        sub = {k[len(f"b{i}."):]: v for k, v in arrays.items()
               if k.startswith(f"b{i}.")}
        out.append(unpack_block(m, sub))
    return out
