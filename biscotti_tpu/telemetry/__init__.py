"""biscotti_tpu.telemetry — the unified telemetry plane.

Four pieces (docs/OBSERVABILITY.md):

  * `MetricsRegistry` — counters / gauges / histograms with labels,
    fixed log-scale latency buckets, bounded label cardinality, and
    Prometheus text rendering (registry.py).
  * `Telemetry.span` — round-correlated timing contexts feeding the
    phase histogram, the legacy PhaseClock totals, and the recorder
    (core.py).
  * `FlightRecorder` — bounded event ring with batched JSONL spill and
    crash dump; every event stamped (wall, monotonic, seq) (recorder.py).
  * `serve_metrics` — optional local HTTP exposition; the peer's
    `Metrics` RPC is the primary scrape path (runtime/peer.py,
    tools/obs.py).

The whole package is stdlib-only: importing it (or running with
telemetry disabled, which swaps in the NULL_* no-op singletons) pulls in
neither jax nor numpy — asserted by tests/test_telemetry.py's smoke test.
"""

from biscotti_tpu.telemetry import tracectx  # noqa: F401
from biscotti_tpu.telemetry.core import (  # noqa: F401
    NULL_RECORDER,
    NULL_REGISTRY,
    NullRecorder,
    NullRegistry,
    Telemetry,
    serve_metrics,
)
from biscotti_tpu.telemetry.recorder import FlightRecorder  # noqa: F401
from biscotti_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    quantile_from_buckets,
)
